#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Driver benchmark: Power-Run geomean query time on the available chip.

Generates raw data with the native generator, registers the tables, runs the
supported TPC-DS query set through the engine (one warm-up pass for
compilation, then one timed pass — the reference's Power Run times a warmed
JVM the same way), and prints ONE JSON line:

    {"metric": "power_geomean_ms", "value": N, "unit": "ms", "vs_baseline": N}

The reference publishes no absolute numbers (BASELINE.md), so ``vs_baseline``
is reported against this framework's own first recorded value when present
(``.bench_baseline.json``), else 1.0.
"""

import json
import math
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SCALE = os.environ.get("NDS_BENCH_SCALE", "0.05")
CACHE = os.path.join(REPO, ".bench_cache", f"sf{SCALE}")
NDSGEN = os.path.join(REPO, "native", "ndsgen", "ndsgen")


def ensure_data():
    if not os.path.exists(NDSGEN):
        subprocess.run(["make", "-C", os.path.dirname(NDSGEN)], check=True,
                       capture_output=True)
    marker = os.path.join(CACHE, ".complete")
    if not os.path.exists(marker):
        os.makedirs(CACHE, exist_ok=True)
        subprocess.run([NDSGEN, "-scale", SCALE, "-dir", CACHE], check=True)
        open(marker, "w").close()
    return CACHE


def bench_queries():
    """Supported query set: generated stream when present, else builtin q3."""
    qdir = os.path.join(REPO, ".bench_cache", "stream")
    try:
        from nds_tpu.queries import generate_query_streams, SUPPORTED_QUERIES
        from nds_tpu.power import gen_sql_from_stream
        if SUPPORTED_QUERIES:
            os.makedirs(qdir, exist_ok=True)
            stream_file = os.path.join(qdir, "query_0.sql")
            if not os.path.exists(stream_file):
                generate_query_streams(qdir, streams=1, rngseed=0,
                                       templates=SUPPORTED_QUERIES,
                                       scale=float(SCALE))
            queries = gen_sql_from_stream(stream_file)
            if queries:
                return list(queries.items())
    except ImportError:
        pass
    return [("query3", """
            select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
                   sum(ss_ext_sales_price) sum_agg
            from date_dim dt, store_sales, item
            where dt.d_date_sk = store_sales.ss_sold_date_sk
              and store_sales.ss_item_sk = item.i_item_sk
              and item.i_manufact_id = 128
              and dt.d_moy = 11
            group by dt.d_year, item.i_brand_id, item.i_brand
            order by dt.d_year, sum_agg desc, brand_id
            limit 100
        """)]


def main():
    data_dir = ensure_data()
    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    queries = bench_queries()
    schemas = get_schemas(use_decimal=True)
    sess = Session()
    for table, fields in schemas.items():
        path = os.path.join(data_dir, f"{table}.dat")
        if os.path.exists(path):
            sess.read_raw_view(table, path, fields)

    # Per-query warmup-then-time (the reference's Power Run times a warmed
    # JVM the same way). A wall-clock budget guards the driver's bench
    # window: queries past the budget are skipped and n_queries reports how
    # many were measured.
    budget_s = float(os.environ.get("NDS_BENCH_BUDGET_S", "3300"))
    t_start = time.perf_counter()
    times = {}
    skipped = 0
    for name, sql in queries:
        if time.perf_counter() - t_start > budget_s:
            skipped += 1
            continue
        tw = time.perf_counter()
        sess.sql(sql).collect()                      # warmup: compile
        t0 = time.perf_counter()
        res = sess.sql(sql)
        res.collect()
        times[name] = (time.perf_counter() - t0) * 1000.0
        print(f"# {name}: warm {tw and t0 - tw:.1f}s timed "
              f"{times[name]/1000:.2f}s", file=sys.stderr)
    if skipped:
        print(f"# budget hit: {skipped} queries skipped", file=sys.stderr)

    geomean = math.exp(sum(math.log(max(t, 1e-3)) for t in times.values())
                       / len(times))

    baseline_file = os.path.join(REPO, ".bench_baseline.json")
    vs = 1.0
    base = None
    if os.path.exists(baseline_file):
        try:
            base = json.load(open(baseline_file))
        except ValueError:
            base = None
    # a baseline only means something for the same query set; re-baseline
    # whenever the supported-query ratchet grows
    if base and base.get("n_queries") == len(times) and base.get("value"):
        vs = base["value"] / geomean
    else:
        json.dump({"metric": "power_geomean_ms", "value": geomean,
                   "n_queries": len(times)}, open(baseline_file, "w"))

    print(json.dumps({
        "metric": "power_geomean_ms",
        "value": round(geomean, 3),
        "unit": "ms",
        "vs_baseline": round(vs, 4),
        "n_queries": len(times),
    }))


if __name__ == "__main__":
    main()
