#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Driver benchmark: Power-Run geomean query time on the available chip.

Generates raw data with the native generator, registers the tables, runs the
supported TPC-DS query set through the engine (per-query warm-up pass for
compilation, then a timed pass — the reference's Power Run times a warmed
JVM the same way), and prints ONE JSON line:

    {"metric": "power_geomean_ms", "value": N, "unit": "ms", "vs_baseline": N}

Execution model: ONE persistent child process serves queries over a line
protocol (stdin: query name, stdout: one JSON result line). The parent
enforces a per-query deadline; a wedged device RPC or crash costs only that
query — the child is killed and restarted for the remainder (the tunnel to
the real chip has been observed to hang a blocked-in-C call indefinitely,
which in-process watchdogs cannot interrupt). A persistent child amortizes
the per-process costs (JAX init, 24-table load) that a chunk-per-process
model paid ~13 times over.

Deadline safety: the budget clock starts at process entry (not after data
generation), queries run cheapest-first (by baseline history) so a timeout
maximizes measured coverage, and the final JSON line is also emitted from a
SIGTERM/SIGINT handler so an external `timeout` kill still yields a parsed
result for whatever was measured.

``vs_baseline`` compares against this framework's own first recorded
per-query times in the COMMITTED ``BASELINE_TIMES.json`` (cross-round
lineage, recomputable from git alone); the reference publishes no absolute
numbers (BASELINE.md).
"""

import argparse
import json
import math
import os
import queue
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SCALE = os.environ.get("NDS_BENCH_SCALE", "0.05")
CACHE = os.path.join(REPO, ".bench_cache", f"sf{SCALE}")
PQ_CACHE = os.path.join(REPO, ".bench_cache", f"sf{SCALE}_parquet")
NDSGEN = os.path.join(REPO, "native", "ndsgen", "ndsgen")
# generous per-query allowance: cold compiles on the chip run minutes
PER_QUERY_TIMEOUT_S = float(os.environ.get("NDS_BENCH_QUERY_TIMEOUT_S", "420"))
# child startup: JAX init + backend attach + 24-table device load
SETUP_TIMEOUT_S = float(os.environ.get("NDS_BENCH_SETUP_TIMEOUT_S", "300"))


def ensure_data():
    if not os.path.exists(NDSGEN):
        subprocess.run(["make", "-C", os.path.dirname(NDSGEN)], check=True,
                       capture_output=True)
    marker = os.path.join(CACHE, ".complete")
    if not os.path.exists(marker):
        os.makedirs(CACHE, exist_ok=True)
        subprocess.run([NDSGEN, "-scale", SCALE, "-dir", CACHE], check=True)
        with open(marker, "w"):
            pass
    # one-time transcode: children load parquet ~5x faster than raw CSV;
    # invalidated whenever the CSV cache is newer (regenerated data)
    pq_marker = os.path.join(PQ_CACHE, ".complete")
    stale = (os.path.exists(pq_marker) and
             os.path.getmtime(pq_marker) < os.path.getmtime(marker))
    if stale or not os.path.exists(pq_marker):
        import pyarrow.parquet as pq

        from nds_tpu.io import read_raw_table
        from nds_tpu.schema import get_schemas
        os.makedirs(PQ_CACHE, exist_ok=True)
        for table, fields in get_schemas(use_decimal=True).items():
            path = os.path.join(CACHE, f"{table}.dat")
            if os.path.exists(path):
                pq.write_table(read_raw_table(path, fields),
                               os.path.join(PQ_CACHE, f"{table}.parquet"))
        with open(pq_marker, "w"):
            pass
    return PQ_CACHE


def bench_queries():
    """Supported query set: generated stream when present, else builtin q3."""
    try:
        from nds_tpu.queries import generate_query_streams, SUPPORTED_QUERIES
        from nds_tpu.power import gen_sql_from_stream
        if SUPPORTED_QUERIES:
            # stream cache keyed by scale (predicate vocabularies band by
            # scale) and by the size of the supported-query ratchet
            qdir = os.path.join(
                REPO, ".bench_cache",
                f"stream_sf{SCALE}_n{len(SUPPORTED_QUERIES)}")
            os.makedirs(qdir, exist_ok=True)
            stream_file = os.path.join(qdir, "query_0.sql")
            if not os.path.exists(stream_file):
                generate_query_streams(qdir, streams=1, rngseed=0,
                                       templates=SUPPORTED_QUERIES,
                                       scale=float(SCALE))
            queries = gen_sql_from_stream(stream_file)
            if queries:
                return list(queries.items())
    except ImportError:
        pass
    return [("query3", """
            select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
                   sum(ss_ext_sales_price) sum_agg
            from date_dim dt, store_sales, item
            where dt.d_date_sk = store_sales.ss_sold_date_sk
              and store_sales.ss_item_sk = item.i_item_sk
              and item.i_manufact_id = 128
              and dt.d_moy = 11
            group by dt.d_year, item.i_brand_id, item.i_brand
            order by dt.d_year, sum_agg desc, brand_id
            limit 100
        """)]


def order_by_history(names, baseline_file):
    """Cheapest-first by baseline history; unmeasured queries go last.

    When the budget runs out mid-run this maximizes the number of measured
    queries, and pushes historically-absent outliers (e.g. an OOM-prone
    query) where their failure can't shadow cheap coverage."""
    try:
        with open(baseline_file) as f:
            hist = json.load(f).get("times") or {}
    except (OSError, ValueError):
        hist = {}
    known = sorted((n for n in names if n in hist), key=lambda n: hist[n])
    unknown = [n for n in names if n not in hist]
    return known + unknown


def run_server():
    """Persistent child: load tables once, then serve query names from
    stdin, one JSON result line on stdout each."""
    data_dir = ensure_data()
    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    wanted = dict(bench_queries())
    sess = Session()
    for table, fields in get_schemas(use_decimal=True).items():
        path = os.path.join(data_dir, f"{table}.parquet")
        if os.path.exists(path):
            sess.read_columnar_view(
                table, path, "parquet",
                canonical_types={f.name: f.type for f in fields})
    try:
        # provenance: the platform that actually executes, stamped into
        # PERF.md by the parent (BENCH_r05 ran 3000s against a chip that
        # never came up — the header must say what really ran, not assume)
        import jax as _jax
        platform = _jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    print(json.dumps({"ready": True, "platform": platform}), flush=True)

    from nds_tpu.engine import ops as _ops

    _ops.enable_compile_meter()
    for line in sys.stdin:
        name = line.strip()
        if not name:
            break
        try:
            sql = wanted[name]
            c0 = _ops.compile_ns()
            tw = time.perf_counter()
            sess.sql(sql).collect()                  # warmup: compile
            # hybrid replay ('auto'): a high-sync query transitions
            # eager -> record+compile -> first trace over its next sights;
            # fold those into warmup so the timed passes below measure
            # steady state (the reference times a warmed JVM the same way)
            for _ in range(3):
                if not sess.replay_pending(sql):
                    break
                sess.sql(sql).collect()
            # min of two timed passes: the tunnel to the chip shows multi-
            # second latency spikes (observed 2x swings on a fixed query);
            # min-of-2 reports steady-state device time, not tunnel weather
            t0 = time.perf_counter()
            sess.sql(sql).collect()
            t1 = time.perf_counter()
            # roofline decomposition measured on the final pass (sync
            # counts are deterministic per query; wait time is weather)
            from nds_tpu.listener import drain_stream_events
            from nds_tpu.obs import export as obs_export
            from nds_tpu.obs import trace as obs_trace
            drain_stream_events()        # count only the final pass's scans
            obs_trace.drain_spans()
            s0, w0 = _ops.sync_count(), _ops.sync_wait_ns()
            sess.sql(sql).collect()
            t2 = time.perf_counter()
            stream_events = drain_stream_events()
            trace_records = obs_trace.drain_spans()
            ms = min(t1 - t0, t2 - t1) * 1000.0
            syncs = _ops.sync_count() - s0
            sync_ms = (_ops.sync_wait_ns() - w0) / 1e6
            scan = sum(getattr(sess, "last_scanned", {}).values())
            gbps = scan / max(t2 - t1, 1e-9) / 1e9
            # measured compile split (jax monitoring): the warm pass's
            # XLA backend-compile seconds — ~0 on a persistent-cache hit
            compile_s = (_ops.compile_ns() - c0) / 1e9
            print(f"# {name}: warm {t0 - tw:.1f}s (compile "
                  f"{compile_s:.1f}s) timed {ms/1000:.2f}s "
                  f"syncs {syncs} syncWait {sync_ms:.0f}ms "
                  f"scan {gbps:.2f}GB/s",
                  file=sys.stderr)
            result = {
                "name": name, "ms": ms, "hostSyncs": syncs,
                "syncWaitMs": round(sync_ms, 1), "scanBytes": scan,
                "scanGBps": round(gbps, 3),
                # warm pass wall = XLA compile (+1 exec): the per-query
                # compile-cost axis the SF10 scaling question turns on
                "warmS": round(t0 - tw, 2),
                "compileS": round(compile_s, 2)}
            if stream_events:
                # >HBM streamed scans: which path served each (compiled
                # chunk pipeline vs eager chunk loop), chunk/sync counts
                # — the per-query face of the streamed sync budget
                from nds_tpu.listener import stream_event_json
                result["streamedScans"] = [
                    stream_event_json(e) for e in stream_events]
            if trace_records:
                # per-phase attribution of the final timed pass (obs
                # layer; zero added syncs): plan vs drive vs materialize
                # per query, plus top sync-charging host-read sites
                roll = obs_export.rollup(trace_records)
                result["tracePhases"] = roll
                trace_d = os.environ.get("NDS_BENCH_TRACE_DIR")
                if trace_d:
                    os.makedirs(trace_d, exist_ok=True)
                    obs_export.write_chrome_trace(
                        os.path.join(trace_d, f"{name}.trace.json"),
                        trace_records, query=name, roll=roll)
            try:
                # per-query HBM footprint where the backend exposes
                # allocator stats (local chips; the tunneled attachment
                # returns None — recorded so the gap is visible, not
                # silent)
                import jax as _jax
                stats = _jax.devices()[0].memory_stats()
                if stats:
                    result["hbmBytesInUse"] = int(
                        stats.get("bytes_in_use", 0))
                    result["peakHbmBytes"] = int(
                        stats.get("peak_bytes_in_use", 0))
            except Exception as exc:
                # allocator stats are best-effort diagnostics, but their
                # absence must leave a trace, not vanish
                print(f"# memory_stats unavailable: {exc}",
                      file=sys.stderr)
            print(json.dumps(result), flush=True)
        except Exception as e:                        # keep serving
            print(json.dumps({"name": name,
                              "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)


def _geomean(vals):
    return math.exp(sum(math.log(max(v, 1e-3)) for v in vals) / len(vals))


def resolve_baseline(baseline_file, times, n_total):
    """vs_baseline policy: the baseline stores each query's FIRST recorded
    time. Any run fills in queries the baseline lacks (so a partial run
    seeds, and an OOM-bound outlier joins whenever it first succeeds) but
    never overwrites an existing entry — the comparison stays longitudinal
    against the first measurement. vs_baseline is the geomean ratio over
    the common query set.

    The baseline is a COMMITTED file (BASELINE_TIMES.json): losing it
    would silently restart the lineage and make vs_baseline compare a
    round against itself (this happened in round 3 when the scratch copy
    was reseeded). A missing file is therefore an explicit, loud event."""
    base = None
    if os.path.exists(baseline_file):
        try:
            with open(baseline_file) as f:
                base = json.load(f)
        except ValueError:
            base = None
    if base is None and not os.environ.get("NDS_BENCH_SEED_BASELINE"):
        print(f"# {os.path.basename(baseline_file)} missing or unreadable: "
              "REFUSING to start a new baseline lineage (restore it from "
              "git, or set NDS_BENCH_SEED_BASELINE=1 to seed one on "
              "purpose); vs_baseline reported as 0.0", file=sys.stderr)
        return 0.0
    base_times = (base or {}).get("times") or {}
    common = sorted(set(times) & set(base_times))
    vs = (_geomean([base_times[q] for q in common]) /
          _geomean([times[q] for q in common])) if common else 1.0
    merged = dict(base_times)
    for q, t in times.items():
        merged.setdefault(q, t)
    if merged != base_times:
        out = {"metric": "power_geomean_ms",
               "value": _geomean(list(merged.values())),
               "n_queries": len(merged), "times": merged}
        if isinstance(base, dict) and "note" in base:
            out["note"] = base["note"]
        with open(baseline_file, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    return vs


class ChildServer:
    """Supervises the persistent serving child with per-request deadlines."""

    def __init__(self):
        self.proc = None
        self.lines = None

    def _reader(self, proc, q):
        for line in proc.stdout:
            q.put(line)
        q.put(None)

    def start(self, deadline_left):
        self.stop()
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--serve"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        self.lines = queue.Queue()
        threading.Thread(target=self._reader,
                         args=(self.proc, self.lines), daemon=True).start()
        msg = self._next_json(min(SETUP_TIMEOUT_S, deadline_left))
        if not (msg and msg.get("ready")):
            # a slow-to-start child left alive would desync the protocol:
            # its late "ready" line would be consumed as a query response
            self.stop()
            return None
        return msg

    def _next_json(self, timeout):
        end = time.perf_counter() + timeout
        while True:
            left = end - time.perf_counter()
            if left <= 0:
                return None
            try:
                line = self.lines.get(timeout=left)
            except queue.Empty:
                return None
            if line is None:
                return None
            try:
                return json.loads(line)
            except ValueError:
                continue                              # stray non-JSON chatter

    def run_query(self, name, timeout):
        try:
            self.proc.stdin.write(name + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            return None
        return self._next_json(timeout)

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def stop(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        self.proc = None


def write_perf(times, perf, platform="unknown"):
    """PERF.md: the per-query roofline table (wall, host-sync count and
    blocked time, bytes scanned, effective bandwidth) the geomean headline
    decomposes into. Committed alongside BENCH_r{N}.json so 'is it fast?'
    is answerable from artifacts (device vs host split per query).
    ``platform`` is the serving child's ``jax.devices()[0].platform`` —
    real provenance, not an assumed "attached chip"."""
    if not perf:
        return
    rows = sorted(times)
    tot_sync = sum(p.get("syncWaitMs", 0) for p in perf.values())
    tot_ms = sum(times.values())
    streamed = [e for p in perf.values()
                for e in p.get("streamedScans", [])]
    with open(os.path.join(REPO, "PERF.md"), "w") as f:
        f.write("# Power Run roofline decomposition\n\n")
        f.write(f"Scale factor {SCALE}; warm min-of-2 wall times; "
                f"platform: {platform}.\n"
                f"Aggregate: {len(times)} queries, "
                f"{tot_sync / max(tot_ms, 1e-9) * 100:.1f}% of summed wall "
                "time blocked on device->host reads.\n")
        if streamed:
            n_comp = sum(1 for e in streamed if e["path"] == "compiled")
            f.write(f"Streamed >HBM scans: {len(streamed)} "
                    f"({n_comp} compiled chunk pipeline, "
                    f"{len(streamed) - n_comp} eager fallback).\n")
        f.write("\n")
        f.write("| query | wall ms | warm s | compile s | host syncs | "
                "sync wait ms | scan MB | scan GB/s |\n"
                "|---|---|---|---|---|---|---|---|\n")
        for q in rows:
            p = perf.get(q, {})
            f.write(f"| {q} | {times[q]:.0f} | {p.get('warmS', '-')} | "
                    f"{p.get('compileS', '-')} | "
                    f"{p.get('hostSyncs', '-')} | "
                    f"{p.get('syncWaitMs', '-')} | "
                    f"{p.get('scanBytes', 0) / 1e6:.1f} | "
                    f"{p.get('scanGBps', '-')} |\n")


_emitted = False


def emit(times, n_total, aborted=None):
    """Print the one JSON metric line (idempotent; also the signal path).
    ``aborted`` labels a fail-fast partial artifact (circuit breaker) so a
    collector can tell "measured everything" from "gave up early"."""
    global _emitted
    if _emitted:
        return
    _emitted = True
    if not times:
        out = {"metric": "power_geomean_ms", "value": None,
               "unit": "ms", "vs_baseline": 0.0, "n_queries": 0}
        if aborted:
            out["aborted"] = aborted
        print(json.dumps(out))
        return
    geomean = _geomean(list(times.values()))
    try:
        vs = resolve_baseline(os.path.join(REPO, "BASELINE_TIMES.json"),
                              times, n_total)
    except Exception as exc:
        # the metric line must survive a baseline-write failure — this
        # path also runs from the SIGTERM handler of an externally
        # timed-out campaign, where losing the partial geomean repeats
        # BENCH_r05's {"value": null} artifact
        print(f"# baseline update failed: {exc}", file=sys.stderr)
        vs = 0.0
    out = {
        "metric": "power_geomean_ms",
        "value": round(geomean, 3),
        "unit": "ms",
        "vs_baseline": round(vs, 4),
        "n_queries": len(times),
    }
    if aborted:
        out["aborted"] = aborted
    print(json.dumps(out), flush=True)


def finalize(times, perf, n_total, platform="unknown", aborted=None):
    """Flush everything the campaign measured so far: the PERF.md
    roofline table and the one JSON metric line. Runs at normal end AND
    from the SIGTERM/SIGINT handler, so an external ``timeout`` kill
    (rc=124) still records the partial geomean of every completed query
    instead of BENCH_r05's ``{"value": null, "n_queries": 0}``. Each
    step is isolated: a PERF.md write failure must not eat the metric
    line."""
    try:
        write_perf(times, perf, platform)
    except Exception as exc:
        print(f"# PERF.md write failed: {exc}", file=sys.stderr)
    emit(times, n_total, aborted)


def load_resume(path, times, perf):
    """Pre-populate times/perf from a previous campaign's results file so
    an at-scale run (SF10: minutes/query) is resumable across invocations
    — measured queries are never re-paid (round-4 verdict: the first SF10
    campaign stopped at 30/103 and the partial work was lost). Returns the
    platform the original campaign stamped (its ``{"platform": ...}`` meta
    line), or None: a rerun satisfied entirely from the resume file starts
    no child and would otherwise overwrite PERF.md's real provenance with
    "unknown"."""
    platform = None
    if not path or not os.path.exists(path):
        return platform
    with open(path) as f:
        for ln in f:
            try:
                msg = json.loads(ln)
            except ValueError:
                continue
            if "ms" in msg:
                times[msg["name"]] = msg["ms"]
                perf[msg["name"]] = {k: msg[k] for k in
                                     ("hostSyncs", "syncWaitMs", "scanBytes",
                                      "scanGBps", "warmS", "compileS",
                                      "streamedScans", "tracePhases")
                                     if k in msg}
            elif "platform" in msg:
                platform = msg["platform"]
    return platform


def run_parent(t_entry):
    budget_s = float(os.environ.get("NDS_BENCH_BUDGET_S", "3000"))
    # margin so the final JSON + baseline write always beat an external kill
    margin_s = 20.0
    times = {}
    perf = {}
    names = []
    child = ChildServer()
    resume_path = os.environ.get("NDS_BENCH_RESULTS_JSONL")
    resume_platform = load_resume(resume_path, times, perf)
    resume_f = None
    if resume_path:
        resume_f = open(resume_path, "a")
    # defined BEFORE the handlers register: a kill during data
    # generation must find every name the handler reads
    platform = resume_platform or "unknown"

    def on_signal(signum, frame):
        # an external `timeout` kill lands here: flush the completed
        # per-query results (PERF.md + partial-geomean metric line +
        # resume JSONL) before the -k SIGKILL grace runs out
        finalize(times, perf, len(names), platform)
        if resume_f is not None:
            try:
                resume_f.close()
            except OSError:
                pass
        child.stop()          # free the device attachment before exiting
        os._exit(0)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    ensure_data()                                    # once, before the child
    names = [n for n, _ in bench_queries()]
    ordered = order_by_history(names,
                               os.path.join(REPO, "BASELINE_TIMES.json"))
    restarts = 0

    def left():
        return budget_s - margin_s - (time.perf_counter() - t_entry)

    pending = [n for n in ordered if n not in times]
    if times:
        print(f"# resume: {len(times)} queries pre-loaded from "
              f"{os.path.basename(resume_path)}", file=sys.stderr)
    attempts = {}
    aborted = None
    setup_fails = 0
    while pending and left() > 0:
        if not child.alive():
            if restarts > 6:                          # crash-looping backend
                break
            restarts += 1
            ready = child.start(left())
            if ready is None:
                # circuit breaker: BENCH_r05 burned its whole 3000s budget
                # on six consecutive 300s setup timeouts against a backend
                # that never came up — after 2 in a row, stop paying and
                # emit the labeled partial artifact instead
                setup_fails += 1
                if setup_fails >= 2:
                    aborted = "child-setup-failure"
                    print(f"# {setup_fails} consecutive child-setup "
                          "failures: backend is not coming up; "
                          "failing fast with a partial artifact",
                          file=sys.stderr)
                    break
                continue                              # dead child -> retry
            setup_fails = 0
            new_plat = ready.get("platform", "unknown")
            if new_plat != "unknown" and new_plat != platform:
                platform = new_plat
                if resume_f is not None:
                    # provenance meta line: lets a later rerun that never
                    # starts a child still stamp the real platform
                    resume_f.write(json.dumps({"platform": platform})
                                   + "\n")
                    resume_f.flush()
        name = pending.pop(0)
        attempts[name] = attempts.get(name, 0) + 1
        deadline = min(PER_QUERY_TIMEOUT_S, left())
        msg = child.run_query(name, deadline)
        if msg is None:                               # wedged or crashed
            # the abort cause drives at-scale diagnosis: a dead child is a
            # crash (OOM, device fault — its exit code says which); a live
            # one blew the per-query deadline
            if child.alive():
                cause = f"timeout after {deadline:.0f}s"
            else:
                cause = f"child crashed (exit {child.proc.returncode})"
            print(f"# {name} aborted ({cause}); restarting child",
                  file=sys.stderr)
            child.stop()
            if attempts[name] < 2:                    # one retry, at the end
                pending.append(name)
            continue
        if "ms" in msg:
            times[msg["name"]] = msg["ms"]
            perf[msg["name"]] = {k: msg[k] for k in
                                 ("hostSyncs", "syncWaitMs", "scanBytes",
                                  "scanGBps", "warmS", "compileS",
                                  "streamedScans")
                                 if k in msg}
            if resume_f is not None:
                resume_f.write(json.dumps(msg) + "\n")
                resume_f.flush()
        else:
            print(f"# {name} failed: {msg.get('error')}", file=sys.stderr)
    child.stop()
    if resume_f is not None:
        resume_f.close()

    if times and len(times) < len(names):
        print(f"# measured {len(times)}/{len(names)} queries",
              file=sys.stderr)
    finalize(times, perf, len(names), platform, aborted)
    if not times:
        sys.exit(1)


def main():
    t_entry = time.perf_counter()
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true",
                    help="persistent child: serve queries over stdin/stdout")
    args = ap.parse_args()
    if args.serve:
        run_server()
    else:
        run_parent(t_entry)


if __name__ == "__main__":
    main()
