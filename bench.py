#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Driver benchmark: Power-Run geomean query time on the available chip.

Generates raw data with the native generator, registers the tables, runs the
supported TPC-DS query set through the engine (per-query warm-up pass for
compilation, then a timed pass — the reference's Power Run times a warmed
JVM the same way), and prints ONE JSON line:

    {"metric": "power_geomean_ms", "value": N, "unit": "ms", "vs_baseline": N}

Fault isolation: queries run in chunked child processes with timeouts, so a
wedged device RPC or a crash loses only that chunk's remainder, never the
whole bench (the tunnel to the real chip has been observed to hang a
blocked-in-C call indefinitely, which in-process watchdogs cannot interrupt).

``vs_baseline`` compares against this framework's own first recorded value
for the same query-set size (``.bench_baseline.json``); the reference
publishes no absolute numbers (BASELINE.md).
"""

import argparse
import json
import math
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SCALE = os.environ.get("NDS_BENCH_SCALE", "0.05")
CACHE = os.path.join(REPO, ".bench_cache", f"sf{SCALE}")
PQ_CACHE = os.path.join(REPO, ".bench_cache", f"sf{SCALE}_parquet")
NDSGEN = os.path.join(REPO, "native", "ndsgen", "ndsgen")
CHUNK = int(os.environ.get("NDS_BENCH_CHUNK", "8"))
# generous per-query allowance: cold compiles on the chip run minutes
PER_QUERY_TIMEOUT_S = float(os.environ.get("NDS_BENCH_QUERY_TIMEOUT_S", "600"))


def ensure_data():
    if not os.path.exists(NDSGEN):
        subprocess.run(["make", "-C", os.path.dirname(NDSGEN)], check=True,
                       capture_output=True)
    marker = os.path.join(CACHE, ".complete")
    if not os.path.exists(marker):
        os.makedirs(CACHE, exist_ok=True)
        subprocess.run([NDSGEN, "-scale", SCALE, "-dir", CACHE], check=True)
        open(marker, "w").close()
    # one-time transcode: children load parquet ~5x faster than raw CSV;
    # invalidated whenever the CSV cache is newer (regenerated data)
    pq_marker = os.path.join(PQ_CACHE, ".complete")
    stale = (os.path.exists(pq_marker) and
             os.path.getmtime(pq_marker) < os.path.getmtime(marker))
    if stale or not os.path.exists(pq_marker):
        import pyarrow.parquet as pq

        from nds_tpu.io import read_raw_table
        from nds_tpu.schema import get_schemas
        os.makedirs(PQ_CACHE, exist_ok=True)
        for table, fields in get_schemas(use_decimal=True).items():
            path = os.path.join(CACHE, f"{table}.dat")
            if os.path.exists(path):
                pq.write_table(read_raw_table(path, fields),
                               os.path.join(PQ_CACHE, f"{table}.parquet"))
        open(pq_marker, "w").close()
    return PQ_CACHE


def bench_queries():
    """Supported query set: generated stream when present, else builtin q3."""
    try:
        from nds_tpu.queries import generate_query_streams, SUPPORTED_QUERIES
        from nds_tpu.power import gen_sql_from_stream
        if SUPPORTED_QUERIES:
            # stream cache keyed by scale (predicate vocabularies band by
            # scale) and by the size of the supported-query ratchet
            qdir = os.path.join(
                REPO, ".bench_cache",
                f"stream_sf{SCALE}_n{len(SUPPORTED_QUERIES)}")
            os.makedirs(qdir, exist_ok=True)
            stream_file = os.path.join(qdir, "query_0.sql")
            if not os.path.exists(stream_file):
                generate_query_streams(qdir, streams=1, rngseed=0,
                                       templates=SUPPORTED_QUERIES,
                                       scale=float(SCALE))
            queries = gen_sql_from_stream(stream_file)
            if queries:
                return list(queries.items())
    except ImportError:
        pass
    return [("query3", """
            select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
                   sum(ss_ext_sales_price) sum_agg
            from date_dim dt, store_sales, item
            where dt.d_date_sk = store_sales.ss_sold_date_sk
              and store_sales.ss_item_sk = item.i_item_sk
              and item.i_manufact_id = 128
              and dt.d_moy = 11
            group by dt.d_year, item.i_brand_id, item.i_brand
            order by dt.d_year, sum_agg desc, brand_id
            limit 100
        """)]


def run_child(names, out_path):
    """Execute the named queries (warmup + timed) and dump {name: ms}."""
    data_dir = ensure_data()
    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    wanted = dict(bench_queries())
    sess = Session()
    for table, fields in get_schemas(use_decimal=True).items():
        path = os.path.join(data_dir, f"{table}.parquet")
        if os.path.exists(path):
            sess.read_columnar_view(
                table, path, "parquet",
                canonical_types={f.name: f.type for f in fields})

    times = {}
    for name in names:
        sql = wanted[name]
        tw = time.perf_counter()
        sess.sql(sql).collect()                      # warmup: compile
        t0 = time.perf_counter()
        res = sess.sql(sql)
        res.collect()
        times[name] = (time.perf_counter() - t0) * 1000.0
        print(f"# {name}: warm {t0 - tw:.1f}s timed {times[name]/1000:.2f}s",
              file=sys.stderr)
        # persist incrementally: a later wedge keeps earlier measurements
        json.dump(times, open(out_path, "w"))
    json.dump(times, open(out_path, "w"))


def _geomean(vals):
    return math.exp(sum(math.log(max(v, 1e-3)) for v in vals) / len(vals))


def resolve_baseline(baseline_file, times, n_total):
    """vs_baseline policy: the baseline stores each query's FIRST recorded
    time. Any run fills in queries the baseline lacks (so a partial run
    seeds, and an OOM-bound outlier joins whenever it first succeeds) but
    never overwrites an existing entry — the comparison stays longitudinal
    against the first measurement. vs_baseline is the geomean ratio over
    the common query set."""
    base = None
    if os.path.exists(baseline_file):
        try:
            base = json.load(open(baseline_file))
        except ValueError:
            base = None
    base_times = (base or {}).get("times") or {}
    common = sorted(set(times) & set(base_times))
    vs = (_geomean([base_times[q] for q in common]) /
          _geomean([times[q] for q in common])) if common else 1.0
    merged = dict(base_times)
    for q, t in times.items():
        merged.setdefault(q, t)
    if merged != base_times:
        json.dump({"metric": "power_geomean_ms",
                   "value": _geomean(list(merged.values())),
                   "n_queries": len(merged), "times": merged},
                  open(baseline_file, "w"))
    return vs


def _run_chunk(chunk, left, budget_s, times):
    out = tempfile.NamedTemporaryFile(suffix=".json", delete=False).name
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--queries", ",".join(chunk), "--out", out]
    # one wedged chunk must never eat the whole budget (larger chunks
    # would otherwise raise the per-chunk cap past it)
    timeout = min(left, PER_QUERY_TIMEOUT_S * len(chunk), budget_s / 2)
    try:
        subprocess.run(cmd, timeout=timeout, check=True)
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError) as e:
        print(f"# chunk {chunk} aborted: {type(e).__name__}",
              file=sys.stderr)
    try:
        times.update(json.load(open(out)))
    except (OSError, ValueError):
        pass
    os.unlink(out)


def run_parent():
    ensure_data()                                    # once, before children
    names = [n for n, _ in bench_queries()]
    budget_s = float(os.environ.get("NDS_BENCH_BUDGET_S", "3300"))
    t_start = time.perf_counter()
    times = {}
    pending = [names[i:i + CHUNK] for i in range(0, len(names), CHUNK)]
    for chunk in pending:
        left = budget_s - (time.perf_counter() - t_start)
        if left <= 0:
            break
        _run_chunk(chunk, left, budget_s, times)
    # retry queries an aborted chunk dragged down, one per child, so a
    # single wedged/crashing query costs only itself
    for name in [n for n in names if n not in times]:
        left = budget_s - (time.perf_counter() - t_start)
        if left <= 0:
            break
        _run_chunk([name], left, budget_s, times)

    if not times:
        print(json.dumps({"metric": "power_geomean_ms", "value": None,
                          "unit": "ms", "vs_baseline": 0.0, "n_queries": 0}))
        sys.exit(1)
    if len(times) < len(names):
        print(f"# measured {len(times)}/{len(names)} queries",
              file=sys.stderr)

    geomean = _geomean(list(times.values()))

    vs = resolve_baseline(os.path.join(REPO, ".bench_baseline.json"),
                          times, len(names))

    print(json.dumps({
        "metric": "power_geomean_ms",
        "value": round(geomean, 3),
        "unit": "ms",
        "vs_baseline": round(vs, 4),
        "n_queries": len(times),
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--queries")
    ap.add_argument("--out")
    args = ap.parse_args()
    if args.child:
        run_child(args.queries.split(","), args.out)
    else:
        run_parent()


if __name__ == "__main__":
    main()
