#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Driver benchmark: Power-Run geomean query time on the available chip.

Generates raw data with the native generator, registers the tables, runs the
supported TPC-DS query set through the engine (per-query warm-up pass for
compilation, then a timed pass — the reference's Power Run times a warmed
JVM the same way), and prints ONE JSON line:

    {"metric": "power_geomean_ms", "value": N, "unit": "ms", "vs_baseline": N}

Execution model: ONE persistent child process serves queries over a line
protocol (stdin: query name, stdout: one JSON result line). The parent
enforces a per-query deadline; a wedged device RPC or crash costs only that
query — the child is killed and restarted for the remainder (the tunnel to
the real chip has been observed to hang a blocked-in-C call indefinitely,
which in-process watchdogs cannot interrupt). A persistent child amortizes
the per-process costs (JAX init, 24-table load) that a chunk-per-process
model paid ~13 times over.

Deadline safety: the budget clock starts at process entry (not after data
generation), queries run cheapest-first (by baseline history) so a timeout
maximizes measured coverage, and the final JSON line is also emitted from a
SIGTERM/SIGINT handler so an external `timeout` kill still yields a parsed
result for whatever was measured.

Evidence ledger: when ``NDS_BENCH_RESULTS_JSONL`` names a file, every
measurement lands there as one validated, schema-versioned record
(nds_tpu/obs/ledger.py), flushed per query — the same file doubles as the
resume artifact. Per-query timeout budgets derive from the committed
BASELINE_TIMES.json walls x NDS_BENCH_BUDGET_HEADROOM (floor
NDS_BENCH_BUDGET_FLOOR_S, cap NDS_BENCH_QUERY_TIMEOUT_S), so ONE
pathological query gets marked ``timeout`` and the round completes instead
of dying at rc=124; a heartbeat thread (NDS_BENCH_HEARTBEAT_S) writes a
progress record + stderr line so a hung child is visible within seconds;
and finalize()/the signal handler write a terminal ``end`` record
(completed/aborted, queries done, wall) so every campaign artifact is
self-describing.

``vs_baseline`` compares against this framework's own first recorded
per-query times in the COMMITTED ``BASELINE_TIMES.json`` (cross-round
lineage, recomputable from git alone); the reference publishes no absolute
numbers (BASELINE.md).
"""

import argparse
import json
import math
import os
import queue
import signal
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SCALE = os.environ.get("NDS_BENCH_SCALE", "0.05")
CACHE = os.path.join(REPO, ".bench_cache", f"sf{SCALE}")
PQ_CACHE = os.path.join(REPO, ".bench_cache", f"sf{SCALE}_parquet")
NDSGEN = os.path.join(REPO, "native", "ndsgen", "ndsgen")
# generous per-query allowance: cold compiles on the chip run minutes.
# This is the CAP; per-query budgets derived from baseline history
# (derive_budgets) tighten it so one wedged query can't eat the round.
PER_QUERY_TIMEOUT_S = float(os.environ.get("NDS_BENCH_QUERY_TIMEOUT_S", "420"))
# child startup: JAX init + backend attach + 24-table device load
SETUP_TIMEOUT_S = float(os.environ.get("NDS_BENCH_SETUP_TIMEOUT_S", "300"))

# per-query result fields mirrored into the in-memory perf dict (PERF.md
# columns + evidence) — ONE list, shared by the live loop and the resume
# loader so a resumed campaign regenerates an identical PERF.md
PERF_KEYS = ("hostSyncs", "syncWaitMs", "scanBytes", "scanGBps", "warmS",
             "compileS", "streamedScans", "tracePhases", "evidence",
             "faultEvents")

def ledger_mod():
    """nds_tpu/obs/ledger.py imported BY FILE PATH (shared helper): the
    module is stdlib-only, and loading it this way keeps the parent
    process off the jax import (the package root pulls jax; the device
    attachment belongs to the serving child alone)."""
    from tools._ledger_load import ledger_mod as _lm
    return _lm()


def faults_mod():
    """The fault registry (engine/faults.py, stdlib-only), by file path
    via the ledger loader — the ``bench-child`` seam and the restart
    backoff policy live against it without touching jax."""
    return ledger_mod()._faults_mod()


def campaign_mod():
    """The campaign module (nds_tpu/obs/campaign.py, stdlib-only), by
    file path: the arm/env-fingerprint stamp every ledger record
    carries, and the resume-fingerprint refusal."""
    from tools._ledger_load import campaign_mod as _cm
    return _cm()


def metrics_mod():
    """The live-metrics registry (nds_tpu/obs/metrics.py, stdlib-only),
    by file path: rolling throughput for the heartbeat, per-query
    ``metrics`` ledger records, and the NDS_TPU_METRICS_FILE exporter
    the heartbeat drives — all without touching jax in the parent."""
    from tools._ledger_load import metrics_mod as _mm
    return _mm()


def restart_backoff_s(restart_n: int) -> float:
    """Deterministic-JITTERED backoff before child restart ``restart_n``
    (2nd start onwards): exponential base (NDS_BENCH_RESTART_BACKOFF_S,
    default 1.0) with a hash-derived jitter fraction so co-scheduled
    campaigns against one flaky backend don't restart in lockstep —
    deterministic per restart index, so tests and wall bounds hold. The
    2-strike setup circuit breaker still bounds the total: backoff
    spaces the retries the breaker allows, it never extends them."""
    try:
        base = float(os.environ.get("NDS_BENCH_RESTART_BACKOFF_S", "1.0"))
    except ValueError:
        base = 1.0
    if base <= 0 or restart_n <= 1:
        return 0.0
    raw = base * (2 ** min(restart_n - 2, 4))
    jitter = ((restart_n * 2654435761) % 1000) / 1000.0  # [0, 1)
    return min(raw * (1.0 + 0.5 * jitter), 30.0)


def drain_parent_faults(ledger):
    """Drain the PARENT-process fault ring into ledger progress notes:
    the ``bench-child`` seam records its degrade events in THIS process
    (the child is the thing that failed), so without a parent-side drain
    that evidence would die in the ring instead of reaching the
    campaign ledger. Returns the drained events either way."""
    F = faults_mod()
    events = F.drain_fault_events()
    if ledger is not None:
        for e in events:
            ledger.progress(note="fault-event", **F.fault_event_json(e))
    return events


def ensure_data():
    if not os.path.exists(NDSGEN):
        subprocess.run(["make", "-C", os.path.dirname(NDSGEN)], check=True,
                       capture_output=True)
    marker = os.path.join(CACHE, ".complete")
    if not os.path.exists(marker):
        os.makedirs(CACHE, exist_ok=True)
        subprocess.run([NDSGEN, "-scale", SCALE, "-dir", CACHE], check=True)
        with open(marker, "w"):
            pass
    # one-time transcode: children load parquet ~5x faster than raw CSV;
    # invalidated whenever the CSV cache is newer (regenerated data)
    pq_marker = os.path.join(PQ_CACHE, ".complete")
    stale = (os.path.exists(pq_marker) and
             os.path.getmtime(pq_marker) < os.path.getmtime(marker))
    if stale or not os.path.exists(pq_marker):
        import pyarrow.parquet as pq

        from nds_tpu.io import read_raw_table
        from nds_tpu.schema import get_schemas
        os.makedirs(PQ_CACHE, exist_ok=True)
        for table, fields in get_schemas(use_decimal=True).items():
            path = os.path.join(CACHE, f"{table}.dat")
            if os.path.exists(path):
                pq.write_table(read_raw_table(path, fields),
                               os.path.join(PQ_CACHE, f"{table}.parquet"))
        with open(pq_marker, "w"):
            pass
    return PQ_CACHE


def bench_queries():
    """Supported query set: generated stream when present, else builtin q3."""
    try:
        from nds_tpu.queries import generate_query_streams, SUPPORTED_QUERIES
        from nds_tpu.power import gen_sql_from_stream
        if SUPPORTED_QUERIES:
            # stream cache keyed by scale (predicate vocabularies band by
            # scale) and by the size of the supported-query ratchet
            qdir = os.path.join(
                REPO, ".bench_cache",
                f"stream_sf{SCALE}_n{len(SUPPORTED_QUERIES)}")
            os.makedirs(qdir, exist_ok=True)
            stream_file = os.path.join(qdir, "query_0.sql")
            if not os.path.exists(stream_file):
                generate_query_streams(qdir, streams=1, rngseed=0,
                                       templates=SUPPORTED_QUERIES,
                                       scale=float(SCALE))
            queries = gen_sql_from_stream(stream_file)
            if queries:
                return list(queries.items())
    except ImportError:
        pass
    return [("query3", """
            select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
                   sum(ss_ext_sales_price) sum_agg
            from date_dim dt, store_sales, item
            where dt.d_date_sk = store_sales.ss_sold_date_sk
              and store_sales.ss_item_sk = item.i_item_sk
              and item.i_manufact_id = 128
              and dt.d_moy = 11
            group by dt.d_year, item.i_brand_id, item.i_brand
            order by dt.d_year, sum_agg desc, brand_id
            limit 100
        """)]


def order_by_history(names, baseline_file):
    """Cheapest-first by baseline history; unmeasured queries go last.

    When the budget runs out mid-run this maximizes the number of measured
    queries, and pushes historically-absent outliers (e.g. an OOM-prone
    query) where their failure can't shadow cheap coverage."""
    try:
        with open(baseline_file) as f:
            hist = json.load(f).get("times") or {}
    except (OSError, ValueError):
        hist = {}
    known = sorted((n for n in names if n in hist), key=lambda n: hist[n])
    unknown = [n for n in names if n not in hist]
    return known + unknown


def derive_budgets(names, baseline_file, headroom=None, floor_s=None,
                   cap_s=None, scale=None):
    """Per-query timeout budgets (seconds) from the committed baseline
    walls x a headroom factor — the BENCH_r05 fix: rc=124 ate the whole
    round because the only deadline was the generous global cap, so one
    wedged query cost everything after it. A query with history gets
    ``baseline_ms/1000 x headroom`` clamped to [floor, cap]; the floor
    absorbs cold-compile time (up to ~35 s on the widest templates —
    warm baseline walls don't include it), the cap is the old global
    allowance. Queries with no history keep the cap: their first
    measurement must not be killed by a budget nobody derived.

    The committed baseline lineage is BENCH-SCALE history (SF 0.05): at
    any other ``scale`` the walls are incommensurable (SF10 runs
    minutes/query), so derivation is OFF — every query keeps the cap —
    unless the operator opted in by setting NDS_BENCH_BUDGET_HEADROOM
    (or passing ``headroom``) explicitly for that campaign."""
    explicit = (headroom is not None
                or "NDS_BENCH_BUDGET_HEADROOM" in os.environ)
    if headroom is None:
        headroom = float(os.environ.get("NDS_BENCH_BUDGET_HEADROOM", "25"))
    if floor_s is None:
        floor_s = float(os.environ.get("NDS_BENCH_BUDGET_FLOOR_S", "90"))
    if cap_s is None:
        cap_s = PER_QUERY_TIMEOUT_S
    if scale not in (None, "0.05") and not explicit:
        return {n: cap_s for n in names}
    try:
        with open(baseline_file) as f:
            hist = json.load(f).get("times") or {}
    except (OSError, ValueError):
        hist = {}
    return {n: min(max(hist[n] / 1e3 * headroom, floor_s), cap_s)
            if n in hist else cap_s for n in names}


def run_server():
    """Persistent child: load tables once, then serve query names from
    stdin, one JSON result line on stdout each."""
    data_dir = ensure_data()
    from nds_tpu.engine.session import Session
    from nds_tpu.schema import get_schemas

    wanted = dict(bench_queries())
    sess = Session()
    for table, fields in get_schemas(use_decimal=True).items():
        path = os.path.join(data_dir, f"{table}.parquet")
        if os.path.exists(path):
            sess.read_columnar_view(
                table, path, "parquet",
                canonical_types={f.name: f.type for f in fields})
    try:
        # provenance: the platform that actually executes, stamped into
        # PERF.md by the parent (BENCH_r05 ran 3000s against a chip that
        # never came up — the header must say what really ran, not assume)
        import jax as _jax
        platform = _jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    print(json.dumps({"ready": True, "platform": platform}), flush=True)

    from nds_tpu.engine import ops as _ops

    _ops.enable_compile_meter()
    for line in sys.stdin:
        name = line.strip()
        if not name:
            break
        try:
            sql = wanted[name]
            c0 = _ops.compile_ns()
            tw = time.perf_counter()
            sess.sql(sql).collect()                  # warmup: compile
            # hybrid replay ('auto'): a high-sync query transitions
            # eager -> record+compile -> first trace over its next sights;
            # fold those into warmup so the timed passes below measure
            # steady state (the reference times a warmed JVM the same way)
            for _ in range(3):
                if not sess.replay_pending(sql):
                    break
                sess.sql(sql).collect()
            # min of two timed passes: the tunnel to the chip shows multi-
            # second latency spikes (observed 2x swings on a fixed query);
            # min-of-2 reports steady-state device time, not tunnel weather
            t0 = time.perf_counter()
            sess.sql(sql).collect()
            t1 = time.perf_counter()
            # roofline decomposition measured on the final pass (sync
            # counts are deterministic per query; wait time is weather)
            from nds_tpu.listener import drain_stream_events
            from nds_tpu.obs import export as obs_export
            from nds_tpu.obs import trace as obs_trace
            drain_stream_events()        # count only the final pass's scans
            obs_trace.drain_spans()
            s0, w0 = _ops.sync_count(), _ops.sync_wait_ns()
            sess.sql(sql).collect()
            t2 = time.perf_counter()
            stream_events = drain_stream_events()
            trace_records = obs_trace.drain_spans()
            ms = min(t1 - t0, t2 - t1) * 1000.0
            syncs = _ops.sync_count() - s0
            sync_ms = (_ops.sync_wait_ns() - w0) / 1e6
            scan = sum(getattr(sess, "last_scanned", {}).values())
            gbps = scan / max(t2 - t1, 1e-9) / 1e9
            # measured compile split (jax monitoring): the warm pass's
            # XLA backend-compile seconds — ~0 on a persistent-cache hit
            compile_s = (_ops.compile_ns() - c0) / 1e9
            print(f"# {name}: warm {t0 - tw:.1f}s (compile "
                  f"{compile_s:.1f}s) timed {ms/1000:.2f}s "
                  f"syncs {syncs} syncWait {sync_ms:.0f}ms "
                  f"scan {gbps:.2f}GB/s",
                  file=sys.stderr)
            result = {
                "name": name, "ms": ms, "hostSyncs": syncs,
                "syncWaitMs": round(sync_ms, 1), "scanBytes": scan,
                "scanGBps": round(gbps, 3),
                # warm pass wall = XLA compile (+1 exec): the per-query
                # compile-cost axis the SF10 scaling question turns on
                "warmS": round(t0 - tw, 2),
                "compileS": round(compile_s, 2)}
            if stream_events:
                # >HBM streamed scans: which path served each (compiled
                # chunk pipeline vs eager chunk loop), chunk/sync counts
                # — the per-query face of the streamed sync budget —
                # plus the aggregated evidence dict the campaign ledger
                # records (computed HERE from the live events, so the
                # parent's ledger write need not re-derive it)
                from nds_tpu.listener import (stream_event_json,
                                              stream_evidence)
                result["streamedScans"] = [
                    stream_event_json(e) for e in stream_events]
                result["evidence"] = stream_evidence(stream_events)
            # fault-recovery evidence (engine/faults.py): every seam
            # recovery since the previous query — retries, degradation
            # ladder steps, watchdog timeouts — next to streamedScans,
            # so a fallback that fired in production is benchmark
            # evidence, not log noise
            from nds_tpu.engine.faults import (drain_fault_events,
                                               fault_event_json)
            fault_events = drain_fault_events()
            if fault_events:
                result["faultEvents"] = [fault_event_json(e)
                                         for e in fault_events]
            if trace_records:
                # per-phase attribution of the final timed pass (obs
                # layer; zero added syncs): plan vs drive vs materialize
                # per query, plus top sync-charging host-read sites
                roll = obs_export.rollup(trace_records)
                result["tracePhases"] = roll
                trace_d = os.environ.get("NDS_BENCH_TRACE_DIR")
                if trace_d:
                    os.makedirs(trace_d, exist_ok=True)
                    obs_export.write_chrome_trace(
                        os.path.join(trace_d, f"{name}.trace.json"),
                        trace_records, query=name, roll=roll)
            try:
                # per-query HBM footprint where the backend exposes
                # allocator stats (local chips; the tunneled attachment
                # returns None — recorded so the gap is visible, not
                # silent)
                import jax as _jax
                stats = _jax.devices()[0].memory_stats()
                if stats:
                    result["hbmBytesInUse"] = int(
                        stats.get("bytes_in_use", 0))
                    result["peakHbmBytes"] = int(
                        stats.get("peak_bytes_in_use", 0))
            except Exception as exc:
                # allocator stats are best-effort diagnostics, but their
                # absence must leave a trace, not vanish
                print(f"# memory_stats unavailable: {exc}",
                      file=sys.stderr)
            print(json.dumps(result), flush=True)
        except Exception as e:                        # keep serving
            print(json.dumps(error_result(name, e)), flush=True)


def error_result(name, exc):
    """The serving loop's one failure-path result line (child side,
    engine loaded): classified status plus THIS query's drained fault
    evidence — left in the thread ring, a failed query's events (incl.
    the watchdog's `timeout`) would misattribute to the NEXT query's
    drain on the success path."""
    from nds_tpu.engine.faults import (StatementTimeout,
                                       drain_fault_events,
                                       fault_event_json)
    out = {"name": name, "error": f"{type(exc).__name__}: {exc}"[:300]}
    fault_events = drain_fault_events()
    if fault_events:
        out["faultEvents"] = [fault_event_json(ev) for ev in fault_events]
    if isinstance(exc, StatementTimeout):
        # the statement watchdog fired: the parent marks the query
        # `timeout` (its classified status), not `error`
        out["timeout"] = True
    return out


def _geomean(vals):
    return math.exp(sum(math.log(max(v, 1e-3)) for v in vals) / len(vals))


def resolve_baseline(baseline_file, times, n_total):
    """vs_baseline policy: the baseline stores each query's FIRST recorded
    time. Any run fills in queries the baseline lacks (so a partial run
    seeds, and an OOM-bound outlier joins whenever it first succeeds) but
    never overwrites an existing entry — the comparison stays longitudinal
    against the first measurement. vs_baseline is the geomean ratio over
    the common query set.

    The baseline is a COMMITTED file (BASELINE_TIMES.json): losing it
    would silently restart the lineage and make vs_baseline compare a
    round against itself (this happened in round 3 when the scratch copy
    was reseeded). A missing file is therefore an explicit, loud event."""
    base = None
    if os.path.exists(baseline_file):
        try:
            with open(baseline_file) as f:
                base = json.load(f)
        except ValueError:
            base = None
    if base is None and not os.environ.get("NDS_BENCH_SEED_BASELINE"):
        print(f"# {os.path.basename(baseline_file)} missing or unreadable: "
              "REFUSING to start a new baseline lineage (restore it from "
              "git, or set NDS_BENCH_SEED_BASELINE=1 to seed one on "
              "purpose); vs_baseline reported as 0.0", file=sys.stderr)
        return 0.0
    base_times = (base or {}).get("times") or {}
    common = sorted(set(times) & set(base_times))
    vs = (_geomean([base_times[q] for q in common]) /
          _geomean([times[q] for q in common])) if common else 1.0
    merged = dict(base_times)
    for q, t in times.items():
        merged.setdefault(q, t)
    if merged != base_times:
        out = {"metric": "power_geomean_ms",
               "value": _geomean(list(merged.values())),
               "n_queries": len(merged), "times": merged}
        if isinstance(base, dict) and "note" in base:
            out["note"] = base["note"]
        with open(baseline_file, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    return vs


class ChildServer:
    """Supervises the persistent serving child with per-request deadlines."""

    def __init__(self):
        self.proc = None
        self.lines = None

    def _reader(self, proc, q):
        for line in proc.stdout:
            q.put(line)
        q.put(None)

    def start(self, deadline_left):
        self.stop()
        F = faults_mod()
        try:
            # bench-child seam (transient): an injected start fault
            # takes the same path as a real setup failure — the caller's
            # backoff + 2-strike circuit breaker own the recovery
            F.fault_point("bench-child")
        except F.FaultInjected as exc:
            F.record_fault_event("bench-child", "degrade",
                                 detail=str(exc)[:200])
            return None
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--serve"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        self.lines = queue.Queue()
        threading.Thread(target=self._reader,
                         args=(self.proc, self.lines), daemon=True).start()
        msg = self._next_json(min(SETUP_TIMEOUT_S, deadline_left))
        if not (msg and msg.get("ready")):
            # a slow-to-start child left alive would desync the protocol:
            # its late "ready" line would be consumed as a query response
            self.stop()
            return None
        return msg

    def _next_json(self, timeout):
        end = time.perf_counter() + timeout
        while True:
            left = end - time.perf_counter()
            if left <= 0:
                return None
            try:
                line = self.lines.get(timeout=left)
            except queue.Empty:
                return None
            if line is None:
                return None
            try:
                return json.loads(line)
            except ValueError:
                continue                              # stray non-JSON chatter

    def run_query(self, name, timeout):
        try:
            self.proc.stdin.write(name + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            return None
        return self._next_json(timeout)

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def stop(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        self.proc = None


def perf_text(times, perf, platform="unknown", scale=None):
    """Render the PERF.md roofline table as text — DETERMINISTIC in its
    inputs (sorted queries, no clocks), so the same ledger always
    regenerates the identical document (``tools/bench_compare.py
    --emit-perf`` makes PERF.md a derived artifact, never hand-edited)."""
    scale = SCALE if scale is None else scale
    rows = sorted(times)
    tot_sync = sum(p.get("syncWaitMs", 0) for p in perf.values())
    tot_ms = sum(times.values())
    streamed = [e for p in perf.values()
                for e in p.get("streamedScans", [])]
    out = ["# Power Run roofline decomposition", "",
           f"Scale factor {scale}; warm min-of-2 wall times; "
           f"platform: {platform}.",
           f"Aggregate: {len(times)} queries, "
           f"{tot_sync / max(tot_ms, 1e-9) * 100:.1f}% of summed wall "
           "time blocked on device->host reads."]
    if streamed:
        n_comp = sum(1 for e in streamed if e["path"] == "compiled")
        out.append(f"Streamed >HBM scans: {len(streamed)} "
                   f"({n_comp} compiled chunk pipeline, "
                   f"{len(streamed) - n_comp} eager fallback).")
    out.append("")
    out.append("| query | wall ms | warm s | compile s | host syncs | "
               "sync wait ms | scan MB | scan GB/s |")
    out.append("|---|---|---|---|---|---|---|---|")
    for q in rows:
        p = perf.get(q, {})
        out.append(f"| {q} | {times[q]:.0f} | {p.get('warmS', '-')} | "
                   f"{p.get('compileS', '-')} | "
                   f"{p.get('hostSyncs', '-')} | "
                   f"{p.get('syncWaitMs', '-')} | "
                   f"{p.get('scanBytes', 0) / 1e6:.1f} | "
                   f"{p.get('scanGBps', '-')} |")
    return "\n".join(out) + "\n"


def write_perf(times, perf, platform="unknown"):
    """PERF.md: the per-query roofline table (wall, host-sync count and
    blocked time, bytes scanned, effective bandwidth) the geomean headline
    decomposes into. Committed alongside BENCH_r{N}.json so 'is it fast?'
    is answerable from artifacts (device vs host split per query).
    ``platform`` is the serving child's ``jax.devices()[0].platform`` —
    real provenance, not an assumed "attached chip"."""
    if not perf:
        return
    with open(os.path.join(REPO, "PERF.md"), "w") as f:
        f.write(perf_text(times, perf, platform))


_emitted = False


def emit(times, n_total, aborted=None):
    """Print the one JSON metric line (idempotent; also the signal path).
    ``aborted`` labels a fail-fast partial artifact (circuit breaker) so a
    collector can tell "measured everything" from "gave up early"."""
    global _emitted
    if _emitted:
        return
    _emitted = True
    if not times:
        out = {"metric": "power_geomean_ms", "value": None,
               "unit": "ms", "vs_baseline": 0.0, "n_queries": 0}
        if aborted:
            out["aborted"] = aborted
        print(json.dumps(out))
        return
    geomean = _geomean(list(times.values()))
    try:
        vs = resolve_baseline(os.path.join(REPO, "BASELINE_TIMES.json"),
                              times, n_total)
    except Exception as exc:
        # the metric line must survive a baseline-write failure — this
        # path also runs from the SIGTERM handler of an externally
        # timed-out campaign, where losing the partial geomean repeats
        # BENCH_r05's {"value": null} artifact
        print(f"# baseline update failed: {exc}", file=sys.stderr)
        vs = 0.0
    out = {
        "metric": "power_geomean_ms",
        "value": round(geomean, 3),
        "unit": "ms",
        "vs_baseline": round(vs, 4),
        "n_queries": len(times),
    }
    if aborted:
        out["aborted"] = aborted
    print(json.dumps(out), flush=True)


def finalize(times, perf, n_total, platform="unknown", aborted=None,
             ledger=None, wall_s=None, end_reason=None):
    """Flush everything the campaign measured so far: the PERF.md
    roofline table, the one JSON metric line, and the ledger's terminal
    ``end`` record (``completed``/``aborted``, queries done, wall
    seconds) — the self-describing close every campaign artifact now
    carries. Runs at normal end AND from the SIGTERM/SIGINT handler, so
    an external ``timeout`` kill (rc=124) still records the partial
    geomean of every completed query instead of BENCH_r05's
    ``{"value": null, "n_queries": 0}``. Each step is isolated: a
    PERF.md write failure must not eat the metric line, and neither may
    eat the terminal record."""
    try:
        write_perf(times, perf, platform)
    except Exception as exc:
        print(f"# PERF.md write failed: {exc}", file=sys.stderr)
    emit(times, n_total, aborted)
    if ledger is not None:
        reason = end_reason or aborted
        status = "aborted" if reason else "completed"
        fields = {"queries": len(times), "total": n_total,
                  "platform": platform}
        if wall_s is not None:
            fields["wallS"] = round(wall_s, 1)
        if reason:
            fields["reason"] = reason
        try:
            ledger.close(status, **fields)
        except Exception as exc:
            print(f"# ledger terminal write failed: {exc}", file=sys.stderr)


def load_resume(path, times, perf):
    """Pre-populate times/perf from a previous campaign's ledger so an
    at-scale run (SF10: minutes/query) is resumable across invocations —
    measured queries are never re-paid (round-4 verdict: the first SF10
    campaign stopped at 30/103 and the partial work was lost). Ported
    onto the ledger loader: records are schema-validated (an
    unknown-version ledger refuses loudly instead of misreading), legacy
    pre-ledger resume lines still load, a torn final line from a kill is
    absorbed, and only status-``ok`` records resume — a ``timeout`` or
    ``error`` query is re-attempted, never trusted. Returns the platform
    the original campaign stamped (meta record), or None: a rerun
    satisfied entirely from the resume file starts no child and would
    otherwise overwrite PERF.md's real provenance with "unknown"."""
    if not path or not os.path.exists(path):
        return None
    data = ledger_mod().load_ledger(path)
    # mixed-arm refusal: a ledger stamped under different knobs must not
    # be resumed — the merged artifact would silently blend two
    # experiments (CampaignResumeError names both fingerprints)
    C = campaign_mod()
    C.check_resume_fingerprint(data.meta.get("envFingerprint"),
                               C.env_fingerprint(), path)
    if data.torn:
        print("# resume ledger: torn final line (in-flight statement of "
              "a kill) dropped", file=sys.stderr)
    for name, rec in data.queries.items():
        if rec["status"] == "ok" and "ms" in rec:
            times[name] = rec["ms"]
            perf[name] = {k: rec[k] for k in PERF_KEYS if k in rec}
    return data.platform


def run_parent(t_entry):
    budget_s = float(os.environ.get("NDS_BENCH_BUDGET_S", "3000"))
    # margin so the final JSON + baseline write always beat an external kill
    margin_s = 20.0
    times = {}
    perf = {}
    names = []
    child = ChildServer()
    resume_path = os.environ.get("NDS_BENCH_RESULTS_JSONL")
    resume_platform = load_resume(resume_path, times, perf)
    ledger = None
    if resume_path:
        # the stamp rides EVERY record (arm name + env fingerprint):
        # cross-arm merges key on recorded provenance, and load_resume's
        # fingerprint refusal has something to check on the next rerun
        ledger = ledger_mod().Ledger(resume_path, driver="bench",
                                     scale=SCALE,
                                     stamp=campaign_mod().campaign_stamp())
    # defined BEFORE the handlers register: a kill during data
    # generation must find every name the handler reads
    platform = resume_platform or "unknown"
    # heartbeat status snapshot, updated by the main loop and read by the
    # heartbeat thread (plain dict: GIL-atomic single-key writes)
    live = {"query": None, "done": len(times), "total": 0}
    # live-metrics registry (nds_tpu/obs/metrics.py): fed as results
    # arrive in THIS loop (the parent's existing evidence point), read
    # by the heartbeat for rolling queries/min + EWMA wall and exported
    # to NDS_TPU_METRICS_FILE on the heartbeat cadence
    metrics_reg = metrics_mod().default()
    metrics_reg.reset()

    def on_signal(signum, frame):
        # an external `timeout` kill lands here: flush the completed
        # per-query results (PERF.md + partial-geomean metric line +
        # terminal ledger record) before the -k SIGKILL grace runs out
        finalize(times, perf, len(names), platform, ledger=ledger,
                 wall_s=time.perf_counter() - t_entry, end_reason="signal")
        child.stop()          # free the device attachment before exiting
        os._exit(0)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    ensure_data()                                    # once, before the child
    names = [n for n, _ in bench_queries()]
    baseline_file = os.path.join(REPO, "BASELINE_TIMES.json")
    ordered = order_by_history(names, baseline_file)
    budgets = derive_budgets(names, baseline_file, scale=SCALE)
    restarts = 0

    def left():
        return budget_s - margin_s - (time.perf_counter() - t_entry)

    pending = [n for n in ordered if n not in times]
    live["total"] = len(names)
    if times:
        print(f"# resume: {len(times)} queries pre-loaded from "
              f"{os.path.basename(resume_path)}", file=sys.stderr)
    # liveness: a hung child is visible within seconds (progress record +
    # stderr line), not at the rc=124 autopsy; 0 disables
    hb_interval = float(os.environ.get("NDS_BENCH_HEARTBEAT_S", "15"))
    heartbeat = None
    if hb_interval > 0:
        # progress context plus the registry's rolling throughput
        # (queries/min, EWMA query wall) — the rolling numbers replace
        # the static counters as the liveness throughput signal in both
        # the ledger progress record and the stderr line
        heartbeat = ledger_mod().Heartbeat(
            hb_interval, ledger=ledger,
            status=lambda: {**{k: v for k, v in live.items()
                               if v is not None},
                            **metrics_reg.heartbeat_rollup()}).start()
    attempts = {}
    aborted = None
    setup_fails = 0
    try:
        while pending and left() > 0:
            if not child.alive():
                if restarts > 6:                      # crash-looping backend
                    break
                restarts += 1
                # jittered backoff BETWEEN restarts (2nd start onwards):
                # a crashing backend gets breathing room instead of an
                # immediate hammer, before the 2-strike breaker trips
                back = min(restart_backoff_s(restarts), max(left(), 0.0))
                if back > 0:
                    print(f"# child restart {restarts}: backing off "
                          f"{back:.1f}s", file=sys.stderr)
                    time.sleep(back)
                ready = child.start(left())
                # bench-child seam evidence (an injected or real start
                # fault) lands in the parent's own ring — ledger it now
                drain_parent_faults(ledger)
                if ready is None:
                    # circuit breaker: BENCH_r05 burned its whole 3000s
                    # budget on six consecutive 300s setup timeouts against
                    # a backend that never came up — after 2 in a row, stop
                    # paying and emit the labeled partial artifact instead
                    setup_fails += 1
                    if setup_fails >= 2:
                        aborted = "child-setup-failure"
                        print(f"# {setup_fails} consecutive child-setup "
                              "failures: backend is not coming up; "
                              "failing fast with a partial artifact",
                              file=sys.stderr)
                        break
                    continue                          # dead child -> retry
                setup_fails = 0
                new_plat = ready.get("platform", "unknown")
                if new_plat != "unknown" and new_plat != platform:
                    platform = new_plat
                    if ledger is not None:
                        # provenance meta record: lets a later rerun that
                        # never starts a child still stamp the real platform
                        ledger.meta(driver="bench", platform=platform)
            name = pending.pop(0)
            attempts[name] = attempts.get(name, 0) + 1
            live["query"] = name
            # per-query budget: baseline wall x headroom, so one
            # pathological query costs its budget, not the round (the
            # BENCH_r05 fix)
            per_q = budgets.get(name, PER_QUERY_TIMEOUT_S)
            deadline = min(per_q, left())
            msg = child.run_query(name, deadline)
            if msg is None:                           # wedged or crashed
                # the abort cause drives at-scale diagnosis: a dead child
                # is a crash (OOM, device fault — its exit code says
                # which); a live one blew a deadline — named truthfully:
                # its own derived budget, or the ROUND's remaining budget
                # (a healthy query killed by round exhaustion must not be
                # blamed on a per-query budget that never limited it)
                if child.alive():
                    status = "timeout"
                    limiter = "budget" if deadline >= per_q \
                        else "round-budget"
                    cause = f"timeout after {deadline:.0f}s ({limiter})"
                else:
                    status = "error"
                    cause = f"child crashed (exit {child.proc.returncode})"
                print(f"# {name} aborted ({cause}); restarting child",
                      file=sys.stderr)
                metrics_reg.inc("queries.total")
                metrics_reg.inc(f"queries.{status}")
                child.stop()
                if ledger is not None:
                    rec = {"error": cause, "budgetS": round(deadline, 1),
                           "attempt": attempts[name]}
                    if status == "timeout":
                        # machine-readable limiter: bench_compare must
                        # not count a round-budget kill as a query that
                        # "stopped completing" (it was never given its
                        # own budget)
                        rec["limiter"] = limiter
                    ledger.query(name, status=status, **rec)
                if attempts[name] < 2:                # one retry, at the end
                    pending.append(name)
                continue
            if "ms" in msg:
                times[msg["name"]] = msg["ms"]
                perf[msg["name"]] = {k: msg[k]
                                     for k in PERF_KEYS if k in msg}
                live["done"] = len(times)
                M = metrics_mod()
                metrics_reg.inc("queries.total")
                metrics_reg.inc("queries.ok")
                metrics_reg.observe(M.QUERY_WALL, msg["ms"])
                if msg.get("syncWaitMs"):
                    metrics_reg.observe(M.SYNC_WAIT, msg["syncWaitMs"])
                if msg.get("faultEvents"):
                    metrics_reg.inc("faults.total",
                                    len(msg["faultEvents"]))
                if ledger is not None:
                    ledger.query(msg["name"], status="ok",
                                 **{k: v for k, v in msg.items()
                                    if k != "name"})
                    # the rolling rollup as of this query: queries/min,
                    # rolling wall quantiles, EWMA — the per-query
                    # metrics record (same vocabulary as power.py's)
                    ledger.metrics(scope="query", query=msg["name"],
                                   **metrics_reg.query_rollup())
            else:
                print(f"# {name} failed: {msg.get('error')}",
                      file=sys.stderr)
                metrics_reg.inc("queries.total")
                metrics_reg.inc("queries.timeout" if msg.get("timeout")
                                else "queries.error")
                if ledger is not None:
                    # an in-process watchdog expiry (StatementTimeout)
                    # is a classified `timeout`, not an `error`: the
                    # statement was marked, the child kept serving
                    status = "timeout" if msg.get("timeout") else "error"
                    rec = {"error": str(msg.get("error"))[:300],
                           "attempt": attempts[name]}
                    if msg.get("faultEvents"):
                        rec["faultEvents"] = msg["faultEvents"]
                    ledger.query(name, status=status, **rec)
    finally:
        child.stop()
        if heartbeat is not None:
            heartbeat.stop()

    if times and len(times) < len(names):
        print(f"# measured {len(times)}/{len(names)} queries",
              file=sys.stderr)
    # a loop that exits with work pending and no abort label ran out of
    # budget (or crash-looped): the terminal record must say so
    end_reason = None if aborted else ("incomplete" if pending else None)
    finalize(times, perf, len(names), platform, aborted, ledger=ledger,
             wall_s=time.perf_counter() - t_entry, end_reason=end_reason)
    if not times:
        sys.exit(1)


def main():
    t_entry = time.perf_counter()
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true",
                    help="persistent child: serve queries over stdin/stdout")
    args = ap.parse_args()
    if args.serve:
        run_server()
    else:
        run_parent(t_entry)


if __name__ == "__main__":
    main()
