-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Delete function DF_CS: remove catalog sales (and their returns) sold inside
-- the [DATE1, DATE2] window (TPC-DS spec 5.3.11; ref: nds/data_maintenance/DF_CS.sql).
DELETE FROM catalog_returns
WHERE cr_order_number IN
  (SELECT DISTINCT cs_order_number
   FROM catalog_sales, date_dim
   WHERE cs_sold_date_sk = d_date_sk
     AND d_date BETWEEN 'DATE1' AND 'DATE2');
DELETE FROM catalog_sales
WHERE cs_sold_date_sk >= (SELECT min(d_date_sk) FROM date_dim
                          WHERE d_date BETWEEN 'DATE1' AND 'DATE2')
  AND cs_sold_date_sk <= (SELECT max(d_date_sk) FROM date_dim
                          WHERE d_date BETWEEN 'DATE1' AND 'DATE2');
