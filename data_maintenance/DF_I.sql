-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Delete function DF_I: remove inventory records inside the [DATE1, DATE2]
-- window (TPC-DS spec 5.3.11; ref: nds/data_maintenance/DF_I.sql).
DELETE FROM inventory
WHERE inv_date_sk >= (SELECT min(d_date_sk) FROM date_dim
                      WHERE d_date BETWEEN 'DATE1' AND 'DATE2')
  AND inv_date_sk <= (SELECT max(d_date_sk) FROM date_dim
                      WHERE d_date BETWEEN 'DATE1' AND 'DATE2');
