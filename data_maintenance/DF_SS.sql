-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Delete function DF_SS: remove store sales (and their returns) sold inside
-- the [DATE1, DATE2] window (TPC-DS spec 5.3.11; ref: nds/data_maintenance/DF_SS.sql).
DELETE FROM store_returns
WHERE sr_ticket_number IN
  (SELECT DISTINCT ss_ticket_number
   FROM store_sales, date_dim
   WHERE ss_sold_date_sk = d_date_sk
     AND d_date BETWEEN 'DATE1' AND 'DATE2');
DELETE FROM store_sales
WHERE ss_sold_date_sk >= (SELECT min(d_date_sk) FROM date_dim
                          WHERE d_date BETWEEN 'DATE1' AND 'DATE2')
  AND ss_sold_date_sk <= (SELECT max(d_date_sk) FROM date_dim
                          WHERE d_date BETWEEN 'DATE1' AND 'DATE2');
