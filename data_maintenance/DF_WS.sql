-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Delete function DF_WS: remove web sales (and their returns) sold inside
-- the [DATE1, DATE2] window (TPC-DS spec 5.3.11; ref: nds/data_maintenance/DF_WS.sql).
DELETE FROM web_returns
WHERE wr_order_number IN
  (SELECT DISTINCT ws_order_number
   FROM web_sales, date_dim
   WHERE ws_sold_date_sk = d_date_sk
     AND d_date BETWEEN 'DATE1' AND 'DATE2');
DELETE FROM web_sales
WHERE ws_sold_date_sk >= (SELECT min(d_date_sk) FROM date_dim
                          WHERE d_date BETWEEN 'DATE1' AND 'DATE2')
  AND ws_sold_date_sk <= (SELECT max(d_date_sk) FROM date_dim
                          WHERE d_date BETWEEN 'DATE1' AND 'DATE2');
