-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Refresh function LF_CR: build catalog_returns rows from the s_catalog_returns
-- refresh feed (TPC-DS spec 5.3; ref: nds/data_maintenance/LF_CR.sql).
CREATE TEMP VIEW refresh_cr AS
SELECT
  d_date_sk                                                        AS cr_returned_date_sk,
  t_time_sk                                                        AS cr_returned_time_sk,
  i_item_sk                                                        AS cr_item_sk,
  c1.c_customer_sk                                                 AS cr_refunded_customer_sk,
  c1.c_current_cdemo_sk                                            AS cr_refunded_cdemo_sk,
  c1.c_current_hdemo_sk                                            AS cr_refunded_hdemo_sk,
  c1.c_current_addr_sk                                             AS cr_refunded_addr_sk,
  c2.c_customer_sk                                                 AS cr_returning_customer_sk,
  c2.c_current_cdemo_sk                                            AS cr_returning_cdemo_sk,
  c2.c_current_hdemo_sk                                            AS cr_returning_hdemo_sk,
  c2.c_current_addr_sk                                             AS cr_returning_addr_sk,
  cc_call_center_sk                                                AS cr_call_center_sk,
  cp_catalog_page_sk                                               AS cr_catalog_page_sk,
  sm_ship_mode_sk                                                  AS cr_ship_mode_sk,
  w_warehouse_sk                                                   AS cr_warehouse_sk,
  r_reason_sk                                                      AS cr_reason_sk,
  cret_order_id                                                    AS cr_order_number,
  cret_return_qty                                                  AS cr_return_quantity,
  cret_return_amt                                                  AS cr_return_amount,
  cret_return_tax                                                  AS cr_return_tax,
  cret_return_amt + cret_return_tax                                AS cr_return_amt_inc_tax,
  cret_return_fee                                                  AS cr_fee,
  cret_return_ship_cost                                            AS cr_return_ship_cost,
  cret_refunded_cash                                               AS cr_refunded_cash,
  cret_reversed_charge                                             AS cr_reversed_charge,
  cret_merchant_credit                                             AS cr_store_credit,
  cret_return_amt + cret_return_tax + cret_return_fee
      - cret_refunded_cash - cret_reversed_charge
      - cret_merchant_credit                                       AS cr_net_loss
FROM s_catalog_returns
LEFT OUTER JOIN date_dim    ON (cast(cret_return_date AS date) = d_date)
LEFT OUTER JOIN time_dim    ON ((cast(substr(cret_return_time, 1, 2) AS integer) * 3600
                                 + cast(substr(cret_return_time, 4, 2) AS integer) * 60
                                 + cast(substr(cret_return_time, 7, 2) AS integer)) = t_time)
LEFT OUTER JOIN item        ON (cret_item_id = i_item_id)
LEFT OUTER JOIN customer c1 ON (cret_return_customer_id = c1.c_customer_id)
LEFT OUTER JOIN customer c2 ON (cret_refund_customer_id = c2.c_customer_id)
LEFT OUTER JOIN reason      ON (cret_reason_id = r_reason_id)
LEFT OUTER JOIN call_center ON (cret_call_center_id = cc_call_center_id)
LEFT OUTER JOIN catalog_page ON (cret_catalog_page_id = cp_catalog_page_id)
LEFT OUTER JOIN ship_mode   ON (cret_shipmode_id = sm_ship_mode_id)
LEFT OUTER JOIN warehouse   ON (cret_warehouse_id = w_warehouse_id)
WHERE i_rec_end_date IS NULL
  AND cc_rec_end_date IS NULL;
INSERT INTO catalog_returns (SELECT * FROM refresh_cr ORDER BY cr_returned_date_sk);
