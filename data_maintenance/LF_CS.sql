-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Refresh function LF_CS: build catalog_sales rows from the s_catalog_order /
-- s_catalog_order_lineitem refresh feed (TPC-DS spec 5.3; ref: nds/data_maintenance/LF_CS.sql).
CREATE TEMP VIEW refresh_cs AS
SELECT
  d1.d_date_sk                                                     AS cs_sold_date_sk,
  t_time_sk                                                        AS cs_sold_time_sk,
  d2.d_date_sk                                                     AS cs_ship_date_sk,
  c1.c_customer_sk                                                 AS cs_bill_customer_sk,
  c1.c_current_cdemo_sk                                            AS cs_bill_cdemo_sk,
  c1.c_current_hdemo_sk                                            AS cs_bill_hdemo_sk,
  c1.c_current_addr_sk                                             AS cs_bill_addr_sk,
  c2.c_customer_sk                                                 AS cs_ship_customer_sk,
  c2.c_current_cdemo_sk                                            AS cs_ship_cdemo_sk,
  c2.c_current_hdemo_sk                                            AS cs_ship_hdemo_sk,
  c2.c_current_addr_sk                                             AS cs_ship_addr_sk,
  cc_call_center_sk                                                AS cs_call_center_sk,
  cp_catalog_page_sk                                               AS cs_catalog_page_sk,
  sm_ship_mode_sk                                                  AS cs_ship_mode_sk,
  w_warehouse_sk                                                   AS cs_warehouse_sk,
  i_item_sk                                                        AS cs_item_sk,
  p_promo_sk                                                       AS cs_promo_sk,
  cord_order_id                                                    AS cs_order_number,
  clin_quantity                                                    AS cs_quantity,
  i_wholesale_cost                                                 AS cs_wholesale_cost,
  i_current_price                                                  AS cs_list_price,
  clin_sales_price                                                 AS cs_sales_price,
  (i_current_price - clin_sales_price) * clin_quantity             AS cs_ext_discount_amt,
  clin_sales_price * clin_quantity                                 AS cs_ext_sales_price,
  i_wholesale_cost * clin_quantity                                 AS cs_ext_wholesale_cost,
  i_current_price * clin_quantity                                  AS cs_ext_list_price,
  i_current_price * cc_tax_percentage                              AS cs_ext_tax,
  clin_coupon_amt                                                  AS cs_coupon_amt,
  clin_ship_cost * clin_quantity                                   AS cs_ext_ship_cost,
  (clin_sales_price * clin_quantity) - clin_coupon_amt             AS cs_net_paid,
  ((clin_sales_price * clin_quantity) - clin_coupon_amt)
      * (1 + cc_tax_percentage)                                    AS cs_net_paid_inc_tax,
  (clin_sales_price * clin_quantity) - clin_coupon_amt
      + (clin_ship_cost * clin_quantity)                           AS cs_net_paid_inc_ship,
  (clin_sales_price * clin_quantity) - clin_coupon_amt
      + (clin_ship_cost * clin_quantity)
      + i_current_price * cc_tax_percentage                        AS cs_net_paid_inc_ship_tax,
  ((clin_sales_price * clin_quantity) - clin_coupon_amt)
      - (clin_quantity * i_wholesale_cost)                         AS cs_net_profit
FROM s_catalog_order
JOIN s_catalog_order_lineitem ON (cord_order_id = clin_order_id)
LEFT OUTER JOIN date_dim d1    ON (cast(cord_order_date AS date) = d1.d_date)
LEFT OUTER JOIN time_dim       ON (cord_order_time = t_time)
LEFT OUTER JOIN customer c1    ON (cord_bill_customer_id = c1.c_customer_id)
LEFT OUTER JOIN customer c2    ON (cord_ship_customer_id = c2.c_customer_id)
LEFT OUTER JOIN call_center    ON (cord_call_center_id = cc_call_center_id AND cc_rec_end_date IS NULL)
LEFT OUTER JOIN ship_mode      ON (cord_ship_mode_id = sm_ship_mode_id)
LEFT OUTER JOIN date_dim d2    ON (cast(clin_ship_date AS date) = d2.d_date)
LEFT OUTER JOIN catalog_page   ON (clin_catalog_page_number = cp_catalog_page_number
                                   AND clin_catalog_number = cp_catalog_number)
LEFT OUTER JOIN warehouse      ON (clin_warehouse_id = w_warehouse_id)
LEFT OUTER JOIN item           ON (clin_item_id = i_item_id AND i_rec_end_date IS NULL)
LEFT OUTER JOIN promotion      ON (clin_promotion_id = p_promo_id);
INSERT INTO catalog_sales (SELECT * FROM refresh_cs ORDER BY cs_sold_date_sk);
