-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Refresh function LF_I: build inventory rows from the s_inventory refresh
-- feed (TPC-DS spec 5.3; ref: nds/data_maintenance/LF_I.sql).
CREATE TEMP VIEW refresh_inv AS
SELECT
  d_date_sk            AS inv_date_sk,
  i_item_sk            AS inv_item_sk,
  w_warehouse_sk       AS inv_warehouse_sk,
  invn_qty_on_hand     AS inv_quantity_on_hand
FROM s_inventory
LEFT OUTER JOIN warehouse ON (invn_warehouse_id = w_warehouse_id)
LEFT OUTER JOIN item      ON (invn_item_id = i_item_id AND i_rec_end_date IS NULL)
LEFT OUTER JOIN date_dim  ON (d_date = invn_date);
INSERT INTO inventory (SELECT * FROM refresh_inv ORDER BY inv_date_sk);
