-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Refresh function LF_SR: build store_returns rows from the s_store_returns
-- refresh feed (TPC-DS spec 5.3; ref: nds/data_maintenance/LF_SR.sql).
CREATE TEMP VIEW refresh_sr AS
SELECT
  d_date_sk                                                        AS sr_returned_date_sk,
  t_time_sk                                                        AS sr_return_time_sk,
  i_item_sk                                                        AS sr_item_sk,
  c_customer_sk                                                    AS sr_customer_sk,
  c_current_cdemo_sk                                               AS sr_cdemo_sk,
  c_current_hdemo_sk                                               AS sr_hdemo_sk,
  c_current_addr_sk                                                AS sr_addr_sk,
  s_store_sk                                                       AS sr_store_sk,
  r_reason_sk                                                      AS sr_reason_sk,
  sret_ticket_number                                               AS sr_ticket_number,
  sret_return_qty                                                  AS sr_return_quantity,
  sret_return_amt                                                  AS sr_return_amt,
  sret_return_tax                                                  AS sr_return_tax,
  sret_return_amt + sret_return_tax                                AS sr_return_amt_inc_tax,
  sret_return_fee                                                  AS sr_fee,
  sret_return_ship_cost                                            AS sr_return_ship_cost,
  sret_refunded_cash                                               AS sr_refunded_cash,
  sret_reversed_charge                                             AS sr_reversed_charge,
  sret_store_credit                                                AS sr_store_credit,
  sret_return_amt + sret_return_tax + sret_return_fee
      - sret_refunded_cash - sret_reversed_charge
      - sret_store_credit                                          AS sr_net_loss
FROM s_store_returns
LEFT OUTER JOIN date_dim ON (cast(sret_return_date AS date) = d_date)
LEFT OUTER JOIN time_dim ON ((cast(substr(sret_return_time, 1, 2) AS integer) * 3600
                              + cast(substr(sret_return_time, 4, 2) AS integer) * 60
                              + cast(substr(sret_return_time, 7, 2) AS integer)) = t_time)
LEFT OUTER JOIN item     ON (sret_item_id = i_item_id)
LEFT OUTER JOIN customer ON (sret_customer_id = c_customer_id)
LEFT OUTER JOIN store    ON (sret_store_id = s_store_id)
LEFT OUTER JOIN reason   ON (sret_reason_id = r_reason_id)
WHERE i_rec_end_date IS NULL
  AND s_rec_end_date IS NULL;
INSERT INTO store_returns (SELECT * FROM refresh_sr ORDER BY sr_returned_date_sk);
