-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Refresh function LF_SS: build store_sales rows from the s_purchase /
-- s_purchase_lineitem refresh feed (TPC-DS spec 5.3; ref: nds/data_maintenance/LF_SS.sql).
CREATE TEMP VIEW refresh_ss AS
SELECT
  d_date_sk                                                        AS ss_sold_date_sk,
  t_time_sk                                                        AS ss_sold_time_sk,
  i_item_sk                                                        AS ss_item_sk,
  c_customer_sk                                                    AS ss_customer_sk,
  c_current_cdemo_sk                                               AS ss_cdemo_sk,
  c_current_hdemo_sk                                               AS ss_hdemo_sk,
  c_current_addr_sk                                                AS ss_addr_sk,
  s_store_sk                                                       AS ss_store_sk,
  p_promo_sk                                                       AS ss_promo_sk,
  purc_purchase_id                                                 AS ss_ticket_number,
  plin_quantity                                                    AS ss_quantity,
  i_wholesale_cost                                                 AS ss_wholesale_cost,
  i_current_price                                                  AS ss_list_price,
  plin_sale_price                                                  AS ss_sales_price,
  (i_current_price - plin_sale_price) * plin_quantity              AS ss_ext_discount_amt,
  plin_sale_price * plin_quantity                                  AS ss_ext_sales_price,
  i_wholesale_cost * plin_quantity                                 AS ss_ext_wholesale_cost,
  i_current_price * plin_quantity                                  AS ss_ext_list_price,
  i_current_price * s_tax_precentage                               AS ss_ext_tax,
  plin_coupon_amt                                                  AS ss_coupon_amt,
  (plin_sale_price * plin_quantity) - plin_coupon_amt              AS ss_net_paid,
  ((plin_sale_price * plin_quantity) - plin_coupon_amt)
      * (1 + s_tax_precentage)                                     AS ss_net_paid_inc_tax,
  ((plin_sale_price * plin_quantity) - plin_coupon_amt)
      - (plin_quantity * i_wholesale_cost)                         AS ss_net_profit
FROM s_purchase
JOIN s_purchase_lineitem ON (purc_purchase_id = plin_purchase_id)
LEFT OUTER JOIN customer  ON (purc_customer_id = c_customer_id)
LEFT OUTER JOIN store     ON (purc_store_id = s_store_id)
LEFT OUTER JOIN date_dim  ON (cast(purc_purchase_date AS date) = d_date)
LEFT OUTER JOIN time_dim  ON (purc_purchase_time = t_time)
LEFT OUTER JOIN promotion ON (plin_promotion_id = p_promo_id)
LEFT OUTER JOIN item      ON (plin_item_id = i_item_id)
WHERE i_rec_end_date IS NULL
  AND s_rec_end_date IS NULL;
INSERT INTO store_sales (SELECT * FROM refresh_ss ORDER BY ss_sold_date_sk);
