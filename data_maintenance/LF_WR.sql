-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Refresh function LF_WR: build web_returns rows from the s_web_returns
-- refresh feed (TPC-DS spec 5.3; ref: nds/data_maintenance/LF_WR.sql).
CREATE TEMP VIEW refresh_wr AS
SELECT
  d_date_sk                                                        AS wr_returned_date_sk,
  t_time_sk                                                        AS wr_returned_time_sk,
  i_item_sk                                                        AS wr_item_sk,
  c1.c_customer_sk                                                 AS wr_refunded_customer_sk,
  c1.c_current_cdemo_sk                                            AS wr_refunded_cdemo_sk,
  c1.c_current_hdemo_sk                                            AS wr_refunded_hdemo_sk,
  c1.c_current_addr_sk                                             AS wr_refunded_addr_sk,
  c2.c_customer_sk                                                 AS wr_returning_customer_sk,
  c2.c_current_cdemo_sk                                            AS wr_returning_cdemo_sk,
  c2.c_current_hdemo_sk                                            AS wr_returning_hdemo_sk,
  c2.c_current_addr_sk                                             AS wr_returning_addr_sk,
  wp_web_page_sk                                                   AS wr_web_page_sk,
  r_reason_sk                                                      AS wr_reason_sk,
  wret_order_id                                                    AS wr_order_number,
  wret_return_qty                                                  AS wr_return_quantity,
  wret_return_amt                                                  AS wr_return_amt,
  wret_return_tax                                                  AS wr_return_tax,
  wret_return_amt + wret_return_tax                                AS wr_return_amt_inc_tax,
  wret_return_fee                                                  AS wr_fee,
  wret_return_ship_cost                                            AS wr_return_ship_cost,
  wret_refunded_cash                                               AS wr_refunded_cash,
  wret_reversed_charge                                             AS wr_reversed_charge,
  wret_account_credit                                              AS wr_account_credit,
  wret_return_amt + wret_return_tax + wret_return_fee
      - wret_refunded_cash - wret_reversed_charge
      - wret_account_credit                                        AS wr_net_loss
FROM s_web_returns
LEFT OUTER JOIN date_dim    ON (cast(wret_return_date AS date) = d_date)
LEFT OUTER JOIN time_dim    ON ((cast(substr(wret_return_time, 1, 2) AS integer) * 3600
                                 + cast(substr(wret_return_time, 4, 2) AS integer) * 60
                                 + cast(substr(wret_return_time, 7, 2) AS integer)) = t_time)
LEFT OUTER JOIN item        ON (wret_item_id = i_item_id)
LEFT OUTER JOIN customer c1 ON (wret_return_customer_id = c1.c_customer_id)
LEFT OUTER JOIN customer c2 ON (wret_refund_customer_id = c2.c_customer_id)
LEFT OUTER JOIN reason      ON (wret_reason_id = r_reason_id)
LEFT OUTER JOIN web_page    ON (wret_web_page_id = wp_web_page_id)
WHERE i_rec_end_date IS NULL
  AND wp_rec_end_date IS NULL;
INSERT INTO web_returns (SELECT * FROM refresh_wr ORDER BY wr_returned_date_sk);
