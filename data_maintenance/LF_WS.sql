-- Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
-- Refresh function LF_WS: build web_sales rows from the s_web_order /
-- s_web_order_lineitem refresh feed (TPC-DS spec 5.3; ref: nds/data_maintenance/LF_WS.sql).
CREATE TEMP VIEW refresh_ws AS
SELECT
  d1.d_date_sk                                                     AS ws_sold_date_sk,
  t_time_sk                                                        AS ws_sold_time_sk,
  d2.d_date_sk                                                     AS ws_ship_date_sk,
  i_item_sk                                                        AS ws_item_sk,
  c1.c_customer_sk                                                 AS ws_bill_customer_sk,
  c1.c_current_cdemo_sk                                            AS ws_bill_cdemo_sk,
  c1.c_current_hdemo_sk                                            AS ws_bill_hdemo_sk,
  c1.c_current_addr_sk                                             AS ws_bill_addr_sk,
  c2.c_customer_sk                                                 AS ws_ship_customer_sk,
  c2.c_current_cdemo_sk                                            AS ws_ship_cdemo_sk,
  c2.c_current_hdemo_sk                                            AS ws_ship_hdemo_sk,
  c2.c_current_addr_sk                                             AS ws_ship_addr_sk,
  wp_web_page_sk                                                   AS ws_web_page_sk,
  web_site_sk                                                      AS ws_web_site_sk,
  sm_ship_mode_sk                                                  AS ws_ship_mode_sk,
  w_warehouse_sk                                                   AS ws_warehouse_sk,
  p_promo_sk                                                       AS ws_promo_sk,
  word_order_id                                                    AS ws_order_number,
  wlin_quantity                                                    AS ws_quantity,
  i_wholesale_cost                                                 AS ws_wholesale_cost,
  i_current_price                                                  AS ws_list_price,
  wlin_sales_price                                                 AS ws_sales_price,
  (i_current_price - wlin_sales_price) * wlin_quantity             AS ws_ext_discount_amt,
  wlin_sales_price * wlin_quantity                                 AS ws_ext_sales_price,
  i_wholesale_cost * wlin_quantity                                 AS ws_ext_wholesale_cost,
  i_current_price * wlin_quantity                                  AS ws_ext_list_price,
  i_current_price * web_tax_percentage                             AS ws_ext_tax,
  wlin_coupon_amt                                                  AS ws_coupon_amt,
  wlin_ship_cost * wlin_quantity                                   AS ws_ext_ship_cost,
  (wlin_sales_price * wlin_quantity) - wlin_coupon_amt             AS ws_net_paid,
  ((wlin_sales_price * wlin_quantity) - wlin_coupon_amt)
      * (1 + web_tax_percentage)                                   AS ws_net_paid_inc_tax,
  ((wlin_sales_price * wlin_quantity) - wlin_coupon_amt)
      - (wlin_quantity * i_wholesale_cost)                         AS ws_net_paid_inc_ship,
  (wlin_sales_price * wlin_quantity) - wlin_coupon_amt
      + (wlin_ship_cost * wlin_quantity)
      + i_current_price * web_tax_percentage                       AS ws_net_paid_inc_ship_tax,
  ((wlin_sales_price * wlin_quantity) - wlin_coupon_amt)
      - (i_wholesale_cost * wlin_quantity)                         AS ws_net_profit
FROM s_web_order
JOIN s_web_order_lineitem ON (word_order_id = wlin_order_id)
LEFT OUTER JOIN date_dim d1 ON (cast(word_order_date AS date) = d1.d_date)
LEFT OUTER JOIN time_dim    ON (word_order_time = t_time)
LEFT OUTER JOIN customer c1 ON (word_bill_customer_id = c1.c_customer_id)
LEFT OUTER JOIN customer c2 ON (word_ship_customer_id = c2.c_customer_id)
LEFT OUTER JOIN web_site    ON (word_web_site_id = web_site_id AND web_rec_end_date IS NULL)
LEFT OUTER JOIN ship_mode   ON (word_ship_mode_id = sm_ship_mode_id)
LEFT OUTER JOIN date_dim d2 ON (cast(wlin_ship_date AS date) = d2.d_date)
LEFT OUTER JOIN item        ON (wlin_item_id = i_item_id AND i_rec_end_date IS NULL)
LEFT OUTER JOIN web_page    ON (wlin_web_page_id = wp_web_page_id AND wp_rec_end_date IS NULL)
LEFT OUTER JOIN warehouse   ON (wlin_warehouse_id = w_warehouse_id)
LEFT OUTER JOIN promotion   ON (wlin_promotion_id = p_promo_id);
INSERT INTO web_sales (SELECT * FROM refresh_ws ORDER BY ws_sold_date_sk);
