// Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
//
// ndsgen: native TPC-DS-style raw data generator for the nds-tpu framework.
//
// Plays the role dsdgen plays in the reference harness (driven per-chunk by
// nds_gen_data.py; ref: nds/nds_gen_data.py:183-244 and the MR wrapper
// nds/tpcds-gen/src/main/java/org/notmysock/tpcds/GenTable.java:188-209):
// emits '|'-delimited flat files per table with dsdgen-compatible naming
// (<table>_<child>_<parallel>.dat) and CLI flags (-scale/-parallel/-child/
// -table/-update/-rngseed/-dir).
//
// Design: every field of every row is a pure function of
// (rngseed, table, row, column) via splitmix64 mixing, so any chunk of any
// table can be generated independently with no cross-chunk or cross-table
// state. Returns re-derive their originating sale row's fields from the same
// hash stream, giving referential integrity (matching ticket/order numbers,
// item_sks and consistent amounts) without coordination. This is what makes
// distributed generation embarrassingly parallel across pod hosts.
//
// NOTE: this generator produces spec-shaped, query-meaningful data (real
// calendar, enumerated demographics, consistent pricing chains, SCD dims),
// not bit-identical dsdgen output. For bit-parity with reference data the
// harness honours $TPCDS_HOME and drives the patched TPC-DS C toolkit
// instead (see nds_tpu/check.py:check_build_ndsgen).

#include <cinttypes>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// Hashing / RNG: stateless splitmix64 over (seed, table, row, col)
// ---------------------------------------------------------------------------

static uint64_t g_seed = 19620718ULL;  // default rngseed

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

static inline uint64_t h4(uint64_t table, uint64_t row, uint64_t col) {
  uint64_t x = g_seed;
  x = splitmix64(x ^ (table * 0xA24BAED4963EE407ULL));
  x = splitmix64(x ^ (row * 0x9FB21C651E98DF25ULL));
  x = splitmix64(x ^ (col * 0xD6E8FEB86659FD93ULL));
  return x;
}

// uniform integer in [lo, hi] inclusive
static inline int64_t uni(uint64_t t, uint64_t r, uint64_t c, int64_t lo, int64_t hi) {
  return lo + (int64_t)(h4(t, r, c) % (uint64_t)(hi - lo + 1));
}

// null decision: true => emit NULL. pct in [0,100]
static inline bool isnull(uint64_t t, uint64_t r, uint64_t c, int pct) {
  return (int)(h4(t, r, c ^ 0x5A5A5A5AULL) % 100) < pct;
}

// ---------------------------------------------------------------------------
// Calendar (Howard Hinnant's civil-days algorithms, public domain technique)
// ---------------------------------------------------------------------------

static constexpr int64_t kJulianEpoch = 2440588;  // julian day of 1970-01-01
static constexpr int64_t kDateSkLo = 2415022;     // 1900-01-02, first d_date_sk
static constexpr int64_t kDateDimRows = 73049;    // through 2100-01-01
static constexpr int64_t kSalesDateLo = 2450816;  // 1998-01-02 (5y sales window)
static constexpr int64_t kSalesDateHi = 2452642;  // 2002-12-31

static int64_t days_from_civil(int y, int m, int d) {
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = (unsigned)(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + (int64_t)doe - 719468;
}

static void civil_from_days(int64_t z, int* yy, int* mm, int* dd) {
  z += 719468;
  const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
  const unsigned doe = (unsigned)(z - era * 146097);
  const unsigned yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
  const int64_t y = (int64_t)yoe + era * 400;
  const unsigned doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
  const unsigned mp = (5 * doy + 2) / 153;
  const unsigned d = doy - (153 * mp + 2) / 5 + 1;
  const unsigned m = mp + (mp < 10 ? 3 : -9);
  *yy = (int)(y + (m <= 2));
  *mm = (int)m;
  *dd = (int)d;
}

static inline void jday_to_civil(int64_t jday, int* y, int* m, int* d) {
  civil_from_days(jday - kJulianEpoch, y, m, d);
}

static inline int64_t civil_to_jday(int y, int m, int d) {
  return days_from_civil(y, m, d) + kJulianEpoch;
}

// 0 = Sunday ... 6 = Saturday
static inline int dow_of_jday(int64_t jday) {
  int64_t z = jday - kJulianEpoch;  // 1970-01-01 was a Thursday (4)
  return (int)(((z % 7) + 7 + 4) % 7);
}

// ---------------------------------------------------------------------------
// Row writer: buffered '|'-delimited output with trailing delimiter
// (dsdgen-compatible; readers strip the trailing empty field)
// ---------------------------------------------------------------------------

struct Row {
  FILE* f;
  explicit Row(FILE* file) : f(file) {}
  void raw(const char* s) { fputs(s, f); fputc('|', f); }
  void nul() { fputc('|', f); }
  void i(int64_t v, bool null = false) { if (null) { nul(); return; } fprintf(f, "%" PRId64 "|", v); }
  void i_or_null(int64_t v, bool null) { if (null) nul(); else i(v); }
  void dec(int64_t cents, bool null = false) {
    if (null) { nul(); return; }
    bool neg = cents < 0;
    if (neg) cents = -cents;
    fprintf(f, "%s%" PRId64 ".%02" PRId64 "|", neg ? "-" : "", cents / 100, cents % 100);
  }
  void s(const std::string& v, bool null = false) { if (null) nul(); else raw(v.c_str()); }
  void date(int64_t jday, bool null = false) {
    if (null) { nul(); return; }
    int y, m, d;
    jday_to_civil(jday, &y, &m, &d);
    fprintf(f, "%04d-%02d-%02d|", y, m, d);
  }
  void end() { fputc('\n', f); }
};

// 16-char business key: base-26 encoding of sk, 'A'-padded (dsdgen-style
// AAAA...X ids). Deterministic so s_* refresh tables can reference dims.
static std::string id16(int64_t sk) {
  char buf[17];
  memset(buf, 'A', 16);
  buf[16] = 0;
  uint64_t v = (uint64_t)sk;
  int pos = 15;
  while (v > 0 && pos >= 0) {
    buf[pos--] = (char)('A' + (v % 26));
    v /= 26;
  }
  return std::string(buf);
}

static std::string date_str(int64_t jday) {
  int y, m, d;
  jday_to_civil(jday, &y, &m, &d);
  char buf[16];
  snprintf(buf, sizeof buf, "%04d-%02d-%02d", y, m, d);
  return std::string(buf);
}

// ---------------------------------------------------------------------------
// Value pools
// ---------------------------------------------------------------------------

#define POOL(name, ...) static const char* name[] = {__VA_ARGS__}; \
  static const int name##_n = (int)(sizeof(name) / sizeof(name[0]))

POOL(kStreetNames, "Main", "Oak", "Park", "Elm", "First", "Second", "Cedar", "Pine", "Maple",
     "Lake", "Hill", "Walnut", "Spring", "North", "Ridge", "Church", "Willow", "Mill", "Sunset",
     "Railroad", "Jackson", "River", "Highland", "Johnson", "Dogwood", "Chestnut", "Spruce",
     "Wilson", "Meadow", "Forest", "Broadway", "Franklin", "Smith", "College", "Washington");
POOL(kStreetTypes, "Street", "Ave", "Blvd", "Road", "Lane", "Court", "Drive", "Circle",
     "Parkway", "Way", "Pkwy", "Ct", "Dr", "Ln", "RD", "ST", "Boulevard", "Wy", "Cir", "Avenue");
POOL(kCities, "Midway", "Fairview", "Oak Grove", "Five Points", "Oakland", "Riverside",
     "Salem", "Georgetown", "Franklin", "New Hope", "Bunker Hill", "Hopewell", "Antioch",
     "Concord", "Clifton", "Marion", "Springfield", "Greenville", "Bridgeport", "Oakdale",
     "Glendale", "Lakeview", "Centerville", "Mount Olive", "Union", "Glenwood", "Pleasant Hill",
     "Liberty", "Sulphur Springs", "Pine Grove", "Waterloo", "Edgewood", "Friendship",
     "Greenwood", "Deerfield", "Shiloh", "Mountain View", "Lakewood", "Summit", "Plainview",
     "Pleasant Valley", "Woodville", "White Oak", "Oakwood", "Harmony", "Highland Park",
     "Kingston", "Red Hill", "Enterprise", "Arlington", "Lebanon", "Clinton", "Spring Hill",
     "Buena Vista", "Newport", "Florence", "Jamestown", "Ashland", "Wildwood", "Macedonia");
POOL(kCounties, "Williamson County", "Walker County", "Ziebach County", "Daviess County",
     "Barrow County", "Franklin Parish", "Luce County", "Richland County", "Furnas County",
     "Maverick County", "Huron County", "Kittitas County", "Mobile County", "Fairfield County",
     "Jackson County", "Dauphin County", "San Miguel County", "Pennington County",
     "Bronx County", "Orange County", "Perry County", "Halifax County", "Dona Ana County",
     "Gogebic County", "Lea County", "Mesa County", "Wadena County", "Pipestone County");
POOL(kStates, "AL", "AK", "AZ", "AR", "CA", "CO", "CT", "DE", "FL", "GA", "HI", "ID", "IL",
     "IN", "IA", "KS", "KY", "LA", "ME", "MD", "MA", "MI", "MN", "MS", "MO", "MT", "NE", "NV",
     "NH", "NJ", "NM", "NY", "NC", "ND", "OH", "OK", "OR", "PA", "RI", "SC", "SD", "TN", "TX",
     "UT", "VT", "VA", "WA", "WV", "WI", "WY");
POOL(kCountries, "United States");
POOL(kLocTypes, "apartment", "condo", "single family");
POOL(kEducation, "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
     "Advanced Degree", "Unknown");
POOL(kMarital, "M", "S", "D", "W", "U");
POOL(kCredit, "Low Risk", "Good", "High Risk", "Unknown");
POOL(kBuyPotential, ">10000", "5001-10000", "1001-5000", "501-1000", "0-500", "Unknown");
POOL(kDayNames, "Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday");
POOL(kShipTypes, "EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY");
POOL(kShipCodes, "AIR", "SURFACE", "SEA", "MSC");
POOL(kCarriers, "UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU", "ZOUROS", "MSC",
     "LATVIAN", "ALLIANCE", "GREAT EASTERN", "DIAMOND", "RUPEKSA", "ORIENTAL", "BOXBUNDLES",
     "GERMA", "HARMSTORF", "PRIVATECARRIER", "STERLING");
POOL(kReasons, "Package was damaged", "Stopped working", "Did not get it on time",
     "Not the product that was ordred", "Parts missing", "Does not work with a product that "
     "I have", "Gift exchange", "Did not like the color", "Did not like the model",
     "Did not like the make", "Did not like the warranty", "No service location in my area",
     "Found a better price in a store", "Found a better extended warranty in a store",
     "Did not fit", "Wrong size", "Lost my job", "unauthoized purchase", "duplicate purchase",
     "its is a boy", "its is a girl", "i do not like it", "reason 23", "reason 24",
     "reason 25", "reason 26", "reason 27", "reason 28", "reason 29", "reason 30",
     "reason 31", "reason 32", "reason 33", "reason 34", "reason 35");
POOL(kCategories, "Women", "Men", "Children", "Sports", "Music", "Books", "Home",
     "Electronics", "Jewelry", "Shoes");
POOL(kClasses, "accessories", "fragrances", "dresses", "pants", "swimwear", "maternity",
     "shirts", "sports-apparel", "infants", "toddlers", "school-uniforms", "athletic",
     "baseball", "basketball", "camping", "fishing", "football", "golf", "hockey", "optics",
     "pools", "sailing", "tennis", "classical", "country", "pop", "rock", "arts", "business",
     "computers", "cooking", "entertainments", "fiction", "history", "home repair", "mystery",
     "parenting", "reference", "romance", "science", "self-help", "sports", "travel",
     "bathroom", "bedding", "blinds/shades", "curtains/drapes", "decor", "flatware",
     "furniture", "glassware", "kids", "lighting", "mattresses", "paint", "rugs", "tables",
     "wallpaper", "audio", "automotive", "cameras", "camcorders", "dvd/vcr players",
     "karoke", "memory", "monitors", "musical", "personal", "portable", "scanners",
     "stereo", "televisions", "wireless", "birdal", "costume", "diamonds", "earings",
     "estate", "gold", "jewelry boxes", "loose stones", "mens watch", "pendants", "rings",
     "semi-precious", "womens watch", "athletic shoes", "kids shoes", "mens shoes", "womens");
POOL(kColors, "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
     "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
     "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan", "dark",
     "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest", "frosted",
     "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew", "hot", "indian",
     "ivory", "khaki", "lace", "lavender", "lawn", "lemon", "light", "lime", "linen",
     "magenta", "maroon", "medium", "metallic", "midnight", "mint", "misty", "moccasin",
     "navajo", "navy", "olive", "orange", "orchid", "pale", "papaya", "peach", "peru",
     "pink", "plum", "powder", "puff", "purple", "red", "rose", "rosy", "royal", "saddle",
     "salmon", "sandy", "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
     "steel", "tan", "thistle", "tomato", "turquoise", "violet", "wheat", "white", "yellow");
POOL(kUnits, "Each", "Dozen", "Case", "Pallet", "Gross", "Box", "Bundle", "Tsp", "Oz",
     "Lb", "Ton", "Dram", "Cup", "Gram", "Pound", "Ounce", "Unknown", "Carton", "Bunch", "N/A");
POOL(kSizes, "small", "medium", "large", "extra large", "economy", "N/A", "petite");
POOL(kHours, "8AM-8AM", "8AM-4PM", "8AM-12AM");
POOL(kFirstNames, "James", "Mary", "John", "Patricia", "Robert", "Jennifer", "Michael",
     "Linda", "William", "Elizabeth", "David", "Barbara", "Richard", "Susan", "Joseph",
     "Jessica", "Thomas", "Sarah", "Charles", "Karen", "Christopher", "Nancy", "Daniel",
     "Lisa", "Matthew", "Margaret", "Anthony", "Betty", "Donald", "Sandra", "Mark",
     "Ashley", "Paul", "Dorothy", "Steven", "Kimberly", "Andrew", "Emily", "Kenneth",
     "Donna", "Joshua", "Michelle", "George", "Carol", "Kevin", "Amanda", "Brian",
     "Melissa", "Edward", "Deborah", "Ronald", "Stephanie", "Timothy", "Rebecca", "Jason",
     "Laura", "Jeffrey", "Sharon", "Ryan", "Cynthia", "Jacob", "Kathleen", "Gary", "Amy",
     "Nicholas", "Shirley", "Eric", "Angela", "Jonathan", "Helen", "Stephen", "Anna",
     "Larry", "Brenda", "Justin", "Pamela", "Scott", "Nicole", "Brandon", "Ruth");
POOL(kLastNames, "Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia", "Miller",
     "Davis", "Rodriguez", "Martinez", "Hernandez", "Lopez", "Gonzalez", "Wilson",
     "Anderson", "Thomas", "Taylor", "Moore", "Jackson", "Martin", "Lee", "Perez",
     "Thompson", "White", "Harris", "Sanchez", "Clark", "Ramirez", "Lewis", "Robinson",
     "Walker", "Young", "Allen", "King", "Wright", "Scott", "Torres", "Nguyen", "Hill",
     "Flores", "Green", "Adams", "Nelson", "Baker", "Hall", "Rivera", "Campbell",
     "Mitchell", "Carter", "Roberts", "Gomez", "Phillips", "Evans", "Turner", "Diaz");
POOL(kSalutationsM, "Mr.", "Dr.", "Sir");
POOL(kSalutationsF, "Mrs.", "Ms.", "Miss", "Dr.");
POOL(kBirthCountries, "UNITED STATES", "CANADA", "MEXICO", "BRAZIL", "GERMANY", "FRANCE",
     "UNITED KINGDOM", "ITALY", "SPAIN", "JAPAN", "CHINA", "INDIA", "AUSTRALIA", "RUSSIA",
     "NETHERLANDS", "GREECE", "TURKEY", "EGYPT", "NIGERIA", "KENYA", "ARGENTINA", "CHILE",
     "PERU", "COLOMBIA", "VENEZUELA", "PORTUGAL", "SWEDEN", "NORWAY", "FINLAND", "DENMARK",
     "POLAND", "HUNGARY", "ROMANIA", "BULGARIA", "THAILAND", "VIETNAM", "PHILIPPINES",
     "INDONESIA", "MALAYSIA", "SINGAPORE", "NEW ZEALAND", "SOUTH AFRICA", "MOROCCO",
     "ALGERIA", "TUNISIA", "ISRAEL", "JORDAN", "IRAQ", "PAKISTAN", "BANGLADESH");
POOL(kWords, "bar", "ought", "able", "pri", "pres", "ese", "anti", "cally", "ation", "eing",
     "ideas", "things", "systems", "results", "members", "children", "questions", "services",
     "countries", "problems", "hands", "parts", "groups", "cases", "women", "interests",
     "companies", "times", "levels", "areas", "markets", "activities", "conditions", "eyes",
     "sales", "figures", "others", "certain", "national", "different", "important", "local",
     "major", "available", "special", "particular", "general", "significant", "recent",
     "natural", "individual", "various", "central", "similar", "necessary", "actual");
POOL(kPromoNames, "ought", "able", "pri", "pres", "ese", "anti", "cally", "ation", "eing",
     "bar");
POOL(kMealTimes, "breakfast", "lunch", "dinner");
POOL(kShifts, "first", "second", "third");
POOL(kSubShifts, "morning", "afternoon", "evening", "night");
POOL(kDepartments, "DEPARTMENT");
POOL(kCatalogTypes, "monthly", "quarterly", "bi-annual");
POOL(kWebTypes, "welcome", "protected", "dynamic", "feedback", "general", "ad", "order");
POOL(kDivNames, "ought", "able", "pri", "pres", "ese", "anti", "cally", "ation", "eing",
     "bar", "ought able", "pri ese");
POOL(kMktClasses, "A bit narrow forces matter.", "Architects survive to a ways.",
     "Political viewers develop for a styles.", "Domestic rates must not lead very.",
     "Large levels show home, final thin", "Significant members might call.",
     "Previous counties ought to approve.", "Alive situations strike o",
     "Tall sources use quite wrong directors.", "New players sell most n");

static const char* pick(const char** pool, int n, uint64_t t, uint64_t r, uint64_t c) {
  return pool[h4(t, r, c) % (uint64_t)n];
}
#define PK(pool, t, r, c) pick(pool, pool##_n, t, r, c)

// Scale-banded state vocabulary, shared with the query sampler
// (nds_tpu/queries/__init__.py active_states — keep the bands in sync).
// The TPC-DS toolkit's fips_county distribution plays the same role: at
// small scales both dsdgen rows and dsqgen substitutions draw from the same
// reduced state set, so state-predicate queries stay non-degenerate.
static int states_active(double sf) {
  if (sf < 1.0) return 8;
  if (sf < 100.0) return 16;
  if (sf < 1000.0) return 32;
  return 50;
}

// same banding idea for the other geographic vocabularies (city/county);
// capped by each pool's size
static int geo_active(double sf, int pool_n) {
  return std::min(pool_n, states_active(sf));
}

// word-salad sentence for descriptions/comments
static std::string sentence(uint64_t t, uint64_t r, uint64_t c, int maxwords) {
  int n = 3 + (int)(h4(t, r, c ^ 0x77ULL) % (uint64_t)(maxwords - 2));
  std::string out;
  for (int i = 0; i < n; i++) {
    if (i) out += ' ';
    out += kWords[h4(t, r, c + 100 + i) % (uint64_t)kWords_n];
  }
  return out;
}

// ---------------------------------------------------------------------------
// Table ids + row-count scaling
// ---------------------------------------------------------------------------

enum TableId {
  T_CUSTOMER_ADDRESS, T_CUSTOMER_DEMOGRAPHICS, T_DATE_DIM, T_WAREHOUSE, T_SHIP_MODE,
  T_TIME_DIM, T_REASON, T_INCOME_BAND, T_ITEM, T_STORE, T_CALL_CENTER, T_CUSTOMER,
  T_WEB_SITE, T_STORE_RETURNS, T_HOUSEHOLD_DEMOGRAPHICS, T_WEB_PAGE, T_PROMOTION,
  T_CATALOG_PAGE, T_INVENTORY, T_CATALOG_RETURNS, T_WEB_RETURNS, T_WEB_SALES,
  T_CATALOG_SALES, T_STORE_SALES,
  // refresh (-update) tables
  T_S_PURCHASE, T_S_PURCHASE_LINEITEM, T_S_CATALOG_ORDER, T_S_CATALOG_ORDER_LINEITEM,
  T_S_WEB_ORDER, T_S_WEB_ORDER_LINEITEM, T_S_STORE_RETURNS, T_S_CATALOG_RETURNS,
  T_S_WEB_RETURNS, T_S_INVENTORY, T_DELETE, T_INVENTORY_DELETE,
  T_MAX
};

static const char* kTableNames[T_MAX] = {
  "customer_address", "customer_demographics", "date_dim", "warehouse", "ship_mode",
  "time_dim", "reason", "income_band", "item", "store", "call_center", "customer",
  "web_site", "store_returns", "household_demographics", "web_page", "promotion",
  "catalog_page", "inventory", "catalog_returns", "web_returns", "web_sales",
  "catalog_sales", "store_sales",
  "s_purchase", "s_purchase_lineitem", "s_catalog_order", "s_catalog_order_lineitem",
  "s_web_order", "s_web_order_lineitem", "s_store_returns", "s_catalog_returns",
  "s_web_returns", "s_inventory", "delete", "inventory_delete",
};

// Geometric interpolation over log10(scale) between anchor points at
// SF {1, 10, 100, 1000, 3000, 10000} — mirrors dsdgen's sublinear dimension
// scaling without reimplementing its internal tables.
static int64_t interp_rows(double sf, const double* anchors) {
  static const double pts[6] = {1, 10, 100, 1000, 3000, 10000};
  if (sf <= 1.0) return (int64_t)std::llround(anchors[0]);
  if (sf >= 10000) return (int64_t)anchors[5];
  int i = 0;
  while (i < 5 && sf > pts[i + 1]) i++;
  double t = (std::log10(sf) - std::log10(pts[i])) / (std::log10(pts[i + 1]) - std::log10(pts[i]));
  double v = anchors[i] * std::pow(anchors[i + 1] / anchors[i], t);
  return (int64_t)std::llround(v);
}

struct Scaling {
  double sf;
  int64_t rows[T_MAX];
  int64_t customers, addresses, items, stores, call_centers, web_sites, warehouses,
      web_pages, promotions, catalog_pages, reasons;
  int64_t ss_tickets, cs_orders, ws_orders;

  explicit Scaling(double sf_) : sf(sf_) {
    static const double aCust[6]   = {100e3, 500e3, 2e6, 12e6, 30e6, 65e6};
    static const double aItem[6]   = {18e3, 102e3, 204e3, 300e3, 360e3, 402e3};
    static const double aStore[6]  = {12, 102, 402, 1002, 1350, 1500};
    static const double aCC[6]     = {6, 12, 24, 30, 36, 42};
    static const double aWebSite[6]= {30, 36, 42, 48, 54, 60};
    static const double aWh[6]     = {5, 10, 15, 20, 22, 25};
    static const double aWebPage[6]= {60, 200, 2040, 3000, 3600, 4002};
    static const double aPromo[6]  = {300, 350, 1000, 1500, 1800, 2000};
    static const double aCatPage[6]= {11718, 12000, 20400, 30000, 36000, 40000};
    static const double aReason[6] = {35, 45, 55, 65, 67, 70};

    customers = std::max<int64_t>(1000, interp_rows(sf, aCust));
    if (sf < 1.0) customers = std::max<int64_t>(1000, (int64_t)(100e3 * sf));
    addresses = customers / 2;
    items = std::max<int64_t>(1000, sf < 1.0 ? (int64_t)(18e3 * (0.25 + 0.75 * sf)) : interp_rows(sf, aItem));
    stores = interp_rows(sf, aStore);
    call_centers = interp_rows(sf, aCC);
    web_sites = interp_rows(sf, aWebSite);
    warehouses = interp_rows(sf, aWh);
    web_pages = interp_rows(sf, aWebPage);
    promotions = interp_rows(sf, aPromo);
    catalog_pages = interp_rows(sf, aCatPage);
    reasons = interp_rows(sf, aReason);

    // facts: linear in SF; tickets/orders carry fixed line counts so that
    // per-row fields derive from (ticket, line) with no cross-row state
    ss_tickets = std::max<int64_t>(100, (int64_t)(240034.0 * sf));
    cs_orders  = std::max<int64_t>(100, (int64_t)(144155.0 * sf));
    ws_orders  = std::max<int64_t>(100, (int64_t)(59949.0 * sf));

    for (int i = 0; i < T_MAX; i++) rows[i] = 0;
    rows[T_CUSTOMER_ADDRESS] = addresses;
    rows[T_CUSTOMER_DEMOGRAPHICS] = 1920800;  // full enumeration, scale-invariant
    rows[T_DATE_DIM] = kDateDimRows;
    rows[T_WAREHOUSE] = warehouses;
    rows[T_SHIP_MODE] = 20;
    rows[T_TIME_DIM] = 86400;
    rows[T_REASON] = reasons;
    rows[T_INCOME_BAND] = 20;
    rows[T_ITEM] = items;
    rows[T_STORE] = stores;
    rows[T_CALL_CENTER] = call_centers;
    rows[T_CUSTOMER] = customers;
    rows[T_WEB_SITE] = web_sites;
    rows[T_HOUSEHOLD_DEMOGRAPHICS] = 7200;  // 20*6*10*6 enumeration
    rows[T_WEB_PAGE] = web_pages;
    rows[T_PROMOTION] = promotions;
    rows[T_CATALOG_PAGE] = catalog_pages;
    rows[T_STORE_SALES] = ss_tickets * 12;
    rows[T_CATALOG_SALES] = cs_orders * 10;
    rows[T_WEB_SALES] = ws_orders * 12;
    rows[T_STORE_RETURNS] = rows[T_STORE_SALES] / 10;
    rows[T_CATALOG_RETURNS] = rows[T_CATALOG_SALES] / 10;
    rows[T_WEB_RETURNS] = rows[T_WEB_SALES] / 10;
    // weekly inventory snapshots over the 5-year sales window; sub-SF1 test
    // scales shrink the window so inventory stays proportionate
    int64_t inv_weeks = sf >= 1.0 ? 261 : std::max<int64_t>(13, (int64_t)(261 * sf * 10));
    rows[T_INVENTORY] = inv_weeks * warehouses * items;
    // refresh set: ~0.1% of the base facts per update
    rows[T_S_PURCHASE] = std::max<int64_t>(10, ss_tickets / 1000);
    rows[T_S_PURCHASE_LINEITEM] = rows[T_S_PURCHASE] * 12;
    rows[T_S_CATALOG_ORDER] = std::max<int64_t>(10, cs_orders / 1000);
    rows[T_S_CATALOG_ORDER_LINEITEM] = rows[T_S_CATALOG_ORDER] * 10;
    rows[T_S_WEB_ORDER] = std::max<int64_t>(10, ws_orders / 1000);
    rows[T_S_WEB_ORDER_LINEITEM] = rows[T_S_WEB_ORDER] * 12;
    rows[T_S_STORE_RETURNS] = std::max<int64_t>(10, rows[T_STORE_RETURNS] / 1000);
    rows[T_S_CATALOG_RETURNS] = std::max<int64_t>(10, rows[T_CATALOG_RETURNS] / 1000);
    rows[T_S_WEB_RETURNS] = std::max<int64_t>(10, rows[T_WEB_RETURNS] / 1000);
    rows[T_S_INVENTORY] = warehouses * std::max<int64_t>(100, items / 100);
    rows[T_DELETE] = 1;
    rows[T_INVENTORY_DELETE] = 1;
  }
};

static const Scaling* S;  // set in main before any emitter runs

// ---------------------------------------------------------------------------
// Shared field helpers (address block, money chain)
// ---------------------------------------------------------------------------

static void emit_address(Row& w, uint64_t t, uint64_t r, uint64_t c0) {
  w.i(uni(t, r, c0 + 0, 1, 1000));                                   // street number
  w.s(std::string(PK(kStreetNames, t, r, c0 + 1)) + " " +
      PK(kStreetNames, t, r, c0 + 5));                               // street name
  w.s(PK(kStreetTypes, t, r, c0 + 2));                               // street type
  char suite[16];
  if (h4(t, r, c0 + 3) & 1)
    snprintf(suite, sizeof suite, "Suite %d", (int)uni(t, r, c0 + 3, 0, 99));
  else
    snprintf(suite, sizeof suite, "Suite %c", (char)('A' + uni(t, r, c0 + 3, 0, 25)));
  w.s(suite);
  w.s(pick(kCities, geo_active(S->sf, kCities_n), t, r, c0 + 4));    // city
  w.s(pick(kCounties, geo_active(S->sf, kCounties_n), t, r, c0 + 6)); // county
  const char* st = pick(kStates, states_active(S->sf), t, r, c0 + 7);
  w.s(st);                                                           // state
  char zip[8];
  snprintf(zip, sizeof zip, "%05d", (int)uni(t, r, c0 + 8, 10000, 99999));
  w.s(zip);                                                          // zip
  w.s(kCountries[0]);                                                // country
  w.dec(-500 - 100 * uni(t, r, c0 + 9, 0, 3));                       // gmt offset -5..-8
}

// per-line pricing chain shared by the three sales channels; all decimal(7,2)
// math in integer cents.  Returns via out params so returns tables can
// re-derive the sale's economics.
struct Money {
  int64_t qty, wholesale, list, sales, ext_discount, ext_sales, ext_wholesale,
      ext_list, ext_tax, coupon, net_paid, net_paid_tax, net_profit, ship, ext_ship,
      net_paid_ship, net_paid_ship_tax;
};

static void money_chain(uint64_t t, uint64_t r, Money* m) {
  const uint64_t c = 900;  // column namespace for money fields
  m->qty = uni(t, r, c + 0, 1, 100);
  m->wholesale = uni(t, r, c + 1, 100, 10000);            // 1.00 .. 100.00
  int64_t markup = uni(t, r, c + 2, 20, 140);             // 20%..140%
  m->list = m->wholesale * (100 + markup) / 100;
  int64_t discount = uni(t, r, c + 3, 0, 100);            // % off list
  m->sales = m->list * (100 - discount) / 100;
  m->ext_discount = (m->list - m->sales) * m->qty;
  m->ext_sales = m->sales * m->qty;
  m->ext_wholesale = m->wholesale * m->qty;
  m->ext_list = m->list * m->qty;
  int64_t tax_pct = uni(t, r, c + 4, 0, 9);
  m->coupon = (h4(t, r, c + 5) % 100 < 15) ? m->ext_sales * (int64_t)(h4(t, r, c + 6) % 50) / 100 : 0;
  m->net_paid = m->ext_sales - m->coupon;
  m->ext_tax = m->net_paid * tax_pct / 100;
  m->net_paid_tax = m->net_paid + m->ext_tax;
  m->ship = uni(t, r, c + 7, 0, 5000);
  m->ext_ship = m->ship * m->qty / 10;
  m->net_paid_ship = m->net_paid + m->ext_ship;
  m->net_paid_ship_tax = m->net_paid_tax + m->ext_ship;
  m->net_profit = m->net_paid - m->ext_wholesale;
}

// ---------------------------------------------------------------------------
// Dimension emitters: one function per table, row index -> one output line
// ---------------------------------------------------------------------------

static void e_customer_address(Row& w, int64_t r) {
  const uint64_t t = T_CUSTOMER_ADDRESS;
  w.i(r + 1);
  w.s(id16(r + 1));
  emit_address(w, t, r, 10);
  w.s(PK(kLocTypes, t, r, 30), isnull(t, r, 30, 2));
}

static void e_customer_demographics(Row& w, int64_t r) {
  // full enumeration: 2*5*7*20*4*7*7*7 = 1,920,800 combinations
  w.i(r + 1);
  w.s((r % 2) ? "F" : "M");
  w.s(kMarital[(r / 2) % 5]);
  w.s(kEducation[(r / 10) % 7]);
  w.i(500 + 500 * ((r / 70) % 20));
  w.s(kCredit[(r / 1400) % 4]);
  w.i((r / 5600) % 7);
  w.i((r / 39200) % 7);
  w.i((r / 274400) % 7);
}

static void e_date_dim(Row& w, int64_t r) {
  int64_t jday = kDateSkLo + r;
  int y, m, d;
  jday_to_civil(jday, &y, &m, &d);
  int dow = dow_of_jday(jday);
  w.i(jday);
  w.s(id16(jday));
  w.date(jday);
  w.i((y - 1900) * 12 + (m - 1));                 // month_seq
  w.i((jday - kDateSkLo + 1) / 7);                // week_seq
  w.i((y - 1900) * 4 + (m - 1) / 3);              // quarter_seq
  w.i(y);
  w.i(dow);
  w.i(m);
  w.i(d);
  w.i((m - 1) / 3 + 1);                           // qoy
  w.i(y);                                         // fy_year
  w.i((y - 1900) * 4 + (m - 1) / 3);              // fy_quarter_seq
  w.i((jday - kDateSkLo + 1) / 7);                // fy_week_seq
  w.s(kDayNames[dow]);
  char qn[16];
  snprintf(qn, sizeof qn, "%04dQ%d", y, (m - 1) / 3 + 1);
  w.s(qn);                                        // quarter_name
  bool holiday = (m == 12 && d == 25) || (m == 1 && d == 1) || (m == 7 && d == 4) ||
                 (m == 11 && d >= 22 && d <= 28 && dow == 4);
  w.s(holiday ? "Y" : "N");
  w.s((dow == 0 || dow == 6) ? "Y" : "N");        // weekend
  bool follows = false;
  {
    int py, pm, pd;
    jday_to_civil(jday - 1, &py, &pm, &pd);
    int pdow = dow_of_jday(jday - 1);
    follows = (pm == 12 && pd == 25) || (pm == 1 && pd == 1) || (pm == 7 && pd == 4) ||
              (pm == 11 && pd >= 22 && pd <= 28 && pdow == 4);
  }
  w.s(follows ? "Y" : "N");
  w.i(civil_to_jday(y, m, 1));                    // first_dom
  int ny = (m == 12) ? y + 1 : y, nm = (m == 12) ? 1 : m + 1;
  w.i(civil_to_jday(ny, nm, 1) - 1);              // last_dom
  w.i(jday - 365);                                // same_day_ly
  w.i(jday - 91);                                 // same_day_lq
  w.s("N"); w.s("N"); w.s("N"); w.s("N"); w.s("N");
}

static void e_warehouse(Row& w, int64_t r) {
  const uint64_t t = T_WAREHOUSE;
  w.i(r + 1);
  w.s(id16(r + 1));
  w.s(sentence(t, r, 2, 3), isnull(t, r, 2, 2));  // name
  w.i(uni(t, r, 3, 50000, 1000000));              // sq ft
  emit_address(w, t, r, 10);
}

static void e_ship_mode(Row& w, int64_t r) {
  w.i(r + 1);
  w.s(id16(r + 1));
  w.s(kShipTypes[r % 5]);
  w.s(kShipCodes[(r / 5) % 4]);
  w.s(kCarriers[r % kCarriers_n]);
  char contract[32];
  snprintf(contract, sizeof contract, "%c%" PRId64, (char)('A' + r % 26), r * 7 + 13);
  w.s(contract);
}

static void e_time_dim(Row& w, int64_t r) {
  int hour = (int)(r / 3600), minute = (int)((r / 60) % 60), second = (int)(r % 60);
  w.i(r);                                         // t_time_sk is 0-based
  w.s(id16(r + 1));
  w.i(r);
  w.i(hour);
  w.i(minute);
  w.i(second);
  w.s(hour < 12 ? "AM" : "PM");
  w.s(kShifts[hour / 8]);
  w.s(kSubShifts[hour / 6]);
  if (hour >= 6 && hour <= 8) w.s("breakfast");
  else if (hour >= 11 && hour <= 13) w.s("lunch");
  else if (hour >= 17 && hour <= 19) w.s("dinner");
  else w.nul();
}

static void e_reason(Row& w, int64_t r) {
  w.i(r + 1);
  w.s(id16(r + 1));
  w.s(kReasons[r % kReasons_n]);
}

static void e_income_band(Row& w, int64_t r) {
  w.i(r + 1);
  w.i(r * 10000 + (r ? 1 : 0));
  w.i((r + 1) * 10000);
}

static void e_item(Row& w, int64_t r) {
  const uint64_t t = T_ITEM;
  int64_t sk = r + 1;
  w.i(sk);
  w.s(id16(r / 2 + 1));                           // SCD: sk pairs share item_id
  // rec_start/rec_end: even row current (open end), odd row historical
  if (r % 2 == 0) { w.date(civil_to_jday(1997, 10, 27)); w.nul(); }
  else { w.date(civil_to_jday(1993, 10, 27)); w.date(civil_to_jday(1997, 10, 26)); }
  w.s(sentence(t, r, 4, 12), isnull(t, r, 4, 1));  // desc
  int64_t wholesale = uni(t, r, 6, 9, 8800);
  int64_t price = wholesale * (100 + uni(t, r, 5, 10, 120)) / 100;
  w.dec(price, isnull(t, r, 5, 1));               // current_price
  w.dec(wholesale, isnull(t, r, 6, 1));
  int64_t manufact = uni(t, r, 13, 1, 1000);
  int64_t cat = h4(t, r, 12) % kCategories_n;
  int64_t cls = h4(t, r, 10) % kClasses_n;
  int64_t brand = uni(t, r, 8, 1, 10);
  w.i(brand * 1000000 + manufact, isnull(t, r, 8, 1));  // brand_id
  char bbuf[64];
  snprintf(bbuf, sizeof bbuf, "%s%s #%d", kWords[manufact % kWords_n],
           kWords[(manufact / 7) % kWords_n], (int)brand);
  w.s(bbuf, isnull(t, r, 9, 1));                  // brand
  w.i(cls + 1, isnull(t, r, 10, 1));              // class_id
  w.s(kClasses[cls], isnull(t, r, 11, 1));
  w.i(cat + 1, isnull(t, r, 12, 1));              // category_id
  w.s(kCategories[cat], isnull(t, r, 12, 1));
  w.i(manufact, isnull(t, r, 13, 1));
  char mbuf[64];
  snprintf(mbuf, sizeof mbuf, "%s%s", kWords[manufact % kWords_n],
           kWords[(manufact * 3 + 1) % kWords_n]);
  w.s(mbuf, isnull(t, r, 14, 1));                 // manufact
  w.s(PK(kSizes, t, r, 15), isnull(t, r, 15, 1));
  char fbuf[32];
  snprintf(fbuf, sizeof fbuf, "%05dst%d", (int)uni(t, r, 16, 0, 99999), (int)(r % 10));
  w.s(fbuf, isnull(t, r, 16, 1));                 // formulation
  {
    std::string color = PK(kColors, t, r, 17);
    w.s(color, isnull(t, r, 17, 1));
  }
  w.s(PK(kUnits, t, r, 18), isnull(t, r, 18, 1));
  w.s("Unknown", isnull(t, r, 19, 1));            // container
  w.i(uni(t, r, 20, 1, 100), isnull(t, r, 20, 1));  // manager_id
  char pbuf[64];
  snprintf(pbuf, sizeof pbuf, "%s%s%s", kWords[r % kWords_n],
           kWords[(r / 3 + 5) % kWords_n], kWords[(r / 7 + 11) % kWords_n]);
  w.s(pbuf, isnull(t, r, 21, 1));                 // product_name
}

static void e_store(Row& w, int64_t r) {
  const uint64_t t = T_STORE;
  w.i(r + 1);
  w.s(id16(r / 2 + 1));                           // SCD pairs
  if (r % 2 == 0) { w.date(civil_to_jday(1997, 3, 13)); w.nul(); }
  else { w.date(civil_to_jday(1994, 3, 13)); w.date(civil_to_jday(1997, 3, 12)); }
  w.i_or_null(uni(t, r, 4, kDateSkLo, kSalesDateLo), !(h4(t, r, 4) % 10 == 0));  // closed: mostly null
  w.s(kPromoNames[r % kPromoNames_n]);            // store name
  w.i(uni(t, r, 6, 200, 300), isnull(t, r, 6, 1));
  w.i(uni(t, r, 7, 5000000, 10000000), isnull(t, r, 7, 1));
  w.s(kHours[r % 3], isnull(t, r, 8, 1));
  w.s(std::string(PK(kFirstNames, t, r, 9)) + " " + PK(kLastNames, t, r, 9), isnull(t, r, 9, 1));
  w.i(uni(t, r, 10, 1, 10), isnull(t, r, 10, 1)); // market_id
  w.s("Unknown", isnull(t, r, 11, 1));            // geography_class
  w.s(sentence(t, r, 12, 14), isnull(t, r, 12, 1));
  w.s(std::string(PK(kFirstNames, t, r, 13)) + " " + PK(kLastNames, t, r, 13), isnull(t, r, 13, 1));
  w.i(uni(t, r, 14, 1, 6), isnull(t, r, 14, 1));  // division_id
  w.s(kDivNames[h4(t, r, 15) % kDivNames_n], isnull(t, r, 15, 1));
  w.i(uni(t, r, 16, 1, 6), isnull(t, r, 16, 1));  // company_id
  w.s("Unknown", isnull(t, r, 17, 1));
  emit_address(w, t, r, 20);
  // emit_address writes gmt_offset as its last field; store needs tax on top
  w.dec(uni(t, r, 31, 0, 11));                    // s_tax_precentage
}

static void e_call_center(Row& w, int64_t r) {
  const uint64_t t = T_CALL_CENTER;
  w.i(r + 1);
  w.s(id16(r / 2 + 1));
  if (r % 2 == 0) { w.date(civil_to_jday(1998, 1, 1)); w.nul(); }
  else { w.date(civil_to_jday(1996, 1, 1)); w.date(civil_to_jday(1997, 12, 31)); }
  w.i_or_null(0, true);                           // closed_date_sk: always null
  w.i(uni(t, r, 5, kDateSkLo, kSalesDateLo));     // open_date_sk
  char nbuf[32];
  snprintf(nbuf, sizeof nbuf, "%s_%d", kWords[r % kWords_n], (int)(r / 2));
  w.s(nbuf);                                      // cc_name
  w.s(kSizes[r % 3]);                             // class: small/medium/large
  w.i(uni(t, r, 8, 50, 7000));                    // employees
  w.i(uni(t, r, 9, 1000000, 4000000));            // sq_ft
  w.s(kHours[r % 3]);
  w.s(std::string(PK(kFirstNames, t, r, 11)) + " " + PK(kLastNames, t, r, 11));
  w.i(uni(t, r, 12, 1, 6));                       // mkt_id
  w.s(PK(kMktClasses, t, r, 13));
  w.s(sentence(t, r, 14, 14));
  w.s(std::string(PK(kFirstNames, t, r, 15)) + " " + PK(kLastNames, t, r, 15));
  w.i(uni(t, r, 16, 1, 6));                       // division
  w.s(kDivNames[h4(t, r, 17) % kDivNames_n]);
  w.i(uni(t, r, 18, 1, 6));                       // company
  w.s(kDivNames[h4(t, r, 19) % kDivNames_n]);
  emit_address(w, t, r, 20);
  w.dec(uni(t, r, 31, 0, 11));                    // tax_percentage
}

static void e_customer(Row& w, int64_t r) {
  const uint64_t t = T_CUSTOMER;
  w.i(r + 1);
  w.s(id16(r + 1));
  w.i_or_null(uni(t, r, 2, 1, 1920800), isnull(t, r, 2, 2));   // cdemo
  w.i_or_null(uni(t, r, 3, 1, 7200), isnull(t, r, 3, 2));      // hdemo
  w.i_or_null(uni(t, r, 4, 1, S->addresses), isnull(t, r, 4, 2));
  int64_t first_sales = uni(t, r, 6, kSalesDateLo - 2000, kSalesDateHi - 1000);
  w.i_or_null(first_sales + uni(t, r, 5, 0, 30), isnull(t, r, 5, 2));  // first_shipto
  w.i_or_null(first_sales, isnull(t, r, 6, 2));
  bool female = h4(t, r, 100) & 1;
  w.s(female ? PK(kSalutationsF, t, r, 7) : PK(kSalutationsM, t, r, 7), isnull(t, r, 7, 3));
  const char* fn = PK(kFirstNames, t, r, 8);
  const char* ln = PK(kLastNames, t, r, 9);
  w.s(fn, isnull(t, r, 8, 3));
  w.s(ln, isnull(t, r, 9, 3));
  w.s((h4(t, r, 10) & 1) ? "Y" : "N", isnull(t, r, 10, 3));
  w.i(uni(t, r, 11, 1, 28), isnull(t, r, 11, 3)); // birth day
  w.i(uni(t, r, 12, 1, 12), isnull(t, r, 12, 3));
  w.i(uni(t, r, 13, 1924, 1992), isnull(t, r, 13, 3));
  w.s(PK(kBirthCountries, t, r, 14), isnull(t, r, 14, 3));
  w.nul();                                        // c_login (always null in dsdgen)
  char email[96];
  snprintf(email, sizeof email, "%s.%s@%s.edu", fn, ln, kWords[h4(t, r, 16) % kWords_n]);
  w.s(email, isnull(t, r, 16, 3));
  w.i_or_null(uni(t, r, 17, kSalesDateHi - 400, kSalesDateHi), isnull(t, r, 17, 3));
}

static void e_web_site(Row& w, int64_t r) {
  const uint64_t t = T_WEB_SITE;
  w.i(r + 1);
  w.s(id16(r / 2 + 1));
  if (r % 2 == 0) { w.date(civil_to_jday(1997, 8, 16)); w.nul(); }
  else { w.date(civil_to_jday(1995, 8, 16)); w.date(civil_to_jday(1997, 8, 15)); }
  char nbuf[32];
  snprintf(nbuf, sizeof nbuf, "site_%d", (int)(r / 2));
  w.s(nbuf);
  w.i(uni(t, r, 5, kDateSkLo, kSalesDateLo));     // open
  w.i_or_null(uni(t, r, 6, kSalesDateLo, kSalesDateHi), !(h4(t, r, 6) % 10 == 0));
  w.s("Unknown");                                 // class
  w.s(std::string(PK(kFirstNames, t, r, 8)) + " " + PK(kLastNames, t, r, 8));
  w.i(uni(t, r, 9, 1, 6));
  w.s(PK(kMktClasses, t, r, 10));
  w.s(sentence(t, r, 11, 14));
  w.s(std::string(PK(kFirstNames, t, r, 12)) + " " + PK(kLastNames, t, r, 12));
  w.i(uni(t, r, 13, 1, 6));
  w.s(kDivNames[h4(t, r, 14) % kDivNames_n]);
  emit_address(w, t, r, 20);
  w.dec(uni(t, r, 31, 0, 11));                    // tax_percentage
}

static void e_household_demographics(Row& w, int64_t r) {
  // 20 income bands * 6 buy potentials * 10 dep counts * 6 vehicle counts
  w.i(r + 1);
  w.i(r % 20 + 1);
  w.s(kBuyPotential[(r / 20) % 6]);
  w.i((r / 120) % 10);
  w.i((r / 1200) % 6);  // vehicle count 0..5
}

static void e_web_page(Row& w, int64_t r) {
  const uint64_t t = T_WEB_PAGE;
  w.i(r + 1);
  w.s(id16(r / 2 + 1));
  if (r % 2 == 0) { w.date(civil_to_jday(1997, 9, 3)); w.nul(); }
  else { w.date(civil_to_jday(1995, 9, 3)); w.date(civil_to_jday(1997, 9, 2)); }
  w.i(uni(t, r, 4, kSalesDateLo - 1000, kSalesDateLo));  // creation
  w.i(uni(t, r, 5, kSalesDateLo, kSalesDateHi));  // access
  bool autogen = h4(t, r, 6) % 100 < 30;
  w.s(autogen ? "Y" : "N");
  w.i_or_null(uni(t, r, 7, 1, S->customers), !autogen);  // customer_sk when autogen
  char url[40];
  snprintf(url, sizeof url, "http://www.foo.com/page%d.html", (int)r);
  w.s(url, isnull(t, r, 8, 2));
  w.s(PK(kWebTypes, t, r, 9));
  w.i(uni(t, r, 10, 100, 8000));                  // char_count
  w.i(uni(t, r, 11, 2, 25));                      // link_count
  w.i(uni(t, r, 12, 1, 7));                       // image_count
  w.i(uni(t, r, 13, 0, 4));                       // max_ad_count
}

static void e_promotion(Row& w, int64_t r) {
  const uint64_t t = T_PROMOTION;
  w.i(r + 1);
  w.s(id16(r + 1));
  int64_t start = uni(t, r, 2, kSalesDateLo, kSalesDateHi - 60);
  w.i_or_null(start, isnull(t, r, 2, 2));
  w.i_or_null(start + uni(t, r, 3, 10, 60), isnull(t, r, 3, 2));
  w.i_or_null(uni(t, r, 4, 1, S->items), isnull(t, r, 4, 2));
  w.dec(100000, isnull(t, r, 5, 2));              // p_cost = 1000.00
  w.i(1);                                         // response_target
  w.s(kPromoNames[r % kPromoNames_n], isnull(t, r, 7, 2));
  for (int c = 8; c <= 15; c++)                   // 8 channel flags
    w.s((h4(t, r, c) & 1) ? "Y" : "N", isnull(t, r, c, 2));
  w.s(sentence(t, r, 16, 10), isnull(t, r, 16, 2));
  w.s("Unknown", isnull(t, r, 17, 2));            // purpose
  w.s((h4(t, r, 18) & 1) ? "Y" : "N");            // discount_active
}

static void e_catalog_page(Row& w, int64_t r) {
  const uint64_t t = T_CATALOG_PAGE;
  w.i(r + 1);
  w.s(id16(r + 1));
  int64_t start = kSalesDateLo + (r / 108) * 30 % (kSalesDateHi - kSalesDateLo);
  w.i(start);
  w.i(start + 30);
  w.s(kDepartments[0], isnull(t, r, 4, 1));
  w.i(r / 108 + 1);                               // catalog_number
  w.i(r % 108 + 1);                               // catalog_page_number
  w.s(sentence(t, r, 7, 10), isnull(t, r, 7, 1));
  w.s(kCatalogTypes[(r / 108) % 3], isnull(t, r, 8, 1));
}

static void e_inventory(Row& w, int64_t r) {
  const uint64_t t = T_INVENTORY;
  // row -> (week, warehouse, item); weekly snapshots across the sales window
  int64_t per_week = S->warehouses * S->items;
  int64_t week = r / per_week;
  int64_t rem = r % per_week;
  int64_t wh = rem / S->items;
  int64_t item = rem % S->items;
  // spread the (possibly sub-SF1-shrunken) snapshot count across the FULL
  // 5-year window so date-window queries (q21/q22 month ranges) always find
  // snapshots; at full scale n_weeks == 261 and the stride is exactly 7 days
  int64_t n_weeks = std::max<int64_t>(1, S->rows[T_INVENTORY] / per_week);
  w.i(kSalesDateLo + ((week * 261) / n_weeks) * 7 + 3);
  w.i(item + 1);
  w.i(wh + 1);
  // stockout-skewed on-hand quantity: ~40% of snapshots near zero, the rest
  // uniform. A pure uniform gives every (item, warehouse) group a coefficient
  // of variation ~0.58, which degenerates q39's `cov > 1` filter to empty;
  // stockouts push per-group cov across 1 the way real inventories do.
  int64_t q = (h4(t, r, 3) % 10) < 4 ? (int64_t)(h4(t, r, 4) % 5)
                                     : (int64_t)uni(t, r, 5, 0, 1000);
  w.i_or_null(q, isnull(t, r, 3, 2));
}

// ---------------------------------------------------------------------------
// Fact emitters. Line-level facts derive shared fields from the parent
// ticket/order hash stream so multi-line tickets are consistent without
// cross-row state; returns re-derive their originating sale.
// ---------------------------------------------------------------------------

struct SsLine {  // store_sales row r = (ticket = r/12, line = r%12)
  int64_t ticket, line, sold_date, sold_time, item, customer, cdemo, hdemo, addr,
      store, promo;
  Money m;
};

static void derive_ss(int64_t r, SsLine* o) {
  const uint64_t t = T_STORE_SALES;
  o->ticket = r / 12 + 1;
  o->line = r % 12;
  uint64_t tk = (uint64_t)o->ticket;
  o->sold_date = kSalesDateLo + (int64_t)(h4(t, tk, 500) % (uint64_t)(kSalesDateHi - kSalesDateLo + 1));
  o->sold_time = 28800 + (int64_t)(h4(t, tk, 501) % 43200);  // 8:00..20:00
  o->customer = 1 + (int64_t)(h4(t, tk, 502) % (uint64_t)S->customers);
  o->cdemo = 1 + (int64_t)(h4(t, tk, 503) % 1920800ULL);
  o->hdemo = 1 + (int64_t)(h4(t, tk, 504) % 7200ULL);
  o->addr = 1 + (int64_t)(h4(t, tk, 505) % (uint64_t)S->addresses);
  o->store = 1 + (int64_t)(h4(t, tk, 506) % (uint64_t)S->stores);
  o->item = 1 + (int64_t)(h4(t, (uint64_t)r, 507) % (uint64_t)S->items);
  o->promo = 1 + (int64_t)(h4(t, (uint64_t)r, 508) % (uint64_t)S->promotions);
  money_chain(t, (uint64_t)r, &o->m);
}

static void e_store_sales(Row& w, int64_t r) {
  const uint64_t t = T_STORE_SALES;
  SsLine L;
  derive_ss(r, &L);
  w.i_or_null(L.sold_date, isnull(t, r, 0, 4));
  w.i_or_null(L.sold_time, isnull(t, r, 1, 4));
  w.i(L.item);
  w.i_or_null(L.customer, isnull(t, r, 3, 4));
  w.i_or_null(L.cdemo, isnull(t, r, 4, 4));
  w.i_or_null(L.hdemo, isnull(t, r, 5, 4));
  w.i_or_null(L.addr, isnull(t, r, 6, 4));
  w.i_or_null(L.store, isnull(t, r, 7, 4));
  w.i_or_null(L.promo, isnull(t, r, 8, 4));
  w.i(L.ticket);
  w.i_or_null(L.m.qty, isnull(t, r, 10, 4));
  w.dec(L.m.wholesale, isnull(t, r, 11, 4));
  w.dec(L.m.list, isnull(t, r, 12, 4));
  w.dec(L.m.sales, isnull(t, r, 13, 4));
  w.dec(L.m.ext_discount, isnull(t, r, 14, 4));
  w.dec(L.m.ext_sales, isnull(t, r, 15, 4));
  w.dec(L.m.ext_wholesale, isnull(t, r, 16, 4));
  w.dec(L.m.ext_list, isnull(t, r, 17, 4));
  w.dec(L.m.ext_tax, isnull(t, r, 18, 4));
  w.dec(L.m.coupon, isnull(t, r, 19, 4));
  w.dec(L.m.net_paid, isnull(t, r, 20, 4));
  w.dec(L.m.net_paid_tax, isnull(t, r, 21, 4));
  w.dec(L.m.net_profit, isnull(t, r, 22, 4));
}

static void e_store_returns(Row& w, int64_t r) {
  const uint64_t t = T_STORE_RETURNS;
  // return r originates from sale row s (stride 10 with jitter)
  int64_t s = r * 10 + (int64_t)(h4(t, r, 600) % 10);
  if (s >= S->rows[T_STORE_SALES]) s = s % S->rows[T_STORE_SALES];
  SsLine L;
  derive_ss(s, &L);
  int64_t ret_date = L.sold_date + 1 + (int64_t)(h4(t, r, 601) % 120);
  int64_t qty = 1 + (int64_t)(h4(t, r, 602) % (uint64_t)L.m.qty);
  int64_t amt = L.m.sales * qty;
  int64_t tax = amt * 5 / 100;
  int64_t fee = 50 + (int64_t)(h4(t, r, 603) % 10000);
  int64_t ship = 100 + (int64_t)(h4(t, r, 604) % 5000);
  int64_t refunded = amt * (int64_t)(h4(t, r, 605) % 101) / 100;
  int64_t reversed = (amt - refunded) / 2;
  int64_t credit = amt - refunded - reversed;
  w.i_or_null(ret_date, isnull(t, r, 0, 4));
  w.i_or_null(28800 + (int64_t)(h4(t, r, 606) % 43200), isnull(t, r, 1, 4));
  w.i(L.item);
  w.i_or_null(L.customer, isnull(t, r, 3, 4));
  w.i_or_null(L.cdemo, isnull(t, r, 4, 4));
  w.i_or_null(L.hdemo, isnull(t, r, 5, 4));
  w.i_or_null(L.addr, isnull(t, r, 6, 4));
  w.i_or_null(L.store, isnull(t, r, 7, 4));
  w.i_or_null(1 + (int64_t)(h4(t, r, 607) % (uint64_t)S->reasons), isnull(t, r, 8, 4));
  w.i(L.ticket);
  w.i_or_null(qty, isnull(t, r, 10, 4));
  w.dec(amt, isnull(t, r, 11, 4));
  w.dec(tax, isnull(t, r, 12, 4));
  w.dec(amt + tax, isnull(t, r, 13, 4));
  w.dec(fee, isnull(t, r, 14, 4));
  w.dec(ship * qty, isnull(t, r, 15, 4));
  w.dec(refunded, isnull(t, r, 16, 4));
  w.dec(reversed, isnull(t, r, 17, 4));
  w.dec(credit, isnull(t, r, 18, 4));
  w.dec(fee + ship * qty + tax, isnull(t, r, 19, 4));  // net_loss
}

struct CsLine {  // catalog_sales row r = (order = r/10, line = r%10)
  int64_t order, line, sold_date, sold_time, ship_date, bill_customer, bill_cdemo,
      bill_hdemo, bill_addr, ship_customer, ship_cdemo, ship_hdemo, ship_addr,
      call_center, catalog_page, ship_mode, warehouse, item, promo;
  Money m;
};

static void derive_cs(int64_t r, CsLine* o) {
  const uint64_t t = T_CATALOG_SALES;
  o->order = r / 10 + 1;
  o->line = r % 10;
  uint64_t ok = (uint64_t)o->order;
  o->sold_date = kSalesDateLo + (int64_t)(h4(t, ok, 500) % (uint64_t)(kSalesDateHi - kSalesDateLo + 1));
  o->sold_time = (int64_t)(h4(t, ok, 501) % 86400);
  o->ship_date = o->sold_date + 2 + (int64_t)(h4(t, (uint64_t)r, 502) % 60);
  o->bill_customer = 1 + (int64_t)(h4(t, ok, 503) % (uint64_t)S->customers);
  o->bill_cdemo = 1 + (int64_t)(h4(t, ok, 504) % 1920800ULL);
  o->bill_hdemo = 1 + (int64_t)(h4(t, ok, 505) % 7200ULL);
  o->bill_addr = 1 + (int64_t)(h4(t, ok, 506) % (uint64_t)S->addresses);
  if (h4(t, ok, 507) % 100 < 85) {  // ship-to == bill-to 85% of the time
    o->ship_customer = o->bill_customer; o->ship_cdemo = o->bill_cdemo;
    o->ship_hdemo = o->bill_hdemo; o->ship_addr = o->bill_addr;
  } else {
    o->ship_customer = 1 + (int64_t)(h4(t, ok, 508) % (uint64_t)S->customers);
    o->ship_cdemo = 1 + (int64_t)(h4(t, ok, 509) % 1920800ULL);
    o->ship_hdemo = 1 + (int64_t)(h4(t, ok, 510) % 7200ULL);
    o->ship_addr = 1 + (int64_t)(h4(t, ok, 511) % (uint64_t)S->addresses);
  }
  o->call_center = 1 + (int64_t)(h4(t, ok, 512) % (uint64_t)S->call_centers);
  o->catalog_page = 1 + (int64_t)(h4(t, (uint64_t)r, 513) % (uint64_t)S->catalog_pages);
  o->ship_mode = 1 + (int64_t)(h4(t, ok, 514) % 20ULL);
  o->warehouse = 1 + (int64_t)(h4(t, (uint64_t)r, 515) % (uint64_t)S->warehouses);
  o->item = 1 + (int64_t)(h4(t, (uint64_t)r, 516) % (uint64_t)S->items);
  o->promo = 1 + (int64_t)(h4(t, (uint64_t)r, 517) % (uint64_t)S->promotions);
  // Cross-channel repurchase correlation: ~20% of catalog lines are the same
  // customer re-buying the same item after a store return (what q17/q25/q29
  // join for: ss -> sr -> cs on customer+item, catalog purchase after the
  // return). Derived from a store_returns row so the triple exists at every
  // scale.
  if (S->rows[T_STORE_RETURNS] > 0 && h4(t, (uint64_t)r, 518) % 5 == 0) {
    uint64_t j = h4(t, (uint64_t)r, 519) % (uint64_t)S->rows[T_STORE_RETURNS];
    int64_t sr = (int64_t)j * 10 + (int64_t)(h4(T_STORE_RETURNS, j, 600) % 10);
    if (sr >= S->rows[T_STORE_SALES]) sr = sr % S->rows[T_STORE_SALES];
    SsLine L;
    derive_ss(sr, &L);
    int64_t ret_date = L.sold_date + 1 + (int64_t)(h4(T_STORE_RETURNS, j, 601) % 120);
    o->bill_customer = L.customer;
    o->item = L.item;
    o->sold_date = std::min<int64_t>(
        kSalesDateHi, ret_date + (int64_t)(h4(t, (uint64_t)r, 520) % 90));
    // ship follows the overridden sale; never before it
    o->ship_date = o->sold_date + 2 + (int64_t)(h4(t, (uint64_t)r, 502) % 60);
  }
  money_chain(t, (uint64_t)r, &o->m);
}

static void e_catalog_sales(Row& w, int64_t r) {
  const uint64_t t = T_CATALOG_SALES;
  CsLine L;
  derive_cs(r, &L);
  w.i_or_null(L.sold_date, isnull(t, r, 0, 4));
  w.i_or_null(L.sold_time, isnull(t, r, 1, 4));
  w.i_or_null(L.ship_date, isnull(t, r, 2, 4));
  w.i_or_null(L.bill_customer, isnull(t, r, 3, 4));
  w.i_or_null(L.bill_cdemo, isnull(t, r, 4, 4));
  w.i_or_null(L.bill_hdemo, isnull(t, r, 5, 4));
  w.i_or_null(L.bill_addr, isnull(t, r, 6, 4));
  w.i_or_null(L.ship_customer, isnull(t, r, 7, 4));
  w.i_or_null(L.ship_cdemo, isnull(t, r, 8, 4));
  w.i_or_null(L.ship_hdemo, isnull(t, r, 9, 4));
  w.i_or_null(L.ship_addr, isnull(t, r, 10, 4));
  w.i_or_null(L.call_center, isnull(t, r, 11, 4));
  w.i_or_null(L.catalog_page, isnull(t, r, 12, 4));
  w.i_or_null(L.ship_mode, isnull(t, r, 13, 4));
  w.i_or_null(L.warehouse, isnull(t, r, 14, 4));
  w.i(L.item);
  w.i_or_null(L.promo, isnull(t, r, 16, 4));
  w.i(L.order);
  w.i_or_null(L.m.qty, isnull(t, r, 18, 4));
  w.dec(L.m.wholesale, isnull(t, r, 19, 4));
  w.dec(L.m.list, isnull(t, r, 20, 4));
  w.dec(L.m.sales, isnull(t, r, 21, 4));
  w.dec(L.m.ext_discount, isnull(t, r, 22, 4));
  w.dec(L.m.ext_sales, isnull(t, r, 23, 4));
  w.dec(L.m.ext_wholesale, isnull(t, r, 24, 4));
  w.dec(L.m.ext_list, isnull(t, r, 25, 4));
  w.dec(L.m.ext_tax, isnull(t, r, 26, 4));
  w.dec(L.m.coupon, isnull(t, r, 27, 4));
  w.dec(L.m.ext_ship, isnull(t, r, 28, 4));
  w.dec(L.m.net_paid, isnull(t, r, 29, 4));
  w.dec(L.m.net_paid_tax, isnull(t, r, 30, 4));
  w.dec(L.m.net_paid_ship, isnull(t, r, 31, 4));
  w.dec(L.m.net_paid_ship_tax, isnull(t, r, 32, 4));
  w.dec(L.m.net_profit, isnull(t, r, 33, 4));
}

static void e_catalog_returns(Row& w, int64_t r) {
  const uint64_t t = T_CATALOG_RETURNS;
  int64_t s = r * 10 + (int64_t)(h4(t, r, 600) % 10);
  if (s >= S->rows[T_CATALOG_SALES]) s = s % S->rows[T_CATALOG_SALES];
  CsLine L;
  derive_cs(s, &L);
  int64_t ret_date = L.ship_date + 1 + (int64_t)(h4(t, r, 601) % 120);
  int64_t qty = 1 + (int64_t)(h4(t, r, 602) % (uint64_t)L.m.qty);
  int64_t amt = L.m.sales * qty;
  int64_t tax = amt * 5 / 100;
  int64_t fee = 50 + (int64_t)(h4(t, r, 603) % 10000);
  int64_t ship = 100 + (int64_t)(h4(t, r, 604) % 5000);
  int64_t refunded = amt * (int64_t)(h4(t, r, 605) % 101) / 100;
  int64_t reversed = (amt - refunded) / 2;
  int64_t credit = amt - refunded - reversed;
  w.i_or_null(ret_date, isnull(t, r, 0, 4));
  w.i_or_null((int64_t)(h4(t, r, 606) % 86400), isnull(t, r, 1, 4));
  w.i(L.item);
  w.i_or_null(L.bill_customer, isnull(t, r, 3, 4));
  w.i_or_null(L.bill_cdemo, isnull(t, r, 4, 4));
  w.i_or_null(L.bill_hdemo, isnull(t, r, 5, 4));
  w.i_or_null(L.bill_addr, isnull(t, r, 6, 4));
  w.i_or_null(L.ship_customer, isnull(t, r, 7, 4));
  w.i_or_null(L.ship_cdemo, isnull(t, r, 8, 4));
  w.i_or_null(L.ship_hdemo, isnull(t, r, 9, 4));
  w.i_or_null(L.ship_addr, isnull(t, r, 10, 4));
  w.i_or_null(L.call_center, isnull(t, r, 11, 4));
  w.i_or_null(L.catalog_page, isnull(t, r, 12, 4));
  w.i_or_null(L.ship_mode, isnull(t, r, 13, 4));
  w.i_or_null(L.warehouse, isnull(t, r, 14, 4));
  w.i_or_null(1 + (int64_t)(h4(t, r, 607) % (uint64_t)S->reasons), isnull(t, r, 15, 4));
  w.i(L.order);
  w.i_or_null(qty, isnull(t, r, 17, 4));
  w.dec(amt, isnull(t, r, 18, 4));
  w.dec(tax, isnull(t, r, 19, 4));
  w.dec(amt + tax, isnull(t, r, 20, 4));
  w.dec(fee, isnull(t, r, 21, 4));
  w.dec(ship * qty, isnull(t, r, 22, 4));
  w.dec(refunded, isnull(t, r, 23, 4));
  w.dec(reversed, isnull(t, r, 24, 4));
  w.dec(credit, isnull(t, r, 25, 4));
  w.dec(fee + ship * qty + tax, isnull(t, r, 26, 4));
}

struct WsLine {  // web_sales row r = (order = r/12, line = r%12)
  int64_t order, line, sold_date, sold_time, ship_date, bill_customer, bill_cdemo,
      bill_hdemo, bill_addr, ship_customer, ship_cdemo, ship_hdemo, ship_addr,
      web_page, web_site, ship_mode, warehouse, item, promo;
  Money m;
};

static void derive_ws(int64_t r, WsLine* o) {
  const uint64_t t = T_WEB_SALES;
  o->order = r / 12 + 1;
  o->line = r % 12;
  uint64_t ok = (uint64_t)o->order;
  o->sold_date = kSalesDateLo + (int64_t)(h4(t, ok, 500) % (uint64_t)(kSalesDateHi - kSalesDateLo + 1));
  o->sold_time = (int64_t)(h4(t, ok, 501) % 86400);
  o->ship_date = o->sold_date + 2 + (int64_t)(h4(t, (uint64_t)r, 502) % 60);
  o->bill_customer = 1 + (int64_t)(h4(t, ok, 503) % (uint64_t)S->customers);
  o->bill_cdemo = 1 + (int64_t)(h4(t, ok, 504) % 1920800ULL);
  o->bill_hdemo = 1 + (int64_t)(h4(t, ok, 505) % 7200ULL);
  o->bill_addr = 1 + (int64_t)(h4(t, ok, 506) % (uint64_t)S->addresses);
  if (h4(t, ok, 507) % 100 < 90) {
    o->ship_customer = o->bill_customer; o->ship_cdemo = o->bill_cdemo;
    o->ship_hdemo = o->bill_hdemo; o->ship_addr = o->bill_addr;
  } else {
    o->ship_customer = 1 + (int64_t)(h4(t, ok, 508) % (uint64_t)S->customers);
    o->ship_cdemo = 1 + (int64_t)(h4(t, ok, 509) % 1920800ULL);
    o->ship_hdemo = 1 + (int64_t)(h4(t, ok, 510) % 7200ULL);
    o->ship_addr = 1 + (int64_t)(h4(t, ok, 511) % (uint64_t)S->addresses);
  }
  o->web_page = 1 + (int64_t)(h4(t, ok, 512) % (uint64_t)S->web_pages);
  o->web_site = 1 + (int64_t)(h4(t, ok, 513) % (uint64_t)S->web_sites);
  o->ship_mode = 1 + (int64_t)(h4(t, ok, 514) % 20ULL);
  o->warehouse = 1 + (int64_t)(h4(t, (uint64_t)r, 515) % (uint64_t)S->warehouses);
  o->item = 1 + (int64_t)(h4(t, (uint64_t)r, 516) % (uint64_t)S->items);
  o->promo = 1 + (int64_t)(h4(t, (uint64_t)r, 517) % (uint64_t)S->promotions);
  money_chain(t, (uint64_t)r, &o->m);
}

static void e_web_sales(Row& w, int64_t r) {
  const uint64_t t = T_WEB_SALES;
  WsLine L;
  derive_ws(r, &L);
  w.i_or_null(L.sold_date, isnull(t, r, 0, 4));
  w.i_or_null(L.sold_time, isnull(t, r, 1, 4));
  w.i_or_null(L.ship_date, isnull(t, r, 2, 4));
  w.i(L.item);
  w.i_or_null(L.bill_customer, isnull(t, r, 4, 4));
  w.i_or_null(L.bill_cdemo, isnull(t, r, 5, 4));
  w.i_or_null(L.bill_hdemo, isnull(t, r, 6, 4));
  w.i_or_null(L.bill_addr, isnull(t, r, 7, 4));
  w.i_or_null(L.ship_customer, isnull(t, r, 8, 4));
  w.i_or_null(L.ship_cdemo, isnull(t, r, 9, 4));
  w.i_or_null(L.ship_hdemo, isnull(t, r, 10, 4));
  w.i_or_null(L.ship_addr, isnull(t, r, 11, 4));
  w.i_or_null(L.web_page, isnull(t, r, 12, 4));
  w.i_or_null(L.web_site, isnull(t, r, 13, 4));
  w.i_or_null(L.ship_mode, isnull(t, r, 14, 4));
  w.i_or_null(L.warehouse, isnull(t, r, 15, 4));
  w.i_or_null(L.promo, isnull(t, r, 16, 4));
  w.i(L.order);
  w.i_or_null(L.m.qty, isnull(t, r, 18, 4));
  w.dec(L.m.wholesale, isnull(t, r, 19, 4));
  w.dec(L.m.list, isnull(t, r, 20, 4));
  w.dec(L.m.sales, isnull(t, r, 21, 4));
  w.dec(L.m.ext_discount, isnull(t, r, 22, 4));
  w.dec(L.m.ext_sales, isnull(t, r, 23, 4));
  w.dec(L.m.ext_wholesale, isnull(t, r, 24, 4));
  w.dec(L.m.ext_list, isnull(t, r, 25, 4));
  w.dec(L.m.ext_tax, isnull(t, r, 26, 4));
  w.dec(L.m.coupon, isnull(t, r, 27, 4));
  w.dec(L.m.ext_ship, isnull(t, r, 28, 4));
  w.dec(L.m.net_paid, isnull(t, r, 29, 4));
  w.dec(L.m.net_paid_tax, isnull(t, r, 30, 4));
  w.dec(L.m.net_paid_ship, isnull(t, r, 31, 4));
  w.dec(L.m.net_paid_ship_tax, isnull(t, r, 32, 4));
  w.dec(L.m.net_profit, isnull(t, r, 33, 4));
}

static void e_web_returns(Row& w, int64_t r) {
  const uint64_t t = T_WEB_RETURNS;
  int64_t s = r * 10 + (int64_t)(h4(t, r, 600) % 10);
  if (s >= S->rows[T_WEB_SALES]) s = s % S->rows[T_WEB_SALES];
  WsLine L;
  derive_ws(s, &L);
  int64_t ret_date = L.ship_date + 1 + (int64_t)(h4(t, r, 601) % 120);
  int64_t qty = 1 + (int64_t)(h4(t, r, 602) % (uint64_t)L.m.qty);
  int64_t amt = L.m.sales * qty;
  int64_t tax = amt * 5 / 100;
  int64_t fee = 50 + (int64_t)(h4(t, r, 603) % 10000);
  int64_t ship = 100 + (int64_t)(h4(t, r, 604) % 5000);
  int64_t refunded = amt * (int64_t)(h4(t, r, 605) % 101) / 100;
  int64_t reversed = (amt - refunded) / 2;
  int64_t credit = amt - refunded - reversed;
  w.i_or_null(ret_date, isnull(t, r, 0, 4));
  w.i_or_null((int64_t)(h4(t, r, 606) % 86400), isnull(t, r, 1, 4));
  w.i(L.item);
  w.i_or_null(L.bill_customer, isnull(t, r, 3, 4));
  w.i_or_null(L.bill_cdemo, isnull(t, r, 4, 4));
  w.i_or_null(L.bill_hdemo, isnull(t, r, 5, 4));
  w.i_or_null(L.bill_addr, isnull(t, r, 6, 4));
  w.i_or_null(L.ship_customer, isnull(t, r, 7, 4));
  w.i_or_null(L.ship_cdemo, isnull(t, r, 8, 4));
  w.i_or_null(L.ship_hdemo, isnull(t, r, 9, 4));
  w.i_or_null(L.ship_addr, isnull(t, r, 10, 4));
  w.i_or_null(L.web_page, isnull(t, r, 11, 4));
  w.i_or_null(1 + (int64_t)(h4(t, r, 607) % (uint64_t)S->reasons), isnull(t, r, 12, 4));
  w.i(L.order);
  w.i_or_null(qty, isnull(t, r, 14, 4));
  w.dec(amt, isnull(t, r, 15, 4));
  w.dec(tax, isnull(t, r, 16, 4));
  w.dec(amt + tax, isnull(t, r, 17, 4));
  w.dec(fee, isnull(t, r, 18, 4));
  w.dec(ship * qty, isnull(t, r, 19, 4));
  w.dec(refunded, isnull(t, r, 20, 4));
  w.dec(reversed, isnull(t, r, 21, 4));
  w.dec(credit, isnull(t, r, 22, 4));
  w.dec(fee + ship * qty + tax, isnull(t, r, 23, 4));
}

// ---------------------------------------------------------------------------
// Refresh (-update) emitters: the s_* source tables Data Maintenance joins
// against (ref: nds/data_maintenance/LF_*.sql), plus the delete-date files.
// ---------------------------------------------------------------------------

static int g_update = 0;  // current -update number (0 = base generation)

static inline int64_t upd_window_lo() { return kSalesDateLo + (int64_t)(g_update - 1) * 14; }
static inline int64_t upd_window_hi() { return upd_window_lo() + 13; }

static inline int64_t upd_date(uint64_t t, int64_t r, uint64_t c) {
  return upd_window_lo() + (int64_t)(h4(t, (uint64_t)r, c) % 14);
}

static std::string time_str(int64_t secs) {
  char buf[12];
  snprintf(buf, sizeof buf, "%02d:%02d:%02d", (int)(secs / 3600), (int)((secs / 60) % 60),
           (int)(secs % 60));
  return std::string(buf);
}

// business-key helpers honouring the SCD pairing of dims (valid ids are
// id16(1 .. n/2) for item/store/call_center/web_site/web_page)
static std::string rk_item(uint64_t t, int64_t r, uint64_t c) {
  return id16(1 + (int64_t)(h4(t, (uint64_t)r, c) % (uint64_t)std::max<int64_t>(1, S->items / 2)));
}
static std::string rk_cust(uint64_t t, int64_t r, uint64_t c) {
  return id16(1 + (int64_t)(h4(t, (uint64_t)r, c) % (uint64_t)S->customers));
}

static void e_s_purchase(Row& w, int64_t r) {
  const uint64_t t = T_S_PURCHASE;
  w.i(g_update * 10000000LL + r + 1);
  w.s(id16(1 + (int64_t)(h4(t, r, 1) % (uint64_t)std::max<int64_t>(1, S->stores / 2))));
  w.s(rk_cust(t, r, 2));
  w.s(date_str(upd_date(t, r, 3)));
  w.i(28800 + (int64_t)(h4(t, r, 4) % 43200));
  w.i(uni(t, r, 5, 1, 1000));   // register
  w.i(uni(t, r, 6, 1, 1000));   // clerk
  w.s(sentence(t, r, 7, 8));
}

static void e_s_purchase_lineitem(Row& w, int64_t r) {
  const uint64_t t = T_S_PURCHASE_LINEITEM;
  w.i(g_update * 10000000LL + r / 12 + 1);
  w.i(r % 12 + 1);
  w.s(rk_item(t, r, 2));
  w.s(id16(1 + (int64_t)(h4(t, r, 3) % (uint64_t)S->promotions)));
  w.i(uni(t, r, 4, 1, 100));
  w.dec(uni(t, r, 5, 100, 30000));
  w.dec((h4(t, r, 6) % 100 < 15) ? uni(t, r, 7, 0, 5000) : 0);
  w.s(sentence(t, r, 8, 8));
}

static void e_s_catalog_order(Row& w, int64_t r) {
  const uint64_t t = T_S_CATALOG_ORDER;
  w.i(g_update * 10000000LL + r + 1);
  w.s(rk_cust(t, r, 1));
  w.s(rk_cust(t, r, 2));
  w.s(date_str(upd_date(t, r, 3)));
  w.i((int64_t)(h4(t, r, 4) % 86400));
  w.s(id16(1 + (int64_t)(h4(t, r, 5) % 20)));
  w.s(id16(1 + (int64_t)(h4(t, r, 6) % (uint64_t)std::max<int64_t>(1, S->call_centers / 2))));
  w.s(sentence(t, r, 7, 8));
}

static void e_s_catalog_order_lineitem(Row& w, int64_t r) {
  const uint64_t t = T_S_CATALOG_ORDER_LINEITEM;
  w.i(g_update * 10000000LL + r / 10 + 1);
  w.i(r % 10 + 1);
  w.s(rk_item(t, r, 2));
  w.s(id16(1 + (int64_t)(h4(t, r, 3) % (uint64_t)S->promotions)));
  w.i(uni(t, r, 4, 1, 100));
  w.dec(uni(t, r, 5, 100, 30000));
  w.dec((h4(t, r, 6) % 100 < 15) ? uni(t, r, 7, 0, 5000) : 0);
  w.s(id16(1 + (int64_t)(h4(t, r, 8) % (uint64_t)S->warehouses)));
  w.s(date_str(upd_date(t, r, 9) + 2 + (int64_t)(h4(t, r, 10) % 30)));
  w.i(uni(t, r, 11, 1, S->catalog_pages / 108 + 1));
  w.i(uni(t, r, 12, 1, 108));
  w.dec(uni(t, r, 13, 0, 5000));
}

static void e_s_web_order(Row& w, int64_t r) {
  const uint64_t t = T_S_WEB_ORDER;
  w.i(g_update * 10000000LL + r + 1);
  w.s(rk_cust(t, r, 1));
  w.s(rk_cust(t, r, 2));
  w.s(date_str(upd_date(t, r, 3)));
  w.i((int64_t)(h4(t, r, 4) % 86400));
  w.s(id16(1 + (int64_t)(h4(t, r, 5) % 20)));
  w.s(id16(1 + (int64_t)(h4(t, r, 6) % (uint64_t)std::max<int64_t>(1, S->web_sites / 2))));
  w.s(sentence(t, r, 7, 8));
}

static void e_s_web_order_lineitem(Row& w, int64_t r) {
  const uint64_t t = T_S_WEB_ORDER_LINEITEM;
  w.i(g_update * 10000000LL + r / 12 + 1);
  w.i(r % 12 + 1);
  w.s(rk_item(t, r, 2));
  w.s(id16(1 + (int64_t)(h4(t, r, 3) % (uint64_t)S->promotions)));
  w.i(uni(t, r, 4, 1, 100));
  w.dec(uni(t, r, 5, 100, 30000));
  w.dec((h4(t, r, 6) % 100 < 15) ? uni(t, r, 7, 0, 5000) : 0);
  w.s(id16(1 + (int64_t)(h4(t, r, 8) % (uint64_t)S->warehouses)));
  w.s(date_str(upd_date(t, r, 9) + 2 + (int64_t)(h4(t, r, 10) % 30)));
  w.dec(uni(t, r, 11, 0, 5000));
  w.s(id16(1 + (int64_t)(h4(t, r, 12) % (uint64_t)std::max<int64_t>(1, S->web_pages / 2))));
}

static void e_s_store_returns(Row& w, int64_t r) {
  const uint64_t t = T_S_STORE_RETURNS;
  int64_t qty = uni(t, r, 100, 1, 50);
  int64_t amt = uni(t, r, 101, 100, 20000) * qty;
  int64_t refunded = amt * (int64_t)(h4(t, r, 102) % 101) / 100;
  int64_t reversed = (amt - refunded) / 2;
  w.s(id16(1 + (int64_t)(h4(t, r, 0) % (uint64_t)std::max<int64_t>(1, S->stores / 2))));
  w.s(id16(g_update * 10000000LL + (int64_t)(h4(t, r, 1) % 1000000) + 1));  // purchase id
  w.i(uni(t, r, 2, 1, 12));
  w.s(rk_item(t, r, 3));
  w.s(rk_cust(t, r, 4));
  w.s(date_str(upd_date(t, r, 5)));
  w.s(time_str((int64_t)(h4(t, r, 6) % 86400)));
  w.i(1 + (int64_t)(h4(t, r, 7) % (uint64_t)S->ss_tickets));
  w.i(qty);
  w.dec(amt);
  w.dec(amt * 5 / 100);
  w.dec(uni(t, r, 8, 50, 10000));
  w.dec(uni(t, r, 9, 100, 5000) * qty);
  w.dec(refunded);
  w.dec(reversed);
  w.dec(amt - refunded - reversed);
  w.s(id16(1 + (int64_t)(h4(t, r, 10) % (uint64_t)S->reasons)));
}

static void e_s_catalog_returns(Row& w, int64_t r) {
  const uint64_t t = T_S_CATALOG_RETURNS;
  int64_t qty = uni(t, r, 100, 1, 50);
  int64_t amt = uni(t, r, 101, 100, 20000) * qty;
  int64_t refunded = amt * (int64_t)(h4(t, r, 102) % 101) / 100;
  int64_t reversed = (amt - refunded) / 2;
  w.s(id16(1 + (int64_t)(h4(t, r, 0) % (uint64_t)std::max<int64_t>(1, S->call_centers / 2))));
  w.i(1 + (int64_t)(h4(t, r, 1) % (uint64_t)S->cs_orders));
  w.i(uni(t, r, 2, 1, 10));
  w.s(rk_item(t, r, 3));
  w.s(rk_cust(t, r, 4));
  w.s(rk_cust(t, r, 5));
  w.s(date_str(upd_date(t, r, 6)));
  w.s(time_str((int64_t)(h4(t, r, 7) % 86400)));
  w.i(qty);
  w.dec(amt);
  w.dec(amt * 5 / 100);
  w.dec(uni(t, r, 8, 50, 10000));
  w.dec(uni(t, r, 9, 100, 5000) * qty);
  w.dec(refunded);
  w.dec(reversed);
  w.dec(amt - refunded - reversed);
  w.s(id16(1 + (int64_t)(h4(t, r, 10) % (uint64_t)S->reasons)));
  w.s(id16(1 + (int64_t)(h4(t, r, 11) % 20)));
  w.s(id16(1 + (int64_t)(h4(t, r, 12) % (uint64_t)S->catalog_pages)));
  w.s(id16(1 + (int64_t)(h4(t, r, 13) % (uint64_t)S->warehouses)));
}

static void e_s_web_returns(Row& w, int64_t r) {
  const uint64_t t = T_S_WEB_RETURNS;
  int64_t qty = uni(t, r, 100, 1, 50);
  int64_t amt = uni(t, r, 101, 100, 20000) * qty;
  int64_t refunded = amt * (int64_t)(h4(t, r, 102) % 101) / 100;
  int64_t reversed = (amt - refunded) / 2;
  w.s(id16(1 + (int64_t)(h4(t, r, 0) % (uint64_t)std::max<int64_t>(1, S->web_pages / 2))));
  w.i(1 + (int64_t)(h4(t, r, 1) % (uint64_t)S->ws_orders));
  w.i(uni(t, r, 2, 1, 12));
  w.s(rk_item(t, r, 3));
  w.s(rk_cust(t, r, 4));
  w.s(rk_cust(t, r, 5));
  w.s(date_str(upd_date(t, r, 6)));
  w.s(time_str((int64_t)(h4(t, r, 7) % 86400)));
  w.i(qty);
  w.dec(amt);
  w.dec(amt * 5 / 100);
  w.dec(uni(t, r, 8, 50, 10000));
  w.dec(uni(t, r, 9, 100, 5000) * qty);
  w.dec(refunded);
  w.dec(reversed);
  w.dec(amt - refunded - reversed);
  w.s(id16(1 + (int64_t)(h4(t, r, 10) % (uint64_t)S->reasons)));
}

static void e_s_inventory(Row& w, int64_t r) {
  const uint64_t t = T_S_INVENTORY;
  int64_t items_tracked = std::max<int64_t>(100, S->items / 100);
  w.s(id16(r / items_tracked + 1));
  w.s(id16(1 + (int64_t)(h4(t, r, 1) % (uint64_t)std::max<int64_t>(1, S->items / 2))));
  w.s(date_str(upd_window_lo()));
  w.i(uni(t, r, 3, 0, 1000));
}

static void e_delete(Row& w, int64_t) {
  w.s(date_str(upd_window_lo()));
  w.s(date_str(upd_window_hi()));
}

// ---------------------------------------------------------------------------
// Driver: chunking, file naming, dispatch
// ---------------------------------------------------------------------------

typedef void (*EmitFn)(Row&, int64_t);

static EmitFn kEmitters[T_MAX] = {
  e_customer_address, e_customer_demographics, e_date_dim, e_warehouse, e_ship_mode,
  e_time_dim, e_reason, e_income_band, e_item, e_store, e_call_center, e_customer,
  e_web_site, e_store_returns, e_household_demographics, e_web_page, e_promotion,
  e_catalog_page, e_inventory, e_catalog_returns, e_web_returns, e_web_sales,
  e_catalog_sales, e_store_sales,
  e_s_purchase, e_s_purchase_lineitem, e_s_catalog_order, e_s_catalog_order_lineitem,
  e_s_web_order, e_s_web_order_lineitem, e_s_store_returns, e_s_catalog_returns,
  e_s_web_returns, e_s_inventory, e_delete, e_delete,
};

// tables too small to split across children (single chunk, child 1 only)
static bool is_small(int tid, int64_t rows) {
  if (tid == T_DELETE || tid == T_INVENTORY_DELETE) return true;
  return rows < 50000;
}

static int gen_table(int tid, const std::string& dir, int parallel, int child) {
  int64_t rows = S->rows[tid];
  int64_t lo = 0, hi = rows;
  if (is_small(tid, rows)) {
    if (child != 1) return 0;  // dsdgen: small tables only in chunk 1
  } else {
    lo = rows * (child - 1) / parallel;
    hi = rows * child / parallel;
  }
  char path[4096];
  if (parallel > 1)
    snprintf(path, sizeof path, "%s/%s_%d_%d.dat", dir.c_str(), kTableNames[tid], child, parallel);
  else
    snprintf(path, sizeof path, "%s/%s.dat", dir.c_str(), kTableNames[tid]);
  // -update file naming carries the update number like dsdgen's delete_<n>
  if (g_update > 0 && (tid == T_DELETE || tid == T_INVENTORY_DELETE))
    snprintf(path, sizeof path, "%s/%s_%d.dat", dir.c_str(), kTableNames[tid], g_update);
  FILE* f = fopen(path, "w");
  if (!f) { fprintf(stderr, "ndsgen: cannot open %s\n", path); return 1; }
  std::vector<char> buf(1 << 20);
  setvbuf(f, buf.data(), _IOFBF, buf.size());
  Row w(f);
  for (int64_t r = lo; r < hi; r++) {
    kEmitters[tid](w, r);
    w.end();
  }
  fclose(f);
  return 0;
}

int main(int argc, char** argv) {
  double scale = 1.0;
  int parallel = 1, child = 1;
  std::string dir = ".", only_table;
  uint64_t seed = 19620718ULL;
  int update = 0;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) { fprintf(stderr, "ndsgen: missing value for %s\n", a.c_str()); exit(2); }
      return argv[++i];
    };
    if (a == "-scale") scale = atof(next());
    else if (a == "-parallel") parallel = atoi(next());
    else if (a == "-child") child = atoi(next());
    else if (a == "-dir") dir = next();
    else if (a == "-table") only_table = next();
    else if (a == "-update") update = atoi(next());
    else if (a == "-rngseed") seed = (uint64_t)atoll(next());
    else if (a == "-help" || a == "--help") {
      printf("usage: ndsgen -scale SF -dir DIR [-parallel N -child C] [-table T] "
             "[-update U] [-rngseed S]\n");
      return 0;
    } else {
      fprintf(stderr, "ndsgen: unknown flag %s\n", a.c_str());
      return 2;
    }
  }
  if (scale <= 0 || parallel < 1 || child < 1 || child > parallel) {
    fprintf(stderr, "ndsgen: invalid -scale/-parallel/-child\n");
    return 2;
  }
  Scaling scaling(scale);
  S = &scaling;
  g_update = update;
  // refresh data varies per update number; delete windows derive from the
  // update number itself, so they stay deterministic
  g_seed = update > 0 ? splitmix64(seed ^ (uint64_t)update * 0xC2B2AE3D27D4EB4FULL) : seed;

  int first = update > 0 ? T_S_PURCHASE : 0;
  int last = update > 0 ? T_MAX : T_S_PURCHASE;
  int status = 0;
  for (int tid = first; tid < last; tid++) {
    if (!only_table.empty() && only_table != kTableNames[tid]) continue;
    status |= gen_table(tid, dir, parallel, child);
  }
  return status;
}
