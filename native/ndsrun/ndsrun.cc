// Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
//
// ndsrun: native distributed data-generation runner.
//
// The role of the reference's Hadoop MapReduce wrapper (ref:
// nds/tpcds-gen/src/main/java/org/notmysock/tpcds/GenTable.java:50-167):
// split the dsdgen child-chunk range across pod hosts, launch one worker
// command per host, supervise exits, and re-run a failed host's span on a
// surviving host (the MR framework's task-retry role, GenTable relies on
// mapreduce.map.maxattempts). Workers exec the framework's own driver in
// `local` mode on each host, landing per-table flat files on the shared
// data directory exactly like the mapper's MultipleOutputs layout.
//
// Launchers:
//   ssh   (default)  ssh <host> <python> <driver> local ...
//   local            run the worker command on this machine (testing; the
//                    scheduling/retry logic is identical)
//
// Usage:
//   ndsrun -hosts h1,h2,h3 -scale 100 -parallel 96 -dir /shared/raw
//          [-range a,b] [-update N] [-rngseed S] [-overwrite]
//          [-driver /repo/nds_gen_data.py] [-python python3]
//          [-launcher ssh|local] [-retries 2]

#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Span {
  int lo = 1, hi = 1;
};

struct Options {
  std::vector<std::string> hosts;
  std::string scale, dir, update, rngseed;
  std::string driver = "nds_gen_data.py";
  std::string python = "python3";
  std::string launcher = "ssh";
  int parallel = 0;
  int range_lo = 0, range_hi = 0;  // 0 = full 1..parallel
  bool overwrite = false;
  int retries = 2;
};

std::vector<std::string> split(const std::string& s, char sep) {
  std::vector<std::string> out;
  size_t start = 0;
  while (start <= s.size()) {
    size_t end = s.find(sep, start);
    if (end == std::string::npos) end = s.size();
    if (end > start) out.push_back(s.substr(start, end - start));
    start = end + 1;
  }
  return out;
}

// contiguous chunk spans, one per host (mirrors the Python driver's
// _split_ranges so both schedulers land identical per-host work)
std::vector<Span> split_spans(int lo, int hi, int n) {
  std::vector<Span> spans;
  int total = hi - lo + 1, start = lo;
  for (int i = 0; i < n; i++) {
    int size = total / n + (i < total % n ? 1 : 0);
    if (size == 0) continue;
    spans.push_back({start, start + size - 1});
    start += size;
  }
  return spans;
}

std::vector<std::string> worker_cmd(const Options& opt,
                                    const std::string& host, Span span) {
  std::vector<std::string> cmd;
  if (opt.launcher == "ssh") {
    cmd = {"ssh", host};
  }
  cmd.insert(cmd.end(), {opt.python, opt.driver, "local", opt.scale,
                         std::to_string(opt.parallel), opt.dir, "--range",
                         std::to_string(span.lo) + "," +
                             std::to_string(span.hi)});
  if (!opt.update.empty()) cmd.insert(cmd.end(), {"--update", opt.update});
  if (!opt.rngseed.empty()) cmd.insert(cmd.end(), {"--rngseed", opt.rngseed});
  if (opt.overwrite) cmd.push_back("--overwrite_output");
  return cmd;
}

pid_t spawn(const std::vector<std::string>& cmd) {
  pid_t pid = fork();
  if (pid != 0) return pid;
  std::vector<char*> argv;
  argv.reserve(cmd.size() + 1);
  for (const auto& a : cmd) argv.push_back(const_cast<char*>(a.c_str()));
  argv.push_back(nullptr);
  execvp(argv[0], argv.data());
  perror("execvp");
  _exit(127);
}

struct Task {
  pid_t pid;
  std::string host;
  Span span;
};

}  // namespace

int main(int argc, char** argv) {
  Options opt;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      if (i + 1 >= argc) {
        std::fprintf(stderr, "missing value for %s\n", a.c_str());
        std::exit(2);
      }
      return argv[++i];
    };
    if (a == "-hosts") {
      opt.hosts = split(next(), ',');
    } else if (a == "-scale") {
      opt.scale = next();
    } else if (a == "-parallel") {
      opt.parallel = std::atoi(next().c_str());
    } else if (a == "-dir") {
      opt.dir = next();
    } else if (a == "-range") {
      auto parts = split(next(), ',');
      if (parts.size() != 2) {
        std::fprintf(stderr, "-range expects a,b\n");
        return 2;
      }
      opt.range_lo = std::atoi(parts[0].c_str());
      opt.range_hi = std::atoi(parts[1].c_str());
    } else if (a == "-update") {
      opt.update = next();
    } else if (a == "-rngseed") {
      opt.rngseed = next();
    } else if (a == "-overwrite") {
      opt.overwrite = true;
    } else if (a == "-driver") {
      opt.driver = next();
    } else if (a == "-python") {
      opt.python = next();
    } else if (a == "-launcher") {
      opt.launcher = next();
    } else if (a == "-retries") {
      opt.retries = std::atoi(next().c_str());
    } else {
      std::fprintf(stderr, "unknown flag %s\n", a.c_str());
      return 2;
    }
  }
  if (opt.hosts.empty() || opt.scale.empty() || opt.dir.empty() ||
      opt.parallel <= 0) {
    std::fprintf(stderr,
                 "usage: ndsrun -hosts h1,h2 -scale S -parallel N -dir D "
                 "[-range a,b] [-update N] [-rngseed S] [-overwrite] "
                 "[-driver path] [-python exe] [-launcher ssh|local] "
                 "[-retries K]\n");
    return 2;
  }
  int lo = opt.range_lo ? opt.range_lo : 1;
  int hi = opt.range_hi ? opt.range_hi : opt.parallel;

  std::vector<Task> running;
  std::vector<std::string> ok_hosts;
  std::vector<Span> failed;

  auto launch = [&](const std::string& host, Span span) {
    auto cmd = worker_cmd(opt, host, span);
    std::string line;
    for (const auto& c : cmd) line += c + " ";
    std::fprintf(stderr, "[ndsrun] %s\n", line.c_str());
    running.push_back({spawn(cmd), host, span});
  };

  auto spans = split_spans(lo, hi, static_cast<int>(opt.hosts.size()));
  for (size_t i = 0; i < spans.size(); i++) launch(opt.hosts[i], spans[i]);

  auto drain = [&]() {
    for (auto& t : running) {
      int status = 0;
      waitpid(t.pid, &status, 0);
      bool good = WIFEXITED(status) && WEXITSTATUS(status) == 0;
      if (good) {
        if (std::find(ok_hosts.begin(), ok_hosts.end(), t.host) ==
            ok_hosts.end())
          ok_hosts.push_back(t.host);
      } else {
        std::fprintf(stderr, "[ndsrun] host %s failed for range %d,%d\n",
                     t.host.c_str(), t.span.lo, t.span.hi);
        failed.push_back(t.span);
      }
    }
    running.clear();
  };
  drain();

  for (int attempt = 0; attempt < opt.retries && !failed.empty(); attempt++) {
    if (ok_hosts.empty()) break;
    auto todo = failed;
    failed.clear();
    for (size_t i = 0; i < todo.size(); i++)
      launch(ok_hosts[i % ok_hosts.size()], todo[i]);
    drain();
  }

  if (!failed.empty()) {
    std::fprintf(stderr, "[ndsrun] %zu range(s) still failing\n",
                 failed.size());
    return 1;
  }
  std::fprintf(stderr, "[ndsrun] complete: chunks %d-%d across %zu host(s)\n",
               lo, hi, opt.hosts.size());
  return 0;
}
