#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Full-benchmark orchestrator.

TPU-build equivalent of the reference orchestrator (ref: nds/nds_bench.py:
34-507). Runs the 7-step NDS pipeline in TPC-DS spec order, scraping each
phase's report files (all cross-phase communication stays file-based so any
phase can be skipped/resumed via the yaml ``skip`` flags):

  0. data generation (raw + per-stream refresh sets)      [untimed]
  1. Load Test (transcode into the snapshot warehouse)  -> Tld
  2. query-stream generation (RNGSEED = load end stamp)
  3. Power Test                                         -> TPower
  4. Throughput Test 1 (streams 1..n/2)                 -> Ttt1
  5. Maintenance Test 1                                 -> Tdm1
  6. Throughput Test 2 (streams n/2+1..n-1)             -> Ttt2
  7. Maintenance Test 2                                 -> Tdm2

and computes the spec metric
``int(SF * Sq*99 / (Tpt*Ttt*Tdm*Tld)^(1/4))`` into ``metrics.csv``.
"""

import argparse
import math
import os
import subprocess
import sys

import yaml

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

PY = sys.executable or "python3"


def get_yaml_params(yaml_file):
    with open(yaml_file, 'r') as f:
        return yaml.safe_load(f)


def get_load_end_timestamp(load_report_file):
    """RNGSEED for stream generation = load end timestamp from the report
    (spec 4.3.1; ref: nds/nds_bench.py:60-74)."""
    with open(load_report_file) as f:
        for line in f:
            if "RNGSEED used:" in line:
                return line.split(":")[1].strip()
    raise Exception(
        f"RNGSEED not found in Load Test report file: {load_report_file}")


def get_load_time(load_report_file):
    with open(load_report_file) as f:
        for line in f:
            if "Load Test Time" in line:
                return line.split(":")[1].strip().split(" ")[0]
    raise Exception(
        f"Load Test Time not found in Load Test report file: {load_report_file}.")


def get_power_time(power_report_file):
    with open(power_report_file) as f:
        for line in f:
            if "Power Test Time" in line:
                return line.split(",")[2].strip()
    raise Exception(
        f"Power Test Time not found in Power Test report file: {power_report_file}.")


def get_start_end_time(report_file):
    start_time = end_time = None
    with open(report_file) as f:
        for line in f:
            if "Power Start Time" in line:
                start_time = line.split(",")[2].strip()
            if "Power End Time" in line:
                end_time = line.split(",")[2].strip()
    if start_time and end_time:
        return start_time, end_time
    raise Exception(
        f"Start or End time not found in Power Test report file: {report_file}")


def get_stream_range(num_streams, first_or_second):
    """Stream ids for throughput/maintenance test 1 or 2: the generated
    streams are split in half (ref: nds/nds_bench.py:126-135)."""
    if first_or_second == 1:
        return list(range(1, num_streams // 2 + 1))
    return list(range(num_streams // 2 + 1, num_streams))


def get_throughput_time(throughput_report_file_base, num_streams,
                        first_or_second):
    """Throughput elapse per Spec 7.4.7.4: max(end) - min(start) across the
    test's streams (ref: nds/nds_bench.py:138-157)."""
    start_time, end_time = [], []
    for stream_num in get_stream_range(num_streams, first_or_second):
        report_file = throughput_report_file_base + f"_{stream_num}.csv"
        s, e = get_start_end_time(report_file)
        start_time.append(float(s))
        end_time.append(float(e))
    return round_up_to_nearest_10_percent(max(end_time) - min(start_time))


def get_refresh_time(maintenance_report_file):
    with open(maintenance_report_file) as f:
        for line in f:
            if "Data Maintenance Time" in line:
                return float(line.split(",")[2].strip())
    raise Exception("Data Maintenance Time not found in Data Maintenance "
                    f"report file: {maintenance_report_file}.")


def get_maintenance_time(maintenance_report_base_path, num_streams,
                         first_or_second):
    """Tdm = sum of refresh times across the test's streams
    (ref: nds/nds_bench.py:176-196)."""
    Tdm = 0.0
    for i in get_stream_range(num_streams, first_or_second):
        Tdm += get_refresh_time(maintenance_report_base_path + f"_{i}.csv")
    return round_up_to_nearest_10_percent(Tdm)


def get_throughput_stream_nums(num_streams, first_or_second):
    return ",".join(str(x) for x in
                    get_stream_range(num_streams, first_or_second))


def round_up_to_nearest_10_percent(num):
    """Spec 7.1.16: elapsed times round up to the nearest 0.1s
    (ref: nds/nds_bench.py:207-208)."""
    return math.ceil(num * 10) / 10


# ----------------------------------------------------------------- phases

def run_data_gen(scale_factor, parallel, data_path, local_or_dist,
                 num_streams):
    subprocess.run([PY, os.path.join(REPO, "nds_gen_data.py"), local_or_dist,
                    scale_factor, parallel, data_path, "--overwrite_output"],
                   check=True)
    for i in range(1, num_streams):
        subprocess.run([PY, os.path.join(REPO, "nds_gen_data.py"),
                        local_or_dist, scale_factor, parallel,
                        data_path + f"_{i}", "--overwrite_output",
                        "--update", str(i)],
                       check=True)


def run_load_test(input_path, output_path, warehouse_type, load_report_file):
    subprocess.run([PY, os.path.join(REPO, "nds_transcode.py"), input_path,
                    output_path, load_report_file,
                    "--output_format", warehouse_type,
                    "--output_mode", "overwrite"],
                   check=True)


def gen_streams(num_streams, template_dir, scale_factor, stream_output_path,
                RNGSEED):
    cmd = [PY, os.path.join(REPO, "nds_gen_query_stream.py")]
    if template_dir:
        cmd.append(template_dir)
    cmd += [scale_factor, stream_output_path,
            "--rngseed", RNGSEED, "--streams", str(num_streams)]
    subprocess.run(cmd, check=True)


def power_test(input_path, stream_path, report_path, property_path,
               output_path, warehouse_type, device):
    cmd = [PY, os.path.join(REPO, "nds_power.py"), input_path, stream_path,
           report_path, "--input_format", warehouse_type, "--device", device]
    if property_path:
        cmd += ["--property_file", property_path]
    if output_path:
        cmd += ["--output_prefix", output_path]
    subprocess.run(cmd, check=True)


def warm_test(input_path, stream_path, report_path, property_path,
              warehouse_type, device):
    """Optional precompile phase (off by default): one untimed pass of the
    Power stream to fill the persistent XLA compile cache, so TPower
    measures execution rather than shape-universe compilation — the
    warmed-JVM analog. Its report carries Warm markers, never Power."""
    cmd = [PY, os.path.join(REPO, "nds_power.py"), input_path, stream_path,
           report_path, "--input_format", warehouse_type, "--device", device,
           "--warm", "--allow_failure"]
    if property_path:
        cmd += ["--property_file", property_path]
    # best-effort by design: a transient failure while cache-filling must
    # not abort the official phases that follow
    subprocess.run(cmd, check=False)


def throughput_test(num_streams, first_or_second, input_path,
                    stream_base_path, report_base_path, property_path,
                    warehouse_type, device):
    cmd = [os.path.join(REPO, "nds-throughput"),
           get_throughput_stream_nums(num_streams, first_or_second),
           PY, os.path.join(REPO, "nds_power.py"), input_path,
           stream_base_path + "/query_{}.sql", report_base_path + "_{}.csv",
           "--input_format", warehouse_type, "--device", device]
    if property_path:
        cmd += ["--property_file", property_path]
    print(cmd)
    subprocess.run(cmd, check=True)


def maintenance_test(num_streams, first_or_second, warehouse_path,
                     maintenance_raw_data_base_path, maintenance_query_path,
                     maintenance_report_base_path, property_path,
                     warehouse_type, device):
    for i in get_stream_range(num_streams, first_or_second):
        cmd = [PY, os.path.join(REPO, "nds_maintenance.py"), warehouse_path,
               maintenance_raw_data_base_path + f"_{i}",
               maintenance_query_path,
               maintenance_report_base_path + f"_{i}.csv",
               "--warehouse_type", warehouse_type, "--device", device]
        if property_path:
            cmd += ["--property_file", property_path]
        subprocess.run(cmd, check=True)


def get_perf_metric(scale_factor, num_streams_in_throughput, Tload, Tpower,
                    Ttt1, Ttt2, Tdm1, Tdm2):
    """Primary metric (spec 7.4.3; ref: nds/nds_bench.py:334-357)."""
    Q = num_streams_in_throughput * 99
    Tpt = (Tpower * num_streams_in_throughput) / 3600
    Ttt = (Ttt1 + Ttt2) / 3600
    Tdm = (Tdm1 + Tdm2) / 3600
    Tld = (0.01 * num_streams_in_throughput * Tload) / 3600
    # float() not int(): sub-1 scale factors are legal in smoke runs
    return int(float(scale_factor) * Q / (Tpt * Ttt * Tdm * Tld) ** (1 / 4))


def write_metrics_report(report_path, metrics_map):
    with open(report_path, 'w') as f:
        for key, value in metrics_map.items():
            f.write(f"{key},{value}\n")


def run_full_bench(yaml_params):
    dg = yaml_params['data_gen']
    scale_factor = str(dg['scale_factor'])
    parallel = str(dg['parallel'])
    raw_data_path = dg['raw_data_path']
    local_or_dist = dg.get('local_or_dist', dg.get('local_or_hdfs', 'local'))
    lt = yaml_params['load_test']
    warehouse_output_path = lt['output_path']
    warehouse_type = lt['warehouse_type']
    load_report_path = lt['report_path']
    gs = yaml_params['generate_query_stream']
    num_streams = gs['num_streams']
    query_template_dir = gs.get('query_template_dir')
    stream_output_path = gs['stream_output_path']
    power_stream_path = os.path.join(stream_output_path, "query_0.sql")
    pt = yaml_params['power_test']
    power_report_path = pt['report_path']
    power_property_path = pt.get('property_path')
    power_output_path = pt.get('output_path')
    device = yaml_params.get('device', 'tpu')
    tt = yaml_params['throughput_test']
    throughput_report_base = tt['report_base_path']
    mt = yaml_params['maintenance_test']
    maintenance_query_dir = mt['query_dir']
    maintenance_report_base_path = mt['maintenance_report_base_path']
    metrics_report = yaml_params['metrics_report_path']

    # 0.
    if not dg['skip']:
        run_data_gen(scale_factor, parallel, raw_data_path, local_or_dist,
                     num_streams)
    # 1.
    if not lt['skip']:
        run_load_test(raw_data_path, warehouse_output_path, warehouse_type,
                      load_report_path)
    Tld = round_up_to_nearest_10_percent(float(get_load_time(load_report_path)))
    # 2.
    if not gs['skip']:
        RNGSEED = get_load_end_timestamp(load_report_path)
        gen_streams(num_streams, query_template_dir, scale_factor,
                    stream_output_path, RNGSEED)
    # 2.5: optional precompile (absent/skip=true by default)
    wt = yaml_params.get('warm_test') or {}
    if not wt.get('skip', True):
        warm_test(warehouse_output_path, power_stream_path,
                  wt.get('report_path') or power_report_path + '.warm',
                  power_property_path, warehouse_type, device)
    # 3.
    if not pt['skip']:
        power_test(warehouse_output_path, power_stream_path,
                   power_report_path, power_property_path, power_output_path,
                   warehouse_type, device)
    # TPower is logged in milliseconds; spec times are seconds rounded up 0.1
    TPower = round_up_to_nearest_10_percent(
        float(get_power_time(power_report_path)) / 1000)
    # 4.
    if not tt['skip']:
        throughput_test(num_streams, 1, warehouse_output_path,
                        stream_output_path, throughput_report_base,
                        power_property_path, warehouse_type, device)
    Ttt1 = get_throughput_time(throughput_report_base, num_streams, 1)
    # 5.
    if not mt['skip']:
        maintenance_test(num_streams, 1, warehouse_output_path,
                         raw_data_path, maintenance_query_dir,
                         maintenance_report_base_path, power_property_path,
                         warehouse_type, device)
    Tdm1 = get_maintenance_time(maintenance_report_base_path, num_streams, 1)
    # 6.
    if not tt['skip']:
        throughput_test(num_streams, 2, warehouse_output_path,
                        stream_output_path, throughput_report_base,
                        power_property_path, warehouse_type, device)
    Ttt2 = get_throughput_time(throughput_report_base, num_streams, 2)
    # 7.
    if not mt['skip']:
        maintenance_test(num_streams, 2, warehouse_output_path,
                         raw_data_path, maintenance_query_dir,
                         maintenance_report_base_path, power_property_path,
                         warehouse_type, device)
    Tdm2 = get_maintenance_time(maintenance_report_base_path, num_streams, 2)

    perf_metric = get_perf_metric(scale_factor, num_streams // 2, Tld, TPower,
                                  Ttt1, Ttt2, Tdm1, Tdm2)
    print(f"====== Performance Metric: {perf_metric} ======")
    metrics_map = {"scale_factor": scale_factor,
                   "num_streams": num_streams,
                   "Tld": Tld,
                   "TPower": TPower,
                   "Ttt1": Ttt1,
                   "Ttt2": Ttt2,
                   "Tdm1": Tdm1,
                   "Tdm2": Tdm2,
                   "perf_metric": perf_metric}
    write_metrics_report(metrics_report, metrics_map)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument('yaml_config',
                        help='yaml config file for the benchmark')
    args = parser.parse_args()
    run_full_bench(get_yaml_params(args.yaml_config))
