#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Raw data generation driver.

TPU-build equivalent of the reference data-gen CLI (ref: nds/nds_gen_data.py):
drives the native generator (`native/ndsgen/ndsgen`, or a user-supplied patched
TPC-DS dsdgen via $TPCDS_HOME) in parallel chunks, then lands per-table flat
files into per-table subdirectories. Supports incremental generation via
``--range`` with a temp-dir merge (ref: nds/nds_gen_data.py:91-117,155-174) and
refresh-data generation via ``--update`` (ref: nds/nds_gen_data.py:119-127).

Modes:
  local  - fan out chunk processes on this host (ref: generate_data_local,
           nds/nds_gen_data.py:183-244)
  dist   - fan out chunk ranges across pod hosts over ssh (the role the
           Hadoop MR wrapper GenTable.java plays in the reference); hosts come
           from --hosts or $NDS_HOSTS (comma-separated). Falls back to local
           when no host list is given.
"""

import argparse
import os
import shutil
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nds_tpu.check import (  # noqa: E402
    check_build_ndsgen,
    check_version,
    get_abs_path,
    get_dir_size,
    parallel_value,
    valid_range,
)
from nds_tpu.schema import MAINTENANCE_TABLE_NAMES, SOURCE_TABLE_NAMES  # noqa: E402

check_version()


def _tool_cmd(tool_path, args, child):
    """Build one chunk command line for whichever generator is installed."""
    if tool_path.name == "dsdgen":
        # spec toolkit surface (ref: nds/nds_gen_data.py:211-220)
        cmd = ["./dsdgen", "-scale", args.scale, "-dir", args._out_dir,
               "-parallel", str(args.parallel), "-child", str(child), "-verbose", "Y"]
        if args.overwrite_output:
            cmd += ["-force", "Y"]
        if args.update:
            cmd += ["-update", args.update]
        return cmd, str(tool_path.parent)
    cmd = [str(tool_path), "-scale", args.scale, "-dir", args._out_dir,
           "-parallel", str(args.parallel), "-child", str(child)]
    if args.update:
        cmd += ["-update", args.update]
    if args.rngseed:
        cmd += ["-rngseed", args.rngseed]
    return cmd, None


def _table_names(args):
    return list(MAINTENANCE_TABLE_NAMES) if args.update else list(SOURCE_TABLE_NAMES)


def _move_into_table_dirs(data_dir, parallel, range_start, range_end, tables):
    """Land flat chunk files in per-table subdirectories
    (ref: nds/nds_gen_data.py:229-243)."""
    for table in tables:
        tdir = os.path.join(data_dir, table)
        os.makedirs(tdir, exist_ok=True)
        candidates = [f"{table}.dat", f"{table}_1.dat"]
        candidates += [f"{table}_{i}_{parallel}.dat" for i in range(range_start, range_end + 1)]
        for fname in candidates:
            src = os.path.join(data_dir, fname)
            if os.path.exists(src):
                shutil.move(src, os.path.join(tdir, fname))


def move_delete_date_tables(data_dir, update):
    """delete_<n>.dat / inventory_delete_<n>.dat land in their own dirs
    (ref: nds/nds_gen_data.py:119-127)."""
    for table in ("delete", "inventory_delete"):
        tdir = os.path.join(data_dir, table)
        os.makedirs(tdir, exist_ok=True)
        fname = f"{table}_{update}.dat"
        src = os.path.join(data_dir, fname)
        if os.path.exists(src):
            shutil.move(src, os.path.join(tdir, fname))


def merge_temp_tables(temp_dir, data_dir, tables):
    """Merge an incremental --range generation out of the temp dir into the
    final location (ref: nds/nds_gen_data.py:91-117)."""
    for table in tables:
        src_dir = os.path.join(temp_dir, table)
        if not os.path.isdir(src_dir):
            continue
        dst_dir = os.path.join(data_dir, table)
        os.makedirs(dst_dir, exist_ok=True)
        for f in os.listdir(src_dir):
            shutil.move(os.path.join(src_dir, f), os.path.join(dst_dir, f))
    shutil.rmtree(temp_dir, ignore_errors=True)


def _run_chunks(args, tool_path, range_start, range_end):
    procs = []
    for child in range(range_start, range_end + 1):
        cmd, cwd = _tool_cmd(tool_path, args, child)
        procs.append(subprocess.Popen(cmd, cwd=cwd))
    failed = [p for p in procs if p.wait() != 0]
    if failed:
        raise RuntimeError(f"{len(failed)} generator chunk(s) failed")


def _split_ranges(lo, hi, n):
    """Split inclusive child range [lo, hi] into n contiguous sub-ranges."""
    total = hi - lo + 1
    out = []
    start = lo
    for i in range(n):
        size = total // n + (1 if i < total % n else 0)
        if size == 0:
            continue
        out.append((start, start + size - 1))
        start += size
    return out


def generate_data_dist(args, tool_path, range_start, range_end):
    """Distributed generation: one ssh subprocess per pod host, each covering
    a contiguous child sub-range and writing to the shared data_dir. This is
    the framework's stand-in for the reference's one-command-per-mapper MR job
    (ref: nds/tpcds-gen/src/main/java/org/notmysock/tpcds/GenTable.java:188-209)."""
    hosts = args.hosts or os.environ.get("NDS_HOSTS", "")
    host_list = [h.strip() for h in hosts.split(",") if h.strip()]
    if not host_list:
        print("no host list for dist mode; running locally")
        return generate_data_local(args, tool_path, range_start, range_end)
    data_dir = _prepare_out_dir(args)

    # native runner (C++ host fan-out with retry, the MR wrapper's role;
    # native/ndsrun); the Python fan-out below is the fallback. Always
    # (re)built from the checked-in source — an opaque prebuilt binary is
    # never executed (it could silently drift from ndsrun.cc, and this
    # path goes on to ssh-exec on remote hosts).
    ndsrun_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "native", "ndsrun")
    ndsrun = os.path.join(ndsrun_dir, "ndsrun")
    ndsrun_ok = False
    if not os.environ.get("NDS_NO_NDSRUN"):
        try:
            build = subprocess.run(["make", "-C", ndsrun_dir],
                                   capture_output=True, text=True)
            err = ((build.stderr.strip() or f"make exited {build.returncode}")
                   if build.returncode else "")
        except OSError as e:              # no make on this host
            err = str(e)
        if err:
            # a failed build must NOT fall back to a stale binary — that
            # would ssh-exec code that no longer matches ndsrun.cc
            print(f"ndsrun build failed, using Python fan-out:\n{err}")
        else:
            ndsrun_ok = os.path.exists(ndsrun)
    if ndsrun_ok:
        cmd = [ndsrun, "-hosts", ",".join(host_list), "-scale", args.scale,
               "-parallel", str(args.parallel), "-dir", data_dir,
               "-range", f"{range_start},{range_end}",
               "-driver", os.path.abspath(__file__),
               "-python", sys.executable]
        if args.update:
            cmd += ["-update", args.update]
        if args.rngseed:
            cmd += ["-rngseed", args.rngseed]
        if args.overwrite_output:
            cmd += ["-overwrite"]
        subprocess.run(cmd, check=True)
        print(f"distributed generation complete across {len(host_list)} "
              f"hosts -> {data_dir}")
        return

    def spawn(host, lo, hi):
        sub = [sys.executable, os.path.abspath(__file__), "local",
               args.scale, str(args.parallel), get_abs_path(args.data_dir),
               "--range", f"{lo},{hi}"]
        if args.update:
            sub += ["--update", args.update]
        if args.overwrite_output:
            sub += ["--overwrite_output"]
        if args.rngseed:
            sub += ["--rngseed", args.rngseed]
        return subprocess.Popen(["ssh", host] + sub)

    spans = _split_ranges(range_start, range_end, len(host_list))
    procs = [(h, lo, hi, spawn(h, lo, hi))
             for h, (lo, hi) in zip(host_list, spans)]
    # failure recovery (the MR wrapper retries failed map tasks,
    # ref: GenTable.java mapreduce defaults): a failed host's chunk range
    # is re-run on a surviving host rather than aborting the whole run
    failed_spans, ok_hosts = [], []
    for host, lo, hi, p in procs:
        if p.wait() != 0:
            print(f"host {host} failed for range {lo},{hi}; will retry")
            failed_spans.append((lo, hi))
        else:
            ok_hosts.append(host)
    for attempt in range(2):
        if not failed_spans:
            break
        if not ok_hosts:
            raise RuntimeError(
                "distributed generation failed on every host")
        retry = [(ok_hosts[i % len(ok_hosts)], lo, hi)
                 for i, (lo, hi) in enumerate(failed_spans)]
        failed_spans = []
        rps = [(h, lo, hi, spawn(h, lo, hi)) for h, lo, hi in retry]
        for host, lo, hi, p in rps:
            if p.wait() != 0:
                print(f"retry on {host} failed for range {lo},{hi}")
                failed_spans.append((lo, hi))
    if failed_spans:
        raise RuntimeError(
            f"ranges still failing after retries: {failed_spans}")
    print(f"distributed generation complete across {len(host_list)} hosts "
          f"-> {data_dir}")


def _prepare_out_dir(args):
    data_dir = get_abs_path(args.data_dir)
    if not os.path.isdir(data_dir):
        os.makedirs(data_dir)
    elif get_dir_size(data_dir) > 0 and not args.overwrite_output and not args.range \
            and not args.update:
        raise RuntimeError(
            f"There's already data in {data_dir}. Use --overwrite_output to overwrite.")
    return data_dir


def generate_data_local(args, tool_path, range_start, range_end):
    data_dir = _prepare_out_dir(args)
    tables = _table_names(args)
    if args.range:
        # incremental generation goes through a per-range temp dir then
        # merges; the range suffix keeps concurrent hosts from clobbering each
        # other's in-flight chunks (ref: nds/nds_gen_data.py:155-174)
        temp_dir = os.path.join(data_dir, f"_temp_{range_start}_{range_end}")
        shutil.rmtree(temp_dir, ignore_errors=True)
        os.makedirs(temp_dir)
        args._out_dir = temp_dir
        _run_chunks(args, tool_path, range_start, range_end)
        _move_into_table_dirs(temp_dir, args.parallel, range_start, range_end, tables)
        if args.update:
            move_delete_date_tables(temp_dir, args.update)
        merge_temp_tables(temp_dir, data_dir, tables)
    else:
        args._out_dir = data_dir
        _run_chunks(args, tool_path, range_start, range_end)
        _move_into_table_dirs(data_dir, args.parallel, range_start, range_end, tables)
        if args.update:
            move_delete_date_tables(data_dir, args.update)
    subprocess.run(["du", "-h", "-d1", data_dir], check=False)


def generate_data(args):
    tool_path = check_build_ndsgen()
    range_start, range_end = 1, int(args.parallel)
    if args.range:
        range_start, range_end = valid_range(args.range, args.parallel)
    if args.type == "dist":
        generate_data_dist(args, tool_path, range_start, range_end)
    else:
        generate_data_local(args, tool_path, range_start, range_end)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("type", choices=["local", "dist"],
                        help="where to run generation: this host, or across pod hosts")
    parser.add_argument("scale", help="volume of data to generate in GB")
    parser.add_argument("parallel", type=parallel_value,
                        help="build data in <parallel_value> separate chunks")
    parser.add_argument("data_dir", help="generate data in directory")
    parser.add_argument("--range",
                        help="incremental generation: which child chunks to build in this "
                             "run, format 'start,end' inclusive within --parallel")
    parser.add_argument("--overwrite_output", action="store_true",
                        help="overwrite existing data in the output path")
    parser.add_argument("--update",
                        help="generate refresh dataset <n> for the Data Maintenance tests")
    parser.add_argument("--hosts", help="comma-separated pod host list for dist mode")
    parser.add_argument("--rngseed", help="random seed for the native generator")
    args = parser.parse_args()
    generate_data(args)
