#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Query-stream generation driver.

TPU-build equivalent of the reference stream-gen CLI (ref:
nds/nds_gen_query_stream.py): emits one specific query (--template) or N
permuted 99-query streams (--streams) in dsqgen's output format, using the
packaged Spark-dialect templates in nds_tpu/queries/templates (the role the
user-downloaded TPC-DS toolkit's query_templates + templates.lst play for
the reference).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nds_tpu.check import check_version, get_abs_path  # noqa: E402
from nds_tpu.queries import TEMPLATE_DIR, generate_query_streams  # noqa: E402

check_version()


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("template_dir",
                        nargs="?",
                        default=TEMPLATE_DIR,
                        help="directory to find query templates; defaults to "
                        "the packaged template corpus.")
    parser.add_argument("scale",
                        help="assume a database of this scale factor.")
    parser.add_argument("output_dir",
                        help="generate query stream(s) in this directory.")
    group = parser.add_mutually_exclusive_group(required=True)
    group.add_argument("--template",
                       help="generate a specific query from a template, e.g. "
                       "'query3.tpl'. Note: query14/23/24/39 contain two "
                       "queries and are written as _part1/_part2 files.")
    group.add_argument("--streams",
                       help="generate how many query streams.")
    parser.add_argument("--rngseed",
                        help="seed the random generation of the queries.")
    args = parser.parse_args()

    template_dir = None
    if args.template_dir != TEMPLATE_DIR:
        template_dir = get_abs_path(args.template_dir)
    generate_query_streams(
        get_abs_path(args.output_dir),
        streams=int(args.streams) if args.streams else None,
        template=args.template,
        rngseed=int(args.rngseed) if args.rngseed else None,
        template_dir=template_dir,
        scale=float(args.scale))
