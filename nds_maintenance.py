#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Data Maintenance driver.

TPU-build equivalent of the reference maintenance CLI (ref:
nds/nds_maintenance.py:40-319): registers the refresh (``s_*``) CSVs as temp
views, loads the LF_*/DF_* refresh functions, substitutes the DATE1/DATE2
placeholders from the generated ``delete``/``inventory_delete`` tables, runs
each function against the snapshot warehouse under a BenchReport, and writes
the CSV time log (seconds) + per-query JSON summaries.
"""

import argparse
import csv
import os
import sys
from datetime import datetime

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nds_tpu.check import check_version, check_json_summary_folder, \
    get_abs_path  # noqa: E402

check_version()

INSERT_FUNCS = [
    'LF_CR',
    'LF_CS',
    'LF_I',
    'LF_SR',
    'LF_SS',
    'LF_WR',
    'LF_WS']
DELETE_FUNCS = [
    'DF_CS',
    'DF_SS',
    'DF_WS']
INVENTORY_DELETE_FUNC = ['DF_I']
DM_FUNCS = INSERT_FUNCS + DELETE_FUNCS + INVENTORY_DELETE_FUNC


def get_delete_date(session):
    """Delete-date tuples for the DELETE functions, from the generated
    ``delete``/``inventory_delete`` tables (ref: nds/nds_maintenance.py:60-73)."""
    date_dict = {}
    for key, table in (("delete", "delete"),
                       ("inventory_delete", "inventory_delete")):
        rows = session.sql(f"select * from `{table}`").collect()
        date_dict[key] = [(str(r[0]), str(r[1])) for r in rows]
    return date_dict


def replace_date(query_list, date_tuple_list):
    """Apply each (date1, date2) tuple to the DELETE statements, earlier date
    first (ref: nds/nds_maintenance.py:75-96)."""
    q_updated = []
    for date_tuple in date_tuple_list:
        earlier, later = sorted(date_tuple)
        for q in query_list:
            q_updated.append(q.replace("DATE1", earlier).replace("DATE2", later))
    return q_updated


def get_valid_query_names(spec_queries):
    if spec_queries:
        for q in spec_queries:
            if q not in DM_FUNCS:
                raise Exception(f"invalid Data Maintenance query: {q}. "
                                f"Valid are: {DM_FUNCS}")
        return spec_queries
    return DM_FUNCS


def split_statements(text: str):
    """Split a refresh-function file into executable statements, dropping
    comment lines and empty fragments."""
    lines = [ln for ln in text.splitlines() if not ln.lstrip().startswith("--")]
    statements = []
    for frag in "\n".join(lines).split(";"):
        frag = frag.strip()
        if frag:
            statements.append(frag + ";")
    return statements


def get_maintenance_queries(session, folder, valid_queries):
    """Load refresh-function statement lists, with DATE substitution for the
    delete functions (ref: nds/nds_maintenance.py:121-147)."""
    delete_date_dict = get_delete_date(session)
    folder_abs_path = get_abs_path(folder)
    q_dict = {}
    for q in valid_queries:
        with open(os.path.join(folder_abs_path, q + '.sql')) as f:
            q_content = split_statements(f.read())
        if q in DELETE_FUNCS:
            # 3 date tuples per DELETE function (TPC-DS spec 5.3.11)
            q_content = replace_date(q_content, delete_date_dict['delete'])
        if q in INVENTORY_DELETE_FUNC:
            q_content = replace_date(q_content,
                                     delete_date_dict['inventory_delete'])
        q_dict[q] = q_content
    return q_dict


def run_dm_query(session, query_list, query_name):
    for q in query_list:
        session.sql(q)


def run_query(session, query_dict, time_log_output_path, json_summary_folder,
              property_file):
    """Run every maintenance function under a BenchReport and write the time
    log in seconds (ref: nds/nds_maintenance.py:207-268)."""
    from nds_tpu.report import BenchReport

    execution_time_list = []
    check_json_summary_folder(json_summary_folder)
    total_time_start = datetime.now()
    app_id = session.app_id
    DM_start = datetime.now()
    for query_name, q_content in query_dict.items():
        print(f"====== Run {query_name} ======")
        q_report = BenchReport(session)
        elapsed_ms = q_report.report_on(run_dm_query, session, q_content,
                                        query_name)
        print(f"Time taken: {elapsed_ms} millis for {query_name}")
        execution_time_list.append((app_id, query_name, elapsed_ms / 1000.0))
        if json_summary_folder:
            if property_file:
                summary_prefix = os.path.join(
                    json_summary_folder,
                    os.path.basename(property_file).split('.')[0])
            else:
                summary_prefix = os.path.join(json_summary_folder, '')
            q_report.write_summary(query_name, prefix=summary_prefix)
    DM_end = datetime.now()
    DM_elapse = (DM_end - DM_start).total_seconds()
    total_elapse = (DM_end - total_time_start).total_seconds()
    print(f"====== Data Maintenance Start Time: {DM_start}")
    print(f"====== Data Maintenance Time: {DM_elapse} s ======")
    print(f"====== Total Time: {total_elapse} s ======")
    execution_time_list.append((app_id, "Data Maintenance Start Time", DM_start))
    execution_time_list.append((app_id, "Data Maintenance End Time", DM_end))
    execution_time_list.append((app_id, "Data Maintenance Time", DM_elapse))
    execution_time_list.append((app_id, "Total Time", total_elapse))

    header = ["application_id", "query", "time/s"]
    with open(time_log_output_path, 'w', encoding='UTF8') as f:
        writer = csv.writer(f)
        writer.writerow(header)
        writer.writerows(execution_time_list)


def register_warehouse_tables(session, warehouse):
    """Attach the warehouse and register its current snapshots as views."""
    from nds_tpu.engine.column import from_arrow
    session.warehouse = warehouse
    for table in warehouse.tables():
        session.create_temp_view(table, from_arrow(warehouse.read(table)),
                                 base=True)


def register_temp_views(session, refresh_data_path):
    """Register the refresh CSVs as temp views
    (ref: nds/nds_maintenance.py:270-274)."""
    from nds_tpu.schema import get_maintenance_schemas
    refresh_tables = get_maintenance_schemas(True)
    for table, fields in refresh_tables.items():
        for path in (os.path.join(refresh_data_path, table),
                     os.path.join(refresh_data_path, table + ".dat")):
            if os.path.exists(path):
                session.read_raw_view(table, path, fields)
                break
        else:
            raise FileNotFoundError(
                f"refresh table {table} not found under {refresh_data_path}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument('warehouse_path',
                        help='warehouse path for Data Maintenance test.')
    parser.add_argument('refresh_data_path',
                        help='path to refresh data')
    parser.add_argument('maintenance_queries_folder',
                        help='folder contains all NDS Data Maintenance '
                        'queries. If "--maintenance_queries" is not set, all '
                        'queries under the folder will be executed.')
    parser.add_argument('time_log',
                        help='path to execution time log, only support local '
                        'path.',
                        default="")
    parser.add_argument('--maintenance_queries',
                        type=lambda s: s.split(','),
                        help='specify Data Maintenance query names by a '
                        'comma separated string. e.g. "LF_CR,LF_CS"')
    parser.add_argument('--property_file',
                        help='property file for engine configuration.')
    parser.add_argument('--json_summary_folder',
                        help='empty folder/path to save JSON summary files.')
    parser.add_argument('--warehouse_type',
                        choices=['iceberg', 'delta'],
                        default='iceberg',
                        help='type of the warehouse used for Data '
                        'Maintenance test (kept for reference CLI parity; '
                        'both map to the snapshot warehouse).')
    parser.add_argument('--device',
                        choices=['tpu', 'cpu'],
                        default='tpu',
                        help='execution device.')
    args = parser.parse_args()

    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")

    from nds_tpu.engine.session import Session  # noqa: E402
    from nds_tpu.warehouse import Warehouse  # noqa: E402

    valid_queries = get_valid_query_names(args.maintenance_queries)
    session = Session()
    warehouse = Warehouse(args.warehouse_path)
    register_warehouse_tables(session, warehouse)
    register_temp_views(session, args.refresh_data_path)
    query_dict = get_maintenance_queries(session,
                                         args.maintenance_queries_folder,
                                         valid_queries)
    run_query(session, query_dict, args.time_log, args.json_summary_folder,
              args.property_file)
