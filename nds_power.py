#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Power Run driver.

TPU-build equivalent of the reference Power Run CLI (ref: nds/nds_power.py:
332-410): runs a generated query stream against the columnar device engine,
recording per-query times to a CSV log and JSON summaries, with the same
argument surface plus a ``--device`` switch (the north star's
``power_run_tpu.template`` contract: same driver, TPU execution).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nds_tpu.check import check_version  # noqa: E402

check_version()


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("input_prefix",
                        help="text to prepend to every input file path; the "
                        "warehouse root for iceberg/delta input formats.")
    parser.add_argument("query_stream_file",
                        help="query stream file that contains NDS queries in "
                        "specific order.")
    parser.add_argument("time_log",
                        nargs="?",
                        help="path to execution time log.",
                        default="")
    parser.add_argument("--input_format",
                        choices=["parquet", "orc", "avro", "csv", "json",
                                 "iceberg", "delta"],
                        default="parquet",
                        help="type for input data source "
                        "(ref: nds/nds_power.py:357-364).")
    parser.add_argument("--output_prefix",
                        help="text to prepend to every output file.")
    parser.add_argument("--output_format",
                        default="parquet",
                        help="type of query output.")
    parser.add_argument("--property_file",
                        help="property file for engine configuration.")
    parser.add_argument("--floats",
                        action="store_true",
                        help="use double instead of decimal for monetary "
                        "columns when loading text data.")
    parser.add_argument("--json_summary_folder",
                        help="empty folder/path to save JSON summary files.")
    parser.add_argument("--extra_time_log",
                        help="extra path to save time log (cloud copy).")
    parser.add_argument("--sub_queries",
                        type=lambda s: [x.strip() for x in s.split(",")],
                        help="comma separated list of queries to run, e.g. "
                        "'query1,query2'. Use _part1/_part2 suffixes for "
                        "query14/23/24/39.")
    parser.add_argument("--allow_failure",
                        action="store_true",
                        help="do not exit non-zero when a query fails.")
    parser.add_argument("--device",
                        choices=["tpu", "cpu"],
                        default="tpu",
                        help="execution device; 'cpu' pins the engine to the "
                        "host platform (useful for baseline/validation runs).")
    parser.add_argument("--profile",
                        help="folder for per-query device profiler traces "
                        "(XProf/TensorBoard dumps).")
    parser.add_argument("--trace-dir",
                        help="folder for per-query Chrome trace_event JSON "
                        "files from the engine's span tracer (load in "
                        "chrome://tracing or Perfetto; aggregate with "
                        "tools/trace_report.py). Zero added host syncs.")
    parser.add_argument("--ledger",
                        help="campaign evidence ledger file (append-only "
                        "JSONL, nds_tpu/obs/ledger.py): one validated "
                        "record per query, flushed as it lands, plus a "
                        "terminal end record — the input to "
                        "tools/bench_compare.py. Also via NDS_TPU_LEDGER.")
    parser.add_argument("--warm",
                        action="store_true",
                        help="precompile pass: execute the stream once to "
                        "populate the persistent XLA compile cache (the "
                        "warmed-JVM analog); the time log is written with "
                        "Warm markers so it can never be mistaken for an "
                        "official Power Run.")
    args = parser.parse_args()

    if args.device == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")

    from nds_tpu.power import gen_sql_from_stream, run_query_stream  # noqa: E402

    query_dict = gen_sql_from_stream(args.query_stream_file)
    run_query_stream(args.input_prefix,
                     args.property_file,
                     query_dict,
                     args.time_log,
                     args.extra_time_log,
                     args.sub_queries,
                     args.input_format,
                     not args.floats,
                     args.output_prefix,
                     args.output_format,
                     args.json_summary_folder,
                     args.allow_failure,
                     profile_folder=args.profile,
                     warm=args.warm,
                     trace_dir=args.trace_dir,
                     ledger_path=args.ledger)
