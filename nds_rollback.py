#!/usr/bin/env python3
# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Rollback utility: undo Data Maintenance via warehouse time travel.

TPU-build equivalent of the reference Iceberg rollback CLI (ref:
nds/nds_rollback.py:37-59): restores the 6 DM-affected fact tables to their
last snapshot at-or-before a timestamp (the
``system.rollback_to_timestamp`` role).
"""

import argparse
import os
import sys
from datetime import datetime

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the 6 fact tables touched by Data Maintenance (ref: nds/nds_rollback.py:37)
tables_to_rollback = [
    'catalog_sales',
    'catalog_returns',
    'inventory',
    'store_returns',
    'store_sales',
    'web_returns',
    'web_sales']


def rollback(warehouse_path: str, timestamp: str) -> None:
    from nds_tpu.warehouse import Warehouse
    ts_ms = int(datetime.strptime(timestamp,
                                  "%Y-%m-%d %H:%M:%S").timestamp() * 1000)
    warehouse = Warehouse(warehouse_path)
    for table in tables_to_rollback:
        if not warehouse.exists(table):
            print(f"skip {table}: not in warehouse")
            continue
        snap_id = warehouse.rollback_to_timestamp(table, ts_ms)
        print(f"rolled back {table} to snapshot {snap_id}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument('warehouse_path',
                        help='warehouse root the Data Maintenance test ran '
                        'against.')
    parser.add_argument('timestamp',
                        help="timestamp to rollback to, e.g. '2026-07-29 "
                        "09:50:00'. Usually the time before a Data "
                        "Maintenance test.")
    args = parser.parse_args()
    rollback(args.warehouse_path, args.timestamp)
