# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""nds-tpu: a TPU-native decision-support (TPC-DS derived) benchmark framework.

Rebuilds the capabilities of the NDS v2.0 harness (spark-rapids-benchmarks)
on a JAX/XLA/Pallas stack: columnar execution on TPU HBM, pjit/shard_map
partitioning over a device mesh, and ICI all-to-all exchange in place of the
network shuffle. See SURVEY.md at the repo root for the structural map of the
reference this build follows.
"""

__version__ = "0.1.0"

# The engine's exact-decimal path is int64 fixed point and date arithmetic is
# 64-bit; x64 must be on before any jax array is created.
import os as _os  # noqa: E402

import jax as _jax  # noqa: E402

_jax.config.update("jax_enable_x64", True)

_comp_cache_enabled = False


def enable_compile_cache() -> bool:
    """Enable the persistent XLA compilation cache (idempotent).

    A Power Run compiles ~100 query pipelines; caching them across processes
    is the TPU analog of the reference's warmed JVM (ref: nds/README.md
    Power Run notes). Called lazily from Session creation, when the backend
    is resolved: CPU is excluded because XLA:CPU AOT reload is
    machine-feature sensitive (SIGILL risk) and the CPU platform only backs
    tests — NDS_TPU_COMP_CACHE=force opts CPU in anyway (same-machine dev
    loops like the coverage sweep); NDS_TPU_NO_COMP_CACHE disables entirely.
    """
    global _comp_cache_enabled
    if _comp_cache_enabled or _os.environ.get("NDS_TPU_NO_COMP_CACHE"):
        return _comp_cache_enabled
    try:
        if _os.environ.get("NDS_TPU_COMP_CACHE") != "force" and \
                _jax.default_backend() == "cpu":
            return False
        # CPU cache dirs are keyed by a machine fingerprint: XLA:CPU AOT
        # artifacts bake the compile host's vector ISA, and loading one on
        # a host without those features segfaults/SIGILLs mid-run (seen:
        # a cross-machine cache killed a 103-query sweep at query 81)
        suffix = ""
        if _jax.default_backend() == "cpu":
            import hashlib
            import platform
            try:
                with open("/proc/cpuinfo") as f:
                    flags = [ln for ln in f if ln.startswith("flags")][0]
            except (OSError, IndexError):  # pragma: no cover - non-Linux
                flags = platform.processor()
            suffix = "_cpu_" + hashlib.sha1(
                flags.encode()).hexdigest()[:12]
        _cache_dir = _os.environ.get(
            "NDS_TPU_COMP_CACHE_DIR",
            _os.path.join(_os.path.expanduser("~"), ".cache",
                          f"nds_tpu_xla{suffix}"))
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        # eager table-at-a-time execution makes many small compilations, so
        # cache everything (the default 1s floor would skip nearly all of it)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _comp_cache_enabled = True
    except Exception:  # pragma: no cover - cache is best-effort
        pass
    return _comp_cache_enabled
