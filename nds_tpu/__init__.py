# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""nds-tpu: a TPU-native decision-support (TPC-DS derived) benchmark framework.

Rebuilds the capabilities of the NDS v2.0 harness (spark-rapids-benchmarks)
on a JAX/XLA/Pallas stack: columnar execution on TPU HBM, pjit/shard_map
partitioning over a device mesh, and ICI all-to-all exchange in place of the
network shuffle. See SURVEY.md at the repo root for the structural map of the
reference this build follows.
"""

__version__ = "0.1.0"

# The engine's exact-decimal path is int64 fixed point and date arithmetic is
# 64-bit; x64 must be on before any jax array is created.
import jax as _jax  # noqa: E402

_jax.config.update("jax_enable_x64", True)
