# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Static analysis over the query corpus and the engine/driver code.

The reference harness leans on Spark's analyzer to reject bad plans before
execution; this package is the TPU build's equivalent, run entirely on host
with no device in the loop:

* :mod:`nds_tpu.analysis.plan_audit` — walks the parsed AST of every query
  template against the :mod:`nds_tpu.schema` catalog: column resolution
  (mirroring the planner's ``alias.column`` suffix-match scoping), dtype
  compatibility of comparisons/joins/aggregate arguments, join-graph
  connectivity (true cartesians), unknown functions, window/grouping misuse.
* :mod:`nds_tpu.analysis.jax_lint` — a Python-``ast`` lint for JAX hazards in
  ``nds_tpu/``: host syncs inside hot-path loops, Python ``if`` on
  tracer-valued parameters, unhashable/unbounded jit-cache keys,
  ``time.time()`` inside jitted regions.
* :mod:`nds_tpu.analysis.exec_audit` — abstract interpreter over the
  planner's decomposition: execution-path classification (compiled-stream
  / eager-fallback / device-resident) and static host-sync bounds.
* :mod:`nds_tpu.analysis.mem_audit` — per-statement peak-HBM byte bounds
  and the stream-accumulator proofs ``engine/stream.py`` sizes from.
* :mod:`nds_tpu.analysis.perf_audit` — the static byte/roofline cost
  model over the same decomposition: exact h2d upload bytes (the padded
  encoded-chunk closed form), per-stage HBM traffic, sharded ICI wire
  bytes from the collective-budget shapes, the fused-kernel launch band,
  and a roofline lower-bound wall with a ranked bottleneck tag per
  statement. Exactness is differentially checked against runtime
  ``StreamEvent`` byte evidence by ``tools/perf_audit_diff.py``.
* :mod:`nds_tpu.analysis.driver_audit` — driver-level hygiene for the
  top-level CLIs and ``tools/``: swallowed exceptions, shell-injection
  surfaces, file handles opened outside context managers.
* :mod:`nds_tpu.analysis.conc_audit` — shared-state/lock-discipline
  audit over the whole package: inventories every module/class-level
  mutable object, classifies each mutation site (lock-guarded /
  thread-local / bounded-ring / atomic-rebind / unguarded), enforces
  the no-sync-no-compile-under-lock and lock-order rules, and checks
  cache-key completeness (every env knob reachable from a cached
  computation appears in its key). Runtime half:
  ``tools/conc_audit_diff.py``'s threaded stress differential.
* :mod:`nds_tpu.analysis.num_audit` — value-range/precision abstract
  interpreter over the same decomposition: proves per statement that
  every FOR/dict codec fits its priced narrow width, every encoded
  compare's ``lit - base`` rebase and kernel threshold stays in int64,
  no SUM/COUNT/AVG accumulator exceeds int64 / f64-exact-integer range
  through join fan-out, decimal scale is preserved exactly, and the
  hash partition+shard route bits fit the mixed 32-bit width — plus
  executable versions of the numeric-safety claims written as comments
  in ``io/columnar.py`` and ``engine/kernels.py``. Runtime half:
  ``tools/num_audit_diff.py``'s boundary-value differential.
* :mod:`nds_tpu.analysis.param_audit` — literal-bindability prover over
  the same decomposition: classifies every literal occurrence BINDABLE
  (safe to ride as a jit operand of the one compiled per-chunk program
  — recorded graph, chunk shapes, codec selection, partition counts,
  residual keys and stream bounds all value-invariant) or FOLD-REQUIRED
  with a machine-readable reason, derives per-template parameter
  signatures with proven safe value domains, and exports the shared
  rule (``conjunct_bind_slots`` / ``skeleton_conjunct_key``) that
  ``engine/stream.py`` uses to canonicalize the pipeline-cache key so
  K parameter vectors share one compile. Runtime half:
  ``tools/param_audit_diff.py``'s one-compile-many-params differential.

``tools/lint.py`` runs all nine and gates on new findings against the
checked-in :data:`BASELINE_PATH` (accepted pre-existing findings); code-lint
findings are suppressible in-source with ``# nds-lint: ignore[rule]``.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import asdict, dataclass

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    """One analyzer finding. ``query`` is the query/template name for plan
    findings and the enclosing scope (function or ``<module>``) for code
    findings; ``line`` is advisory (0 for plan findings, which carry no
    source positions) and excluded from baseline identity so unrelated
    edits don't churn the baseline."""

    file: str
    query: str
    rule: str
    severity: str
    message: str
    line: int = 0

    def key(self) -> str:
        return f"{self.file}::{self.query}::{self.rule}::{self.message}"

    def to_dict(self) -> dict:
        return asdict(self)

    def __str__(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        return f"{loc} [{self.query}] {self.severity} {self.rule}: {self.message}"


_SUPPRESS_RE = re.compile(r"#\s*nds-lint:\s*ignore(?:\[([\w\-, ]*)\])?")


def suppressed(source_lines: list, lineno: int, rule: str) -> bool:
    """True when ``# nds-lint: ignore[rule]`` (or a bare ``ignore``) appears
    on the flagged line, or on a comment-ONLY line directly above it (a
    trailing comment on the previous statement suppresses only that
    statement). ``lineno`` is 1-based, as in ``ast`` nodes."""
    for ln in (lineno, lineno - 1):
        if not 1 <= ln <= len(source_lines):
            continue
        text = source_lines[ln - 1]
        if ln != lineno and not text.lstrip().startswith("#"):
            continue
        m = _SUPPRESS_RE.search(text)
        if m:
            rules = m.group(1)
            if rules is None:
                return True
            if rule in {r.strip() for r in rules.split(",")}:
                return True
    return False


def load_baseline(path: str | None = None) -> dict:
    """Baseline as ``{finding key: accepted count}``; {} when absent."""
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    return dict(doc.get("keys", {}))

def write_baseline(findings, path: str | None = None) -> None:
    keys: dict = {}
    for f in findings:
        keys[f.key()] = keys.get(f.key(), 0) + 1
    doc = {"version": 1,
           "note": ("Accepted pre-existing findings; tools/lint.py fails "
                    "only on findings NOT covered here. Regenerate with "
                    "tools/lint.py --update-baseline after review."),
           "keys": dict(sorted(keys.items()))}
    with open(path or BASELINE_PATH, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def diff_against_baseline(findings, baseline: dict) -> list:
    """Findings not covered by the baseline. A baseline entry absorbs up to
    its accepted COUNT of identical keys, so a second instance of an
    accepted hazard in the same scope still fails the gate."""
    remaining = dict(baseline)
    new = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    return new
