# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Concurrency auditor: shared-state inventory + lock-discipline lint.

The serving front (ROADMAP item 5) runs concurrent query streams through
ONE process: the pipeline cache, the expression-fusion caches, the mesh
cache, the listener and the span tracer are all shared mutable state on
the query path. This pass is the static half of the concurrency
contract (the runtime half is ``tools/conc_audit_diff.py``'s threaded
stress differential): it inventories every module-level and class-level
mutable object in ``nds_tpu/`` plus every ``threading.local``/``Lock``,
classifies each mutation site, and enforces the lock discipline the
engine's caches follow. Python-``ast`` based like ``jax_lint``; no JAX
import, no device. Suppressible in-source with
``# nds-lint: ignore[rule]``.

State classification — every mutation site of a module/class-level
object must fall into one of the ACCEPTED classes:

* **lock-guarded** — the mutation is lexically dominated by a
  ``with <lock>`` on a module/class-level ``threading.Lock``/``RLock``,
  and every other guarded mutation of the same state uses the SAME lock
  (a lock dedicated to that state — two locks "guarding" one dict is a
  race with extra steps). Aliasing through plain parameters is resolved
  like ``jax_lint``'s cache rules: ``_identity_cache(cache, ...)`` /
  ``_fused_run(cache, ...)`` mutation sites count against the module
  global each call site passes in, carrying the callee's guard.
* **thread-local** — an attribute store on a module-level
  ``threading.local()``: per-thread by construction (the sync counters,
  span rings, StreamEvent rings).
* **bounded-evidence-ring** — ``append``/``appendleft``/``clear`` on a
  module/class-level ``deque(maxlen=...)`` (the listener's
  ``unattributed`` pattern): GIL-atomic single-op mutations of a bounded
  diagnostics ring; a torn multi-op invariant cannot exist because there
  is no multi-op invariant.
* **atomic-rebind** — a plain ``global NAME; NAME = <expr>`` rebind of a
  module scalar/flag (``_pallas_broken``, ``trace._enabled``): one
  GIL-atomic pointer store, last-writer-wins by design. An AUGMENTED
  rebind (``NAME += 1``) is a read-modify-write and stays a finding, and
  a rebind of a container that elsewhere has a dedicated lock must hold
  that lock.
* module import scope — mutations at module body level run under the
  import lock, exactly once; exempt.

Everything else is **unguarded-mutation** (error when the site is
reachable from the concurrent entry points — Planner statement
execution via ``Session.sql``, pipeline build/drive, the listener/span
drains, the throughput driver threads, the bench heartbeat — warning
otherwise).

Lock-discipline rules:

* ``mixed-guard`` — state mutated under a lock at one site and off-lock
  (or under a different lock) at another: the lock protects nothing.
* ``sync-under-lock`` — an ``ops.host_read``-family call (``host_read``,
  ``timed_read``, ``guarded_scalar_read``, ``host_sync``, ``count_int``,
  ``resolve_counts``, ``.item()``, ``.to_int()``, ``device_get``)
  lexically inside a ``with <lock>`` body, directly or one level down
  into a module-local helper: a device->host sync holds every waiter for
  a full round trip (and under GSPMD a full-mesh barrier).
* ``compile-under-lock`` — a ``jax.jit(...)`` call (or a one-level-down
  helper that makes one) inside a ``with <lock>`` body: a compile under
  ``_PIPELINE_LOCK`` would serialize every Throughput stream behind
  XLA's optimizer. The engine's pattern is claim-under-lock /
  compile-off-lock / land-under-lock (the singleflight registries).
* ``wait-under-lock`` — a blocking ``.wait()``/``.join()``/``.get()``
  inside a ``with <lock>`` body: the classic lost-wakeup/deadlock shape
  (the waiter holds the lock its waker needs).
* ``lock-order-cycle`` — the directed acquired-while-holding graph
  (lexical ``with`` nesting plus one level down through precisely
  resolved calls) contains a cycle: two threads taking the locks in
  opposite orders deadlock. Acyclic order = deadlock-free.

Cache-key completeness (the rule PR 9 established by hand for encodings
and PR 12 for the Pallas mode, now checked statically): every recognized
cache declares its key-building and value-building functions in
:data:`CACHE_REGISTRY`; every env knob (``os.environ`` read) reachable
from the value builder through the package call graph must appear in the
knob set reachable from the key expression, or be exempted by name WITH
a justification (``cache-key-missing-knob`` otherwise). A module-level
``*_CACHE``/``*_cache``-named dict mutated by key anywhere that is NOT
registered raises ``cache-unregistered`` — a new cache must declare its
contract to land, which is the "nothing stops the next PR" hook.

Import-time env freeze (``env-freeze``): a module-level constant
assigned from ``os.environ`` at import bakes the process start
environment into compiled behavior — the ``_ACC_ROWS``/``_STREAM_FANOUT``
bug class PR 6 fixed. Knobs read at build/use time (functions) are the
accepted pattern; a deliberate process-lifetime freeze (``_MIN_BUCKET``)
carries an in-source suppression with its justification.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from nds_tpu.analysis import Finding, suppressed

# ---------------------------------------------------------------------------
# matchers
# ---------------------------------------------------------------------------

# constructors whose module-level assignment is shared mutable state
_CONTAINER_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict"}
_LOCK_CTORS = {"Lock", "RLock"}
_MUTATING_METHODS = {"append", "appendleft", "extend", "add", "insert",
                     "remove", "discard", "pop", "popitem", "popleft",
                     "clear", "update", "setdefault", "sort", "reverse"}
_RING_METHODS = {"append", "appendleft", "clear"}
# ops.host_read-family: every counted device->host read funnels through
# these entry points (shared with jax_lint's shard-map/pallas rules)
_HOST_READ_FUNCS = {"host_read", "timed_read", "guarded_scalar_read",
                    "host_sync", "count_int", "resolve_counts"}

# concurrent entry points: functions the Throughput driver threads, the
# bench heartbeat, and the per-query path enter from multiple threads at
# once. Matched as (path suffix, function-name prefix); reachability is
# the call-graph closure from here.
ENTRY_POINTS = (
    ("engine/session.py", "sql"),            # Planner statement execution
    ("engine/stream.py", "stream_execute"),  # pipeline build/drive
    # the bounded prefetch ring: its worker thread runs concurrently
    # with the driver by construction. All ring state is INSTANCE-scoped
    # (one queue + stop event per ring, never module-level), handed
    # between exactly two threads through the queue's own lock —
    # workers never touch the session caches — so the inventory below
    # stays at zero findings; the runtime half is conc_audit_diff's
    # ring-liveness probe.
    ("engine/prefetch.py", ""),
    # the fault registry + recovery layer: fault_point/with_retry run on
    # every thread (drivers, ring workers, watchdog helpers). Its shared
    # state is exactly three things — the occurrence counters (ONE dict
    # under the dedicated _FAULT_LOCK), the FaultEvent ring
    # (thread-local deque(maxlen)), and the statement clock
    # (thread-local) — so the inventory stays at zero findings; the
    # runtime half is tools/fault_diff.py's injection matrix.
    ("engine/faults.py", ""),
    ("listener.py", "record_stream_event"),
    ("listener.py", "drain_stream_events"),
    ("listener.py", "report_task_failure"),
    ("listener.py", "notify_all"),
    ("obs/trace.py", "span"),
    ("obs/trace.py", "annotate"),
    ("obs/trace.py", "drain_spans"),
    ("obs/trace.py", "note_sync"),
    ("obs/trace.py", "attach"),
    ("obs/ledger.py", "beat"),               # bench heartbeat thread
    ("obs/ledger.py", "_loop"),
    # the live-metrics registry: inc/observe run on every driver thread
    # (query loop, heartbeat, admission waits) while snapshot/export
    # reads from the heartbeat thread. All counter/gauge/histogram
    # state is INSTANCE-scoped on the Registry behind its ONE dedicated
    # _lock; module level holds only import-time constants (EDGES, the
    # metric-name vocabulary) and the _DEFAULT instance binding — so the
    # whole-module inventory stays at zero findings; the runtime half is
    # conc_audit_diff's "metrics" lock probe (threaded-quantile drift).
    ("obs/metrics.py", ""),
    # the campaign driver: single-threaded BY CONTRACT — all run state
    # (manifest dict, in-flight child handle) is local to run_campaign,
    # module level holds only import-time constants (PRESETS, knob
    # tuple), and the only cross-thread surface it touches is the fault
    # registry's thread-local ring — so the whole-module inventory stays
    # at zero findings; auditing it whole pins that contract against a
    # future "parallel arms" edit quietly adding shared state.
    ("obs/campaign.py", ""),
    ("parallel/admission.py", ""),           # admission runs per stream
    ("parallel/exchange.py", "stream_mesh"),
    ("parallel/exchange.py", "exchange_join_pairs"),
)


@dataclass
class CacheSpec:
    """Registered contract of one recognized cache: which functions build
    its key and its value, which modules the value-build closure may span
    (method calls resolve by name inside this set only — the planner
    drives ops/kernels/exprs through instance methods the call graph
    cannot type), and which reachable knobs are deliberately NOT key
    members, each with its justification."""

    key_fns: tuple
    builder_fns: tuple
    modules: tuple                      # path suffixes the closure spans
    exempt: dict = field(default_factory=dict)   # knob -> justification
    identity_keyed: bool = False        # value derives from keyed arrays
    #                                     alone: env-exempt by design


# the engine's module-set for caches whose value is a traced program of
# planner/engine code (the pipeline and the fusion caches)
_ENGINE_MODULES = ("engine/stream.py", "sql/planner.py", "engine/ops.py",
                   "engine/kernels.py", "engine/exprs.py",
                   "engine/column.py", "engine/table.py",
                   "engine/window.py", "parallel/exchange.py",
                   "analysis/mem_audit.py", "analysis/kernel_spec.py",
                   "io/columnar.py")

# knobs that are deliberately not pipeline-key members; every entry is a
# reviewed claim the stress differential can falsify
_PIPELINE_EXEMPT = {
    "NDS_TPU_STREAM_STRICT": "error ROUTING only: strict re-raises "
    "instead of falling back eager; the compiled program is identical",
    "NDS_TPU_STREAM_EXEC": "routing decided BEFORE the cache is "
    "consulted (eager escape hatch never reaches the build)",
    "NDS_TPU_NO_EXPR_FUSE": "inside the pipeline trace both arms inline "
    "into the same recorded program; the fusion caches are bypassed, "
    "not re-keyed",
    "NDS_TPU_NO_PK_GATHER": "plan-shape knob: its effect changes "
    "join_preds/sources, which are key members",
    "NDS_TPU_DEFER_FILTER_MAX_ROWS": "its effect is the part's physical "
    "length, which is a key member via part specs",
    "NDS_TPU_ENCODED": "encodings ride the chunk/part specs, which are "
    "key members (enc_key per column)",
    "NDS_TPU_STREAM_CHUNK_ROWS": "chunk capacity is a key member "
    "(chunk_cap) — the knob only feeds table construction",
    "NDS_TPU_PALLAS_SMOKE": "build-time smoke-probe toggle: flips "
    "_pallas_broken, which scan_kernels_active()/_pallas_mode() (key "
    "members) already reflect",
    "NDS_TPU_MIN_BUCKET": "deliberately import-frozen process-wide "
    "shape contract (ops._MIN_BUCKET, suppressed env-freeze): "
    "mem_audit's live read equals the frozen value under the contract, "
    "so the key cannot go stale within one process",
    "NDS_TPU_CHUNK_STORE": "source routing only: the persistent chunk "
    "store's wire path produces bit-identical buffers (same codecs, "
    "same lowering math, encodings already key members via enc_key), "
    "so a store on/off flip can never stale a compiled pipeline",
    "NDS_TPU_CHUNK_STORE_VERIFY": "load-time CRC toggle only: it "
    "decides whether wire files are verified before the mmap, never "
    "what the buffers contain — same bit-identical-buffers argument "
    "as NDS_TPU_CHUNK_STORE",
    "NDS_TPU_FAULT": "deterministic fault injection (engine/faults.py): "
    "an injected build fault PREVENTS the cache entry (the build "
    "raises/degrades), and a non-injected build bakes nothing of the "
    "knob into the program — the knob can never stale a compiled "
    "pipeline; tools/fault_diff.py additionally resets the pipeline "
    "cache around every injected run",
    "NDS_TPU_FAULT_HANG_S": "injection timing only (how long a "
    "hang-kind fault blocks before raising): never reaches a compiled "
    "program's values",
    "NDS_TPU_FAULT_DRIFT": "harness-only recovery suppression for the "
    "--inject-drift self-test: changes whether a retry happens, never "
    "what a successful build compiles",
    "NDS_TPU_STATEMENT_DEADLINE_S": "watchdog timing only: decides WHEN "
    "a hung blocking read raises StatementTimeout, never what a "
    "completed read returns — a timed-out statement produces no result "
    "to cache",
    "NDS_TPU_CHUNK_STORE_LOCK_STALE_S": "writer-lock steal age of the "
    "chunk store: write-side contention policy, never the wire bytes "
    "(same bit-identical-buffers argument as NDS_TPU_CHUNK_STORE)",
}

CACHE_REGISTRY = {
    ("engine/stream.py", "_PIPELINE_CACHE"): CacheSpec(
        key_fns=("_cache_key",),
        builder_fns=("_build_pipeline",),
        modules=_ENGINE_MODULES,
        exempt=_PIPELINE_EXEMPT),
    ("sql/planner.py", "_MASK_FUSE_CACHE"): CacheSpec(
        key_fns=("_fused_run",),
        builder_fns=("_fused_run",),
        modules=("sql/planner.py", "engine/exprs.py", "engine/ops.py",
                 "engine/column.py", "engine/kernels.py"),
        exempt={
            "NDS_TPU_NO_EXPR_FUSE": "checked before the cache is "
            "consulted: the knob disables the cache, it cannot stale it",
            "NDS_TPU_PALLAS": "segment kernels never trace inside "
            "scalar-expression fusion (no aggregation in _fused_run)",
            "NDS_TPU_PALLAS_MAX_GROUPS": "same: group-count gate of "
            "segment kernels, unreachable from scalar expressions",
            "NDS_TPU_EXACT_ONEHOT_BUDGET": "same segment-kernel gate",
            "NDS_TPU_PALLAS_SMOKE": "same segment-kernel arm surface",
            "NDS_TPU_PAIR_BUDGET": "join-probe bucket budget: joins "
            "never trace inside scalar-expression fusion",
            "NDS_TPU_GROUP_PACK_MIN": "group-by packing: no grouping "
            "inside scalar-expression fusion",
            "NDS_TPU_LAZY_SHRINK_ROWS": "compaction policy: fusion "
            "programs never compact",
            "NDS_TPU_STREAM_FANOUT": "stream-join bucket allowance: no "
            "joins inside scalar-expression fusion",
            "NDS_TPU_DEFER_FILTER_MAX_ROWS": "plan routing above the "
            "fusion layer; inputs are keyed by column signature",
        }),
    ("parallel/exchange.py", "_STREAM_MESHES"): CacheSpec(
        key_fns=("stream_mesh",),
        builder_fns=("stream_mesh",),
        modules=("parallel/exchange.py",),
        exempt={
            "NDS_TPU_STREAM_MESH_AXIS": "the axis name IS the second "
            "key component (resolved before the lookup)"}),
    ("parallel/exchange.py", "_exchange_step_cache"): CacheSpec(
        key_fns=("exchange_join_pairs",),
        builder_fns=("_exchange_join_step",),
        modules=("parallel/exchange.py",)),
    # identity-keyed memos: the cached value is a pure function of the
    # keyed host arrays (dictionary sorts/merges/uniques) — env-exempt by
    # design, declared so the unregistered-cache gate stays meaningful
    ("engine/ops.py", "_rank_cache"): CacheSpec(
        (), (), ("engine/ops.py",), identity_keyed=True),
    ("engine/ops.py", "_merged_cache"): CacheSpec(
        (), (), ("engine/ops.py",), identity_keyed=True),
    ("engine/ops.py", "_dense_dim_cache"): CacheSpec(
        (), (), ("engine/ops.py",), identity_keyed=True),
    ("engine/ops.py", "_dim_span_cache"): CacheSpec(
        (), (), ("engine/ops.py",), identity_keyed=True),
    ("engine/ops.py", "_union_cache"): CacheSpec(
        (), (), ("engine/ops.py",), identity_keyed=True),
    ("engine/exprs.py", "_str_literal_dicts"): CacheSpec(
        (), (), ("engine/exprs.py",), identity_keyed=True),
    ("engine/exprs.py", "_map_dict_cache"): CacheSpec(
        (), (), ("engine/exprs.py",), identity_keyed=True),
}
# _EXPR_FUSE_CACHE shares _MASK_FUSE_CACHE's whole contract (same
# builder, same key shape, same exemptions)
CACHE_REGISTRY[("sql/planner.py", "_EXPR_FUSE_CACHE")] = \
    CACHE_REGISTRY[("sql/planner.py", "_MASK_FUSE_CACHE")]


# ---------------------------------------------------------------------------
# per-module model
# ---------------------------------------------------------------------------


@dataclass
class Mutation:
    """One mutation site of a shared object (or of a function parameter,
    resolved to a shared object through call-site aliasing)."""

    target: str            # global name or "Class.attr"
    scope: str             # enclosing function qualname
    lineno: int
    kind: str              # "store" | "method:<name>" | "rebind" |
    #                        "aug-rebind" | "del" | "tls-attr"
    guards: tuple          # lock names held lexically at the site
    module_scope: bool     # True when at module body level (import-time)


@dataclass
class FuncInfo:
    qualname: str
    lineno: int
    params: list = field(default_factory=list)    # ordered param names
    calls: list = field(default_factory=list)     # resolved-late refs
    env_reads: set = field(default_factory=set)
    lock_withs: list = field(default_factory=list)  # lock names taken
    param_mutations: dict = field(default_factory=dict)  # param -> [Mutation]
    param_forwards: list = field(default_factory=list)   # (param, callee,
    #                                                       arg idx, via_self)
    jit_calls: list = field(default_factory=list)        # linenos
    first_sync: tuple | None = None               # (lineno, what) | None
    # calls made while holding each lock: lock -> [(callee ref, lineno)]
    calls_under_lock: dict = field(default_factory=dict)
    syncs_under_lock: list = field(default_factory=list)  # (lock, what, line)
    jit_under_lock: list = field(default_factory=list)    # (lock, line)
    waits_under_lock: list = field(default_factory=list)  # (lock, what, line)
    nested_locks: list = field(default_factory=list)      # (outer, inner, ln)


@dataclass
class ModuleInfo:
    rel: str
    lines: list
    globals_kind: dict = field(default_factory=dict)  # name -> kind
    env_freeze: list = field(default_factory=list)    # (name, lineno)
    functions: dict = field(default_factory=dict)     # qualname -> FuncInfo
    mutations: list = field(default_factory=list)     # [Mutation]
    imports: dict = field(default_factory=dict)       # alias -> module rel
    from_imports: dict = field(default_factory=dict)  # name -> (mod, name)
    cache_writes: dict = field(default_factory=dict)  # cache -> [(key ast,
    #                                                   scope, lineno)]
    cache_arg_calls: list = field(default_factory=list)  # (callee, arg idx,
    #                                                       via_self, name)


def _ctor_kind(node) -> str | None:
    """Shared-state kind of a module/class-level assignment RHS."""
    if isinstance(node, ast.Dict):
        return "dict"
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(node, ast.Call):
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name in _CONTAINER_CTORS:
            return {"dict": "dict", "defaultdict": "dict",
                    "OrderedDict": "dict", "list": "list",
                    "set": "set"}[name]
        if name == "deque":
            has_maxlen = any(kw.arg == "maxlen" for kw in node.keywords)
            return "ring" if has_maxlen else "list"
        if name in _LOCK_CTORS:
            return "lock"
        if name == "local":
            return "tls"
        if name == "Event":
            return "event"
    if isinstance(node, ast.Constant):
        return "scalar"
    if isinstance(node, ast.Name) and node.id in ("None", "True", "False"):
        return "scalar"
    return None


def _reads_environ(node) -> set | None:
    """Env var names a (key/value) expression reads, or None when it
    makes no environment read at all. Unresolvable names read as
    ``<dynamic>``."""
    out: set = set()
    found = False
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("environ",):
            found = True
        if isinstance(n, ast.Call):
            f = n.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name in ("get", "getenv"):
                owner = f.value if isinstance(f, ast.Attribute) else None
                owner_env = owner is not None and any(
                    isinstance(x, ast.Attribute) and x.attr == "environ"
                    or isinstance(x, ast.Name) and x.id == "os"
                    for x in ast.walk(owner))
                if owner_env and n.args:
                    found = True
                    a = n.args[0]
                    out.add(a.value if isinstance(a, ast.Constant)
                            else "<dynamic>")
        if isinstance(n, ast.Subscript):
            v = n.value
            if isinstance(v, ast.Attribute) and v.attr == "environ":
                found = True
                s = n.slice
                out.add(s.value if isinstance(s, ast.Constant)
                        else "<dynamic>")
    return out if found else None


class _ModuleScan(ast.NodeVisitor):
    """One pass over a module AST building its :class:`ModuleInfo`."""

    def __init__(self, rel: str, source: str):
        self.info = ModuleInfo(rel, source.splitlines())
        self.scope: list = []          # FuncInfo stack
        self.class_stack: list = []
        self.lock_stack: list = []     # lock names currently held
        self.param_stack: list = []    # param-name sets per function

    # -- imports ------------------------------------------------------------

    def visit_Import(self, node):
        for a in node.names:
            if a.name.startswith("nds_tpu"):
                alias = a.asname or a.name.split(".")[0]
                self.info.imports[alias] = a.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        if mod.startswith("nds_tpu"):
            for a in node.names:
                self.info.from_imports[a.asname or a.name] = (mod, a.name)
        self.generic_visit(node)

    # -- shared-state inventory ----------------------------------------------

    def _note_state(self, name: str, value, lineno: int) -> None:
        kind = _ctor_kind(value)
        if kind:
            self.info.globals_kind.setdefault(name, kind)
        env = _reads_environ(value) if value is not None else None
        if env is not None:
            self.info.env_freeze.append((name, lineno))

    def visit_Assign(self, node):
        if not self.scope:
            owner = ".".join(self.class_stack)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    full = f"{owner}.{tgt.id}" if owner else tgt.id
                    self._note_state(full, node.value, node.lineno)
        self._note_mutation_targets(node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if not self.scope and isinstance(node.target, ast.Name) and \
                node.value is not None:
            owner = ".".join(self.class_stack)
            full = f"{owner}.{node.target.id}" if owner \
                else node.target.id
            self._note_state(full, node.value, node.lineno)
        if isinstance(node.target, ast.Subscript):
            self._note_subscript_store(node.target, node.lineno)
        self.generic_visit(node)

    # -- scopes ---------------------------------------------------------------

    def visit_ClassDef(self, node):
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def visit_FunctionDef(self, node):
        qual = ".".join(self.class_stack + [node.name]) if \
            self.class_stack and not self.scope else node.name
        fi = self.info.functions.setdefault(
            qual, FuncInfo(qual, node.lineno))
        args = node.args
        ordered = [a.arg for a in
                   args.posonlyargs + args.args + args.kwonlyargs]
        fi.params = ordered
        params = set(ordered)
        self.scope.append(fi)
        self.param_stack.append(params)
        saved_locks = self.lock_stack
        self.lock_stack = []           # a def body runs at CALL time
        self.generic_visit(node)
        self.lock_stack = saved_locks
        self.param_stack.pop()
        self.scope.pop()
        if self.scope:
            # a nested def's effects fold into the enclosing function
            # too: its body runs (at most) within the caller's dynamic
            # extent for the closures the engine jits
            outer = self.scope[-1]
            outer.calls.extend(fi.calls)
            outer.env_reads |= fi.env_reads

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- with-lock tracking ----------------------------------------------------

    def _lock_name(self, expr) -> str | None:
        """Resolve a with-context expression to a known lock name:
        ``_LOCK_NAME`` (module global), ``Class._lock`` / ``cls._lock`` /
        ``self._lock`` (class attribute)."""
        if isinstance(expr, ast.Name):
            if self.info.globals_kind.get(expr.id) == "lock":
                return expr.id
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            owner = expr.value.id
            if owner in ("cls", "self") and self.class_stack:
                owner = self.class_stack[-1]
            full = f"{owner}.{expr.attr}"
            if self.info.globals_kind.get(full) == "lock":
                return full
        return None

    def visit_With(self, node):
        locks = [self._lock_name(item.context_expr)
                 for item in node.items]
        locks = [l for l in locks if l]
        fi = self.scope[-1] if self.scope else None
        if fi is not None:
            fi.lock_withs.extend(locks)
        for outer in self.lock_stack:
            for inner in locks:
                if outer != inner and fi is not None:
                    fi.nested_locks.append((outer, inner, node.lineno))
        self.lock_stack.extend(locks)
        self.generic_visit(node)
        for _ in locks:
            self.lock_stack.pop()

    # -- mutations -------------------------------------------------------------

    def _target_of(self, expr) -> tuple | None:
        """(kind, name) of a mutation target expression: a module global,
        a class attribute, or an attribute of a threading.local."""
        if isinstance(expr, ast.Name):
            k = self.info.globals_kind.get(expr.id)
            if k and k not in ("lock",):
                return (k, expr.id)
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            owner = expr.value.id
            if self.info.globals_kind.get(owner) == "tls":
                return ("tls", owner)
            if owner in ("cls", "self") and self.class_stack:
                owner = self.class_stack[-1]
            full = f"{owner}.{expr.attr}"
            k = self.info.globals_kind.get(full)
            if k and k not in ("lock",):
                return (k, full)
        return None

    def _emit_mutation(self, target: tuple, kind: str,
                       lineno: int) -> None:
        tkind, name = target
        mut = Mutation(name, self.scope[-1].qualname if self.scope
                       else "<module>", lineno,
                       "tls-attr" if tkind == "tls" else kind,
                       tuple(self.lock_stack), not self.scope)
        self.info.mutations.append(mut)

    def _note_subscript_store(self, tgt, lineno: int) -> None:
        target = self._target_of(tgt.value)
        if target:
            self._emit_mutation(target, "store", lineno)
            if target[0] == "dict":
                self.info.cache_writes.setdefault(
                    target[1], []).append(
                    (tgt.slice, self.scope[-1].qualname if self.scope
                     else "<module>", lineno))
        elif self.scope and isinstance(tgt.value, ast.Name) and \
                tgt.value.id in self.param_stack[-1]:
            self.scope[-1].param_mutations.setdefault(
                tgt.value.id, []).append(Mutation(
                    tgt.value.id, self.scope[-1].qualname, lineno,
                    "store", tuple(self.lock_stack), False))

    def _note_mutation_targets(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                self._note_subscript_store(tgt, tgt.lineno)
            elif isinstance(tgt, (ast.Name, ast.Attribute)):
                target = self._target_of(tgt)
                if target and self.scope:
                    # a bare-name rebind inside a function only reaches
                    # the module global through a `global` declaration;
                    # conservatively treat Name stores in functions as
                    # rebinds (a local shadow of a tracked global name
                    # is rare and reads as shadowing anyway)
                    self._emit_mutation(target, "rebind", tgt.lineno)

    def visit_AugAssign(self, node):
        tgt = node.target
        if isinstance(tgt, ast.Subscript):
            self._note_subscript_store(tgt, node.lineno)
        else:
            target = self._target_of(tgt) if isinstance(
                tgt, (ast.Name, ast.Attribute)) else None
            if target and self.scope:
                self._emit_mutation(target, "aug-rebind", node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node):
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                target = self._target_of(tgt.value)
                if target:
                    self._emit_mutation(target, "del", node.lineno)
        self.generic_visit(node)

    # -- calls ------------------------------------------------------------------

    def _callee_ref(self, f) -> tuple | None:
        """Late-resolved callee reference: ("name", x) | ("self", m) |
        ("mod", alias, attr)."""
        if isinstance(f, ast.Name):
            return ("name", f.id)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id in ("self", "cls"):
                return ("self", f.attr)
            return ("mod", f.value.id, f.attr)
        return None

    def _sync_call(self, node) -> str | None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args:
                return ".item()"
            if f.attr == "to_int" and not node.args:
                return ".to_int()"
            if f.attr == "device_get":
                return "device_get()"
            if f.attr in _HOST_READ_FUNCS:
                return f"{f.attr}()"
        elif isinstance(f, ast.Name) and f.id in _HOST_READ_FUNCS:
            return f"{f.id}()"
        return None

    def visit_Call(self, node):
        fi = self.scope[-1] if self.scope else None
        f = node.func
        # env reads
        env = _reads_environ(node)
        if env is not None and fi is not None:
            fi.env_reads |= env
        # method-style mutations
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
            target = self._target_of(f.value)
            if target:
                self._emit_mutation(target, f"method:{f.attr}",
                                    node.lineno)
                if target[0] == "dict" and f.attr == "setdefault" \
                        and node.args:
                    self.info.cache_writes.setdefault(
                        target[1], []).append(
                        (node.args[0],
                         fi.qualname if fi else "<module>",
                         node.lineno))
            elif fi is not None and isinstance(f.value, ast.Name) and \
                    self.param_stack and \
                    f.value.id in self.param_stack[-1]:
                fi.param_mutations.setdefault(f.value.id, []).append(
                    Mutation(f.value.id, fi.qualname, node.lineno,
                             f"method:{f.attr}", tuple(self.lock_stack),
                             False))
        if fi is not None:
            ref = self._callee_ref(f)
            if ref:
                fi.calls.append(ref)
                callee = ref[1] if ref[0] in ("name", "self") else None
                if callee:
                    via_self = ref[0] == "self"
                    for i, a in enumerate(node.args):
                        if isinstance(a, ast.Name):
                            # *_cache aliasing through parameters (the
                            # jax_lint pattern): a shared container
                            # passed in, or a parameter forwarded on.
                            # The raw argument index is recorded with
                            # the call KIND — whether a self-call binds
                            # an implicit first parameter depends on the
                            # callee's signature (staticmethods do not),
                            # resolved at join time.
                            if self.info.globals_kind.get(a.id) in \
                                    ("dict", "list", "set", "ring"):
                                self.info.cache_arg_calls.append(
                                    (callee, i, via_self, a.id))
                            elif self.param_stack and \
                                    a.id in self.param_stack[-1]:
                                fi.param_forwards.append(
                                    (a.id, callee, i, via_self))
            # jit compiles
            is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit") \
                or (isinstance(f, ast.Name) and f.id == "jit")
            if is_jit:
                fi.jit_calls.append(node.lineno)
                if self.lock_stack:
                    fi.jit_under_lock.append(
                        (self.lock_stack[-1], node.lineno))
            what = self._sync_call(node)
            if what and fi.first_sync is None:
                fi.first_sync = (node.lineno, what)
            # under-lock discipline
            if self.lock_stack:
                if what:
                    fi.syncs_under_lock.append(
                        (self.lock_stack[-1], what, node.lineno))
                # .wait() (Event/Condition) and argless .join() (Thread;
                # str.join always takes the iterable) are blocking
                is_wait = isinstance(f, ast.Attribute) and (
                    f.attr == "wait" or
                    (f.attr == "join" and not node.args))
                if is_wait:
                    fi.waits_under_lock.append(
                        (self.lock_stack[-1], f".{f.attr}()",
                         node.lineno))
                if ref:
                    fi.calls_under_lock.setdefault(
                        self.lock_stack[-1], []).append(
                        (ref, node.lineno))
        self.generic_visit(node)


def scan_module(path: str, rel: str) -> ModuleInfo | None:
    with open(path) as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    # two passes: the inventory must exist before function bodies are
    # classified (a lock defined after its first use still guards it)
    pre = _ModuleScan(rel, source)
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in tgts:
                if isinstance(t, ast.Name) and node.value is not None:
                    pre._note_state(t.id, node.value, node.lineno)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    tgts = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in tgts:
                        if isinstance(t, ast.Name) and \
                                sub.value is not None:
                            pre._note_state(f"{node.name}.{t.id}",
                                            sub.value, sub.lineno)
    scan = _ModuleScan(rel, source)
    scan.info.globals_kind = pre.info.globals_kind
    scan.visit(tree)
    return scan.info


# ---------------------------------------------------------------------------
# package-level joins
# ---------------------------------------------------------------------------


class PackageModel:
    """Every module's :class:`ModuleInfo` plus the cross-module joins:
    call-graph closure, env-knob propagation, parameter-aliased mutation
    resolution."""

    def __init__(self, modules: dict):
        self.modules = modules          # rel -> ModuleInfo
        # (rel, qualname) -> FuncInfo
        self.functions = {(rel, q): fi
                          for rel, mi in modules.items()
                          for q, fi in mi.functions.items()}
        # method name -> [(rel, qualname)] for name-based resolution
        self.by_name: dict = {}
        for (rel, q), fi in self.functions.items():
            self.by_name.setdefault(q.split(".")[-1], []).append((rel, q))

    def resolve(self, rel: str, ref, fuzzy_modules=None):
        """Function keys a callee reference may reach. Precise edges:
        bare name in the same module, from-imports, module-alias attrs,
        self/cls methods. ``fuzzy_modules`` additionally matches unknown
        attr calls by bare method name within the given module set (the
        planner's instance-typed engine calls)."""
        mi = self.modules[rel]
        out = []
        kind = ref[0]
        if kind == "name":
            name = ref[1]
            if (rel, name) in self.functions:
                out.append((rel, name))
            elif name in mi.from_imports:
                mod, orig = mi.from_imports[name]
                target = _module_rel(mod)
                for cand_rel in self.modules:
                    if target and cand_rel.endswith(target) and \
                            (cand_rel, orig) in self.functions:
                        out.append((cand_rel, orig))
        elif kind == "self":
            name = ref[1]
            for q in self.modules[rel].functions:
                if q.split(".")[-1] == name and "." in q:
                    out.append((rel, q))
            if not out and (rel, name) in self.functions:
                out.append((rel, name))
        elif kind == "mod":
            alias, attr = ref[1], ref[2]
            mod = mi.imports.get(alias)
            if mod is None and alias in mi.from_imports:
                # `from nds_tpu.engine import ops as E` arrives as a
                # from-import of a SUBMODULE
                m, orig = mi.from_imports[alias]
                mod = f"{m}.{orig}"
            if mod:
                target = _module_rel(mod)
                for cand_rel in self.modules:
                    if target and cand_rel.endswith(target):
                        if (cand_rel, attr) in self.functions:
                            out.append((cand_rel, attr))
                        else:
                            out.extend(
                                (cand_rel, q) for q in
                                self.modules[cand_rel].functions
                                if q.split(".")[-1] == attr and "." in q)
            elif fuzzy_modules is not None:
                out.extend(k for k in self.by_name.get(attr, ())
                           if any(k[0].endswith(s)
                                  for s in fuzzy_modules))
        if not out and fuzzy_modules is not None and kind in ("mod",):
            out.extend(k for k in self.by_name.get(ref[-1], ())
                       if any(k[0].endswith(s) for s in fuzzy_modules))
        return out

    def knob_closure(self, roots, fuzzy_modules=None) -> set:
        """Env vars read by ``roots`` (function keys) or anything they
        transitively call through resolvable edges."""
        seen = set()
        knobs: set = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            fi = self.functions[key]
            knobs |= fi.env_reads
            for ref in fi.calls:
                for nxt in self.resolve(key[0], ref, fuzzy_modules):
                    if nxt not in seen:
                        stack.append(nxt)
        return knobs

    def reachable(self, entry_points) -> set:
        """Function keys reachable from the entry-point patterns through
        the widest (name-fuzzy, package-wide) edges — an over-
        approximation, which is the safe direction for deciding what
        runs concurrently."""
        all_suffixes = tuple(self.modules)
        roots = []
        for (suffix, prefix) in entry_points:
            for (rel, q) in self.functions:
                if rel.endswith(suffix) and \
                        q.split(".")[-1].startswith(prefix):
                    roots.append((rel, q))
        seen = set()
        stack = roots
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            fi = self.functions[key]
            for ref in fi.calls:
                cands = self.resolve(key[0], ref, all_suffixes)
                if not cands and ref[0] in ("name", "self"):
                    cands = [k for k in self.by_name.get(ref[1], ())]
                stack.extend(c for c in cands if c not in seen)
        return seen


def _module_rel(dotted: str) -> str | None:
    """``nds_tpu.engine.ops`` -> ``engine/ops.py`` (suffix form)."""
    if not dotted.startswith("nds_tpu"):
        return None
    parts = dotted.split(".")[1:]
    if not parts:
        return None
    return "/".join(parts) + ".py"


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


def _emit(findings, mi, scope, rule, severity, msg, lineno):
    if suppressed(mi.lines, lineno, rule):
        return
    findings.append(Finding(mi.rel, scope, rule, severity, msg, lineno))


def _resolve_param_aliases(model: PackageModel) -> None:
    """Attribute mutation sites inside callees that received a shared
    container as a parameter back to the module global, carrying the
    callee's guard state — transitively through parameter forwards
    (depth-bounded: ``_fused_run`` forwards its ``cache`` parameter to
    ``_fuse_insert``, whose mutations must count against the module
    caches the original call sites pass in). Name-based callee
    resolution like jax_lint: a collision only widens coverage."""
    for rel, mi in model.modules.items():
        for (callee, idx, via_self, gname) in mi.cache_arg_calls:
            seen = set()
            stack = [(callee, idx, via_self, 0)]
            while stack:
                cname, cidx, cself, depth = stack.pop()
                if depth > 3 or (cname, cidx, cself) in seen:
                    continue
                seen.add((cname, cidx, cself))
                for (frel, fq) in model.by_name.get(cname, ()):
                    fi = model.functions[(frel, fq)]
                    # a self-call binds an implicit first parameter only
                    # when the callee actually declares one — a
                    # staticmethod invoked through self does not
                    cpos = cidx + (1 if cself and fi.params and
                                   fi.params[0] in ("self", "cls")
                                   else 0)
                    if cpos >= len(fi.params):
                        continue
                    pname = fi.params[cpos]
                    for m in fi.param_mutations.get(pname, ()):
                        # the finding lands on the CALLEE's module: the
                        # flagged line is the real mutation site, so the
                        # report points at actionable code and an
                        # in-source suppression THERE is honored
                        model.modules[frel].mutations.append(Mutation(
                            gname, f"{fq}(via {cname})", m.lineno,
                            m.kind, m.guards, False))
                    for (fwd_param, fwd_callee, fwd_idx, fwd_self) in \
                            fi.param_forwards:
                        if fwd_param == pname:
                            stack.append((fwd_callee, fwd_idx,
                                          fwd_self, depth + 1))


def audit_package(root: str, repo: str | None = None,
                  registry: dict | None = None,
                  entry_points=ENTRY_POINTS) -> list:
    """Run the concurrency audit over every ``.py`` under ``root``.
    Returns the findings list (same :class:`Finding` shape as the other
    five passes)."""
    registry = CACHE_REGISTRY if registry is None else registry
    repo = repo or os.path.dirname(os.path.abspath(root))
    modules: dict = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                rel = os.path.relpath(p, repo)
                mi = scan_module(p, rel)
                if mi is not None:
                    modules[rel] = mi
    model = PackageModel(modules)
    _resolve_param_aliases(model)
    reachable = model.reachable(entry_points)
    findings: list = []

    for rel, mi in sorted(modules.items()):
        _audit_mutations(findings, model, mi, reachable)
        _audit_lock_bodies(findings, model, mi)
        _audit_env_freeze(findings, mi)
        _audit_caches(findings, model, mi, registry)
    _audit_lock_order(findings, model)
    return findings


def _state_guard_map(mi: ModuleInfo) -> dict:
    """state name -> set of locks observed guarding its mutations."""
    guards: dict = {}
    for m in mi.mutations:
        if m.module_scope or m.kind == "tls-attr":
            continue
        if m.guards:
            guards.setdefault(m.target, set()).add(m.guards[-1])
    return guards


def _audit_mutations(findings, model, mi, reachable) -> None:
    guard_map = _state_guard_map(mi)
    for m in mi.mutations:
        if m.module_scope:
            continue                    # import-time: serialized
        kind = mi.globals_kind.get(m.target, "")
        if m.kind == "tls-attr":
            continue                    # thread-local by construction
        if kind == "ring" and (
                m.kind.startswith("method:") and
                m.kind.split(":")[1] in _RING_METHODS):
            continue                    # bounded evidence ring
        state_locks = guard_map.get(m.target, set())
        if m.guards:
            if len(state_locks) > 1:
                _emit(findings, mi, m.scope, "mixed-guard", "error",
                      f"{m.target} is guarded by more than one lock "
                      f"({', '.join(sorted(state_locks))}): a lock can "
                      "only protect state it exclusively guards",
                      m.lineno)
            continue                    # lock-guarded (consistency above)
        if m.kind == "rebind" and not state_locks:
            # atomic rebind: one GIL-atomic pointer store, last-writer-
            # wins — accepted for flags/latches and whole-object resets
            continue
        reach = any(k[0] == mi.rel and
                    (k[1] == m.scope or m.scope.startswith(k[1]))
                    for k in reachable) or "(via " in m.scope
        sev = "error" if reach else "warning"
        if state_locks:
            _emit(findings, mi, m.scope, "mixed-guard", "error",
                  f"{m.target} is mutated off-lock here but under "
                  f"{', '.join(sorted(state_locks))} elsewhere: every "
                  "mutation must hold the state's dedicated lock",
                  m.lineno)
        else:
            _emit(findings, mi, m.scope, "unguarded-mutation", sev,
                  f"{m.target} ({kind or 'shared object'}) is mutated "
                  "with no dedicated lock, thread-local scope, or "
                  "bounded-ring pattern: concurrent query streams race "
                  "here — add a module Lock with double-checked "
                  "insert (see _PIPELINE_LOCK) or make it thread-local",
                  m.lineno)


def _audit_lock_bodies(findings, model, mi) -> None:
    """sync/compile/wait inside a with-lock body, one level down."""
    for q, fi in sorted(mi.functions.items()):
        for (lock, what, ln) in fi.syncs_under_lock:
            _emit(findings, mi, q, "sync-under-lock", "error",
                  f"{what} while holding {lock}: a device->host sync "
                  "holds every waiter for a full round trip — resolve "
                  "before acquiring or after releasing", ln)
        for (lock, ln) in fi.jit_under_lock:
            _emit(findings, mi, q, "compile-under-lock", "error",
                  f"jax.jit(...) while holding {lock}: an XLA compile "
                  "under a shared lock serializes every concurrent "
                  "stream — claim under the lock, compile off-lock, "
                  "land under the lock (the singleflight pattern)", ln)
        for (lock, what, ln) in fi.waits_under_lock:
            _emit(findings, mi, q, "wait-under-lock", "error",
                  f"blocking {what} while holding {lock}: the waiter "
                  "holds the lock its waker needs (lost-wakeup/"
                  "deadlock shape) — wait off-lock and re-check", ln)
        # one level down: a called module-local helper that syncs or
        # compiles directly
        for lock, calls in fi.calls_under_lock.items():
            for (ref, ln) in calls:
                for key in model.resolve(mi.rel, ref):
                    if key[0] != mi.rel:
                        continue
                    callee = model.functions[key]
                    if callee.first_sync:
                        sln, what = callee.first_sync
                        _emit(findings, mi, q, "sync-under-lock",
                              "error",
                              f"{key[1]}() (syncs via {what} at line "
                              f"{sln}) called while holding {lock}: "
                              "one host sync per acquisition hidden "
                              "one level down", ln)
                    if callee.jit_calls:
                        _emit(findings, mi, q, "compile-under-lock",
                              "error",
                              f"{key[1]}() (jits at line "
                              f"{callee.jit_calls[0]}) called while "
                              f"holding {lock}: a compile hidden one "
                              "level down", ln)


def _audit_env_freeze(findings, mi) -> None:
    for (name, ln) in mi.env_freeze:
        _emit(findings, mi, "<module>", "env-freeze", "warning",
              f"{name} snapshots os.environ at import: a knob set after "
              "import is silently ignored and a compiled-behavior knob "
              "escapes every cache key — read it at build/use time "
              "(stream_fanout() pattern), or suppress with a "
              "justification if the freeze is a process contract", ln)


def _audit_caches(findings, model, mi, registry) -> None:
    for cname, writes in sorted(mi.cache_writes.items()):
        writes = [w for w in writes if w[1] != "<module>"]
        if not writes:
            continue                    # import-time table construction
        spec = None
        for (suffix, reg_name), s in registry.items():
            if cname == reg_name and mi.rel.endswith(suffix):
                spec = s
                break
        looks_cache = "cache" in cname.lower() or \
            cname in ("_STREAM_MESHES",)
        if spec is None:
            if looks_cache:
                _emit(findings, mi, writes[0][1], "cache-unregistered",
                      "warning",
                      f"{cname} is keyed and written on the query path "
                      "but not declared in conc_audit.CACHE_REGISTRY: "
                      "register its key/builder functions (or mark it "
                      "identity-keyed) so cache-key completeness is "
                      "checked", writes[0][2])
            continue
        if spec.identity_keyed:
            continue
        key_roots = [(rel, q) for (rel, q) in model.functions
                     if q.split(".")[-1] in spec.key_fns and
                     any(rel.endswith(s) for s in spec.modules)]
        builder_roots = [(rel, q) for (rel, q) in model.functions
                         if q.split(".")[-1] in spec.builder_fns and
                         any(rel.endswith(s) for s in spec.modules)]
        key_knobs = model.knob_closure(key_roots,
                                       fuzzy_modules=spec.modules)
        builder_knobs = model.knob_closure(builder_roots,
                                           fuzzy_modules=spec.modules)
        missing = (builder_knobs - key_knobs) - set(spec.exempt) - \
            {"<dynamic>"}
        for knob in sorted(missing):
            _emit(findings, mi, writes[0][1], "cache-key-missing-knob",
                  "error",
                  f"{cname}: env knob {knob} is reachable from the "
                  f"cached computation ({'/'.join(spec.builder_fns)}) "
                  "but absent from the key expression "
                  f"({'/'.join(spec.key_fns)}) — a post-change lookup "
                  "would serve a stale artifact; add it to the key or "
                  "exempt it WITH a justification in CACHE_REGISTRY",
                  writes[0][2])


def _audit_lock_order(findings, model) -> None:
    """Global acquired-while-holding graph; any cycle is a deadlock."""
    edges: dict = {}
    sites: dict = {}
    for (rel, q), fi in model.functions.items():
        for (outer, inner, ln) in fi.nested_locks:
            edges.setdefault((rel, outer), set()).add((rel, inner))
            sites.setdefault(((rel, outer), (rel, inner)), (rel, q, ln))
        # one level down: a call made under `outer` into a function that
        # takes `inner` (precise resolution only)
        for outer, calls in fi.calls_under_lock.items():
            for (ref, ln) in calls:
                for key in model.resolve(rel, ref):
                    callee = model.functions[key]
                    for inner in callee.lock_withs:
                        if (key[0], inner) != (rel, outer):
                            edges.setdefault((rel, outer), set()).add(
                                (key[0], inner))
                            sites.setdefault(
                                ((rel, outer), (key[0], inner)),
                                (rel, q, ln))
    # DFS cycle detection
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {}
    stack_path = []

    def dfs(node):
        color[node] = GRAY
        stack_path.append(node)
        for nxt in sorted(edges.get(node, ())):
            if color.get(nxt, WHITE) == GRAY:
                cyc = stack_path[stack_path.index(nxt):] + [nxt]
                names = " -> ".join(f"{r}:{n}" for (r, n) in cyc)
                rel, q, ln = sites.get((node, nxt), (node[0], "?", 0))
                mi = model.modules[rel]
                _emit(findings, mi, q, "lock-order-cycle", "error",
                      f"lock acquisition cycle {names}: two threads "
                      "taking these locks in opposite orders deadlock — "
                      "impose one global order (or merge the locks)",
                      ln)
            elif color.get(nxt, WHITE) == WHITE:
                dfs(nxt)
        stack_path.pop()
        color[node] = BLACK

    for node in sorted(edges):
        if color.get(node, WHITE) == WHITE:
            dfs(node)


def audit_concurrency(root: str | None = None) -> list:
    """The sixth ``tools/lint.py`` pass: audit the shipped ``nds_tpu/``
    package (or ``root``)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return audit_package(root)
