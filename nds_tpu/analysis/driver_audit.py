# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Driver lint: hygiene checks for the top-level CLIs and ``tools/``.

The drivers orchestrate multi-hour campaigns as subprocess trees; the
failure modes that waste a campaign are not kernel bugs but driver bugs:
an exception swallowed into ``pass``, a template expanded through a shell,
a report handle never flushed. Rules (suppressible with
``# nds-lint: ignore[rule]``):

* ``swallowed-exception`` — a bare ``except:`` or ``except Exception:``
  whose body is only ``pass``: the campaign continues with no record of
  what was lost. Narrow excepts (``except OSError: pass``) are allowed —
  they document a decision.
* ``shell-injection`` — ``os.system``/``os.popen`` with a non-constant
  command, or ``subprocess.*(..., shell=True)``: template/param expansion
  through a shell turns a query string into an execution vector.
* ``unmanaged-file-handle`` — ``open()`` neither used as a context manager
  nor assigned to a name that is later ``.close()``d in the same scope:
  on CPython the report usually survives via refcounting, but a crashed
  driver loses buffered output exactly when the artifact matters.
"""

from __future__ import annotations

import ast
import glob
import os

from nds_tpu.analysis import Finding, suppressed

_BROAD = (None, "Exception", "BaseException")


def _exc_name(node) -> str | None:
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return "<expr>"


class _Audit(ast.NodeVisitor):
    def __init__(self, rel: str, source: str):
        self.rel = rel
        self.lines = source.splitlines()
        self.findings: list = []
        self.scope_stack = ["<module>"]
        # open() assignments pending a .close() in the same scope:
        # scope depth -> {name -> lineno}
        self.open_assigns: list = [{}]
        self.closed_names: list = [set()]

    def _emit(self, rule: str, severity: str, message: str,
              lineno: int) -> None:
        if suppressed(self.lines, lineno, rule):
            return
        self.findings.append(Finding(self.rel, self.scope_stack[-1], rule,
                                     severity, message, lineno))

    # -- scopes -------------------------------------------------------------

    def visit_FunctionDef(self, node):
        self.scope_stack.append(node.name)
        self.open_assigns.append({})
        self.closed_names.append(set())
        self.generic_visit(node)
        self._flush_opens()
        self.scope_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _flush_opens(self) -> None:
        opens = self.open_assigns.pop()
        closed = self.closed_names.pop()
        for name, lineno in opens.items():
            if name not in closed:
                self._emit("unmanaged-file-handle", "warning",
                           f"open() assigned to {name!r} but never closed "
                           "in this scope (use a with-statement)", lineno)

    # -- exceptions ---------------------------------------------------------

    def visit_ExceptHandler(self, node):
        only_pass = all(isinstance(s, ast.Pass) for s in node.body)
        if only_pass and _exc_name(node.type) in _BROAD:
            what = _exc_name(node.type) or "bare except"
            self._emit("swallowed-exception", "warning",
                       f"{what} swallowed with pass: failures vanish "
                       "without a log line", node.lineno)
        self.generic_visit(node)

    # -- calls --------------------------------------------------------------

    def visit_Call(self, node):
        # shell=True is checked on ANY call spelling — subprocess.run,
        # sp.run, bare run from `from subprocess import run` — the kwarg
        # itself is the hazard, not the callee's name
        for kw in node.keywords:
            if kw.arg == "shell" and isinstance(
                    kw.value, ast.Constant) and kw.value.value:
                self._emit("shell-injection", "error",
                           "subprocess call with shell=True",
                           node.lineno)
        f = node.func
        if isinstance(f, ast.Attribute):
            owner = f.value.id if isinstance(f.value, ast.Name) else None
            if owner == "os" and f.attr in ("system", "popen"):
                if node.args and not isinstance(node.args[0], ast.Constant):
                    self._emit("shell-injection", "error",
                               f"os.{f.attr}() with a computed command "
                               "string; use subprocess with an argv list",
                               node.lineno)
            if f.attr == "close" and isinstance(f.value, ast.Name):
                self.closed_names[-1].add(f.value.id)
        self.generic_visit(node)

    # -- open() tracking ----------------------------------------------------

    def _is_open_call(self, node) -> bool:
        return isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Name) and node.func.id == "open"

    def visit_With(self, node):
        # open() as a with-item is the managed pattern; don't descend into
        # the item expressions with the generic open() check
        for item in node.items:
            self._mark_with_opens(item.context_expr)
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    def _mark_with_opens(self, expr) -> None:
        for n in ast.walk(expr):
            if self._is_open_call(n):
                n._nds_managed = True  # type: ignore[attr-defined]

    def _track_open_assign(self, tgt, value, lineno: int) -> None:
        if isinstance(tgt, ast.Name):
            value._nds_managed = True  # type: ignore[attr-defined]
            prev = self.open_assigns[-1].get(tgt.id)
            if prev is not None and tgt.id not in self.closed_names[-1]:
                # name re-bound to a second open() before the first was
                # closed: the first handle leaks right here
                self._emit("unmanaged-file-handle", "warning",
                           f"open() assigned to {tgt.id!r} is re-bound "
                           "before being closed (use a with-statement)",
                           prev)
            # a close() seen so far covered the PREVIOUS handle; the
            # new one needs its own
            self.closed_names[-1].discard(tgt.id)
            self.open_assigns[-1][tgt.id] = lineno
        elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
            # a handle stored on an object (self.f = open(...)) has a
            # deliberate cross-method lifetime; closing it is the
            # owner's contract, not an inline leak this lint can see
            value._nds_managed = True  # type: ignore[attr-defined]

    def visit_Assign(self, node):
        if self._is_open_call(node.value) and len(node.targets) == 1:
            self._track_open_assign(node.targets[0], node.value,
                                    node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        # f: IO = open(p) is the same tracked pattern as f = open(p)
        if node.value is not None and self._is_open_call(node.value):
            self._track_open_assign(node.target, node.value, node.lineno)
        self.generic_visit(node)

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            if self._is_open_call(child) and \
                    not getattr(child, "_nds_managed", False):
                self._emit("unmanaged-file-handle", "warning",
                           "open() result used inline without a "
                           "with-statement: the handle is never closed "
                           "deterministically", child.lineno)
        super().generic_visit(node)


def audit_file(path: str, rel: str | None = None) -> list:
    with open(path) as f:
        source = f.read()
    rel = rel or path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rel, "<module>", "syntax-error", "error",
                        str(e), e.lineno or 0)]
    audit = _Audit(rel, source)
    audit.visit(tree)
    audit._flush_opens()
    return audit.findings


def driver_files(repo_root: str | None = None) -> list:
    """The driver surface: top-level ``nds_*.py`` + ``bench.py`` CLIs and
    every script in ``tools/``."""
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    files = sorted(glob.glob(os.path.join(repo_root, "nds_*.py")))
    files += [p for p in (os.path.join(repo_root, "bench.py"),)
              if os.path.exists(p)]
    files += sorted(glob.glob(os.path.join(repo_root, "tools", "*.py")))
    return files


def audit_drivers(repo_root: str | None = None) -> list:
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    findings: list = []
    for p in driver_files(repo_root):
        findings.extend(audit_file(p, os.path.relpath(p, repo_root)))
    return findings
