# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Static execution auditor: prove the control path before the data path runs.

PR 2's compiled streaming executor enforces its host-sync budget only
*empirically*: a template that falls back to the eager chunk loop (subquery
residual, cartesian layout, chunk-data-dependent host read) is discovered
mid-campaign, on device, at scale. This module is the static twin — an
abstract interpreter over the planner's decomposition that, host-only and
with no device in the loop, answers for every template:

1. **Which path will it take?** ``compiled-stream`` (the chunk pipeline of
   :mod:`nds_tpu.engine.stream`), ``eager-fallback`` (the per-chunk loop),
   or ``device-resident`` (no >HBM scan bound; whole-query record/replay
   applies per :func:`nds_tpu.engine.replay.record_eligible`) — with
   machine-readable reason codes mirroring the executor's real routing:

   * ``subquery-residual`` — RETIRED from the shipped corpus by
     multi-pass streaming: subquery conjuncts pre-plan their inner
     queries into device-resident residuals that ride the per-chunk
     program as ordinary jit operands (scans tagged
     ``streamed-subquery``; NOT IN's null probe additionally
     ``recorded-scalar``). The code survives for foreign corpora whose
     shapes the residual machinery cannot serve.
   * ``chunk-dependent-host-read`` — the streamed graph has unconnected
     components: ``Planner._cartesian`` lays out the pair expansion from
     host row counts, and ``DeviceCount.to_int`` inside a stream-bounds
     region raises ``StreamSyncError`` (observed runtime reason:
     "not chunk-invariant").
   * ``outer-join-extras`` — the chunked scan sits on a side of an outer
     join the multi-pass deferral cannot serve (ON keys not covering the
     probe side's PK, post-join WHERE over an outer-build side): outer
     extras semantics then need the whole side materialized, so the
     survivor accumulator holds the entire >HBM scan and overflows by
     construction (overflow ⇒ eager rerun). Eligible LEFT joins instead
     DEFER into the streamed graph (``outer-gather`` — per-chunk PK
     gather on the preserved side; ``outer-build`` — inner pairs plus an
     on-device unmatched-key accumulator, extras at materialize) and
     classify compiled.
   * ``accumulator-overflow`` — same mechanism without the outer-join
     context: a bare streamed scan (no filter, no join) keeps every chunk
     row AND the static memory model (:mod:`nds_tpu.analysis.mem_audit`)
     cannot prove the survivor accumulator fits the HBM capacity model.
     A bare scan whose proven bound FITS is ``compiled-stream``: the
     runtime sizes the accumulator from the same proof, so the overflow
     rerun can never fire (lockstep rule — both sides changed together).
   * ``non-invariant-graph`` — conservative catch-all for graphs the model
     cannot prove chunk-invariant (currently: a chunked scan bound by a
     statement shape outside the SELECT/join-graph forms modeled here).
   * ``parse-error`` — the statement did not parse; classification is
     ``unknown`` (plan-audit reports the parse error itself).

2. **How many host syncs can it cost?** A conservative static bound walked
   against the sync-effect model of :mod:`nds_tpu.engine.ops` (documented
   in DESIGN.md "Sync-effect model"): which operations materialize a
   device->host read, which defer into the thread's batched count
   resolution, and which ride the replay log. Two numbers are reported:

   * ``sync_bound`` — the statement-level bound (None when any scan takes
     the eager loop: its cost is O(chunks), reported as ``per_chunk``).
   * per-scan ``gate_bound`` — the steady-state budget of one compiled
     streamed scan *in its local context*: the pipeline's single
     materializing sync + its SELECT's post-aggregation syncs + outer-join
     materializations it feeds + one output resolution. This is exactly
     what ``tests/test_synccount.py::test_streamed_chunked_sync_budget``
     pins for single-graph statements; the lint gate fails when a
     streamable plan's gate_bound exceeds :data:`SYNC_BUDGET`.

   One-time record/compile costs (dimension-side plan reads riding the
   replay log, identity-cached per dimension) are reported separately as
   ``first_sight`` and are NOT gated: they amortize across a Power Run's
   2-4 executions the same way XLA compiles do.

   **The partition pass costs zero syncs.** A graph whose proven
   accumulator bound is past the capacity model runs the grace-style
   PARTITIONED pipeline (``engine/stream.py``): an extra jitted pass
   hashes every chunk row to a partition (histogram device-resident),
   each partition dispatches into its own accumulator, and the single
   materializing sync fetches every partition's count + flag in ONE
   transfer — so a partitioned statement's sync bound is IDENTICAL to
   the unpartitioned one and no classification moves. That zero is a
   checked contract: ``tools/exec_audit_diff.py`` drives the fan-out
   A/B templates through the partitioned pipeline (forced
   ``NDS_TPU_STREAM_PARTITIONS``) and fails if any ``stream.partition``
   span ever charges a host sync. The per-partition memory bounds
   themselves live in :mod:`nds_tpu.analysis.mem_audit` (the
   ``hbm-capacity`` gate + ``--mem-report``).

**Encoded columnar execution is sync-free.** The streamed chunk path may
upload int/date/decimal columns as narrow FOR/dictionary codes
(``io/columnar.py`` + ``engine/column.py``): the encoding plan is built
on HOST from whole-table stats before any chunk uploads (chunk-invariant,
like the string dictionaries), predicates and join keys either evaluate
directly on encoded values or decode through a fused elementwise widen
INSIDE the jitted per-chunk program, and the wide materialization happens
on host after the single materializing transfer (mirroring
``dict_values[codes]``). No step of encode or decode ever reads the
device, so the sync-effect model charges encoded execution NOTHING — no
bound in this module changes when ``NDS_TPU_ENCODED`` is on (the
default). The contract is checked the same way as every other zero: the
A/B templates run encoded by default through both differential harnesses,
whose static sync bounds would fail if encode/decode started paying.

**The prefetch worker is sync-free.** The bounded prefetch ring
(``engine/prefetch.py``, ``NDS_TPU_PREFETCH_DEPTH``) moves the host
slice + narrow encode + async upload of upcoming chunks onto a worker
thread while the driver dispatches compute. None of that work ever
reads the device (numpy slicing plus an asynchronous ``device_put``),
so the sync-effect model charges the ring NOTHING — no bound in this
module changes with the ring on (the default) or at any depth, and
``StreamEvent.syncs`` is identical between depth 0 and depth N (the
slow-source differential in ``tests/test_prefetch.py`` pins it). The
zero is enforced two ways: statically by the
``host-sync-in-prefetch-worker`` jax_lint rule (a host read or span in
any callable handed to the ring is an error — the worker's thread-local
counters would swallow it), and at runtime by the same span/event sync
cross-checks the differential harness already runs (a worker sync would
surface as an event-vs-bound mismatch).

**Fault-recovery retries RE-CHARGE the same bound, never re-budget it.**
The fault-tolerance layer (``engine/faults.py``, DESIGN.md
"Fault-tolerance contract") wraps every blocking device->host fetch in
a bounded transient retry (``sync`` seam) and may degrade a compiled
pipeline to the eager loop (``pipeline-compile``/``exchange`` seams).
The sync model here bounds the FAULT-FREE run: a transient retry
re-executes the SAME charged read (attempt k pays the identical sync
the model already counted once — under fault the realized count is
bound × attempts, bounded by the seam's registered retry allowance,
never unbounded), and a degradation lands on the eager path whose
O(chunks) cost the model already reports per scan. Neither moves a
classification or a bound in this module; both are evidence-recorded
as FaultEvents, so ``tools/fault_diff.py`` can subtract recoveries
when holding runtime evidence against the static bounds — a recovered
run must still be bit-for-bit, and an unrecovered one must raise a
classified error within its deadline rather than drift past the model
silently.

**Trace instrumentation is sync-free.** The obs span layer
(:mod:`nds_tpu.obs`) wraps the instrumented phases in host-clock spans
that read only the thread's existing sync/wait/compile counters, so the
sync-effect model charges instrumentation NOTHING — no bound in this
module changes when tracing is on (the default). That zero is itself a
checked contract: the differential harness cross-checks every drained
``stream`` span's sync delta against its ``StreamEvent.syncs`` on the A/B
templates, so the trace layer cannot silently start paying for its own
metrics without failing tier-1.

The model is a **checked contract**, not documentation: the differential
harness (``tools/exec_audit_diff.py``) replays the ``test_synccount`` A/B
templates through the real engine and fails when the static path or bound
disagrees with the runtime ``StreamEvent`` evidence — the same lockstep
rule that ties ``plan_audit`` to ``Planner._resolve_name``. **When you
change the planner's routing (``_stream_join_parts``, ``stream_execute``)
or the sync behavior of an engine op, update this model in the same PR**;
the harness and ``tests/test_analysis.py`` will fail until you do.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from nds_tpu.analysis import Finding
from nds_tpu.analysis.plan_audit import _single_row_query, type_class
from nds_tpu.queries import (TEMPLATE_DIR, instantiate_template,
                             list_templates, load_template)
from nds_tpu.schema import COMPOSITE_PRIMARY_KEYS, PRIMARY_KEYS, get_schemas
from nds_tpu.sql import ast as A
from nds_tpu.sql.parser import ParseError, expr_key, parse

# the streamed-path host-sync budget every compiled scan must prove
# (ROADMAP "Streamed-path sync budget"; tests/test_synccount.py pins it)
SYNC_BUDGET = 6

# static COLLECTIVE budget of the sharded streamed pipeline
# (NDS_TPU_STREAM_SHARDS > 1, engine/stream.py): per chunk, the only
# collectives are the hash-exchange pass's all-to-alls — at most one per
# uploaded buffer (data + validity per kept column) plus the partition-id
# and validity planes, so <= 2 x scan columns + 2; the widest streamed
# fact (catalog_sales, 34 columns) bounds the corpus at 70. At the single
# materializing sync, ONE cross-shard reduce runs: an all-gather of the
# per-shard counts, a psum of the overflow flags, a psum of the partition
# histogram, and one psum-OR per deferred outer-build bitmap — a fixed
# handful, gated at 8 (+1 per outer build is still far below). The
# per-chunk program itself must contain ZERO collectives (every shard
# works its own rows; builds ride replicated). Checked against runtime
# trace-time accounting (StreamEvent.collectives) by
# tools/exec_audit_diff.py under a forced multi-device mesh.
COLLECTIVE_CHUNK_BUDGET = 72
COLLECTIVE_FINAL_BUDGET = 8

# >HBM binding model: the catalog tables bound as host-resident
# ChunkedTables at the audited scale (SF10 with NDS_TPU_STREAM_BYTES=1.5e9
# streams exactly these four; session.read_columnar_view decides at load
# from arrow.nbytes, which the audit cannot see — this set is the static
# stand-in and is parameterizable per ExecAuditor).
DEFAULT_STREAMED = ("catalog_sales", "inventory", "store_sales", "web_sales")
# (round 11 corpus: 96 compiled-stream / 7 device-resident / 0
# eager-fallback — multi-pass streaming retired the subquery-residual
# and outer-join-extras fallbacks; the counts are pinned in tier-1 by
# tests/test_analysis.py::test_stream_report_classification_counts_pinned)

# descending resident-size rank of the streamable facts: when a graph binds
# several chunked scans the planner streams the LARGEST (by nbytes) and
# binds the others whole; the audit mirrors that choice by SF row weight
_SIZE_RANK = {"store_sales": 4, "catalog_sales": 3, "web_sales": 2,
              "inventory": 1}

CLASS_COMPILED = "compiled-stream"
CLASS_EAGER = "eager-fallback"
CLASS_DEVICE = "device-resident"
CLASS_UNKNOWN = "unknown"

R_SUBQUERY = "subquery-residual"
R_OUTER = "outer-join-extras"
R_CHUNK_READ = "chunk-dependent-host-read"
R_OVERFLOW = "accumulator-overflow"
R_NON_INVARIANT = "non-invariant-graph"
R_PARSE = "parse-error"


@dataclass
class ScanVerdict:
    """The audited fate of one >HBM streamed scan (one join graph binding a
    chunked table)."""

    alias: str                 # FROM alias of the chunked scan
    table: str                 # catalog table name
    compiled: bool             # True = the chunk pipeline serves it
    reasons: tuple = ()        # eager-fallback reason codes (empty if compiled)
    gate_bound: int = 0        # steady-state local sync bound (gated <= 6)
    per_chunk: int = 0         # eager loop: syncs charged PER CHUNK
    first_sight: int = 0       # one-time record/compile extras (not gated)
    mechanisms: tuple = ()     # multi-pass conversions serving this scan
    #                            ("streamed-subquery", "outer-gather",
    #                             "outer-build", "recorded-scalar")
    shards: int = 1            # modeled mesh shard count
    #                            (NDS_TPU_STREAM_SHARDS; 1 = single-device)
    a2a_chunk: int = 0         # collective budget, per chunk: upper bound
    #                            on the exchange pass's all-to-alls (0 =
    #                            no exchange can run — unsharded, or no
    #                            hashable equi keys)
    coll_final: int = 0        # collective budget at the materializing
    #                            sync: the one cross-shard reduce's ops
    kernel_scan_chunk: int = 0  # fused Pallas scan-pass launches per
    #                            chunk (EXACT: 1 iff the shared
    #                            eligibility rule lowers >=1 chunk-local
    #                            conjunct under an explicit
    #                            NDS_TPU_PALLAS mode) — checked against
    #                            StreamEvent.kernel_launches
    kernel_stages: int = 0     # fused stages per scan launch (EXACT:
    #                            eligible conjuncts + the routing-hash
    #                            stage) == kernel_fused_stages
    kernel_probe_chunk: int = 0  # UPPER bound on fused join-probe
    #                            launches per chunk-program dispatch
    #                            (the graph's hash batches; the probe
    #                            may decline per batch — f64 keys,
    #                            oversized dimension)


@dataclass
class ExecReport:
    """Classification + sync bound of one template statement."""

    file: str
    query: str
    classification: str
    reasons: tuple = ()
    sync_bound: int | None = None   # statement bound; None = O(chunks)
    per_chunk: int = 0              # eager per-chunk charge (0 if bounded)
    first_sight: int = 0
    scans: tuple = ()               # ScanVerdicts, FROM order
    detail: str = ""

    def to_dict(self) -> dict:
        return {
            "file": self.file, "query": self.query,
            "classification": self.classification,
            "reasons": list(self.reasons),
            "sync_bound": self.sync_bound, "per_chunk": self.per_chunk,
            "first_sight": self.first_sight,
            "scans": [{"alias": s.alias, "table": s.table,
                       "compiled": s.compiled, "reasons": list(s.reasons),
                       "gate_bound": s.gate_bound,
                       "per_chunk": s.per_chunk,
                       "first_sight": s.first_sight,
                       "mechanisms": list(s.mechanisms),
                       "shards": s.shards,
                       "a2a_chunk": s.a2a_chunk,
                       "coll_final": s.coll_final,
                       "kernel_scan_chunk": s.kernel_scan_chunk,
                       "kernel_stages": s.kernel_stages,
                       "kernel_probe_chunk": s.kernel_probe_chunk}
                      for s in self.scans],
            "detail": self.detail,
        }


class _Rel:
    """One relation in a join graph. ``cols`` maps each FROM alias the
    relation answers for to its bare (lowercase) column names — a
    materialized outer join keeps BOTH sides' aliases addressable, exactly
    like the planner's alias-qualified merged columns."""

    __slots__ = ("cols", "classes", "source", "chunked", "single_row",
                 "outer_mech")

    def __init__(self, alias, columns, classes=None, source=None,
                 chunked=False, single_row=False):
        self.cols = {alias.lower(): {c.lower() for c in columns}}
        self.classes = classes or {}
        self.source = source          # pristine base-table name, else None
        self.chunked = chunked
        self.single_row = single_row
        # multi-pass streaming marker: "outer-gather" (deferred probe) /
        # "outer-build" (unmatched-key accumulator) when this rel entered
        # the graph through a deferred LEFT join
        self.outer_mech = None

    @property
    def alias(self) -> str:
        return next(iter(self.cols))

    def owns(self, ref: A.ColumnRef) -> str | None:
        """The bare column name when this relation provides ``ref``."""
        name = ref.name.lower()
        if ref.table:
            t = ref.table.lower()
            cols = self.cols.get(t)
            return name if cols is not None and name in cols else None
        for cols in self.cols.values():
            if name in cols:
                return name
        return None

    def merged_with(self, other: "_Rel") -> "_Rel":
        out = _Rel(self.alias, ())
        out.cols = {**self.cols, **other.cols}
        out.classes = {**self.classes, **other.classes}
        return out


class _Cost:
    """Accumulator for the statement walk: statement-fixed sync bound,
    eager per-chunk charge, one-time extras, and the streamed-scan
    verdicts whose gate bounds grow as downstream costs apply."""

    def __init__(self):
        self.fixed = 0
        self.per_chunk = 0
        self.first_sight = 0
        self.scans: list = []
        self.needed = None               # statement pruning set (mem model)


def _children(e):
    """Direct expression children of an AST expression node (dataclass
    fields that are expressions, or lists/tuples containing them)."""
    if not hasattr(e, "__dataclass_fields__"):
        return
    for f in vars(e).values():
        if isinstance(f, A.Expr):
            yield f
        elif isinstance(f, (list, tuple)):
            for x in f:
                if isinstance(x, A.Expr):
                    yield x
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, A.Expr):
                            yield y


def _has_subquery(e) -> bool:
    if isinstance(e, (A.ScalarSubquery, A.InSubquery, A.Exists,
                      A.QuantifiedCompare)):
        return True
    return any(_has_subquery(c) for c in _children(e))


def _subquery_nodes(e) -> list:
    """Top-level subquery nodes of one expression (no descent into a
    found subquery's own body): the residuals the streamed pipeline
    pre-plans for this conjunct, one resolve each."""
    if isinstance(e, (A.ScalarSubquery, A.InSubquery, A.Exists,
                      A.QuantifiedCompare)):
        return [e]
    out = []
    for c in _children(e):
        out.extend(_subquery_nodes(c))
    return out


def _column_refs(e):
    out = []

    def walk(node):
        if isinstance(node, A.ColumnRef):
            out.append(node)
            return
        if isinstance(node, (A.ScalarSubquery, A.InSubquery, A.Exists,
                             A.QuantifiedCompare)):
            return                     # a subquery's refs are its own scope
        for c in _children(node):
            walk(c)
    walk(e)
    return out


def _split_conjuncts(e):
    if isinstance(e, A.BinaryOp) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e] if e is not None else []


def _split_disjuncts(e):
    if isinstance(e, A.BinaryOp) and e.op == "or":
        return _split_disjuncts(e.left) + _split_disjuncts(e.right)
    return [e]


def _fold_bool(op, exprs):
    out = exprs[0]
    for e in exprs[1:]:
        out = A.BinaryOp(op, out, e)
    return out


def _hoist_or_conjuncts(e):
    """Mirror of ``Planner._hoist_or_conjuncts`` (q13/q48/q85: equi keys
    hidden under an OR of conjunctions), compared by ``expr_key`` so the
    audit factors exactly what the planner factors."""
    if not (isinstance(e, A.BinaryOp) and e.op == "or"):
        return [e]
    conj_lists = [_split_conjuncts(d) for d in _split_disjuncts(e)]
    keys = [{expr_key(c) for c in dl} for dl in conj_lists]
    common = [c for c in conj_lists[0]
              if all(expr_key(c) in ks for ks in keys[1:])]
    if not common:
        return [e]
    common_keys = {expr_key(c) for c in common}
    rests = []
    for dl in conj_lists:
        rest = [c for c in dl if expr_key(c) not in common_keys]
        if not rest:
            return common
        rests.append(_fold_bool("and", rest))
    return common + [_fold_bool("or", rests)]


def _conjuncts_of(e):
    return [h for c in _split_conjuncts(e) for h in _hoist_or_conjuncts(c)]


class ExecAuditor:
    """Host-only abstract interpreter over the planner's decomposition.

    ``catalog`` maps table name -> {bare column -> type class}; default is
    the full TPC-DS schema. ``streamed`` names the tables bound as >HBM
    ChunkedTables (the binding model); ``base_tables`` carry schema
    guarantees (PK uniqueness for gather joins) — default: every catalog
    table, matching a session that loads them as base scans."""

    def __init__(self, catalog: dict | None = None,
                 streamed=None, base_tables=None, mem_model=None):
        if catalog is None:
            catalog = {
                t: {f.name.lower(): type_class(f.type) for f in fields}
                for t, fields in get_schemas(use_decimal=True).items()}
        self.catalog = catalog
        self.streamed = set(DEFAULT_STREAMED if streamed is None
                            else streamed)
        self.base_tables = set(catalog if base_tables is None
                               else base_tables)
        if mem_model is None:
            # lazy: mem_audit imports this module's AST helpers at top
            from nds_tpu.analysis.mem_audit import MemModel
            mem_model = MemModel()
        self.mem = mem_model

    # -- entry points -------------------------------------------------------

    def audit_sql(self, sql: str, file: str = "<sql>",
                  query: str = "<sql>") -> ExecReport:
        """Classify one SQL statement and bound its host syncs."""
        try:
            stmt = parse(sql)
        except ParseError as e:
            return ExecReport(file, query, CLASS_UNKNOWN, (R_PARSE,),
                              detail=str(e))
        cost = _Cost()
        # the statement's referenced-column set (planner projection
        # pushdown mirror): the accumulator-fit test below prices only
        # the columns a bare streamed scan would actually upload
        from nds_tpu.analysis.mem_audit import statement_needed_names
        cost.needed = statement_needed_names(
            stmt, {t: list(cols) for t, cols in self.catalog.items()})
        env = {name: (set(cols), name in self.base_tables)
               for name, cols in self.catalog.items()}
        try:
            if isinstance(stmt, A.Query):
                self._audit_query(stmt, env, None, cost)
            elif isinstance(stmt, (A.InsertInto, A.CreateTempView)):
                self._audit_query(stmt.query, env, None, cost)
            elif isinstance(stmt, A.DeleteFrom):
                return ExecReport(file, query, CLASS_DEVICE,
                                  sync_bound=1,
                                  detail="DML: device-resident delete")
            else:
                return ExecReport(file, query, CLASS_UNKNOWN,
                                  (R_NON_INVARIANT,),
                                  detail=f"unmodeled statement "
                                         f"{type(stmt).__name__}")
        except RecursionError:                      # pathological nesting
            return ExecReport(file, query, CLASS_UNKNOWN,
                              (R_NON_INVARIANT,), detail="recursion limit")
        # the one output resolution every statement pays (collect() /
        # ORDER BY+LIMIT shaping; batched with any still-lazy counts)
        cost.fixed += 1
        for s in cost.scans:
            if s.compiled:
                s.gate_bound += 1
        if not cost.scans:
            classification = CLASS_DEVICE
        elif all(s.compiled for s in cost.scans):
            classification = CLASS_COMPILED
        else:
            classification = CLASS_EAGER
        reasons = []
        for s in cost.scans:
            for r in s.reasons:
                if r not in reasons:
                    reasons.append(r)
        return ExecReport(
            file, query, classification, tuple(reasons),
            sync_bound=cost.fixed if cost.per_chunk == 0 else None,
            per_chunk=cost.per_chunk, first_sight=cost.first_sight,
            scans=tuple(cost.scans))

    # -- query / set-expression walk ---------------------------------------

    def _audit_query(self, q: A.Query, env: dict, outer, cost: _Cost):
        """Walk one query expression; returns its output column names."""
        env = dict(env)
        for cname, cq in q.ctes:
            out = self._audit_query(cq, env, outer, cost)
            # a CTE result is a device table whatever it scanned; it may
            # SHADOW a chunked catalog name (the planner resolves CTEs
            # first, so the statement does not stream the shadowed table)
            env[cname.lower()] = (set(out), False)
        return self._audit_body(q.body, env, outer, cost)
        # ORDER BY / LIMIT: lexsort is device-side and LIMIT's count
        # resolution batches into the output read — no extra charge

    def _audit_body(self, body, env: dict, outer, cost: _Cost):
        if isinstance(body, A.SetOp):
            left = self._audit_body(body.left, env, outer, cost)
            self._audit_body(body.right, env, outer, cost)
            if body.op == "union_all":
                # concat_tables resolves every branch's lazy count in one
                # batched transfer
                cost.fixed += 1
            elif body.op == "union":
                cost.fixed += 2          # concat resolve + distinct grouping
            else:
                # intersect/except: distinct grouping + null-safe semi
                # probe (generic multi-key path sizes candidate pairs)
                cost.fixed += 2
            return left
        if isinstance(body, A.Query):
            return self._audit_query(body, env, outer, cost)
        return self._audit_select(body, env, outer, cost)

    # -- SELECT -------------------------------------------------------------

    def _audit_select(self, sel: A.Select, env: dict, outer,
                      cost: _Cost) -> list:
        where = _conjuncts_of(sel.where)
        local_scans: list = []
        parts, preds = self._flatten_from(sel.from_, env, outer, where,
                                          cost, local_scans)
        scope = (parts, env, outer)
        if parts or where:
            self._audit_graph(parts, preds, where, scope, cost,
                              local_scans, outer_ctx=False)
        # subqueries outside the WHERE (scalar subqueries in the
        # projection — the q9 shape — and in HAVING/GROUP BY) execute
        # during this statement: their plans charge the walk too
        for item in sel.items:
            self._audit_expr_subqueries(item.expr, scope, cost)
        if sel.having is not None:
            self._audit_expr_subqueries(sel.having, scope, cost)
        # post-FROM sync charges (ops.py sync-effect model):
        post = 0
        if sel.group_by is not None:
            post += 1                    # group_ids' batched count resolve
            if len(sel.group_by.exprs) > 1:
                post += 1                # packed-plan key-range probe
        # keyless aggregates (no GROUP BY) ride device validity: no charge
        if sel.distinct:
            post += 1                    # distinct = one more grouping
        cost.fixed += post
        for s in local_scans:
            if s.compiled:
                s.gate_bound += post
        return self._projected_names(sel, parts)

    def _projected_names(self, sel: A.Select, parts) -> list:
        out = []
        for i, item in enumerate(sel.items):
            if isinstance(item.expr, A.Star):
                qual = item.expr.table and item.expr.table.lower()
                for p in parts:
                    for alias, cols in p.cols.items():
                        if qual is None or alias == qual:
                            out.extend(sorted(cols))
                continue
            if item.alias:
                out.append(item.alias.lower())
            elif isinstance(item.expr, A.ColumnRef):
                out.append(item.expr.name.lower())
            else:
                out.append(f"_c{i}")
        return out

    # -- FROM flattening (mirror of Planner._flatten_from) ------------------

    def _flatten_from(self, node, env: dict, outer, where: list,
                      cost: _Cost, local_scans: list, top: bool = True):
        if node is None:
            return [], []
        if isinstance(node, A.TableRef):
            name = node.name.lower()
            alias = (node.alias or node.name).lower()
            cols, is_base = env.get(name, (set(), False))
            chunked = is_base and name in self.streamed
            classes = self.catalog.get(name, {}) if is_base else {}
            rel = _Rel(alias, cols, classes,
                       source=name if is_base else None, chunked=chunked)
            return [rel], []
        if isinstance(node, A.SubqueryRef):
            out = self._audit_query(node.query, env, outer, cost)
            return [_Rel(node.alias, out,
                         single_row=_single_row_query(node.query))], []
        if isinstance(node, A.Join):
            if node.kind in ("cross", "inner"):
                lp, lj = self._flatten_from(node.left, env, outer, where,
                                            cost, local_scans, top=False)
                rp, rj = self._flatten_from(node.right, env, outer, where,
                                            cost, local_scans, top=False)
                return lp + rp, lj + rj + _conjuncts_of(node.condition)
            # outer/semi/anti join: each side is its own join graph,
            # materialized whole before the join — WHERE conjuncts owned
            # by the null-preserving side push below it first. A LEFT
            # join with a chunked side may instead DEFER into the
            # streamed graph (multi-pass mechanisms b1/b2, mirroring
            # Planner._flatten_from): the sides' rels then join the
            # enclosing graph with the ON conjuncts as ordinary edges.
            lp, lj = self._flatten_from(node.left, env, outer, where,
                                        cost, local_scans, top=False)
            deferred = self._deferred_left(node, lp, lj, env, outer,
                                           where, cost, local_scans, top)
            if deferred is not None:
                return deferred
            lw = self._consume_pushable(where, lp) \
                if node.kind == "left" else []
            self._audit_graph(lp, lj, lw, (lp, env, outer), cost,
                              local_scans, outer_ctx=True)
            rp, rj = self._flatten_from(node.right, env, outer, where,
                                        cost, local_scans, top=False)
            return self._finish_outer(node, lp, rp, rj, env, outer, where,
                                      cost, local_scans)
        if isinstance(node, A.Query):        # parenthesized join tree
            return self._flatten_from(getattr(node.body, "from_", None),
                                      env, outer, where, cost, local_scans)
        return [], []

    def _finish_outer(self, node, lp, rp, rj, env, outer, where, cost,
                      local_scans):
        """The materialize-both-sides completion of one outer/semi/anti
        join (the right side already flattened; the left side already
        audited)."""
        rw = self._consume_pushable(where, rp) \
            if node.kind == "right" else []
        self._audit_graph(rp, rj, rw, (rp, env, outer), cost,
                          local_scans, outer_ctx=True)
        join_cost = self._binary_join_cost(node, lp, rp, cost)
        # every streamed scan flattened so far in this SELECT feeds (or
        # conservatively precedes) this materialized join: its result
        # rides through the join's syncs on the way to the output
        for s in local_scans:
            if s.compiled:
                s.gate_bound += join_cost
        sides = lp + rp
        if not sides:
            return [], []
        merged = sides[0]
        for p in sides[1:]:
            merged = merged.merged_with(p)
        merged.single_row = False
        merged.chunked = False
        merged.source = None
        return [merged], []

    def _deferred_left(self, node, lp, lj, env, outer, where, cost,
                       local_scans, top):
        """Mirror of the planner's multi-pass LEFT-join deferral
        (``Planner._flatten_from`` mechanisms b1/b2): returns the merged
        ``(parts, preds)`` when the join defers into the streamed graph,
        the completed materialize-path result when a side had to be
        flattened to decide (no double audit), or None when the
        pre-checks already exclude deferral (caller runs today's path).
        ``top`` mirrors the planner's whole-FROM requirement for the
        outer-build deferral."""
        if node.kind != "left" or node.condition is None:
            return None
        conjs = _conjuncts_of(node.condition)
        if not conjs or any(_has_subquery(c) for c in conjs):
            return None

        def plain_pairs(rel):
            """(left key, right key) bare names per conjunct when every
            conjunct is a plain cross-side equi pair against ``rel``."""
            out = []
            for c in conjs:
                if not (isinstance(c, A.BinaryOp) and c.op == "=" and
                        isinstance(c.left, A.ColumnRef) and
                        isinstance(c.right, A.ColumnRef)):
                    return None
                rk = rel.owns(c.left)
                lk_ref = c.right
                if rk is None:
                    rk = rel.owns(c.right)
                    lk_ref = c.left
                if rk is None:
                    return None
                if not any(p.owns(lk_ref) for p in lp):
                    return None
                out.append((lk_ref, rk))
            return out

        l_chunk = any(p.chunked for p in lp)
        if l_chunk:
            if os.environ.get("NDS_TPU_NO_PK_GATHER"):
                return None              # the b1 gather arm is disabled
            # mechanism (b1): preserved chunk side — right must be one
            # pristine scan whose ON keys are exactly its (composite) PK
            rp, rj = self._flatten_from(node.right, env, outer, where,
                                        cost, local_scans, top=False)
            eligible = len(rp) == 1 and not rj and rp[0].source and \
                not rp[0].chunked
            if eligible:
                pairs = plain_pairs(rp[0])
                pk = COMPOSITE_PRIMARY_KEYS.get(rp[0].source)
                if pk is None and rp[0].source in PRIMARY_KEYS:
                    pk = (PRIMARY_KEYS[rp[0].source],)
                eligible = pairs is not None and pk is not None and \
                    {rk for (_lr, rk) in pairs} == set(pk)
                if eligible and len(pk) > 1 and any(
                        rp[0].classes.get(k) != "num" for k in pk):
                    eligible = False     # composite pack is int-only
            if eligible:
                rp[0].outer_mech = "outer-gather"
                return lp + rp, lj + conjs
            # ineligible after flattening: the planner's materialize
            # path, reusing the flattened right side
            lw = self._consume_pushable(where, lp)
            self._audit_graph(lp, lj, lw, (lp, env, outer), cost,
                              local_scans, outer_ctx=True)
            return self._finish_outer(node, lp, rp, rj, env, outer,
                                      where, cost, local_scans)
        # mechanism (b2): null-introducing chunk side — single device
        # part on the left (the build side, materialized first with its
        # pushed WHERE conjuncts), single chunked scan on the right, the
        # join being the SELECT's whole FROM, and no remaining WHERE
        # conjunct at all (post-join structure would need the extras,
        # emitted only at materialize, to flow through it)
        if len(lp) != 1 or lp[0].chunked:
            return None
        lw = self._consume_pushable(where, lp)
        rp, rj = self._flatten_from(node.right, env, outer, where, cost,
                                    local_scans, top=False)
        eligible = top and len(rp) == 1 and not rj and rp[0].chunked \
            and not (where or [])
        if eligible:
            pairs = plain_pairs(rp[0])
            eligible = pairs is not None
        if eligible:
            lp[0].outer_mech = "outer-build"
            lp[0].single_row = False
            return rp + lp, lj + conjs
        # fall back: audit the build side as its own (device) graph and
        # finish with the materialize path
        self._audit_graph(lp, lj, lw, (lp, env, outer), cost,
                          local_scans, outer_ctx=True)
        return self._finish_outer(node, lp, rp, rj, env, outer, where,
                                  cost, local_scans)

    def _binary_join_cost(self, node: A.Join, lp, rp, cost: _Cost) -> int:
        """Sync charge of one materialized (outer/semi/anti) binary join.

        LEFT joins whose ON keys cover the right side's declared
        (composite) primary key run as exact merge-probe gathers — no pair
        sizing, no extras resolution, zero steady-state syncs (the
        dimension span plan is identity-cached; first sight pays one
        fused range read). Everything else pays the hash probe's
        candidate-total sync plus one batched extras resolution."""
        conjuncts = _conjuncts_of(node.condition)
        if node.kind == "left" and len(rp) == 1 and rp[0].source:
            src = rp[0].source
            pk = COMPOSITE_PRIMARY_KEYS.get(src)
            if pk is None and src in PRIMARY_KEYS:
                pk = (PRIMARY_KEYS[src],)
            if pk is not None:
                rkeys = set()
                for c in conjuncts:
                    if isinstance(c, A.BinaryOp) and c.op == "=" and \
                            isinstance(c.left, A.ColumnRef) and \
                            isinstance(c.right, A.ColumnRef):
                        for ref in (c.left, c.right):
                            got = rp[0].owns(ref)
                            if got:
                                rkeys.add(got)
                if rkeys == set(pk):
                    cost.first_sight += 1        # dim span/range plan
                    return 0
        if node.kind in ("semi", "anti"):
            # single integer-comparable key takes the sort-probe (0);
            # charge the generic candidate-sizing sync conservatively
            charge = 1
        else:
            charge = 2                   # probe total + batched extras
        cost.fixed += charge
        return charge

    def _consume_pushable(self, where: list, parts) -> list:
        """Mirror of ``Planner._consume_pushable``: remove (in place) and
        return the subquery-free conjuncts whose every column reference
        resolves within ``parts``."""
        taken = []
        for c in list(where):
            if _has_subquery(c):
                continue
            refs = _column_refs(c)
            if refs and all(any(p.owns(r) for p in parts) for r in refs):
                taken.append(c)
                where.remove(c)
        return taken

    # -- join-graph audit (mirror of Planner._join_parts routing) -----------

    def _owners(self, c, parts) -> set:
        """Indexes of the graph parts a conjunct references (refs that
        resolve only in outer scopes — correlation — own nothing here,
        matching ``Planner._expr_tables`` over the parts' columns)."""
        owners = set()
        for ref in _column_refs(c):
            for i, p in enumerate(parts):
                if p.owns(ref):
                    owners.add(i)
                    break                # planner takes the first match
        return owners

    def _equi_edge(self, c, parts):
        """(li, ri) when the conjunct is an equi edge the planner would
        join on: a plain ``col = col`` across two parts, or an
        expression-equi conjunct whose sides each live wholly in one
        distinct part (``Planner._synthetic_edge``)."""
        if not (isinstance(c, A.BinaryOp) and c.op == "="):
            return None
        if isinstance(c.left, A.ColumnRef) and \
                isinstance(c.right, A.ColumnRef):
            li = ri = None
            for i, p in enumerate(parts):
                if li is None and p.owns(c.left):
                    li = i
                if ri is None and p.owns(c.right):
                    ri = i
            if li is not None and ri is not None and li != ri:
                return li, ri, c
            return None

        def side_owner(e):
            refs = _column_refs(e)
            if not refs:
                return None
            owner = None
            for r in refs:
                cands = [i for i, p in enumerate(parts) if p.owns(r)]
                if len(cands) != 1:
                    return None
                if owner is None:
                    owner = cands[0]
                elif owner != cands[0]:
                    return None
            return owner

        li, ri = side_owner(c.left), side_owner(c.right)
        if li is not None and ri is not None and li != ri:
            return li, ri, c
        return None

    def _pk_batch(self, parts, a, b, edge_conjs):
        """Dim-side part index when the (a, b) edge batch qualifies for the
        PK gather join (``Planner._pk_gather_plan``): the dimension side's
        bare key-name set is exactly its declared primary key, on a
        pristine base-table scan; composite keys must be numeric to pack."""
        for fact, dim in ((a, b), (b, a)):
            src = parts[dim].source
            if not src:
                continue
            pk = COMPOSITE_PRIMARY_KEYS.get(src)
            if pk is None and src in PRIMARY_KEYS:
                pk = (PRIMARY_KEYS[src],)
            if pk is None:
                continue
            dks = set()
            for (li, ri, c) in edge_conjs:
                side = c.right if ri == dim else c.left
                if not isinstance(side, A.ColumnRef):
                    dks = None
                    break
                got = parts[dim].owns(side)
                if got is None:
                    dks = None
                    break
                dks.add(got)
            if dks != set(pk):
                continue
            if len(pk) > 1 and any(parts[dim].classes.get(k) != "num"
                                   for k in pk):
                continue
            return dim
        return None

    def _audit_graph(self, parts, preds, where, scope, cost: _Cost,
                     local_scans: list, outer_ctx: bool) -> list:
        """Audit one ``_join_parts`` invocation; returns the ScanVerdicts
        it created (appended to ``cost.scans`` and ``local_scans``)."""
        conjuncts = list(preds) + list(where)
        filters = [[] for _ in parts]
        edges = []                       # (li, ri, conjunct)
        residual = []
        subq = []
        subq_cost = _Cost()
        for c in conjuncts:
            if _has_subquery(c):
                subq.append(c)
                self._audit_expr_subqueries(c, scope, subq_cost)
                continue
            owners = self._owners(c, parts)
            if len(owners) == 1:
                filters[owners.pop()].append(c)
                continue
            edge = self._equi_edge(c, parts)
            if edge:
                edges.append(edge)
            else:
                residual.append(c)

        # union-find over parts: components joined by equi edges; the
        # planner cartesians the leftover slots
        parent = list(range(len(parts)))

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        batches: dict = {}               # sorted part pair -> [edges]
        for (li, ri, c) in edges:
            batches.setdefault(tuple(sorted((li, ri))), []).append(
                (li, ri, c))
        for (a, b) in batches:
            parent[find(a)] = find(b)
        ncomp = len({find(i) for i in range(len(parts))}) if parts else 0
        n_cart = max(ncomp - 1, 0)
        pk_dims = []
        hash_batches = 0
        for (a, b), ec in batches.items():
            dim = self._pk_batch(parts, a, b, ec)
            if dim is not None and not parts[dim].chunked:
                # chunked dim side is masked by the executor (its key
                # ranges would bake chunk data into the program): that
                # batch takes the hash arm
                pk_dims.append(dim)
            else:
                hash_batches += 1

        chunked_idx = [i for i, p in enumerate(parts) if p.chunked]
        if not chunked_idx:
            # device-resident graph: hash probes sync for their candidate
            # totals; PK gathers ride identity-cached host plans (first
            # sight builds them); cartesians resolve both counts batched
            cost.fixed += hash_batches + n_cart + subq_cost.fixed
            cost.per_chunk += subq_cost.per_chunk
            cost.first_sight += len(pk_dims) + subq_cost.first_sight
            cost.scans.extend(subq_cost.scans)
            return []

        # streamed graph: mirror stream_execute's eligibility
        keep = max(chunked_idx,
                   key=lambda i: (_SIZE_RANK.get(parts[i].source, 0), -i))
        reasons = []
        mechanisms = []
        if subq:
            # multi-pass streaming, mechanism (a): subquery conjuncts
            # pre-plan their inner tables into device-resident RESIDUALS
            # (recorded/driven as ordinary jit operands), so they no
            # longer break the chunk-invariant trace — the conjunct
            # reduces to a device-side membership/compare mask per chunk
            mechanisms.append("streamed-subquery")
            if any(isinstance(nq, A.InSubquery) and nq.negated
                   for c in subq for nq in _subquery_nodes(c)):
                # ANSI NOT IN consults the residual's null count: a
                # recorded scalar with a device-side staleness guard
                # (mechanism c)
                mechanisms.append("recorded-scalar")
        for p in parts:
            if p.outer_mech and p.outer_mech not in mechanisms:
                mechanisms.append(p.outer_mech)
        if ncomp > 1:
            reasons.append(R_CHUNK_READ)
        incident = any(keep in (li, ri) for (li, ri, _c) in edges) or \
            bool(filters[keep]) or \
            any(keep in self._owners(c, parts) for c in residual + subq)
        if not incident:
            if outer_ctx:
                reasons.append(R_OUTER)
            elif not self.mem.bare_scan_fits(parts[keep].source,
                                             cost.needed):
                # the survivor accumulator keeps every chunk row and the
                # memory proof cannot admit it — overflow rerun at scale.
                # A bare scan whose proven bound FITS streams compiled:
                # the runtime sizes the accumulator from the same proof.
                reasons.append(R_OVERFLOW)
        compiled = not reasons

        verdicts = []
        if compiled:
            # pipeline steady state: ONE materializing sync (count +
            # overflow flag + outer-extras counts in the same transfer);
            # the upfront part-count resolve batches counts the statement
            # owed anyway. Record-phase dimension plan reads ride the
            # replay log: first-sight only. Each subquery residual is
            # re-planned per execution — its table resolves once (the
            # inner plan's own costs are subq_cost).
            n_resid = sum(len(_subquery_nodes(c)) for c in subq)
            shards, a2a_chunk, coll_final = self._collective_budget(
                parts, keep, conjuncts, cost)
            k_scan, k_stages, k_probe = self._kernel_budget(
                parts, keep, filters[keep], conjuncts, mechanisms,
                hash_batches, len(subq), cost)
            v = ScanVerdict(parts[keep].alias, parts[keep].source or "?",
                            True, (), gate_bound=1,
                            first_sight=len(pk_dims) + 1,
                            mechanisms=tuple(mechanisms),
                            shards=shards, a2a_chunk=a2a_chunk,
                            coll_final=coll_final,
                            kernel_scan_chunk=k_scan,
                            kernel_stages=k_stages,
                            kernel_probe_chunk=k_probe)
            cost.fixed += 1 + subq_cost.fixed + n_resid
            cost.first_sight += v.first_sight + subq_cost.first_sight
        else:
            # eager chunk loop: every chunk re-plans the graph — each
            # hash batch pays its probe sync and each cartesian its
            # layout resolve PER CHUNK; subquery predicates re-evaluate
            # per chunk too. One final batched resolve concatenates the
            # surviving chunks.
            per_chunk = hash_batches + n_cart + \
                subq_cost.fixed + subq_cost.per_chunk
            v = ScanVerdict(parts[keep].alias, parts[keep].source or "?",
                            False, tuple(reasons), per_chunk=per_chunk,
                            first_sight=len(pk_dims),
                            mechanisms=tuple(mechanisms))
            cost.fixed += 1
            cost.per_chunk += per_chunk
            cost.first_sight += len(pk_dims) + subq_cost.first_sight
        cost.scans.extend(subq_cost.scans)
        cost.scans.append(v)
        local_scans.append(v)
        verdicts.append(v)
        # further chunked parts bind whole (one streaming axis per graph)
        for i in chunked_idx:
            if i != keep:
                w = ScanVerdict(parts[i].alias, parts[i].source or "?",
                                compiled, v.reasons,
                                gate_bound=v.gate_bound,
                                per_chunk=v.per_chunk,
                                mechanisms=v.mechanisms)
                cost.scans.append(w)
                local_scans.append(w)
                verdicts.append(w)
        return verdicts

    def _kernel_budget(self, parts, keep, chunk_filters, conjuncts,
                       mechanisms, hash_batches, n_subq, cost):
        """``(kernel_scan_chunk, kernel_stages, kernel_probe_chunk)`` of
        one compiled streamed scan — the static fused-Pallas-kernel
        prediction (DESIGN.md "Fused chunk kernels", sync model: every
        kernel pass is DEVICE-ONLY, zero host syncs — launches never
        move any sync bound).

        The scan-pass prediction is EXACT by construction: eligibility
        is the ONE shared rule (``analysis/kernel_spec.eligible_
        conjunct``) the runtime lowering applies to the same chunk-local
        conjuncts, and the hash stage mirrors the executor's partition
        trigger (forced count + hashable equi keys surviving the column
        pruning). ``tools/exec_audit_diff.py`` fails when drained
        ``StreamEvent.kernel_fused_stages`` differs or
        ``kernel_launches`` falls outside
        ``[scan x chunks, (scan + probe x P) x chunks]``.

        Predictions are live only under an EXPLICIT ``NDS_TPU_PALLAS``
        mode (``interpret``/``tpu``): ``auto`` resolves against the
        backend at runtime, which a host-only auditor cannot see, and a
        wrong guess would be model drift by construction. Outer-join
        graphs keep the whole XLA chain (the executor never splits
        their pre/post conjuncts), mirrored here."""
        mode = os.environ.get("NDS_TPU_PALLAS", "auto")
        if mode not in ("interpret", "tpu"):
            return 0, 0, 0
        # probe bound: every bound-bucket join in the per-chunk program
        # may take the fused probe — the plain hash batches, plus one
        # per deferred outer-BUILD (its matched-pair inner join probes
        # per dispatch) and one per subquery conjunct (a residual pair
        # probe, the q16-class EXISTS shape)
        n_builds = sum(1 for p in parts if p.outer_mech == "outer-build")
        probe = hash_batches + n_builds + n_subq
        if any(m in ("outer-gather", "outer-build") for m in mechanisms):
            return 0, 0, probe
        from nds_tpu.analysis.kernel_spec import count_eligible
        rel = parts[keep]

        def class_of(ref):
            bare = rel.owns(ref)
            return None if bare is None else rel.classes.get(bare)

        n = count_eligible(chunk_filters, class_of)
        if n == 0:
            return 0, 0, probe
        # hash stage: the executor attaches key slots when the pipeline
        # partitions (forced count + stream_partition_keys surviving
        # projection pruning) — same rule shape as _collective_budget
        from nds_tpu.analysis.mem_audit import (stream_partition_keys,
                                                stream_partitions_env)
        hash_stage = 0
        forced = stream_partitions_env()
        if forced is not None and forced > 1:
            part_cols = [{c for cols in p.cols.values() for c in cols}
                         for p in parts]
            sources = [p.source for p in parts]
            keys = stream_partition_keys(part_cols, sources, keep,
                                         conjuncts)
            if keys and (cost.needed is None
                         or all(k in cost.needed for k in keys)):
                hash_stage = 1
        return 1, n + hash_stage, probe

    def _collective_budget(self, parts, keep, conjuncts, cost):
        """``(shards, a2a_chunk, coll_final)`` of one compiled streamed
        scan — the static collective budget of the sharded pipeline
        (``NDS_TPU_STREAM_SHARDS``; all zeros when unsharded).

        ``a2a_chunk`` is an UPPER bound on the per-chunk exchange pass's
        all-to-alls: the pass MAY run only when the graph has hashable
        equi keys on the streamed slot (``stream_partition_keys`` — the
        same predicate the executor's partition/exchange trigger uses),
        and it exchanges at most every uploaded buffer (data + validity
        per pruned column) plus the partition-id and validity planes.
        ``coll_final`` bounds the one cross-shard materialize reduce:
        count all-gather + overflow psum + histogram psum + one psum-OR
        per deferred outer-build bitmap. The per-chunk program itself is
        collective-free by construction — every explicit collective the
        runtime issues is trace-time counted, and
        ``tools/exec_audit_diff.py`` fails when the measured
        ``StreamEvent.collectives`` ever exceeds
        ``a2a_chunk x chunks + coll_final``."""
        from nds_tpu.analysis.mem_audit import (stream_partition_keys,
                                                stream_shards_env)
        shards = stream_shards_env()
        if shards <= 1:
            return 1, 0, 0
        part_cols = [{c for cols in p.cols.values() for c in cols}
                     for p in parts]
        sources = [p.source for p in parts]
        keys = stream_partition_keys(part_cols, sources, keep, conjuncts)
        source = parts[keep].source or ""
        cols = self.catalog.get(source, {})
        n_cols = len(cols) or 1
        if cost.needed is not None and cols:
            kept = {c for c in cols if c in cost.needed}
            if kept and len(kept) < len(cols):
                n_cols = len(kept)
        a2a_chunk = (2 * n_cols + 2) if keys else 0
        n_builds = sum(1 for p in parts if p.outer_mech == "outer-build")
        return shards, a2a_chunk, 3 + n_builds

    # -- subqueries inside expressions --------------------------------------

    def _audit_expr_subqueries(self, e, scope, cost: _Cost) -> None:
        """Charge every subquery nested in one expression: the subquery's
        own plan cost plus its membership-probe cost. Single-key integer
        IN/NOT IN takes the sort probe (sync-free, DESIGN.md item 2);
        generic quantified compares pay the candidate-sizing sync.
        Scalar subqueries defer their one-row check into the batched
        resolution (0)."""
        parts, env, outer = scope

        def walk(node):
            if isinstance(node, A.InSubquery):
                self._audit_query(node.query, env, scope, cost)
                if not isinstance(node.expr, A.ColumnRef):
                    cost.fixed += 1
                walk_children(node.expr)
                return
            if isinstance(node, A.ScalarSubquery):
                self._audit_query(node.query, env, scope, cost)
                return
            if isinstance(node, (A.Exists, A.QuantifiedCompare)):
                self._audit_query(node.query, env, scope, cost)
                cost.fixed += 1
                if isinstance(node, A.QuantifiedCompare):
                    walk_children(node.expr)
                return
            walk_children(node)

        def walk_children(node):
            for c in _children(node):
                walk(c)

        walk(e)


# ---------------------------------------------------------------------------
# corpus driver + lint-gate findings
# ---------------------------------------------------------------------------

# pinned instantiation seed, shared with plan_audit: classifications must
# not depend on sampled parameter values, and a fixed seed keeps the gate
# and the report deterministic either way
_AUDIT_SEED = 20260803


def audit_exec_template_text(text: str, file: str,
                             auditor: ExecAuditor | None = None) -> list:
    """Instantiate one template (pinned seed) and audit each statement;
    returns ExecReports."""
    auditor = auditor or ExecAuditor()
    sql = instantiate_template(text, np.random.default_rng(_AUDIT_SEED))
    stmts = [s for s in sql.split(";") if s.strip()]
    base = os.path.basename(file)
    out = []
    for i, stmt in enumerate(stmts):
        qname = base[:-4] if base.endswith(".tpl") else base
        if len(stmts) > 1:
            qname = f"{qname}_part{i + 1}"
        out.append(auditor.audit_sql(stmt, file=base, query=qname))
    return out


def audit_exec_corpus(template_dir: str | None = None,
                      streamed=None) -> list:
    """ExecReports for every template in templates.lst order."""
    template_dir = template_dir or TEMPLATE_DIR
    auditor = ExecAuditor(streamed=streamed)
    reports: list = []
    for name in list_templates(template_dir):
        reports.extend(audit_exec_template_text(
            load_template(name, template_dir), name, auditor))
    return reports


def reports_to_findings(reports) -> list:
    """Lint-gate findings from exec reports: a streamable (compiled) scan
    whose steady-state gate bound exceeds the budget is an error — the
    compiled pipeline would hold >6 syncs per execution, which is exactly
    the regression the streamed-path budget forbids. Classifications
    themselves are a report, not findings."""
    findings = []
    for r in reports:
        for s in r.scans:
            if s.compiled and s.gate_bound > SYNC_BUDGET:
                findings.append(Finding(
                    r.file, r.query, "stream-sync-budget", "error",
                    f"streamed scan {s.table!r} has a static sync bound of "
                    f"{s.gate_bound} (> {SYNC_BUDGET}): the compiled "
                    "pipeline would exceed the streamed-path budget every "
                    "execution"))
            if s.compiled and s.shards > 1 and (
                    s.a2a_chunk > COLLECTIVE_CHUNK_BUDGET
                    or s.coll_final > COLLECTIVE_FINAL_BUDGET
                    + sum(1 for m in s.mechanisms if m == "outer-build")):
                findings.append(Finding(
                    r.file, r.query, "collective-budget", "error",
                    f"streamed scan {s.table!r} has a static collective "
                    f"budget of {s.a2a_chunk}/chunk + {s.coll_final} at "
                    f"materialize (> {COLLECTIVE_CHUNK_BUDGET}/"
                    f"{COLLECTIVE_FINAL_BUDGET}): the sharded pipeline "
                    "would pay more than one exchange per chunk or more "
                    "than the single cross-shard reduce"))
    return findings


def exec_audit_findings(template_dir: str | None = None) -> list:
    """The lint pass entry point (tools/lint.py fourth pass)."""
    return reports_to_findings(audit_exec_corpus(template_dir))


def format_stream_report(reports) -> str:
    """The per-template classification table (``tools/lint.py
    --stream-report``): the worklist for widening streamability."""
    lines = ["# exec-audit: per-template execution-path classification",
             f"# binding model: chunked = {', '.join(DEFAULT_STREAMED)}",
             f"{'template':<18} {'class':<16} {'bound':>6}  detail"]
    counts: dict = {}
    for r in reports:
        counts[r.classification] = counts.get(r.classification, 0) + 1
        if r.sync_bound is not None:
            bound = str(r.sync_bound)
        else:
            bound = f"~{r.per_chunk}/ch"
        bits = []
        for s in r.scans:
            if s.compiled:
                mech = f" [{','.join(s.mechanisms)}]" if s.mechanisms \
                    else ""
                shard = f" S={s.shards} coll<={s.a2a_chunk}/ch+" \
                    f"{s.coll_final}" if s.shards > 1 else ""
                bits.append(f"{s.table}: compiled{mech} "
                            f"gate={s.gate_bound}"
                            f"(+{s.first_sight} first-sight){shard}")
            else:
                bits.append(f"{s.table}: eager [{','.join(s.reasons)}] "
                            f"{s.per_chunk}/chunk")
        if not bits and r.reasons:
            bits.append(",".join(r.reasons))
        lines.append(f"{r.query:<18} {r.classification:<16} {bound:>6}  "
                     + "; ".join(bits))
    summary = ", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
    lines.append(f"# {len(reports)} statements — {summary}")
    return "\n".join(lines)
