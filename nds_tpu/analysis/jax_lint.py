# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Tracer-hazard lint: JAX-specific static checks over ``nds_tpu/``.

Python-``ast`` based; no JAX import, no tracing. Rules (each suppressible
with ``# nds-lint: ignore[rule]`` on the flagged line or the line above):

* ``host-sync-in-loop`` — a device->host synchronization primitive
  (``.item()``, ``np.asarray``/``np.array`` over device values,
  ``jax.device_get``, ``float()``/``int()`` of arrays is not detectable
  statically so it is out of scope) lexically inside a ``for``/``while``
  loop of the hot-path modules (``engine/ops.py``, ``sql/planner.py``).
  One sync per query is accounting; one per loop iteration is a dispatch
  stall. Warning severity: the existing accounted reads are baselined.
* ``tracer-if`` — a Python ``if``/``while`` whose test references a
  non-static parameter of a ``jax.jit``-decorated function. Under tracing
  this raises ``TracerBoolConversionError`` at best and silently bakes a
  branch at worst.
* ``cache-key-list`` — a raw ``list``/``set``/``dict`` display or
  comprehension inside the key expression of a ``*_CACHE`` dict: lists are
  unhashable, and even via tuple() the unbounded contents make the jit
  cache key explode. A cache threaded through a helper as a plain
  parameter (the planner's ``_fused_run(self, cache, ...)``) is covered
  too: call sites passing a ``*_CACHE`` alias it to the callee's
  parameter, and the callee's writes/evictions/keys count against the
  module cache.
* ``unbounded-cache`` — a module-level ``*_CACHE`` dict written by
  subscript somewhere in its module with no eviction evidence (no
  ``len()`` guard, ``pop``/``popitem``/``clear``) anywhere: every new key
  pins a jitted executable for process lifetime.
* ``time-in-jit`` — ``time.time()``/``time.perf_counter()`` inside a
  ``jax.jit``-decorated function: it runs once at trace time and becomes
  a constant in the compiled program.
* ``span-in-jit`` — an ``obs.span(...)`` trace context entered inside a
  ``jax.jit``-decorated function. Spans read the host clock and the
  thread's sync counters at enter/exit; under tracing those run ONCE at
  trace time (measuring compile, not execution) and the span would be
  recorded on every retrace instead of every run. The runtime half of
  this guard is ``obs.trace.span()`` returning a null span under
  ``replay_mode() == "replay"``; this rule catches the static case the
  runtime guard cannot see — a span lexically inside a jitted body.
  Only obs-owned calls trip it: conventional module names
  (``obs``/``_obs``/``obs_trace``), any ``nds_tpu.obs`` import alias,
  and bare names from-imported from the obs package — an unrelated
  ``.span()`` (``re.Match.span()``) or a local helper does not.
* ``host-sync-in-shard-map`` — a host-sync primitive, an
  ``ops.host_read``-charging call (``host_read``, ``timed_read``,
  ``guarded_scalar_read``, ``host_sync``, ``count_int``,
  ``resolve_counts``, ``.to_int()``, ``.item()``, ``device_get``,
  ``np.asarray``), or an ``obs.span(...)`` trace context inside a
  function passed to ``shard_map``/``pjit``. A shard_map body is traced
  once and runs as one SPMD program on every mesh device: a host read
  there is at best a tracer error and at worst a per-dispatch full-mesh
  barrier, and a span would clock the trace, not the execution (the
  ``span-in-jit`` hazard, but the runtime null-span guard cannot see a
  shard_map body that is traced outside replay mode). The rule resolves
  the body by name — any function whose name is passed as the first
  argument to a ``shard_map``/``pjit`` call in the module — and also
  sees ONE level down into module-local helpers, like
  ``chunk-loop-host-sync``. Error severity: the sharded streamed
  pipeline's collective budget proves these bodies sync-free, so a
  violation is a correctness bug, not a perf note.
* ``host-read-in-pallas`` — a host-sync primitive, an
  ``ops.host_read``-charging call, or an ``obs.span(...)`` trace context
  inside a function passed to ``pl.pallas_call``. A Pallas kernel body
  is compiled to Mosaic and runs per grid cell ON the device: a host
  read there is not merely slow, it cannot exist (tracer error at best),
  and a span would clock the kernel trace. Resolution mirrors
  ``host-sync-in-shard-map``: any function whose name is passed as the
  first argument to a ``pallas_call`` in the module, one level down into
  module-local helpers. Error severity — the fused chunk-scan/probe
  kernels (``engine/kernels.py``) are priced at ZERO host syncs by the
  exec-audit sync model, so a violation is a correctness bug.
* ``host-sync-in-prefetch-worker`` — a host-sync primitive, an
  ``ops.host_read``-charging call, or an ``obs.span(...)`` trace
  context inside a callable handed to the bounded prefetch ring
  (``engine/prefetch.py``: the ``prepare`` step of
  ``chunk_ring``/``ChunkRing``, any named function passed to those
  constructors, or the callee of a call expression passed as the
  source iterator — the generator's per-item body runs on the worker
  too). The ring runs these on its WORKER thread, whose sync counters
  and span ring are thread-local: a host read there would charge syncs
  the driver's accounting (and the exec-audit sync model's "prefetch
  worker = 0" row) never sees, and a span would land in the
  ``unattributed`` diagnostics ring instead of the query's trace.
  Resolution mirrors ``host-sync-in-shard-map``: name-based (module-
  local), one level down into module-local helpers. Error severity —
  the worker's zero-sync contract is what lets ingest leave the driver
  thread at all.
* ``swallowed-fault`` — an ``except`` handler that catches one of the
  fault layer's classified errors (``FaultError`` / ``FaultInjected`` /
  ``StatementTimeout``, bare or attribute-qualified) whose body neither
  records a :class:`nds_tpu.engine.faults.FaultEvent`
  (``record_fault_event(...)``) nor re-raises. A recovery path that
  absorbs a classified fault silently breaks the fault-tolerance
  contract's evidence rule (DESIGN.md "Fault-tolerance contract"):
  ``tools/fault_diff.py`` proves FaultEvent counts match injections
  exactly, so a swallowed fault is an un-auditable fallback — exactly
  the failure-as-log-noise pattern the registry exists to end. Error
  severity.
* ``chunk-loop-host-sync`` — a host-sync primitive (``.item()``,
  ``np.asarray``/``np.array``, ``device_get``, ``.to_int()``, or the
  engine's ``host_sync``/``count_int``/``resolve_counts``) lexically
  inside a ``for`` loop over ``device_chunks()``/``padded_chunks()``,
  in ANY module. A >HBM table streams hundreds of chunks: one sync per
  chunk is the O(chunks) control-plane cost the compiled streaming
  executor (``engine/stream.py``) exists to remove — new chunk loops
  must stay device-resident or route through it. The surviving eager
  fallback loop is baselined. The rule also sees ONE level down: a call
  from the loop body to a module-local helper (bare name or
  ``self.method``) whose body syncs directly is flagged at the call
  site — the gap that let a sync hide behind a one-line refactor.
"""

from __future__ import annotations

import ast
import os

from nds_tpu.analysis import Finding, suppressed

# modules whose loops are hot paths (per-query, per-chunk dispatch loops)
HOT_PATH_FILES = ("engine/ops.py", "sql/planner.py")

_SYNC_NP_FUNCS = {"asarray", "array"}
_TIME_FUNCS = {"time", "perf_counter", "perf_counter_ns", "monotonic"}
# iterator methods that yield device chunks of a >HBM streamed table
_CHUNK_ITER_FUNCS = {"device_chunks", "padded_chunks"}
# engine entry points that resolve a device scalar on host
_ENGINE_SYNC_FUNCS = {"host_sync", "count_int", "resolve_counts"}
# ops.host_read-charging entry points (every counted device->host read
# funnels through host_read; these are the call forms code reaches it by)
_HOST_READ_FUNCS = {"host_read", "timed_read", "guarded_scalar_read"}
# the fault layer's classified error types (engine/faults.py): a handler
# catching one must record a FaultEvent or re-raise (swallowed-fault)
_FAULT_ERROR_NAMES = {"FaultError", "FaultInjected", "StatementTimeout"}
# the recorder call forms a compliant handler may use
_FAULT_RECORD_FUNCS = {"record_fault_event"}


def _sync_primitive(node) -> str | None:
    """The host-sync primitive a Call node invokes, or None. One shared
    matcher for the direct chunk-loop check and the helper pre-pass."""
    f = node.func
    if isinstance(f, ast.Attribute):
        owner = f.value.id if isinstance(f.value, ast.Name) else None
        if f.attr == "item" and not node.args:
            return ".item()"
        if owner in ("np", "numpy") and f.attr in _SYNC_NP_FUNCS:
            return f"np.{f.attr}()"
        if f.attr == "device_get":
            return "device_get()"
        if f.attr == "to_int" and not node.args:
            return ".to_int()"
        if f.attr in _ENGINE_SYNC_FUNCS:
            return f"{f.attr}()"
    elif isinstance(f, ast.Name) and f.id in _ENGINE_SYNC_FUNCS:
        return f"{f.id}()"
    return None


def _collect_sync_helpers(tree) -> dict:
    """Map each module-local function/method to (lineno, primitive) of
    the first host-sync primitive its body calls directly — the
    one-level-down index the chunk-loop rule resolves call sites
    against. Methods are keyed ``(ClassName, name)`` and module-level or
    nested functions ``(None, name)``, so a ``self.helper()`` call only
    resolves against its own class — a same-named method on an unrelated
    class in the module is not evidence. Nested function definitions
    attribute to the innermost def (matching how a call would reach
    them)."""
    helpers: dict = {}

    class _Scan(ast.NodeVisitor):
        def __init__(self):
            self.stack: list = []       # (class-or-None, name) per def
            self.classes: list = []

        def visit_ClassDef(self, node):
            self.classes.append(node.name)
            self.generic_visit(node)
            self.classes.pop()

        def visit_FunctionDef(self, node):
            # a def at class-body level is that class's method; any other
            # def (module-level, or nested in a function) is reachable as
            # a bare name
            cls = self.classes[-1] if self.classes and not self.stack \
                else None
            self.stack.append((cls, node.name))
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            what = _sync_primitive(node)
            if what and self.stack:
                helpers.setdefault(self.stack[-1],
                                   (node.lineno, what))
            self.generic_visit(node)

    _Scan().visit(tree)
    return helpers


def _collect_shard_bodies(tree) -> set:
    """Names of functions passed as the first argument to a
    ``shard_map``/``pjit`` call anywhere in the module (including the
    engine's ``shard_map_compat`` shim) — the bodies the
    ``host-sync-in-shard-map`` rule polices. Name-based resolution: the
    conventional pattern defines the body and wraps it in the same
    scope, so a name collision only widens coverage."""
    bodies = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name in ("shard_map", "shard_map_compat", "pjit") and \
                node.args and isinstance(node.args[0], ast.Name):
            bodies.add(node.args[0].id)
    return bodies


def _collect_prefetch_bodies(tree) -> set:
    """Names of callables the prefetch ring runs on its worker thread:
    arguments of a ring constructor (``chunk_ring``/``ChunkRing``) —
    positional or keyword, bare name or ``self.method`` — PLUS the
    callee of a call expression passed as the source iterator
    (``chunk_ring(scan.device_chunks(self), ...)``: the generator's
    per-item body runs on the worker too). Name-based like the
    shard/pallas collectors: a collision only widens coverage."""
    bodies = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name not in ("chunk_ring", "ChunkRing"):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                bodies.add(arg.id)
            elif isinstance(arg, ast.Attribute):
                bodies.add(arg.attr)
            elif isinstance(arg, ast.Call):
                cf = arg.func
                if isinstance(cf, ast.Name):
                    bodies.add(cf.id)
                elif isinstance(cf, ast.Attribute):
                    bodies.add(cf.attr)
    return bodies


def _collect_pallas_bodies(tree) -> set:
    """Names of functions passed as the first argument to a
    ``pallas_call`` anywhere in the module (``pl.pallas_call(kernel,
    ...)`` / bare ``pallas_call``) — the kernel bodies the
    ``host-read-in-pallas`` rule polices. Name-based resolution like
    ``_collect_shard_bodies``: the conventional pattern defines the
    body and wraps it in the same scope."""
    bodies = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name == "pallas_call" and node.args and \
                isinstance(node.args[0], ast.Name):
            bodies.add(node.args[0].id)
    return bodies


def _is_jit_decorator(dec) -> tuple[bool, set]:
    """(is jax.jit, static arg positions/names) for one decorator node."""
    static: set = set()
    # @jax.jit / @jit
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return True, static
    if isinstance(dec, ast.Name) and dec.id == "jit":
        return True, static
    # @functools.partial(jax.jit, static_argnums=(..)) / static_argnames
    # and the decorator-factory spelling @jax.jit(static_argnums=(..))
    if isinstance(dec, ast.Call):
        f = dec.func
        is_partial = (isinstance(f, ast.Attribute) and f.attr == "partial") \
            or (isinstance(f, ast.Name) and f.id == "partial")
        is_jit_factory = (isinstance(f, ast.Attribute) and f.attr == "jit") \
            or (isinstance(f, ast.Name) and f.id == "jit")
        if (is_partial and dec.args and _is_jit_decorator(dec.args[0])[0]) \
                or is_jit_factory:
            for kw in dec.keywords:
                if kw.arg in ("static_argnums", "static_argnames"):
                    for elt in ast.walk(kw.value):
                        if isinstance(elt, ast.Constant):
                            static.add(elt.value)
            return True, static
    return False, static


class _Lint(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str,
                 sync_helpers: dict | None = None,
                 shard_bodies: set | None = None,
                 pallas_bodies: set | None = None,
                 prefetch_bodies: set | None = None):
        self.rel = rel
        self.sync_helpers = sync_helpers or {}
        self.shard_bodies = shard_bodies or set()
        self.shard_depth = 0         # inside a shard_map/pjit body
        self.pallas_bodies = pallas_bodies or set()
        self.pallas_depth = 0        # inside a pallas_call kernel body
        self.prefetch_bodies = prefetch_bodies or set()
        self.prefetch_depth = 0      # inside a prefetch-worker callable
        self.lines = source.splitlines()
        self.findings: list = []
        self.scope_stack = ["<module>"]
        self.class_stack: list = []  # enclosing class names (self.X calls)
        self.loop_depth = 0
        self.chunk_loop_depth = 0    # for-loops over device/padded chunks
        self.jit_params: list = []   # stack of traced-param name sets
        self.jit_depth = 0           # count of enclosing jax.jit functions
        self.is_hot = any(rel.endswith(h) for h in HOT_PATH_FILES)
        # *_CACHE dicts assigned at module level in this file
        self.module_caches: set = set()
        self.cache_writes: dict = {}     # name -> [lineno]
        self.cache_evictions: set = set()
        # a module cache is often threaded through a helper as a plain
        # parameter (planner's `_fused_run(self, cache, ...)`): record how
        # each function USES its parameters cache-wise, plus every call
        # site that passes a *_CACHE in, and join the two at finish()
        self.fn_param_use: dict = {}     # func name -> (params, records)
        self.param_use_stack: list = []  # (param names, {param: record})
        self.cache_arg_calls: list = []  # (callee, pos|kwarg, cache name)
        # span-in-jit: names that refer to the obs trace module (by
        # convention or import alias) and to its span() function (by
        # from-import). An unrelated .span() — re.Match.span(), a local
        # helper — must NOT trip the rule.
        self.obs_aliases: set = {"obs", "_obs", "obs_trace"}
        self.span_funcs: set = set()

    def visit_Import(self, node):
        for a in node.names:
            if a.asname and a.name.startswith("nds_tpu.obs"):
                self.obs_aliases.add(a.asname)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        if mod.startswith("nds_tpu.obs") or mod == "nds_tpu":
            for a in node.names:
                bound = a.asname or a.name
                if a.name == "span":
                    self.span_funcs.add(bound)
                elif a.name in ("trace", "export", "obs"):
                    # only actual submodule names become module aliases —
                    # a from-imported function/class (SpanRecord, rollup)
                    # is not an owner whose .span() is a trace context
                    self.obs_aliases.add(bound)
        self.generic_visit(node)

    def _emit(self, rule: str, severity: str, message: str,
              lineno: int) -> None:
        if suppressed(self.lines, lineno, rule):
            return
        self.findings.append(Finding(self.rel, self.scope_stack[-1], rule,
                                     severity, message, lineno))

    # -- scope / jit tracking ----------------------------------------------

    def visit_FunctionDef(self, node):
        jit_static: set | None = None
        for dec in node.decorator_list:
            is_jit, static = _is_jit_decorator(dec)
            if is_jit:
                jit_static = static
        self.scope_stack.append(node.name)
        args = node.args
        names = [a.arg for a in
                 args.posonlyargs + args.args + args.kwonlyargs]
        if jit_static is not None:
            traced = {n for i, n in enumerate(names)
                      if i not in jit_static and n not in jit_static}
            self.jit_depth += 1
        elif self.jit_depth:
            # a nested helper defined inside a jit function still runs
            # under the trace: closures over the enclosing traced params
            # stay traced (its own params shadow them — their tracedness
            # is not knowable statically, so they are not flagged)
            traced = (self.jit_params[-1] if self.jit_params
                      else set()) - set(names)
        else:
            traced = set()
        self.jit_params.append(traced)
        self.param_use_stack.append((names, {}))
        is_shard = node.name in self.shard_bodies
        self.shard_depth += is_shard
        is_pallas = node.name in self.pallas_bodies
        self.pallas_depth += is_pallas
        is_prefetch = node.name in self.prefetch_bodies
        self.prefetch_depth += is_prefetch
        saved_loop = self.loop_depth
        saved_chunk = self.chunk_loop_depth
        self.loop_depth = 0
        self.chunk_loop_depth = 0
        self.generic_visit(node)
        self.loop_depth = saved_loop
        self.chunk_loop_depth = saved_chunk
        self.shard_depth -= is_shard
        self.pallas_depth -= is_pallas
        self.prefetch_depth -= is_prefetch
        self.jit_params.pop()
        if jit_static is not None:
            self.jit_depth -= 1
        self.fn_param_use[node.name] = self.param_use_stack.pop()
        self.scope_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _in_jit(self) -> bool:
        return self.jit_depth > 0

    # -- loops --------------------------------------------------------------

    def visit_For(self, node):
        is_chunk = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr in _CHUNK_ITER_FUNCS
            for n in ast.walk(node.iter))
        self.loop_depth += 1
        self.chunk_loop_depth += is_chunk
        self.generic_visit(node)
        self.chunk_loop_depth -= is_chunk
        self.loop_depth -= 1

    def visit_While(self, node):
        self._check_tracer_test(node.test, node.lineno, "while")
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    # -- fault-layer recovery paths -----------------------------------------

    def visit_Try(self, node):
        for h in node.handlers:
            if h.type is not None and self._catches_fault_error(h.type):
                if not self._handler_records_or_raises(h):
                    self._emit(
                        "swallowed-fault", "error",
                        "except clause catches a classified fault "
                        "(FaultError family) but neither records a "
                        "FaultEvent (record_fault_event) nor re-raises "
                        "— recovery paths must stay auditable "
                        "(DESIGN.md 'Fault-tolerance contract')",
                        h.lineno)
        self.generic_visit(node)

    visit_TryStar = visit_Try

    @staticmethod
    def _catches_fault_error(type_expr) -> bool:
        """Does the handler's type expression name one of the fault
        layer's classified errors (bare, attribute-qualified, or inside
        a tuple)?"""
        for n in ast.walk(type_expr):
            if isinstance(n, ast.Name) and n.id in _FAULT_ERROR_NAMES:
                return True
            if isinstance(n, ast.Attribute) and \
                    n.attr in _FAULT_ERROR_NAMES:
                return True
        return False

    @staticmethod
    def _handler_records_or_raises(handler) -> bool:
        for stmt in handler.body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Raise):
                    return True
                if isinstance(n, ast.Call):
                    f = n.func
                    name = f.id if isinstance(f, ast.Name) else (
                        f.attr if isinstance(f, ast.Attribute) else None)
                    if name in _FAULT_RECORD_FUNCS:
                        return True
        return False

    def visit_If(self, node):
        self._check_tracer_test(node.test, node.lineno, "if")
        self.generic_visit(node)

    def _check_tracer_test(self, test, lineno: int, kind: str) -> None:
        if not self._in_jit():
            return
        traced = self.jit_params[-1]

        def hazardous(node) -> bool:
            # identity tests (x is None) are pytree-structure checks and
            # .dtype/.shape/.ndim/.size are static metadata — both are
            # legal on tracers
            if isinstance(node, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops):
                return False
            if isinstance(node, ast.Attribute) and \
                    node.attr in ("dtype", "shape", "ndim", "size"):
                return False
            if isinstance(node, ast.Name):
                return node.id in traced
            return any(hazardous(c) for c in ast.iter_child_nodes(node))

        if hazardous(test):
            names = sorted({n.id for n in ast.walk(test)
                            if isinstance(n, ast.Name) and n.id in traced})
            self._emit("tracer-if", "error",
                       f"Python {kind} on traced parameter "
                       f"{', '.join(repr(n) for n in names)} inside a "
                       "jax.jit function", lineno)

    # -- calls / attributes -------------------------------------------------

    def _check_chunk_loop_sync(self, node) -> None:
        """Flag host syncs inside a ``device_chunks()``/``padded_chunks()``
        loop: per-chunk host decisions are the O(chunks) dispatch cost the
        compiled streaming executor removes (engine/stream.py)."""
        if not self.chunk_loop_depth:
            return
        what = _sync_primitive(node)
        if what:
            self._emit("chunk-loop-host-sync", "warning",
                       f"{what} inside a device_chunks() loop syncs once "
                       "per chunk (O(chunks) round trips); keep the chunk "
                       "pipeline device-resident or route it through the "
                       "compiled streaming executor", node.lineno)
            return
        # one level down: a call to a module-local helper whose body syncs
        # directly — the refactor that used to hide a per-chunk sync.
        # ``self.helper()`` resolves only against the enclosing class's
        # methods; a bare name only against module-level/nested functions.
        f = node.func
        key = None
        if isinstance(f, ast.Name):
            key = (None, f.id)
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" \
                and self.class_stack:
            key = (self.class_stack[-1], f.attr)
        hit = key is not None and self.sync_helpers.get(key)
        if hit and key[1] not in _CHUNK_ITER_FUNCS:
            lineno, prim = hit
            self._emit("chunk-loop-host-sync", "warning",
                       f"{key[1]}() (defined in this module, syncs via "
                       f"{prim} at line {lineno}) called inside a "
                       "device_chunks() loop: one host sync per chunk "
                       "hidden one level down", node.lineno)

    def _check_shard_map_sync(self, node) -> None:
        """Flag host reads / spans inside a shard_map or pjit body: the
        body is one traced SPMD program — a host read there is a tracer
        hazard and a full-mesh barrier, a span clocks the trace."""
        if not self.shard_depth:
            return
        f = node.func
        what = _sync_primitive(node)
        if what is None:
            if isinstance(f, ast.Attribute) and \
                    f.attr in _HOST_READ_FUNCS:
                what = f"{f.attr}()"
            elif isinstance(f, ast.Name) and f.id in _HOST_READ_FUNCS:
                what = f"{f.id}()"
        is_span = (isinstance(f, ast.Attribute) and f.attr == "span"
                   and isinstance(f.value, ast.Name)
                   and f.value.id in self.obs_aliases) or \
            (isinstance(f, ast.Name) and f.id in self.span_funcs)
        if what or is_span:
            self._emit("host-sync-in-shard-map", "error",
                       f"{what or 'obs.span(...)'} inside a shard_map/"
                       "pjit body: the body is one traced SPMD program — "
                       "host reads are tracer hazards and full-mesh "
                       "barriers; resolve on host before the dispatch or "
                       "ride the overflow/collective channels",
                       node.lineno)
            return
        # one level down: a module-local helper whose body syncs directly
        key = None
        if isinstance(f, ast.Name):
            key = (None, f.id)
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" \
                and self.class_stack:
            key = (self.class_stack[-1], f.attr)
        hit = key is not None and self.sync_helpers.get(key)
        if hit:
            lineno, prim = hit
            self._emit("host-sync-in-shard-map", "error",
                       f"{key[1]}() (defined in this module, syncs via "
                       f"{prim} at line {lineno}) called inside a "
                       "shard_map/pjit body: one host sync per dispatch "
                       "hidden one level down", node.lineno)

    def _check_pallas_sync(self, node) -> None:
        """Flag host reads / spans inside a pallas_call kernel body: the
        body compiles to a Mosaic program running per grid cell on the
        device — host reads cannot exist there, spans would clock the
        kernel trace."""
        if not self.pallas_depth:
            return
        f = node.func
        what = _sync_primitive(node)
        if what is None:
            if isinstance(f, ast.Attribute) and \
                    f.attr in _HOST_READ_FUNCS:
                what = f"{f.attr}()"
            elif isinstance(f, ast.Name) and f.id in _HOST_READ_FUNCS:
                what = f"{f.id}()"
        is_span = (isinstance(f, ast.Attribute) and f.attr == "span"
                   and isinstance(f.value, ast.Name)
                   and f.value.id in self.obs_aliases) or \
            (isinstance(f, ast.Name) and f.id in self.span_funcs)
        if what or is_span:
            self._emit("host-read-in-pallas", "error",
                       f"{what or 'obs.span(...)'} inside a pallas_call "
                       "kernel body: the kernel is one Mosaic device "
                       "program per grid cell — host reads cannot exist "
                       "there and spans clock the kernel trace; compute "
                       "on refs only and resolve on host outside the "
                       "launch", node.lineno)
            return
        # one level down: a module-local helper whose body syncs directly
        key = None
        if isinstance(f, ast.Name):
            key = (None, f.id)
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" \
                and self.class_stack:
            key = (self.class_stack[-1], f.attr)
        hit = key is not None and self.sync_helpers.get(key)
        if hit:
            lineno, prim = hit
            self._emit("host-read-in-pallas", "error",
                       f"{key[1]}() (defined in this module, syncs via "
                       f"{prim} at line {lineno}) called inside a "
                       "pallas_call kernel body: a host sync hidden one "
                       "level down", node.lineno)

    def _check_prefetch_sync(self, node) -> None:
        """Flag host reads / spans inside a callable the prefetch ring
        runs on its worker thread: the worker's sync counters and span
        ring are thread-local, so a sync there escapes the driver's
        accounting (the exec-audit "prefetch worker = 0 host syncs"
        row) and a span lands unattributed."""
        if not self.prefetch_depth:
            return
        f = node.func
        what = _sync_primitive(node)
        if what is None:
            if isinstance(f, ast.Attribute) and \
                    f.attr in _HOST_READ_FUNCS:
                what = f"{f.attr}()"
            elif isinstance(f, ast.Name) and f.id in _HOST_READ_FUNCS:
                what = f"{f.id}()"
        is_span = (isinstance(f, ast.Attribute) and f.attr == "span"
                   and isinstance(f.value, ast.Name)
                   and f.value.id in self.obs_aliases) or \
            (isinstance(f, ast.Name) and f.id in self.span_funcs)
        if what or is_span:
            self._emit("host-sync-in-prefetch-worker", "error",
                       f"{what or 'obs.span(...)'} inside a prefetch-"
                       "ring worker callable: the worker's sync "
                       "counters and span ring are thread-local — a "
                       "host read there escapes the driver's sync "
                       "accounting and a span lands unattributed; "
                       "resolve on the driver before handing work to "
                       "the ring", node.lineno)
            return
        # one level down: a module-local helper whose body syncs directly
        key = None
        if isinstance(f, ast.Name):
            key = (None, f.id)
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" \
                and self.class_stack:
            key = (self.class_stack[-1], f.attr)
        hit = key is not None and self.sync_helpers.get(key)
        if hit:
            lineno, prim = hit
            self._emit("host-sync-in-prefetch-worker", "error",
                       f"{key[1]}() (defined in this module, syncs via "
                       f"{prim} at line {lineno}) called inside a "
                       "prefetch-ring worker callable: a host sync "
                       "hidden one level down", node.lineno)

    def visit_Call(self, node):
        self._check_chunk_loop_sync(node)
        self._check_shard_map_sync(node)
        self._check_pallas_sync(node)
        self._check_prefetch_sync(node)
        f = node.func
        if isinstance(f, ast.Attribute):
            owner = f.value.id if isinstance(f.value, ast.Name) else None
            if self.is_hot and self.loop_depth > 0:
                if f.attr == "item" and not node.args:
                    self._emit("host-sync-in-loop", "warning",
                               ".item() inside a hot-path loop blocks on "
                               "device->host transfer per iteration",
                               node.lineno)
                elif owner in ("np", "numpy") and \
                        f.attr in _SYNC_NP_FUNCS:
                    self._emit("host-sync-in-loop", "warning",
                               f"np.{f.attr}() inside a hot-path loop "
                               "forces a device->host copy per iteration",
                               node.lineno)
                elif f.attr == "device_get":
                    self._emit("host-sync-in-loop", "warning",
                               "device_get() inside a hot-path loop",
                               node.lineno)
            if owner in ("time", "_time") and f.attr in _TIME_FUNCS and \
                    self._in_jit():
                self._emit("time-in-jit", "error",
                           f"time.{f.attr}() inside a jax.jit function is "
                           "evaluated once at trace time", node.lineno)
            if f.attr == "span" and owner in self.obs_aliases and \
                    self._in_jit():
                self._emit("span-in-jit", "error",
                           "obs.span(...) inside a jax.jit function reads "
                           "the host clock at trace time (tracer hazard); "
                           "open the span around the jitted call instead",
                           node.lineno)
        elif isinstance(f, ast.Name) and f.id in self.span_funcs and \
                self._in_jit():
            self._emit("span-in-jit", "error",
                       "span(...) inside a jax.jit function reads the "
                       "host clock at trace time (tracer hazard); open "
                       "the span around the jitted call instead",
                       node.lineno)
        self._note_cache_method_write(node)
        # a *_CACHE passed as an argument aliases it to the callee's
        # parameter — resolved against the callee's use at finish()
        callee, self_off = None, 0
        if isinstance(f, ast.Name):
            callee = f.id
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self":
            callee, self_off = f.attr, 1
        if callee is not None:
            for i, a in enumerate(node.args):
                cname = self._is_cache_name(a)
                if cname:
                    self.cache_arg_calls.append(
                        (callee, i + self_off, cname))
            for kw in node.keywords:
                cname = self._is_cache_name(kw.value)
                if cname and kw.arg is not None:
                    self.cache_arg_calls.append((callee, kw.arg, cname))
        self.generic_visit(node)

    # -- cache hygiene ------------------------------------------------------

    def _is_cache_name(self, node) -> str | None:
        if isinstance(node, ast.Name) and node.id.endswith("_CACHE"):
            return node.id
        return None

    def _param_record(self, node) -> dict | None:
        """The cache-use record for ``node`` when it names a parameter of
        the innermost function, else None."""
        if not (isinstance(node, ast.Name) and self.param_use_stack):
            return None
        params, records = self.param_use_stack[-1]
        if node.id not in params:
            return None
        return records.setdefault(node.id, {
            "write": None, "evict": False, "keyhaz": [],
            "scope": self.scope_stack[-1]})

    def visit_Assign(self, node):
        # module-level NAME_CACHE = {} / dict()
        if self.scope_stack == ["<module>"]:
            for tgt in node.targets:
                name = self._is_cache_name(tgt)
                if name and isinstance(node.value, (ast.Dict, ast.Call)):
                    self.module_caches.add(name)
        # NAME_CACHE[key] = value
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                name = self._is_cache_name(tgt.value)
                if name:
                    self.cache_writes.setdefault(name, []).append(
                        tgt.lineno)
                    self._check_cache_key(name, tgt.slice, tgt.lineno)
                else:
                    rec = self._param_record(tgt.value)
                    if rec is not None:
                        if rec["write"] is None:
                            rec["write"] = tgt.lineno
                        rec["keyhaz"].extend(
                            self._key_hazards(tgt.slice, tgt.lineno))
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if self.scope_stack == ["<module>"]:
            name = self._is_cache_name(node.target)
            if name and node.value is not None:
                self.module_caches.add(name)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if isinstance(node.ctx, ast.Load):
            name = self._is_cache_name(node.value)
            if name:
                self._check_cache_key(name, node.slice, node.lineno)
            else:
                rec = self._param_record(node.value)
                if rec is not None:
                    rec["keyhaz"].extend(
                        self._key_hazards(node.slice, node.lineno))
        self.generic_visit(node)

    def visit_Compare(self, node):
        # len(NAME_CACHE) >= ... counts as eviction evidence
        for sub in [node.left] + list(node.comparators):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Name) and \
                    sub.func.id == "len" and sub.args:
                name = self._is_cache_name(sub.args[0])
                if name:
                    self.cache_evictions.add(name)
                else:
                    rec = self._param_record(sub.args[0])
                    if rec is not None:
                        rec["evict"] = True
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if node.attr in ("pop", "popitem", "clear"):
            name = self._is_cache_name(node.value)
            if name:
                self.cache_evictions.add(name)
            else:
                rec = self._param_record(node.value)
                if rec is not None:
                    rec["evict"] = True
        self.generic_visit(node)

    def _note_cache_method_write(self, node) -> None:
        """CACHE.setdefault(k, v) / CACHE.update(...) grow the cache like a
        subscript store does (setdefault's first argument is the key)."""
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("setdefault", "update")):
            return
        name = self._is_cache_name(f.value)
        if name:
            self.cache_writes.setdefault(name, []).append(node.lineno)
            if f.attr == "setdefault" and node.args:
                self._check_cache_key(name, node.args[0], node.lineno)
            return
        rec = self._param_record(f.value)
        if rec is not None:
            if rec["write"] is None:
                rec["write"] = node.lineno
            if f.attr == "setdefault" and node.args:
                rec["keyhaz"].extend(
                    self._key_hazards(node.args[0], node.lineno))

    def _key_hazards(self, key, lineno: int) -> list:
        for n in ast.walk(key):
            if isinstance(n, (ast.List, ast.ListComp, ast.Set, ast.SetComp,
                              ast.Dict, ast.DictComp)):
                return [(lineno, type(n).__name__)]
        return []

    def _check_cache_key(self, name: str, key, lineno: int) -> None:
        for lineno, tname in self._key_hazards(key, lineno):
            self._emit("cache-key-list", "error",
                       f"raw {tname} in {name} key: unhashable and "
                       "unbounded as a jit-cache key", lineno)

    def _resolve_cache_aliases(self) -> None:
        """Join call sites that pass a module *_CACHE with the callee's
        parameter use, so writes/evictions/key hazards through the alias
        count against the module cache."""
        emitted: set = set()
        for callee, pos, cname in self.cache_arg_calls:
            info = self.fn_param_use.get(callee)
            if info is None:
                continue
            params, records = info
            pname = pos if isinstance(pos, str) else (
                params[pos] if pos < len(params) else None)
            rec = records.get(pname)
            if rec is None:
                continue
            if rec["write"] is not None:
                self.cache_writes.setdefault(cname, []).append(rec["write"])
            if rec["evict"]:
                self.cache_evictions.add(cname)
            for lineno, tname in rec["keyhaz"]:
                if (lineno, cname) in emitted:
                    continue
                emitted.add((lineno, cname))
                self.scope_stack = ["<module>", rec["scope"]]
                self._emit("cache-key-list", "error",
                           f"raw {tname} in {cname} key (through parameter "
                           f"{pname!r} of {callee}()): unhashable and "
                           "unbounded as a jit-cache key", lineno)

    def finish(self) -> None:
        self._resolve_cache_aliases()
        for name in sorted(self.module_caches):
            writes = self.cache_writes.get(name)
            if writes and name not in self.cache_evictions:
                self.scope_stack = ["<module>"]
                self._emit("unbounded-cache", "warning",
                           f"{name} grows without eviction (no len() "
                           "guard or pop/popitem/clear in module)",
                           writes[0])


def lint_file(path: str, rel: str | None = None) -> list:
    with open(path) as f:
        source = f.read()
    rel = rel or path
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rel, "<module>", "syntax-error", "error",
                        str(e), e.lineno or 0)]
    lint = _Lint(path, rel, source, _collect_sync_helpers(tree),
                 _collect_shard_bodies(tree), _collect_pallas_bodies(tree),
                 _collect_prefetch_bodies(tree))
    lint.visit(tree)
    lint.finish()
    return lint.findings


def lint_tree(root: str | None = None) -> list:
    """Lint every ``.py`` file under ``nds_tpu/`` (or ``root``)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(os.path.abspath(root))
    findings: list = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                findings.extend(lint_file(p, os.path.relpath(p, repo)))
    return findings
