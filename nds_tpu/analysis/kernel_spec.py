# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Shared eligibility + threshold rules of the fused Pallas chunk-scan
kernel (DESIGN.md "Fused chunk kernels").

The streamed per-chunk hot path can fuse its chunk-local predicates into
one VMEM-resident Pallas pass (``engine/kernels.fused_chunk_scan``) when
every lowered conjunct fits a small encoded-space opcode set. TWO
independent consumers must agree on *which* conjuncts lower:

* the runtime (``engine/exprs.lower_scan_spec`` -> ``engine/stream.py``),
  which extracts the spec at pipeline-build time, and
* the static model (``analysis/exec_audit.py``), which predicts the
  kernel launch/stage counts that ``tools/exec_audit_diff.py`` checks
  against drained ``StreamEvent`` evidence.

Keeping the ONE rule here — a jax-free module importable by the host-only
auditors — is what makes the lockstep contract hold by construction: a
new lowerable shape lands in :func:`eligible_conjunct` once and both
sides move together. The rule is deliberately COARSE (type classes, not
device kinds): the static side only knows schema classes while the
runtime sees real kinds and encodings, so any rule that distinguished
``i64`` from ``dec(7,2)`` would drift the two apart.

The module also hosts the exact integer threshold math the runtime
lowering uses to move ordered comparisons into ENCODED space (Fraction
boundaries -> stored-code thresholds; sorted-dict values -> code indexes
via bisect), so unit tests can pin it without a device in the loop.
"""

from __future__ import annotations

import bisect
import math
from decimal import Decimal
from fractions import Fraction

import numpy as np

from nds_tpu.sql import ast as A

# conjuncts with more IN-list items than this stay on the XLA path: each
# item is one fused equality in the kernel body, so the cap bounds
# generated kernel code (and is part of the shared eligibility rule)
IN_LIST_MAX = 16

_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}


def _is_num_literal(v) -> bool:
    return isinstance(v, (int, float, Decimal)) and not isinstance(v, bool)


def _ref_lit(e):
    """(ColumnRef, literal node, op-as-written-with-ref-on-left) of a
    comparison, or None. ``5 < ss_x`` flips to ``ss_x > 5``."""
    _FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
             "=": "=", "<>": "<>"}
    if not (isinstance(e, A.BinaryOp) and e.op in _CMP_OPS):
        return None
    left, right = e.left, e.right
    if isinstance(left, A.ColumnRef) and \
            isinstance(right, (A.Literal, A.DateLiteral)):
        return left, right, e.op
    if isinstance(right, A.ColumnRef) and \
            isinstance(left, (A.Literal, A.DateLiteral)):
        return right, left, _FLIP[e.op]
    return None


def eligible_conjunct(c, class_of) -> bool:
    """True when this conjunct lowers to the fused scan kernel's opcode
    set. ``class_of(ref)`` returns the referenced column's coarse type
    class (``"num" | "date" | "str" | "bool"``) — or None when the ref
    does not resolve to a kernel-addressable column (not chunk-owned,
    ambiguous, unknown type), which makes the conjunct ineligible.

    The ONE rule shared by the runtime lowering and the static auditor;
    see the module docstring for why it must stay coarse."""
    got = _ref_lit(c)
    if got is not None:
        ref, lit, op = got
        cls = class_of(ref)
        if cls == "num":
            return isinstance(lit, A.Literal) and (
                lit.value is None or _is_num_literal(lit.value))
        if cls == "date":
            if isinstance(lit, A.DateLiteral):
                # an unparseable DateLiteral raises at eager eval — the
                # conjunct must stay in the graph so both arms raise
                return parse_days(lit.text) is not None
            return isinstance(lit, A.Literal) and (
                lit.value is None or _is_num_literal(lit.value)
                or isinstance(lit.value, str))
        if cls == "str":
            return op in ("=", "<>") and isinstance(lit, A.Literal) and (
                lit.value is None or isinstance(lit.value, str))
        return False
    if isinstance(c, A.Between):
        if not isinstance(c.expr, A.ColumnRef):
            return False
        cls = class_of(c.expr)
        if cls not in ("num", "date"):
            return False

        def bound_ok(b):
            if isinstance(b, A.DateLiteral):
                return cls == "date" and parse_days(b.text) is not None
            if not isinstance(b, A.Literal):
                return False
            if _is_num_literal(b.value):
                return True
            # date-string bounds must parse: Kleene NOT over a
            # half-invalid range is not expressible in the opcode set
            return cls == "date" and isinstance(b.value, str) and \
                parse_days(b.value) is not None
        if c.negated and any(isinstance(b, A.Literal)
                             and isinstance(b.value, float)
                             for b in (c.low, c.high)):
            # negated mixed-lane range (int column, float bound) has no
            # single fused entry — per-conjunct fallback
            return False
        return bound_ok(c.low) and bound_ok(c.high)
    if isinstance(c, A.InList):
        if not isinstance(c.expr, A.ColumnRef):
            return False
        if len(c.items) > IN_LIST_MAX or not c.items:
            return False
        if not all(isinstance(it, A.Literal) for it in c.items):
            return False
        cls = class_of(c.expr)
        vals = [it.value for it in c.items]
        if cls in ("num", "date"):
            return all(v is None or _is_num_literal(v) for v in vals)
        if cls == "str":
            return all(v is None or isinstance(v, str) for v in vals)
        return False
    if isinstance(c, A.IsNull):
        return isinstance(c.expr, A.ColumnRef) and \
            class_of(c.expr) is not None
    return False


def count_eligible(conjuncts, class_of) -> int:
    """Eligible-conjunct count of one chunk-local filter list — the
    number both the runtime spec's ``n_conjuncts`` and the static
    ``kernel_stages`` prediction are built from."""
    return sum(1 for c in conjuncts if eligible_conjunct(c, class_of))


# ---------------------------------------------------------------------------
# exact threshold math (value space -> stored/encoded space)
# ---------------------------------------------------------------------------
#
# Ordered comparisons against a rational boundary q reduce to integer
# thresholds on the stored representation:
#
#   v <  q   <=>   v <= ceil(q) - 1
#   v <= q   <=>   v <= floor(q)
#   v >  q   <=>   v >= floor(q) + 1
#   v >= q   <=>   v >= ceil(q)
#   v =  q   <=>   v == q     (only when q is integral, else FALSE)
#   v <> q   <=>   v != q     (only when q is integral, else TRUE)
#
# and both narrow codecs are order-preserving, so a value-space threshold
# T maps into code space exactly: FOR by subtracting the base, sorted
# dictionaries through bisect on the sorted value table.


def value_cmp(op: str, q: Fraction):
    """Entry kind + integer threshold of ``value OP q`` in VALUE space:
    ``("ieq"|"ine"|"ile"|"ige", T)`` or ``("true",)`` / ``("false",)``."""
    if op == "=":
        return ("ieq", int(q)) if q.denominator == 1 else ("false",)
    if op == "<>":
        return ("ine", int(q)) if q.denominator == 1 else ("true",)
    if op == "<":
        return ("ile", math.ceil(q) - 1)
    if op == "<=":
        return ("ile", math.floor(q))
    if op == ">":
        return ("ige", math.floor(q) + 1)
    if op == ">=":
        return ("ige", math.ceil(q))
    raise ValueError(f"not a comparison op: {op}")


def shift_for(entry, base: int):
    """Rebase a value-space entry into FOR code space (stored = value -
    base)."""
    kind = entry[0]
    if kind in ("ieq", "ine", "ile", "ige"):
        return (kind, entry[1] - base)
    if kind == "irange":
        return (kind, entry[1] - base, entry[2] - base)
    return entry


def dict_map(entry, values):
    """Map a value-space entry into sorted-dict CODE space. ``values`` is
    the codec's sorted logical value table (any sequence bisect can
    search — ints for numeric dicts, strs for string dictionaries).

    Codes are clipped into ``[0, len(values))`` at encode time (the
    out-of-range guard), so a threshold of ``len(values)`` or ``-1``
    correctly selects nothing."""
    kind = entry[0]
    if kind in ("true", "false", "isnull", "notnull"):
        return entry
    if kind == "ieq" or kind == "ine":
        t = entry[1]
        i = bisect.bisect_left(values, t)
        if i < len(values) and values[i] == t:
            return (kind, i)
        return ("false",) if kind == "ieq" else ("true",)
    if kind == "ile":
        return ("ile", bisect.bisect_right(values, entry[1]) - 1)
    if kind == "ige":
        return ("ige", bisect.bisect_left(values, entry[1]))
    if kind == "irange":
        lo = bisect.bisect_left(values, entry[1])
        hi = bisect.bisect_right(values, entry[2]) - 1
        return ("irange", lo, hi)
    raise ValueError(f"unmappable entry {entry!r}")


_EPOCH = np.datetime64("1970-01-01", "D")


def parse_days(text: str) -> int | None:
    """Days-since-epoch of a date string, or None when unparseable —
    numerically identical to ``engine/exprs._parse_date`` (both go
    through ``np.datetime64``), so a lowered date threshold can never
    disagree with the eager cast."""
    try:
        return int((np.datetime64(str(text), "D") - _EPOCH).astype(int))
    except Exception:
        return None
