# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Static HBM-footprint auditor: prove per-statement memory bounds on host.

The streaming executor used to guard device memory with a *guess*: a global
survivor-accumulator ceiling (``NDS_TPU_STREAM_ACC_ROWS``, default 2^23)
plus a device-side overflow flag that throws away a whole streamed run and
re-executes eagerly. This module is the third abstract interpreter over the
planner's decomposition — sibling to :mod:`plan_audit` (name/type
resolution) and :mod:`exec_audit` (control path + sync bounds) — and
answers, host-only and with no device in the loop, for every statement of
every template:

1. **How many bytes can it ever hold on device?** A conservative
   *peak-HBM byte bound* composed from:

   * **dtype widths** from :mod:`nds_tpu.schema` through the planner's
     column pruning (only columns the statement references anywhere are
     ever uploaded; a ``SELECT *`` disables pruning, conservatively, for
     the whole statement). Widths mirror the device representation of
     :mod:`nds_tpu.engine.column`: int32/date = 4 B, int64/double and
     scaled-decimal = 8 B, strings = 4 B dictionary codes (value tables
     live on host), plus 1 B validity per row — exactly the shapes
     ``ChunkedTable.padded_chunks`` materializes. Under ENCODED
     execution (``NDS_TPU_ENCODED``, default on) streamed chunks are
     priced at the statically-provable narrow widths instead
     (:func:`encoded_type_width`: ``decimal(p<=9)`` -> 4+1 B,
     spec-bounded quantities -> 2+1 B, ticket numbers -> 4+1 B at the
     audited scale), mirroring the runtime codecs of
     ``io/columnar.plan_column_codec`` — conservatively: a column the
     model cannot prove narrow is priced plain even when the runtime
     (which sees real stats) encodes it.
   * **cardinality bounds propagated through joins**: a join batch whose
     keys cover the non-streamed side's declared (composite) primary key
     on a pristine base-table scan is unique on that side — output rows
     stay bounded by the fact side. Every other batch is bounded by the
     stream-bounds pair bucket the runtime enforces
     (probe-bucket × ``NDS_TPU_STREAM_FANOUT``; inside the compiled
     pipeline exceeding it raises the device overflow flag, so the bound
     is *enforced*, not estimated). Unconnected components multiply
     (cartesian layout — exact product).
   * **filters**: no reduction assumed (a filter may keep every row).
   * **group-bys**: output bounded by the product of the group keys'
     value domains (a base-table column's domain is at most its table's
     row bound) clamped at input rows.

2. **How large can a streamed scan's survivor accumulator grow?** The
   per-scan *accumulator row bound*: ``min(n_chunks × per-chunk output
   bucket, bucket_len(table rows) × fanout^k)`` where ``k`` counts the
   join batches that may fan out survivor rows
   (:func:`stream_graph_fanout`). This is the number the runtime now
   **sizes the accumulator from** (``engine/stream.py``): a statement
   whose proven bound fits the HBM capacity model can never trip the
   overflow rerun, and `exec_audit` reclassifies its former
   ``accumulator-overflow`` fallback to ``compiled-stream`` in lockstep.

3. **When the whole-statement bound exceeds capacity, can a grace-style
   partition decomposition admit it?** A streamed graph whose survivor
   bound is past ``NDS_TPU_HBM_BYTES`` but which joins on plain equi
   keys is hash-partitioned by the executor: every chunk row lands in
   exactly one of ``P`` partitions (join-key hash), each partition
   drives the same compiled per-chunk program into its OWN accumulator,
   and the *per-partition bound* is
   ``min(n_chunks × per-chunk bucket × fanout^k,
   bucket_len(ceil(rows / P) × skew) × fanout^k)``
   (:func:`partition_row_bound`; ``skew`` = ``NDS_TPU_STREAM_SKEW``,
   default 2 — hash partitions are only probabilistically even, so the
   proof is skew-conditional and the runtime ENFORCES it with a
   per-partition overflow flag: a hotter-than-assumed partition reruns
   eagerly, correctness never rides the proof). The partition count is
   chosen STATICALLY from the proof (:func:`choose_partitions` —
   smallest power of two whose per-partition bound fits capacity;
   ``NDS_TPU_STREAM_PARTITIONS`` pins it), so it joins the pipeline
   cache key. The ``hbm-capacity`` gate then tests the per-partition
   bound — which is what retired the 7 fan-out findings
   (q17/q24×2/q25/q29/q64/q72) from the baseline.

The capacity model is ``NDS_TPU_HBM_BYTES`` (default 16 GiB, one v5-lite
chip); the cardinality model is a conservative SF10 row-bound table
(:data:`DEFAULT_ROW_BOUNDS`), both parameterizable per :class:`MemModel`.

**The model is a checked contract.** ``tools/mem_audit_diff.py`` replays
the ``test_synccount`` A/B templates through the real engine and fails
when a measured survivor count or materialized byte volume ever exceeds
the static bound (soundness), and proves the gate can fail via
``--inject-drift`` — the same lockstep rule that ties ``exec_audit`` to
the executor's routing. **When you change the planner's join bounds,
``ChunkedTable`` chunk shapes, or the schema widths, update this model in
the same PR**; ``tests/test_analysis.py`` runs both in tier-1.

The lint gate (``hbm-capacity``, ``tools/lint.py``) fails any
device-resident statement whose peak bound exceeds the configured
capacity, and any streamed statement whose accumulator bound exceeds it;
``--mem-report`` prints the per-statement table.

**Fused Pallas chunk kernels change NOTHING here by design** (DESIGN.md
"Fused chunk kernels"): the fused scan pass only pre-masks rows the
recorded graph would have filtered anyway — survivors are a subset, the
proof-sized accumulators, partition shares and shard slices are reused
unchanged, and encoded widths stay the priced widths (the kernel
evaluates predicates ON the codes). ``tools/mem_audit_diff.py``'s
kernel sweep re-checks every bound on the ``NDS_TPU_PALLAS=interpret``
arm so this invariant is measured, not assumed.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

import numpy as np

from nds_tpu.analysis import Finding
from nds_tpu.analysis.exec_audit import (_children, _column_refs,
                                         _conjuncts_of, _has_subquery)
from nds_tpu.analysis.plan_audit import _single_row_query
from nds_tpu.queries import (TEMPLATE_DIR, instantiate_template,
                             list_templates, load_template)
from nds_tpu.schema import (COMPOSITE_PRIMARY_KEYS, PRIMARY_KEYS,
                            decimal_precision_scale, get_schemas,
                            is_decimal, is_string)
from nds_tpu.sql import ast as A
from nds_tpu.sql.parser import ParseError, parse

# HBM capacity model: the proof budget every per-statement bound is gated
# against (and the admission test for proof-sized stream accumulators).
# Default: one v5-lite chip's 16 GiB.
DEFAULT_HBM_BYTES = 16 << 30


def hbm_capacity_bytes() -> int:
    """The configured device-memory capacity (``NDS_TPU_HBM_BYTES``)."""
    return int(os.environ.get("NDS_TPU_HBM_BYTES", str(DEFAULT_HBM_BYTES)))


# Conservative SF10 row-count upper bounds (TPC-DS spec scaling, rounded
# UP — the audit must never under-bound a cardinality). The static
# stand-in for the arrow row counts a live session would know exactly;
# parameterizable per MemModel (tools/mem_audit_diff.py passes the toy
# session's real counts).
DEFAULT_ROW_BOUNDS = {
    "call_center": 30,
    "catalog_page": 12_100,
    "catalog_returns": 1_500_000,
    "catalog_sales": 14_500_000,
    "customer": 500_000,
    "customer_address": 250_000,
    "customer_demographics": 1_920_800,
    "date_dim": 73_049,
    "household_demographics": 7_200,
    "income_band": 20,
    "inventory": 133_200_000,
    "item": 102_000,
    "promotion": 500,
    "reason": 45,
    "ship_mode": 20,
    "store": 102,
    "store_returns": 2_900_000,
    "store_sales": 28_900_000,
    "time_dim": 86_400,
    "warehouse": 10,
    "web_page": 200,
    "web_returns": 800_000,
    "web_sales": 7_300_000,
    "web_site": 42,
}


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 1).bit_length() if n > 2 else 2


def _bucket(n: int) -> int:
    """Mirror of ``ops.bucket_len``: smallest power-of-two capacity >= n
    with the same ``NDS_TPU_MIN_BUCKET`` floor — the audit's row bounds
    must round exactly like the engine's physical buckets."""
    floor = _pow2_ceil(int(os.environ.get("NDS_TPU_MIN_BUCKET", "16")))
    if n <= floor:
        return floor
    return 1 << (int(n) - 1).bit_length()


def type_width(t: str) -> int:
    """Device bytes per row of one column of canonical type ``t``,
    validity byte included — mirrors ``engine/column.py``'s lowering
    (int32/date -> int32, decimals -> scaled int64, strings -> int32
    dictionary codes with a host-side value table)."""
    if is_string(t):
        return 4 + 1
    if is_decimal(t):
        return 8 + 1
    if t in ("int32", "date"):
        return 4 + 1
    return 8 + 1                       # int64 / double / unknown


# ---------------------------------------------------------------------------
# encoded columnar execution: the static width model of the streamed path
# ---------------------------------------------------------------------------
#
# The streamed scan path uploads int-path columns in a NARROW encoded
# representation (io/columnar.plan_column_codec: frame-of-reference /
# sorted-dict), and survivors stay encoded through the accumulator, so
# the widths the proof prices shrink with the data. The RUNTIME chooses
# widths from whole-table stats; this model mirrors that choice from
# static knowledge only — schema types plus spec-fixed value domains at
# the audited scale — and is deliberately conservative: a column the
# model cannot prove narrow statically is priced at its plain width even
# though the runtime may encode it narrower (sound for the capacity
# gate; the runtime sizes its own accumulators from the ACTUAL encoded
# dtypes, so the executor is never constrained by the model's caution).


# the ONE NDS_TPU_ENCODED gate (read at model build time like every
# other executor knob) — shared with the runtime so the model and the
# executor can never read the flag differently
from nds_tpu.io.columnar import encoded_enabled  # noqa: E402

# the ONE NDS_TPU_PREFETCH_DEPTH reader (engine/prefetch.py — stdlib
# only), shared with the runtime so the ring's live-set pricing below
# and the executor's admission arithmetic can never read the knob
# differently
from nds_tpu.engine.prefetch import prefetch_depth  # noqa: E402


# spec-fixed value-domain upper bounds (TPC-DS: quantities are 1..100,
# inventory levels 0..1000) — int64 columns a FOR encoding provably
# narrows to int16 offsets at ANY scale factor
SPEC_INT_DOMAINS = {
    "ss_quantity": 100, "cs_quantity": 100, "ws_quantity": 100,
    "sr_return_quantity": 100, "cr_return_quantity": 100,
    "wr_return_quantity": 100, "inv_quantity_on_hand": 1000,
}

# int64 sequence columns whose value domain is bounded by their table's
# row bound at the audited scale (ticket numbers are assigned per sale)
ROW_BOUND_DOMAINS = {
    "ss_ticket_number": "store_sales",
    "sr_ticket_number": "store_returns",
}


def encoded_type_width(col: str, t: str, row_bounds: dict) -> int:
    """Static streamed-chunk bytes per row of one column under encoded
    execution (validity byte included). Mirrors the runtime codec rules
    on what is provable WITHOUT data: a decimal's precision bounds its
    scaled int64 (p <= 9 always fits an int32 FOR code), and the spec /
    row-bound domains above prove int16/int32 for the quantity and
    ticket-number columns. Everything else keeps its plain width."""
    if is_decimal(t):
        p, _s = decimal_precision_scale(t)
        if p <= 9:
            return 4 + 1
        return 8 + 1
    w = type_width(t)
    dom = SPEC_INT_DOMAINS.get(col)
    if dom is None and col in ROW_BOUND_DOMAINS:
        dom = row_bounds.get(ROW_BOUND_DOMAINS[col])
    if dom is not None:
        if dom < (1 << 15):
            return min(w, 2 + 1)
        if dom < (1 << 31):
            return min(w, 4 + 1)
    return w


# ---------------------------------------------------------------------------
# shared survivor-bound core (used by engine/stream.py at pipeline build)
# ---------------------------------------------------------------------------


def _owns_key(colset, ref: A.ColumnRef) -> str | None:
    """Bare column name when a part whose lowercase ``alias.col`` key set
    is ``colset`` provides ``ref`` — mirroring the planner's qualified /
    suffix-match resolution over its internal column names."""
    name = ref.name.lower()
    if ref.table:
        return name if f"{ref.table.lower()}.{name}" in colset else None
    for c in colset:
        if c == name or c.endswith("." + name):
            return name
    return None


def _equi_sides(c, part_cols):
    """``(li, ri, lkey, rkey)`` when the conjunct is an equi edge between
    two distinct parts: a plain ``col = col`` (bare key names returned),
    or an expression-equi conjunct whose sides each live wholly in one
    part (keys None — an expression can never cover a primary key)."""
    if not (isinstance(c, A.BinaryOp) and c.op == "="):
        return None
    if isinstance(c.left, A.ColumnRef) and isinstance(c.right, A.ColumnRef):
        li = ri = None
        lk = rk = None
        for i, cols in enumerate(part_cols):
            if li is None:
                got = _owns_key(cols, c.left)
                if got:
                    li, lk = i, got
            if ri is None:
                got = _owns_key(cols, c.right)
                if got:
                    ri, rk = i, got
        if li is not None and ri is not None and li != ri:
            return li, ri, lk, rk
        return None

    def side_owner(e):
        refs = _column_refs(e)
        if not refs:
            return None
        owner = None
        for r in refs:
            cands = [i for i, cols in enumerate(part_cols)
                     if _owns_key(cols, r)]
            if len(cands) != 1:
                return None
            if owner is None:
                owner = cands[0]
            elif owner != cands[0]:
                return None
        return owner

    li, ri = side_owner(c.left), side_owner(c.right)
    if li is not None and ri is not None and li != ri:
        return li, ri, None, None
    return None


def _table_pk(src: str | None):
    if not src:
        return None
    pk = COMPOSITE_PRIMARY_KEYS.get(src)
    if pk is None and src in PRIMARY_KEYS:
        pk = (PRIMARY_KEYS[src],)
    return pk


def _batch_unique_side(part_cols, sources, keep, a, b, batch) -> bool:
    """True when one side of the (a, b) edge batch is unique on its join
    keys: the side is a pristine base-table scan whose bare key-name set
    covers its declared (composite) primary key. When the batch touches
    the streamed slot (``keep``), only the OTHER side counts — per-chunk
    multiplicity is bounded by the non-chunk side's uniqueness, and the
    executor masks chunk-side PK plans anyway (their host key ranges
    would bake chunk data into the chunk-invariant program)."""
    cands = [s for s in (a, b) if s != keep] if keep in (a, b) else [a, b]
    for side in cands:
        pk = _table_pk(sources[side])
        if pk is None:
            continue
        keys = set()
        for (li, ri, lk, rk) in batch:
            k = lk if li == side else (rk if ri == side else None)
            if k is not None:
                keys.add(k)
        if keys >= set(pk):
            return True
    return False


def stream_graph_fanout(part_cols, sources, keep, conjuncts):
    """Conservative survivor-multiplicity exponent ``k`` of a streamed
    join graph, or None when the multiplicity is unprovable.

    ``part_cols`` is the per-part set of lowercase ``alias.col`` column
    keys, ``sources`` the per-part pristine catalog table name (None for
    derived relations), ``keep`` the streamed part's index, ``conjuncts``
    the join predicates + WHERE conjuncts (AST expressions).

    The survivor rows of the whole streamed graph are then bounded by
    ``bucket_len(streamed table rows) × fanout^k``: each of the ``k``
    join batches with no unique (PK-covered) side is clamped at runtime
    by the stream-bounds pair bucket (probe bucket × fanout, device
    overflow flag past it), and every unique batch keeps per-row
    multiplicity at <= 1. Subquery conjuncts are FILTERS: multi-pass
    streaming pre-plans their inner tables into device residuals and the
    conjunct reduces to a membership/compare mask over joined rows —
    never growing them — so they do not affect the bound. Returns None
    when some part is not connected to the streamed slot by equi edges
    (cartesian layout: a chunk-data-dependent host read, eager
    fallback)."""
    n = len(part_cols)
    batches: dict = {}
    for c in conjuncts:
        if _has_subquery(c):
            continue
        e = _equi_sides(c, part_cols)
        if e is None:
            # single-part filter, correlation, or a cross-part non-equi
            # residual: applied to joined rows, never grows them
            continue
        li, ri, lk, rk = e
        batches.setdefault(tuple(sorted((li, ri))), []).append(e)

    parent = list(range(n))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for (a, b) in batches:
        parent[find(a)] = find(b)
    if n and any(find(i) != find(keep) for i in range(n)):
        return None
    k = 0
    for (a, b), batch in batches.items():
        if not _batch_unique_side(part_cols, sources, keep, a, b, batch):
            k += 1
    return k


def _deep_children(e):
    """Every AST expression nested in ``e``, reached through arbitrary
    dataclass / list / tuple containers (unlike ``exec_audit._children``
    this descends into non-Expr dataclasses such as WindowSpec, whose
    partition/order expressions the pruning model must see — a missed
    reference would UNDER-bound a width)."""

    def rec(v):
        if isinstance(v, A.Expr):
            yield v
        elif isinstance(v, (list, tuple)):
            for x in v:
                yield from rec(x)
        elif hasattr(v, "__dataclass_fields__"):
            for f in vars(v).values():
                yield from rec(f)

    if hasattr(e, "__dataclass_fields__"):
        for f in vars(e).values():
            yield from rec(f)


def structural_row_bound(rows: int, k: int, fanout: int) -> int:
    """``bucket_len(rows) × fanout^k`` — the structural term of the
    survivor proof. ONE definition shared by :meth:`MemModel.acc_row_bound`
    (the audit) and ``engine/stream.py._proved_row_bound`` (the runtime
    accumulator sizing), so the two can never drift apart."""
    return _bucket(max(int(rows), 1)) * (int(fanout) ** int(k))


# ---------------------------------------------------------------------------
# partitioned (grace-style) fan-out accumulation: the per-partition proof
# ---------------------------------------------------------------------------

# partition-count search ceiling: past 256 partitions the per-chunk
# dispatch fan-out dominates any accumulator saving
_MAX_PARTITIONS = 256


def _pow2_at_least(n: int) -> int:
    n = int(n)
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def stream_partitions_env() -> int | None:
    """``NDS_TPU_STREAM_PARTITIONS``: pins the partition count of every
    partitionable streamed graph (rounded up to a power of two; <= 1
    disables partitioning). Unset = the proof chooses statically
    (:func:`choose_partitions`). Read at model/pipeline BUILD time.
    Clamped to :data:`_MAX_PARTITIONS` so the partition-bit window of the
    routing hash stays inside the mixed 32-bit width at any legal setting
    (num_audit hash-bit rule: ``log2(P) + log2(S) <= 32``)."""
    env = os.environ.get("NDS_TPU_STREAM_PARTITIONS")
    return min(_pow2_at_least(int(env)), _MAX_PARTITIONS) if env else None


def stream_skew_factor() -> int:
    """``NDS_TPU_STREAM_SKEW``: the hash-skew safety factor of the
    per-partition bound (default 2 — one partition may hold up to
    ``skew ×`` its even share before the enforced overflow flag fires)."""
    return max(int(os.environ.get("NDS_TPU_STREAM_SKEW", "2")), 1)


def stream_shards_env() -> int:
    """``NDS_TPU_STREAM_SHARDS``: shard count of the streamed pipeline's
    device mesh (rounded up to a power of two; <= 1 disables sharding).
    Read at model/pipeline BUILD time like the partition knob. The audit
    models the requested count; the runtime additionally requires that
    many local devices (``parallel.exchange.stream_mesh``) and falls back
    to 1 otherwise — the differential harness closes that gap by checking
    ``StreamEvent.shards`` against the model. Clamped to
    :data:`_MAX_PARTITIONS` like the partition knob: together the two
    route windows consume at most 8 + 8 of the 32 mixed hash bits."""
    env = os.environ.get("NDS_TPU_STREAM_SHARDS")
    return min(_pow2_at_least(int(env)), _MAX_PARTITIONS) if env else 1


def shard_row_bound(rows: int, n_shards: int, n_partitions: int, k: int,
                    fanout: int, skew: int | None = None) -> int:
    """Per-shard survivor-row bound of a mesh-sharded streamed graph:
    the structural bound of one shard's skew-factored row share —
    ``rows/shards × skew`` through the fan-out exponent. Composes with
    grace-style partitioning (``n_partitions`` > 1): the partition share
    re-shares over the mesh, each level keeping its own skew allowance.
    Sound under the skew assumption; the runtime enforces it with
    per-shard overflow flags (overflow ⇒ eager rerun), exactly like
    :func:`partition_row_bound`. Shared by the audit and
    ``engine/stream.py`` — one definition, no drift."""
    if skew is None:
        skew = stream_skew_factor()
    rows = max(int(rows), 1)
    share = rows
    if n_partitions > 1:
        share = min(share, -(-share // int(n_partitions)) * int(skew))
    if n_shards > 1:
        share = min(share, -(-share // int(n_shards)) * int(skew))
    return structural_row_bound(share, k, fanout)


def partition_row_bound(rows: int, n_partitions: int, k: int, fanout: int,
                        skew: int | None = None) -> int:
    """Per-partition survivor-row bound of a hash-partitioned streamed
    graph: the structural bound of one partition's skew-factored row
    share. Sound under the skew assumption; the runtime enforces it with
    a per-partition overflow flag (overflow ⇒ eager rerun). Shared by
    the audit and ``engine/stream.py`` — one definition, no drift."""
    if skew is None:
        skew = stream_skew_factor()
    rows = max(int(rows), 1)
    share = min(rows, -(-rows // max(int(n_partitions), 1)) * int(skew))
    return structural_row_bound(share, k, fanout)


def choose_partitions(rows: int, k: int, fanout: int, row_bytes: int,
                      capacity_bytes: int, forced: int | None = None,
                      skew: int | None = None):
    """``(n_partitions, per_partition_row_bound)`` for one streamed graph.

    ``forced`` (``NDS_TPU_STREAM_PARTITIONS``) pins the count; auto picks
    the smallest power of two whose skew-factored per-partition
    accumulator bound fits ``capacity_bytes`` — statically, so the count
    can join the pipeline-cache key. ``(1, None)`` means unpartitioned:
    either the whole bound already fits, or no count up to
    ``_MAX_PARTITIONS`` admits it (the caller keeps today's legacy-clamp
    behavior)."""
    row_bytes = max(int(row_bytes), 1)
    if forced is not None:
        p = _pow2_at_least(forced)
        if p <= 1:
            return 1, None
        return p, partition_row_bound(rows, p, k, fanout, skew)
    if structural_row_bound(rows, k, fanout) * row_bytes <= capacity_bytes:
        return 1, None
    p = 2
    while p <= _MAX_PARTITIONS:
        bound = partition_row_bound(rows, p, k, fanout, skew)
        if bound * row_bytes <= capacity_bytes:
            return p, bound
        p <<= 1
    return 1, None


def stream_partition_keys(part_cols, sources, keep, conjuncts):
    """Bare chunk-side column names the partition hash keys on, or None
    when the streamed graph is not partitionable (no plain-column equi
    edge incident to the streamed slot — bare scans, expression-only
    edges; subquery conjuncts are skipped like in
    :func:`stream_graph_fanout`, they are residual-planned filters).

    Prefers a fan-out batch (no PK-unique side — the batch whose
    multiplicity forced partitioning in the first place) so rows that
    co-fan-out land in one partition; falls back to any incident equi
    batch (any chunk-row partitioning keeps the per-partition bound
    valid, since multiplicity is per-row). Deterministic: batches walk
    in sorted part order, keys return sorted."""
    batches: dict = {}
    for c in conjuncts:
        if _has_subquery(c):
            continue
        e = _equi_sides(c, part_cols)
        if e is None:
            continue
        li, ri, _lk, _rk = e
        batches.setdefault(tuple(sorted((li, ri))), []).append(e)
    best = None
    for (a, b) in sorted(batches):
        if keep not in (a, b):
            continue
        batch = batches[(a, b)]
        keys = sorted({(lk if li == keep else rk)
                       for (li, ri, lk, rk) in batch
                       if (lk if li == keep else rk) is not None})
        if not keys:
            continue
        fan_out = not _batch_unique_side(part_cols, sources, keep,
                                         a, b, batch)
        if best is None or (fan_out and not best[0]):
            best = (fan_out, tuple(keys))
    return best[1] if best else None


def statement_needed_names(stmt, catalog_cols: dict | None = None) \
        -> set | None:
    """Bare lowercase column names the statement references anywhere —
    the audit's mirror of the planner's projection pushdown
    (``Planner._collect_needed_names``) — or None when pruning is unsafe.

    ``SELECT *`` is resolved SCOPED, like the planner: a star over a
    derived table (CTE or FROM-subquery) needs nothing new (its inner
    projection is explicit and walked); a star over a catalog table adds
    that table's full column set; only a star over an unresolvable name
    disables pruning. ``catalog_cols`` maps table -> column names
    (default: the TPC-DS schema)."""
    if catalog_cols is None:
        catalog_cols = {t: [f.name for f in fields]
                        for t, fields in get_schemas(True).items()}
    names: set = set()
    disabled = [False]

    def add_table(name):
        cols = catalog_cols.get(name)
        if cols is None:
            disabled[0] = True
        else:
            names.update(c.lower() for c in cols)

    def rel_entries(f, out):
        """(alias, catalog name | None-for-derived) per FROM leaf."""
        if isinstance(f, A.TableRef):
            out.append(((f.alias or f.name).lower(), f.name.lower()))
        elif isinstance(f, A.SubqueryRef):
            out.append((f.alias.lower(), None))
        elif isinstance(f, A.Join):
            rel_entries(f.left, out)
            rel_entries(f.right, out)
        elif isinstance(f, A.Query):
            rel_entries(getattr(f.body, "from_", None), out)

    def walk_expr(e, ctes, rels):
        if isinstance(e, A.Star):
            qual = e.table and e.table.lower()
            if qual is None:
                for _alias, src in rels:
                    if src is not None and src not in ctes:
                        add_table(src)
                if not rels:
                    disabled[0] = True
            else:
                hit = [src for alias, src in rels
                       if alias == qual or src == qual]
                if hit and hit[0] is not None and hit[0] not in ctes:
                    add_table(hit[0])
                elif (hit and (hit[0] is None or hit[0] in ctes)) \
                        or qual in ctes:
                    pass               # star over a derived relation
                    #                    (subquery alias, CTE name, or an
                    #                    ALIAS over a CTE reference)
                elif qual in catalog_cols:
                    add_table(qual)
                else:
                    disabled[0] = True
            return
        if isinstance(e, A.ColumnRef):
            names.add(e.name.lower())
            return
        if isinstance(e, (A.ScalarSubquery, A.InSubquery, A.Exists,
                          A.QuantifiedCompare)):
            walk_query(e.query, ctes)
            if isinstance(e, (A.InSubquery, A.QuantifiedCompare)):
                walk_expr(e.expr, ctes, rels)
            return
        for c in _deep_children(e):
            walk_expr(c, ctes, rels)

    def walk_from(f, ctes, rels):
        if isinstance(f, A.SubqueryRef):
            walk_query(f.query, ctes)
        elif isinstance(f, A.Join):
            walk_from(f.left, ctes, rels)
            walk_from(f.right, ctes, rels)
            if f.condition is not None:
                walk_expr(f.condition, ctes, rels)
        elif isinstance(f, A.Query):
            walk_from(getattr(f.body, "from_", None), ctes, rels)

    def walk_sel(sel, ctes):
        rels = []
        rel_entries(sel.from_, rels)
        walk_from(sel.from_, ctes, rels)
        for item in sel.items:
            walk_expr(item.expr, ctes, rels)
        if sel.where is not None:
            walk_expr(sel.where, ctes, rels)
        if sel.group_by is not None:
            for e in sel.group_by.exprs:
                walk_expr(e, ctes, rels)
        if sel.having is not None:
            walk_expr(sel.having, ctes, rels)

    def walk_body(b, ctes):
        if isinstance(b, A.SetOp):
            walk_body(b.left, ctes)
            walk_body(b.right, ctes)
        elif isinstance(b, A.Query):
            walk_query(b, ctes)
        else:
            walk_sel(b, ctes)

    def walk_query(q, ctes):
        ctes = set(ctes)
        for cname, cq in q.ctes:
            walk_query(cq, ctes)
            ctes.add(cname.lower())
        walk_body(q.body, ctes)
        for ent in q.order_by:
            walk_expr(ent[0], ctes, [])

    if isinstance(stmt, A.Query):
        walk_query(stmt, set())
    elif isinstance(stmt, (A.InsertInto, A.CreateTempView)):
        walk_query(stmt.query, set())
    elif isinstance(stmt, A.DeleteFrom) and stmt.where is not None:
        walk_expr(stmt.where, set(), [])
    return None if disabled[0] else names


# ---------------------------------------------------------------------------
# the capacity / cardinality model
# ---------------------------------------------------------------------------


class MemModel:
    """Capacity + cardinality model every bound is computed against.

    ``row_bounds`` maps catalog table -> row upper bound (default: the
    conservative SF10 table); ``capacity_bytes`` is the HBM budget
    (``NDS_TPU_HBM_BYTES``); ``fanout``/``chunk_rows``/``acc_ceiling``
    mirror the executor's env knobs, read at construction time so a model
    built after the environment changed sees the change (the same
    build-time discipline ``engine/stream.py`` follows)."""

    def __init__(self, row_bounds=None, capacity_bytes=None, fanout=None,
                 chunk_rows=None, acc_ceiling="env", catalog=None):
        self.row_bounds = dict(DEFAULT_ROW_BOUNDS if row_bounds is None
                               else row_bounds)
        self.capacity_bytes = (hbm_capacity_bytes() if capacity_bytes is None
                               else int(capacity_bytes))
        self.fanout = _pow2_ceil(int(
            os.environ.get("NDS_TPU_STREAM_FANOUT", "4"))
            if fanout is None else int(fanout))
        self.chunk_rows = int(
            os.environ.get("NDS_TPU_STREAM_CHUNK_ROWS", str(1 << 22))
            if chunk_rows is None else chunk_rows)
        if acc_ceiling == "env":
            env = os.environ.get("NDS_TPU_STREAM_ACC_ROWS")
            acc_ceiling = int(env) if env else None
        self.acc_ceiling = acc_ceiling
        # partitioned accumulation knobs (same build-time env discipline)
        self.partitions = stream_partitions_env()  # None = proof-chosen
        self.skew = stream_skew_factor()
        # mesh-sharded execution knob (NDS_TPU_STREAM_SHARDS): the per-
        # shard bound divides the survivor share over the mesh exactly
        # like the partition share rule (shard_row_bound)
        self.shards = stream_shards_env()
        # async-ingest knob (NDS_TPU_PREFETCH_DEPTH, engine/prefetch.py):
        # up to ``depth`` prepared chunks wait in the bounded prefetch
        # ring beyond the two the drive loop already holds — priced into
        # every streamed peak and subtracted from the capacity admission
        # decisions see (the executor mirrors this at pipeline build:
        # the lockstep rule). Depth <= 0 = ring off, priced zero.
        self.prefetch_depth = max(prefetch_depth(), 0)
        if catalog is None:
            catalog = {
                t: {f.name.lower(): type_width(f.type) for f in fields}
                for t, fields in get_schemas(use_decimal=True).items()}
        self.widths = catalog              # table -> {col -> bytes/row}
        # encoded execution (NDS_TPU_ENCODED, default on): streamed chunk
        # scans are priced at the statically-provable encoded widths —
        # the bounds (and therefore choose_partitions) shrink with the
        # data. Same build-time env discipline as the other knobs.
        self.encoded = encoded_enabled()
        if self.encoded:
            self.enc_widths = {
                t: {c: encoded_type_width(c, f.type, self.row_bounds)
                    for c, f in ((f.name.lower(), f) for f in fields)}
                for t, fields in get_schemas(use_decimal=True).items()}
        else:
            self.enc_widths = {}

    def table_rows(self, name: str) -> int | None:
        return self.row_bounds.get(name)

    def pruned_width(self, table: str, needed: set | None,
                     encoded: bool = False) -> int:
        """Bytes per row of ``table`` after the planner's column pruning
        (``needed`` = names the statement references; None disables
        pruning). An empty intersection keeps every column, exactly like
        the planner (it never prunes to zero columns). ``encoded`` prices
        the streamed-chunk representation (narrow codecs)."""
        cols = (self.enc_widths if encoded and self.encoded
                else self.widths).get(table, {})
        if not cols:
            return 9                       # unknown table: one wide column
        if needed is not None:
            kept = {c: w for c, w in cols.items() if c in needed}
            if kept and len(kept) < len(cols):
                cols = kept
        return sum(cols.values())

    def chunk_cap(self) -> int:
        return _bucket(self.chunk_rows)

    def acc_row_bound(self, stream_rows: int, k: int) -> int:
        """Proven survivor-row bound of one streamed graph: the tighter
        of the per-chunk-bucket sum and the structural
        ``bucket_len(rows) × fanout^k`` bound (both sound; the runtime
        sizes its accumulator from the same minimum)."""
        mult = self.fanout ** k
        n_chunks = max(1, math.ceil(stream_rows / self.chunk_rows))
        base = n_chunks * self.chunk_cap() * mult
        return min(base, structural_row_bound(stream_rows, k, self.fanout))

    def partition_bound(self, stream_rows: int, k: int,
                        n_partitions: int) -> int:
        """Per-partition accumulator row bound: the tighter of the
        per-chunk-bucket sum (each of a partition's dispatches still
        contributes at most one chunk output bucket) and the
        skew-factored structural share (:func:`partition_row_bound`)."""
        mult = self.fanout ** k
        n_chunks = max(1, math.ceil(stream_rows / self.chunk_rows))
        base = n_chunks * self.chunk_cap() * mult
        return min(base, partition_row_bound(stream_rows, n_partitions, k,
                                             self.fanout, self.skew))

    def ring_bytes(self, chunk_row_width: int) -> int:
        """Extra live bytes of the bounded prefetch ring: ``depth`` more
        padded chunks resident beyond the in-flight pair the chunk-bytes
        term already prices. Comes off the admitting capacity and joins
        the streamed peak — the static twin of ``stream._ring_bytes``
        (which prices the ACTUAL first-chunk upload bytes; this model
        prices the conservative ``chunk_cap × pruned width``)."""
        return self.prefetch_depth * self.chunk_cap() \
            * max(int(chunk_row_width), 0)

    def admit_capacity(self, chunk_row_width: int) -> int:
        """Capacity the streamed admission decisions compare against:
        ``NDS_TPU_HBM_BYTES`` minus the prefetch ring's live set."""
        return max(self.capacity_bytes - self.ring_bytes(chunk_row_width),
                   1)

    def bare_scan_fits(self, table: str | None, needed: set | None) -> bool:
        """Can a bare streamed scan of ``table`` (no filter, no join: the
        survivor accumulator keeps every row) be proven to fit? True when
        the proven accumulator bound fits the capacity model (net of the
        prefetch ring's live set) AND the env ceiling (if one is set)
        admits the table's rows — exactly the condition under which the
        runtime's proof-sized accumulator can never trip the overflow
        rerun. This is the predicate that retires
        ``accumulator-overflow`` fallbacks (`exec_audit` lockstep)."""
        rows = self.row_bounds.get(table or "")
        if rows is None:
            return False
        if self.acc_ceiling is not None and rows > self.acc_ceiling:
            return False                   # hard ceiling: overflow certain
        bound = self.acc_row_bound(rows, 0)
        w = self.pruned_width(table, needed, encoded=True)
        return bound * w <= self.admit_capacity(w)


# ---------------------------------------------------------------------------
# report types
# ---------------------------------------------------------------------------


@dataclass
class ScanBound:
    """The proven memory fate of one >HBM streamed scan."""

    alias: str
    table: str
    rows: int                  # streamed table row bound
    fanout_k: int | None       # survivor-multiplicity exponent; None =
    #                            unprovable (subquery / cartesian: the
    #                            executor falls back eager there)
    acc_rows: int | None       # proven accumulator row bound (provable)
    acc_bytes: int | None      # acc_rows x streamed-graph row width
    chunk_bytes: int = 0       # one padded chunk's bytes (x2 in flight)
    partitions: int = 1        # grace-style partition count (1 = whole)
    part_rows: int | None = None   # per-partition accumulator row bound
    part_bytes: int | None = None  # part_rows x streamed-graph row width
    shards: int = 1            # mesh shard count (NDS_TPU_STREAM_SHARDS)
    shard_rows: int | None = None  # per-shard survivor-row bound across
    #                                partitions (rows/shards x skew through
    #                                the fan-out — what StreamEvent's
    #                                shard_rows evidence is checked against)
    shard_bytes: int | None = None  # per-(partition, shard) accumulator
    #                                 unit bound x row width — the
    #                                 allocation unit a sharded pipeline's
    #                                 per-shard overflow flags enforce
    ring_bytes: int = 0        # prefetch-ring live set (depth x one
    #                            padded chunk) priced into the streamed
    #                            peak and off the admitting capacity
    #                            (NDS_TPU_PREFETCH_DEPTH; 0 = ring off)

    @property
    def provable(self) -> bool:
        return self.fanout_k is not None


@dataclass
class MemReport:
    """Peak-HBM byte bound of one template statement."""

    file: str
    query: str
    mode: str                  # "streamed" | "device" | "unknown"
    peak_bytes: int = 0
    out_rows: int = 0          # statement output row bound (soundness-
    #                            checked by tools/mem_audit_diff.py)
    scans: tuple = ()          # ScanBounds, FROM order
    detail: str = ""

    def to_dict(self) -> dict:
        return {
            "file": self.file, "query": self.query, "mode": self.mode,
            "peak_bytes": int(self.peak_bytes),
            "out_rows": int(self.out_rows),
            "scans": [{"alias": s.alias, "table": s.table,
                       "rows": int(s.rows), "fanout_k": s.fanout_k,
                       "acc_rows": None if s.acc_rows is None
                       else int(s.acc_rows),
                       "acc_bytes": None if s.acc_bytes is None
                       else int(s.acc_bytes),
                       "chunk_bytes": int(s.chunk_bytes),
                       "partitions": int(s.partitions),
                       "part_rows": None if s.part_rows is None
                       else int(s.part_rows),
                       "part_bytes": None if s.part_bytes is None
                       else int(s.part_bytes),
                       "shards": int(s.shards),
                       "shard_rows": None if s.shard_rows is None
                       else int(s.shard_rows),
                       "shard_bytes": None if s.shard_bytes is None
                       else int(s.shard_bytes),
                       "ring_bytes": int(s.ring_bytes),
                       "provable": s.provable} for s in self.scans],
            "detail": self.detail,
        }


class _MRel:
    """One relation in the walk: row bound + per-column widths and value
    domains, addressable by every alias the relation answers for (a
    materialized outer join keeps both sides' aliases, exactly like the
    planner's merged alias-qualified columns)."""

    __slots__ = ("cols", "widths", "dom", "rows", "source", "chunked",
                 "single_row", "plain_widths")

    def __init__(self, alias, widths: dict, rows: int, dom: dict | None =
                 None, source=None, chunked=False, single_row=False):
        a = alias.lower()
        self.widths = {a: dict(widths)}
        self.cols = {a: set(widths)}
        self.dom = {a: dict(dom or {c: rows for c in widths})}
        self.rows = int(rows)
        self.source = source
        self.chunked = chunked
        self.single_row = single_row
        # encoded execution: a chunked rel's ``widths`` price the narrow
        # streamed representation; ``plain_widths`` keeps the unencoded
        # widths for the paths that materialize the table whole (a
        # non-kept chunked part binds device-resident, unencoded)
        self.plain_widths = None

    @property
    def alias(self) -> str:
        return next(iter(self.cols))

    @property
    def width(self) -> int:
        return sum(w for cols in self.widths.values()
                   for w in cols.values())

    @property
    def plain_width(self) -> int:
        """Unencoded width (equals ``width`` for unencoded rels) — the
        byte size the runtime's whole-table materialization pays, and the
        keep-choice tiebreak (the executor picks by arrow nbytes)."""
        if self.plain_widths is None:
            return self.width
        return sum(self.plain_widths.values())

    def use_plain_widths(self) -> None:
        """Re-price this rel at its unencoded widths (non-kept chunked
        parts materialize whole through the plain device path)."""
        if self.plain_widths is not None:
            self.widths = {self.alias: dict(self.plain_widths)}
            self.plain_widths = None

    def colset(self) -> set:
        return {f"{a}.{c}" for a, cols in self.cols.items() for c in cols}

    def owns(self, ref: A.ColumnRef) -> str | None:
        name = ref.name.lower()
        if ref.table:
            t = ref.table.lower()
            cols = self.cols.get(t)
            return name if cols is not None and name in cols else None
        for cols in self.cols.values():
            if name in cols:
                return name
        return None

    def col_width(self, ref) -> int:
        name = ref.name.lower()
        aliases = [ref.table.lower()] if ref.table else list(self.cols)
        for a in aliases:
            w = self.widths.get(a, {}).get(name)
            if w is not None:
                return w
        return 9

    def col_domain(self, ref) -> int:
        name = ref.name.lower()
        aliases = [ref.table.lower()] if ref.table else list(self.cols)
        for a in aliases:
            d = self.dom.get(a, {}).get(name)
            if d is not None:
                return d
        return self.rows

    def merged_with(self, other: "_MRel", rows: int) -> "_MRel":
        out = _MRel(self.alias, {}, rows)
        out.cols = {**self.cols, **other.cols}
        out.widths = {**self.widths, **other.widths}
        out.dom = {**self.dom, **other.dom}
        out.rows = int(rows)
        return out


class _MemCost:
    """Accumulator for one statement walk: running peak-byte sum (a
    conservative everything-live-at-once over-approximation) plus the
    streamed-scan bounds discovered along the way."""

    def __init__(self):
        self.peak = 0
        self.scans: list = []


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


class MemAuditor:
    """Host-only abstract interpreter computing peak-HBM byte bounds.

    ``streamed`` names the tables bound as >HBM ChunkedTables (the same
    binding model `exec_audit` uses); ``model`` carries capacities and
    cardinalities. The walk mirrors ``Planner._flatten_from`` →
    ``_join_parts`` → downstream aggregation, tracking (row bound,
    per-column width, per-column domain) per relation."""

    DEFAULT_STREAMED = ("catalog_sales", "inventory", "store_sales",
                        "web_sales")

    def __init__(self, streamed=None, model: MemModel | None = None,
                 base_tables=None):
        self.model = model or MemModel()
        self.streamed = set(self.DEFAULT_STREAMED if streamed is None
                            else streamed)
        self.base_tables = set(self.model.widths if base_tables is None
                               else base_tables)
        self.needed: set | None = None

    # -- entry point --------------------------------------------------------

    def audit_sql(self, sql: str, file: str = "<sql>",
                  query: str = "<sql>") -> MemReport:
        try:
            stmt = parse(sql)
        except ParseError as e:
            return MemReport(file, query, "unknown", detail=str(e))
        self.needed = statement_needed_names(stmt)
        cost = _MemCost()
        env = self._base_env()
        out_rows = 0
        try:
            if isinstance(stmt, A.Query):
                out_rows = self._audit_query(stmt, env, cost).rows
            elif isinstance(stmt, (A.InsertInto, A.CreateTempView)):
                out_rows = self._audit_query(stmt.query, env, cost).rows
            elif isinstance(stmt, A.DeleteFrom):
                name = stmt.table.lower()
                rows = self.model.table_rows(name) or 1
                cost.peak += rows * self.model.pruned_width(name, None)
            else:
                return MemReport(file, query, "unknown",
                                 detail=f"unmodeled statement "
                                        f"{type(stmt).__name__}")
        except RecursionError:
            return MemReport(file, query, "unknown",
                             detail="recursion limit")
        mode = "streamed" if cost.scans else "device"
        return MemReport(file, query, mode, peak_bytes=cost.peak,
                         out_rows=out_rows, scans=tuple(cost.scans))

    def _base_env(self) -> dict:
        env = {}
        for name, widths in self.model.widths.items():
            rows = self.model.table_rows(name) or 1
            env[name] = (widths, rows, name in self.base_tables)
        return env

    # -- query / set-expression walk ---------------------------------------

    def _audit_query(self, q: A.Query, env: dict, cost: _MemCost) -> _MRel:
        env = dict(env)
        for cname, cq in q.ctes:
            out = self._audit_query(cq, env, cost)
            widths = {c: w for cols in out.widths.values()
                      for c, w in cols.items()}
            # a CTE result is a device table whatever it scanned; it may
            # shadow a chunked catalog name (the planner resolves CTEs
            # first, so the statement does not stream the shadowed table)
            env[cname.lower()] = (widths, out.rows, False)
        out = self._audit_body(q.body, env, cost)
        # ORDER BY: the device lexsort holds one index vector alongside
        # the input — 8 B per row, already dominated by the conservative
        # sum; LIMIT clamps the output rows exactly
        if q.limit is not None:
            out.rows = min(out.rows, max(int(q.limit), 0))
        return out

    def _audit_body(self, body, env: dict, cost: _MemCost) -> _MRel:
        if isinstance(body, A.SetOp):
            left = self._audit_body(body.left, env, cost)
            right = self._audit_body(body.right, env, cost)
            rows = left.rows + right.rows
            # the concatenated buffer is a fresh allocation alongside the
            # branches (UNION's distinct grouping reuses it in place)
            cost.peak += _bucket(max(rows, 1)) * max(left.width,
                                                     right.width, 1)
            if body.op in ("intersect", "except"):
                rows = left.rows         # both are subsets of the left
            elif body.op == "union":
                # distinct union: also bounded by the output columns'
                # value-domain product (same rule as SELECT DISTINCT)
                doms = [d for cols in left.dom.values()
                        for d in cols.values()]
                if doms:
                    dom = 1
                    for v in doms:
                        dom = min(dom * max(v, 1), max(rows, 1))
                    rows = min(rows, max(dom, 1))
            out = _MRel(left.alias, {}, rows)
            out.cols, out.widths, out.dom = left.cols, left.widths, left.dom
            out.rows = rows
            return out
        if isinstance(body, A.Query):
            return self._audit_query(body, env, cost)
        return self._audit_select(body, env, cost)

    # -- SELECT -------------------------------------------------------------

    def _audit_select(self, sel: A.Select, env: dict,
                      cost: _MemCost) -> _MRel:
        where = _conjuncts_of(sel.where)
        parts, preds = self._flatten_from(sel.from_, env, cost, where)
        if parts:
            joined = self._audit_graph(parts, list(preds) + list(where),
                                       env, cost)
        else:
            joined = _MRel("_dual", {}, 1, single_row=True)
        for item in sel.items:
            self._walk_subqueries(item.expr, env, cost)
        if sel.having is not None:
            self._walk_subqueries(sel.having, env, cost)
        if not parts:
            # with a FROM graph the WHERE conjuncts were handed to
            # _audit_graph, which walks their subqueries exactly once
            for c in where:
                self._walk_subqueries(c, env, cost)

        rows = joined.rows
        if sel.group_by is not None:
            gb = sel.group_by
            # group output <= product of the key value domains (a base
            # column's domain is at most its table's rows), clamped at
            # input rows; grouping sets replay the aggregation per set
            dom = 1
            for e in gb.exprs:
                d = joined.col_domain(e) if isinstance(e, A.ColumnRef) \
                    else rows
                dom = min(dom * max(d, 1), max(rows, 1))
            n_sets = max(len(gb.sets), 1) if gb.kind != "plain" else 1
            rows = min(rows, max(dom, 1)) * n_sets
        elif self._has_aggregate_items(sel):
            rows = 1                       # keyless aggregate: one row

    # -- projection: output widths/domains ----------------------------------

        widths, dom = {}, {}
        for i, item in enumerate(sel.items):
            e = item.expr
            if isinstance(e, A.Star):
                qual = e.table and e.table.lower()
                for a, cols in joined.widths.items():
                    if qual is None or a == qual:
                        widths.update(cols)
                        dom.update(joined.dom.get(a, {}))
                continue
            if item.alias:
                name = item.alias.lower()
            elif isinstance(e, A.ColumnRef):
                name = e.name.lower()
            else:
                name = f"_c{i}"
            if isinstance(e, A.ColumnRef):
                widths[name] = joined.col_width(e)
                dom[name] = joined.col_domain(e)
            else:
                widths[name] = 9
                dom[name] = rows
        out = _MRel("_out", widths, rows, dom=dom)
        if sel.distinct and dom:
            d = 1
            for v in dom.values():
                d = min(d * max(v, 1), max(rows, 1))
            out.rows = rows = min(rows, max(d, 1))
        # the projected output is a fresh materialization
        cost.peak += _bucket(max(rows, 1)) * max(out.width, 1)
        return out

    def _has_aggregate_items(self, sel: A.Select) -> bool:
        from nds_tpu.sql.parser import AGG_FUNCS

        def has_agg(e) -> bool:
            if isinstance(e, A.FuncCall) and e.name.lower() in AGG_FUNCS:
                return True
            return any(has_agg(c) for c in _children(e))

        return any(has_agg(i.expr) for i in sel.items
                   if not isinstance(i.expr, A.Star))

    # -- FROM flattening (mirror of Planner._flatten_from) ------------------

    def _flatten_from(self, node, env: dict, cost: _MemCost, where=None,
                      top: bool = True):
        if node is None:
            return [], []
        if isinstance(node, A.TableRef):
            name = node.name.lower()
            alias = (node.alias or node.name).lower()
            widths, rows, is_base = env.get(name, ({}, 1, False))
            widths = self._prune(widths)
            chunked = is_base and name in self.streamed
            enc_widths = None
            if chunked and self.model.encoded:
                # streamed scans upload (and accumulate) the narrow
                # encoded representation — the width the proof prices
                enc_cols = self.model.enc_widths.get(name, {})
                enc_widths = {c: enc_cols.get(c, w)
                              for c, w in widths.items()}
            rel = _MRel(alias, enc_widths if enc_widths is not None
                        else widths, rows,
                        source=name if is_base else None, chunked=chunked)
            if enc_widths is not None:
                rel.plain_widths = dict(widths)
            if is_base and not chunked:
                # a device-resident base scan uploads its pruned columns
                cost.peak += _bucket(rows) * rel.width
            return [rel], []
        if isinstance(node, A.SubqueryRef):
            out = self._audit_query(node.query, env, cost)
            rel = _MRel(node.alias,
                        {c: w for cols in out.widths.values()
                         for c, w in cols.items()}, out.rows,
                        single_row=_single_row_query(node.query))
            return [rel], []
        if isinstance(node, A.Join):
            if node.kind in ("cross", "inner"):
                lp, lj = self._flatten_from(node.left, env, cost, where,
                                            top=False)
                rp, rj = self._flatten_from(node.right, env, cost, where,
                                            top=False)
                return lp + rp, lj + rj + _conjuncts_of(node.condition)
            lp, lj = self._flatten_from(node.left, env, cost, top=False)
            got = self._deferred_left(node, lp, lj, env, cost, where, top)
            if got is not None:
                return got
            # outer/semi/anti join: each side materializes whole first
            left = self._audit_graph(lp, lj, env, cost)
            rp, rj = self._flatten_from(node.right, env, cost)
            return self._finish_outer(node, left, rp, rj, env, cost)
        if isinstance(node, A.Query):        # parenthesized join tree
            return self._flatten_from(getattr(node.body, "from_", None),
                                      env, cost, where)
        return [], []

    def _finish_outer(self, node, left, rp, rj, env, cost):
        right = self._audit_graph(rp, rj, env, cost)
        rows = self._binary_join_rows(node, left, right)
        merged = left.merged_with(right, rows)
        cost.peak += _bucket(max(rows, 1)) * merged.width
        return [merged], []

    def _deferred_left(self, node, lp, lj, env, cost, where, top=True):
        """Mirror of the planner's multi-pass LEFT-join deferral (and of
        ``exec_audit._deferred_left``): an eligible join's sides flow
        into the enclosing streamed graph with the ON conjuncts as plain
        edges — the bound rules (PK-unique side => multiplicity 1) then
        price the join exactly like an inner PK batch, and the outer
        extras stay bounded by the preserved side's rows (every preserved
        row appears exactly once, matched or null-extended)."""
        if node.kind != "left" or node.condition is None:
            return None
        conjs = _conjuncts_of(node.condition)
        if not conjs or any(_has_subquery(c) for c in conjs):
            return None

        def plain_pairs(rel):
            out = []
            for c in conjs:
                if not (isinstance(c, A.BinaryOp) and c.op == "=" and
                        isinstance(c.left, A.ColumnRef) and
                        isinstance(c.right, A.ColumnRef)):
                    return None
                rk = rel.owns(c.left)
                lref = c.right
                if rk is None:
                    rk = rel.owns(c.right)
                    lref = c.left
                if rk is None or not any(p.owns(lref) for p in lp):
                    return None
                out.append((lref, rk))
            return out

        l_chunk = any(p.chunked for p in lp)
        if l_chunk:
            if os.environ.get("NDS_TPU_NO_PK_GATHER"):
                return None              # the b1 gather arm is disabled
            # (b1): preserved chunk side — one pristine right scan whose
            # ON keys are exactly its declared (composite) primary key
            rp, rj = self._flatten_from(node.right, env, cost, top=False)
            eligible = len(rp) == 1 and not rj and rp[0].source and \
                not rp[0].chunked
            if eligible:
                pairs = plain_pairs(rp[0])
                pk = _table_pk(rp[0].source)
                eligible = pairs is not None and pk is not None and \
                    {rk for (_l, rk) in pairs} == set(pk)
            if eligible:
                return lp + rp, lj + conjs
            left = self._audit_graph(lp, lj, env, cost)
            return self._finish_outer(node, left, rp, rj, env, cost)
        # (b2): null-introducing chunk side — single build part on the
        # left, single chunked scan on the right, the join being the
        # SELECT's whole FROM, and no remaining WHERE conjunct beyond
        # those the planner consumes below the join (build-side only)
        if len(lp) != 1 or lp[0].chunked:
            return None
        rp, rj = self._flatten_from(node.right, env, cost, top=False)
        eligible = top and len(rp) == 1 and not rj and rp[0].chunked and \
            plain_pairs(rp[0]) is not None
        if eligible:
            for c in (where or []):
                if _has_subquery(c):
                    eligible = False
                    break
                refs = _column_refs(c)
                # conjuncts fully on the build side are consumed below
                # the join by the planner (lw) and do not block
                if refs and all(lp[0].owns(r) for r in refs):
                    continue
                eligible = False
                break
        if eligible:
            lp[0].single_row = False
            return rp + lp, lj + conjs
        left = self._audit_graph(lp, lj, env, cost)
        return self._finish_outer(node, left, rp, rj, env, cost)

    def _prune(self, widths: dict) -> dict:
        if self.needed is None:
            return dict(widths)
        kept = {c: w for c, w in widths.items() if c in self.needed}
        return kept if kept and len(kept) < len(widths) else dict(widths)

    def _binary_join_rows(self, node: A.Join, left: _MRel,
                          right: _MRel) -> int:
        """Row bound of one materialized (outer/semi/anti) binary join.
        Semi/anti never grow the left side; a LEFT join against a side
        whose ON keys cover its declared primary key is 1:1 (matches +
        extras <= left rows); everything else is bounded by the pair
        bucket plus the null-extended extras."""
        if node.kind in ("semi", "anti"):
            return left.rows
        conjuncts = _conjuncts_of(node.condition)
        part_cols = [left.colset(), right.colset()]
        sources = [left.source, right.source]
        unique = {}
        for side, other in ((1, 0), (0, 1)):
            pk = _table_pk(sources[side])
            keys = set()
            for c in conjuncts:
                e = _equi_sides(c, part_cols)
                if e is None:
                    continue
                li, ri, lk, rk = e
                k = lk if li == side else (rk if ri == side else None)
                if k is not None:
                    keys.add(k)
            unique[side] = pk is not None and keys >= set(pk)
        pairs = left.rows if unique.get(1) else (
            right.rows if unique.get(0) and node.kind != "left"
            else _bucket(max(left.rows, 1)) * self.model.fanout)
        if node.kind == "left":
            return pairs + left.rows
        if node.kind == "right":
            return pairs + right.rows
        if node.kind == "full":
            return pairs + left.rows + right.rows
        return pairs

    # -- join-graph bounds (mirror of Planner._join_parts) ------------------

    def _audit_graph(self, parts, conjuncts, env, cost: _MemCost) -> _MRel:
        if not parts:
            return _MRel("_dual", {}, 1, single_row=True)
        if len(parts) == 1 and not any(p.chunked for p in parts):
            for c in conjuncts:
                self._walk_subqueries(c, env, cost)
            return parts[0]
        part_cols = [p.colset() for p in parts]
        sources = [p.source for p in parts]
        batches: dict = {}
        for c in conjuncts:
            if _has_subquery(c):
                # multi-pass streaming: the subquery pre-plans into a
                # device residual and the conjunct filters joined rows —
                # it neither grows rows nor breaks the proof
                self._walk_subqueries(c, env, cost)
                continue
            e = _equi_sides(c, part_cols)
            if e is not None:
                li, ri, _lk, _rk = e
                batches.setdefault(tuple(sorted((li, ri))), []).append(e)

        parent = list(range(len(parts)))

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for (a, b) in batches:
            parent[find(a)] = find(b)

        # per-component row bound: the largest member, times the enforced
        # fanout bucket for every batch with no unique side; components
        # multiply (cartesian layout is an exact product)
        comp_rows: dict = {}
        for i, p in enumerate(parts):
            r = find(i)
            base = 1 if p.single_row else max(p.rows, 1)
            comp_rows[r] = max(comp_rows.get(r, 1), base)
        chunked_idx = [i for i, p in enumerate(parts) if p.chunked]
        # keep-choice mirrors the executor (largest by UNENCODED bytes:
        # the runtime picks by arrow nbytes); non-kept chunked parts bind
        # whole through the plain device path, so they re-price plain
        keep = max(chunked_idx, key=lambda i: parts[i].rows *
                   max(parts[i].plain_width, 1)) if chunked_idx else None
        for i in chunked_idx:
            if i != keep:
                parts[i].use_plain_widths()
        for (a, b), batch in batches.items():
            if not _batch_unique_side(part_cols, sources,
                                      keep if keep is not None else -1,
                                      a, b, batch):
                r = find(a)
                comp_rows[r] = _bucket(comp_rows[r]) * self.model.fanout
        joined_rows = 1
        for r in comp_rows.values():
            joined_rows *= r

        merged = parts[0]
        for p in parts[1:]:
            merged = merged.merged_with(p, joined_rows)
        merged.rows = joined_rows

        if keep is None:
            # device-resident graph: the joined result materializes whole
            cost.peak += _bucket(max(joined_rows, 1)) * merged.width
            return merged

        # streamed graph: non-kept chunked parts bind whole (one
        # streaming axis per graph) — charge their resident bytes
        for i in chunked_idx:
            if i != keep:
                cost.peak += _bucket(parts[i].rows) * parts[i].width
        kept = parts[keep]
        k = stream_graph_fanout(part_cols, sources, keep, conjuncts)
        chunk_bytes = self.model.chunk_cap() * kept.width
        # async ingest: the bounded prefetch ring holds up to ``depth``
        # MORE prepared chunks beyond the in-flight pair — priced into
        # the peak below and off the capacity every admission decision
        # here compares against (lockstep with engine/stream.py)
        ring_bytes = self.model.ring_bytes(kept.width)
        admit_cap = self.model.admit_capacity(kept.width)
        n_parts, part_rows, part_bytes = 1, None, None
        if k is not None:
            acc_rows = self.model.acc_row_bound(kept.rows, k)
            if self.model.acc_ceiling is not None:
                acc_rows = min(acc_rows, self.model.acc_ceiling)
            acc_bytes = acc_rows * merged.width
            survivors = min(joined_rows, acc_rows)
            # grace-style partition decomposition: when the whole-graph
            # bound is past capacity (or NDS_TPU_STREAM_PARTITIONS pins a
            # count), a graph with plain equi keys on the streamed slot
            # is proven per partition instead — the rule the executor
            # mirrors at pipeline build (engine/stream.py)
            forced = self.model.partitions
            if (acc_bytes > admit_cap
                    or (forced is not None and forced > 1)):
                keys = stream_partition_keys(part_cols, sources, keep,
                                             conjuncts)
                if keys:
                    p, _ = choose_partitions(
                        kept.rows, k, self.model.fanout,
                        max(merged.width, 1), admit_cap,
                        forced=forced, skew=self.model.skew)
                    if p > 1:
                        n_parts = p
                        part_rows = self.model.partition_bound(
                            kept.rows, k, p)
                        if self.model.acc_ceiling is not None:
                            part_rows = min(part_rows,
                                            self.model.acc_ceiling)
                        part_bytes = part_rows * merged.width
        else:
            # eager loop: survivors concatenate up to the graph bound
            acc_rows = acc_bytes = None
            survivors = joined_rows
        # mesh-sharded execution (NDS_TPU_STREAM_SHARDS): the per-shard
        # survivor bound is the share rule applied over the mesh —
        # rows/shards x skew through the fan-out — and the allocation
        # unit is the (partition, shard) composition. The eager loop
        # never shards, so unprovable scans keep shards=1.
        n_shards, srows, sbytes = 1, None, None
        if k is not None and self.model.shards > 1:
            n_shards = self.model.shards
            srows = min(acc_rows,
                        shard_row_bound(kept.rows, n_shards, 1, k,
                                        self.model.fanout, self.model.skew))
            unit = min(part_rows if part_rows is not None else acc_rows,
                       shard_row_bound(kept.rows, n_shards, n_parts, k,
                                       self.model.fanout, self.model.skew))
            if self.model.acc_ceiling is not None:
                srows = min(srows, self.model.acc_ceiling)
                unit = min(unit, self.model.acc_ceiling)
            sbytes = unit * merged.width
        sb = ScanBound(kept.alias, kept.source or "?", kept.rows, k,
                       acc_rows, acc_bytes, chunk_bytes,
                       partitions=n_parts, part_rows=part_rows,
                       part_bytes=part_bytes, shards=n_shards,
                       shard_rows=srows, shard_bytes=sbytes,
                       ring_bytes=ring_bytes)
        cost.scans.append(sb)
        # working set: two chunks in flight + the prefetch ring's live
        # set (depth more prepared chunks) + the survivor accumulator(s)
        # (partitioned: every partition's proof-sized accumulator is live
        # until the single materializing sync; eager: the concatenated
        # survivor union)
        if part_bytes is not None:
            held = n_parts * part_bytes
        elif acc_bytes is not None:
            held = acc_bytes
        else:
            held = _bucket(max(survivors, 1)) * merged.width
        cost.peak += 2 * chunk_bytes + ring_bytes + held
        merged.rows = survivors
        return merged

    # -- subqueries inside expressions --------------------------------------

    def _walk_subqueries(self, e, env: dict, cost: _MemCost) -> None:
        def walk(node):
            if isinstance(node, (A.InSubquery, A.ScalarSubquery, A.Exists,
                                 A.QuantifiedCompare)):
                self._audit_query(node.query, env, cost)
                return
            for c in _children(node):
                walk(c)

        walk(e)


# ---------------------------------------------------------------------------
# corpus driver + lint-gate findings
# ---------------------------------------------------------------------------

# pinned instantiation seed shared with plan_audit/exec_audit: bounds must
# not depend on sampled parameter values
_AUDIT_SEED = 20260803


def audit_mem_template_text(text: str, file: str,
                            auditor: MemAuditor | None = None) -> list:
    auditor = auditor or MemAuditor()
    sql = instantiate_template(text, np.random.default_rng(_AUDIT_SEED))
    stmts = [s for s in sql.split(";") if s.strip()]
    base = os.path.basename(file)
    out = []
    for i, stmt in enumerate(stmts):
        qname = base[:-4] if base.endswith(".tpl") else base
        if len(stmts) > 1:
            qname = f"{qname}_part{i + 1}"
        out.append(auditor.audit_sql(stmt, file=base, query=qname))
    return out


def audit_mem_corpus(template_dir: str | None = None, streamed=None,
                     model: MemModel | None = None) -> list:
    """MemReports for every template in templates.lst order."""
    template_dir = template_dir or TEMPLATE_DIR
    auditor = MemAuditor(streamed=streamed, model=model)
    reports: list = []
    for name in list_templates(template_dir):
        reports.extend(audit_mem_template_text(
            load_template(name, template_dir), name, auditor))
    return reports


def reports_to_findings(reports, capacity_bytes: int | None = None) -> list:
    """``hbm-capacity`` findings: a device-resident statement whose peak
    bound exceeds the configured capacity cannot be admitted at the
    audited scale, and a streamed statement whose proven accumulator
    bound exceeds it would be sized past HBM (the runtime would fall back
    to the legacy ceiling and risk the overflow rerun the proof exists to
    retire). A PARTITIONED scan is gated on its per-partition bound
    instead — the unit the executor allocates and the per-partition
    overflow flag enforces; that rule is what cleared the 7 fan-out
    accumulators from the baseline. Eager-fallback scans (unprovable
    multiplicity) are reported in ``--mem-report`` but not gated — the
    eager loop's working set is per-chunk."""
    cap = hbm_capacity_bytes() if capacity_bytes is None else capacity_bytes
    findings = []
    for r in reports:
        if r.mode == "device" and r.peak_bytes > cap:
            findings.append(Finding(
                r.file, r.query, "hbm-capacity", "error",
                f"device-resident peak bound {r.peak_bytes:,} B exceeds "
                f"the configured HBM capacity {cap:,} B "
                "(NDS_TPU_HBM_BYTES)"))
        for s in r.scans:
            if not s.provable:
                continue
            if s.shards > 1 and s.shard_bytes is not None:
                # sharded pipeline: the allocation unit is one
                # (partition, shard) accumulator — the bound the per-shard
                # overflow flags enforce
                if s.shard_bytes > cap:
                    findings.append(Finding(
                        r.file, r.query, "hbm-capacity", "error",
                        f"streamed scan {s.table!r} per-shard accumulator "
                        f"bound {s.shard_bytes:,} B ({s.shards} shards x "
                        f"{s.partitions} partitions) exceeds the "
                        f"configured HBM capacity {cap:,} B"))
                continue
            if s.partitions > 1 and s.part_bytes is not None:
                if s.part_bytes > cap:
                    findings.append(Finding(
                        r.file, r.query, "hbm-capacity", "error",
                        f"streamed scan {s.table!r} per-partition "
                        f"accumulator bound {s.part_bytes:,} B "
                        f"({s.part_rows:,} rows x {s.partitions} "
                        f"partitions) exceeds the configured HBM "
                        f"capacity {cap:,} B"))
            elif s.acc_bytes is not None and s.acc_bytes > cap:
                findings.append(Finding(
                    r.file, r.query, "hbm-capacity", "error",
                    f"streamed scan {s.table!r} accumulator bound "
                    f"{s.acc_bytes:,} B ({s.acc_rows:,} rows) exceeds the "
                    f"configured HBM capacity {cap:,} B"))
    return findings


def mem_audit_findings(template_dir: str | None = None) -> list:
    """The lint pass entry point (tools/lint.py fifth pass)."""
    return reports_to_findings(audit_mem_corpus(template_dir))


def _human(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return str(n)


def format_mem_report(reports) -> str:
    """The per-statement bound table (``tools/lint.py --mem-report``)."""
    cap = hbm_capacity_bytes()
    depth = max(prefetch_depth(), 0)
    lines = ["# mem-audit: per-statement peak-HBM byte bounds",
             f"# capacity model: {_human(cap)} (NDS_TPU_HBM_BYTES); "
             f"prefetch ring depth {depth} (NDS_TPU_PREFETCH_DEPTH) — "
             "ring live set (depth x chunk bytes) priced into every "
             "streamed peak and off the admitting capacity",
             f"{'template':<18} {'mode':<9} {'peak':>9}  accumulators"]
    worst = 0
    for r in reports:
        worst = max(worst, r.peak_bytes)
        bits = []
        for s in r.scans:
            ring = f" + ring {_human(s.ring_bytes)}" if s.ring_bytes \
                else ""
            if s.provable and s.shards > 1:
                bits.append(f"{s.table}: S={s.shards}"
                            + (f" x P={s.partitions}"
                               if s.partitions > 1 else "")
                            + f" x {_human(s.shard_bytes)}/shard "
                            f"({s.shard_rows:,} rows/shard, "
                            f"k={s.fanout_k}){ring}")
            elif s.provable and s.partitions > 1:
                bits.append(f"{s.table}: P={s.partitions} x "
                            f"{_human(s.part_bytes)}/part "
                            f"({s.part_rows:,} rows/part, "
                            f"k={s.fanout_k}){ring}")
            elif s.provable:
                bits.append(f"{s.table}: {_human(s.acc_bytes)} "
                            f"({s.acc_rows:,} rows, k={s.fanout_k})"
                            f"{ring}")
            else:
                bits.append(f"{s.table}: unprovable (eager loop){ring}")
        lines.append(f"{r.query:<18} {r.mode:<9} "
                     f"{_human(r.peak_bytes):>9}  " + "; ".join(bits))
    lines.append(f"# {len(reports)} statements — worst peak bound "
                 f"{_human(worst)} vs capacity {_human(cap)}")
    return "\n".join(lines)
