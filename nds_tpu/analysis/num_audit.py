# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Static numeric-safety auditor: value-range/precision proofs on host.

The encoded execution path does all of its hot arithmetic in deliberately
narrow integer spaces — int16/int32 frame-of-reference offsets, sorted-dict
codes, ``lit - base`` literal rebasing folded at trace time, Fraction-exact
threshold math baked into the fused scan kernel, int64 accumulators over
SF-scale row counts — and every failure mode there is *silent* wraparound,
not a crash. This module is the sixth abstract interpreter over the
planner's decomposition (sibling to plan/exec/mem/conc/perf) and proves,
host-only and per statement:

(a) **codec fit** — every column a streamed chunk scan uploads narrow
    provably fits its chosen width: the static value interval's span sits
    inside the FOR int16/int32 window exactly like
    ``io/columnar.plan_column_codec`` requires, and the model's priced
    encoded width (:func:`mem_audit.encoded_type_width`) never under-prices
    the statically provable codec width;
(b) **accumulator fit** — no SUM/COUNT/AVG accumulator can exceed its
    carrying range at the audited scale factor: the pre-aggregation row
    bound (the SAME union-find join formula ``mem_audit._audit_graph``
    enforces, via the shared helpers) times the argument's interval
    magnitude stays below int64 for the exact integer/decimal lanes
    (``ops.agg_sum`` / the ``kernels.segment_sum_exact`` limb path) and
    below the f64-exact-integer range (2^53) for the float-accumulated
    integer AVG lane (``ops._agg_avg_impl``);
(c) **hash-bit budget** — the partition/shard routing of ``hash_mix``
    consumes ``log2(P)`` low bits plus the next ``log2(S)`` bits
    (``engine/stream.py``: ``pids = h & (P-1)``,
    ``dest = (h >> log2(P)) & (S-1)``): the windows are disjoint by
    construction and the audit proves their sum never exceeds the mixed
    32-bit width at any legal (P, S) — the env readers clamp both knobs to
    the partition search ceiling (:data:`mem_audit._MAX_PARTITIONS`), so
    8 + 8 bits is the legal maximum;
(d) **scale preservation** — decimal scales survive encoded-space
    comparison and aggregate rescaling exactly: every ``× 10^Δ`` scale
    unification the engine performs in int64 (``exprs._align_decimals`` /
    ``_unify``) is proven not to overflow at the operands' static bounds,
    and decimal SUM keeps its argument scale (``dec(38, s)``) while AVG
    divides the exact int64 sum once in f64.

Interval abstraction: one ``[lo, hi]`` integer interval per column in
SCALED space (a ``decimal(p, s)`` column is the integer interval
``±(10^p - 1)`` at scale ``s`` — its device representation), seeded from
schema dtypes, the spec-fixed value domains
(:data:`mem_audit.SPEC_INT_DOMAINS` / ``ROW_BOUND_DOMAINS``) and the
table row bounds; intervals propagate through projections, set ops,
CASE/COALESCE and int64 arithmetic (each ``+``/``-``/``×`` site itself
checked against int64), while division and double columns drop to the f64
lane whose sums are tolerance-contract approximate by engine semantics
(``ops.agg_sum`` f64 path) and are not gated.

Anything unprovable is a ``num-overflow`` / ``num-precision`` finding
gated against the shrink-only baseline (``tools/lint.py`` eighth pass),
and every numeric claim ``io/columnar.py`` + ``engine/kernels.py`` make
in comments is an executable check here (:func:`kernel_claim_checks` /
:func:`codec_claim_checks` — rule ``num-claim``), not reviewer prose.

Lockstep (the standing rule): ``tools/num_audit_diff.py`` builds
adversarial boundary-value tables (FOR spans at the 2^15/2^31 edges,
4096-distinct dictionaries, max-scale decimals, hot hash keys), drives
the A/B sweep across base/kernel/sharded/encoded-off arms demanding
bit-for-bit equality with the plain-width reference, and requires exact
agreement between these static verdicts and the runtime overflow-flag
evidence (``StreamEvent.reason``); ``tools/bench_compare.py --audit-num``
re-checks a recorded campaign ledger's evidence the same way.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from fractions import Fraction

from nds_tpu.analysis import Finding
from nds_tpu.analysis.exec_audit import (CLASS_COMPILED, CLASS_UNKNOWN,
                                         ExecAuditor, _AUDIT_SEED,
                                         _conjuncts_of, _has_subquery)
from nds_tpu.analysis.kernel_spec import parse_days, value_cmp
from nds_tpu.analysis.mem_audit import (ROW_BOUND_DOMAINS, SPEC_INT_DOMAINS,
                                        MemAuditor, MemModel, _batch_unique_side,
                                        _bucket, _equi_sides, _table_pk,
                                        statement_needed_names,
                                        stream_partitions_env,
                                        stream_shards_env)
from nds_tpu.queries import (TEMPLATE_DIR, instantiate_template,
                             list_templates, load_template)
from nds_tpu.schema import (decimal_precision_scale, get_schemas, is_decimal,
                            is_string)
from nds_tpu.sql import ast as A
from nds_tpu.sql.parser import AGG_FUNCS, ParseError, parse

# ---------------------------------------------------------------------------
# numeric ranges (the carrying capacities every proof compares against)
# ---------------------------------------------------------------------------

I64_MAX = (1 << 63) - 1        # int64 accumulators / threshold scalars
F64_EXACT = 1 << 53            # largest range where every int is exact f64
FOR16_SPAN = 1 << 15           # plan_column_codec: int16 FOR iff span < 2^15
FOR32_SPAN = (1 << 31) - 1     # int32 FOR iff span < 2^31 - 1 (8 B logical)
HASH_BITS = 32                 # hash_mix produces a uint32
# mirror of engine/exprs._MAX_DEC_SCALE (jax-free here by design; the
# lockstep unit test pins the two constants equal)
MAX_DEC_SCALE = 10


# ---------------------------------------------------------------------------
# the interval abstraction
# ---------------------------------------------------------------------------

# the float lane marker: doubles, divisions, AVG results — engine f64
# semantics, tolerance-contract approximate, never gated for exactness
F64 = "f64"


class IVal:
    """Closed integer interval ``[lo, hi]`` in scaled space: the abstract
    value of one int-lane column/expression, where a decimal at scale
    ``s`` is represented by its scaled int64 (``value × 10^s``) exactly
    like ``engine/column.py`` lowers it. Host Python ints — the analysis
    itself can never wrap.

    ``mass`` (optional) bounds ``Σ|v|`` over ALL rows of the producing
    relation — the key that keeps re-aggregation proofs linear: a SUM
    output column carries ``mass = rows × max|arg|``, and any later
    SUM/AVG over those group sums accumulates ``≤ Σ|group sums| ≤ mass``
    (triangle inequality) instead of multiplying by the outer row bound
    again. Mass survives subsetting (filters, group-by, DISTINCT,
    outer-join null extension — nulls add zero) and concatenation
    (masses add across UNION branches / CASE arms), but NOT replication:
    resolving a column in a multi-part join scope strips it."""

    __slots__ = ("lo", "hi", "scale", "mass")

    def __init__(self, lo: int, hi: int, scale: int = 0, mass=None):
        self.lo, self.hi, self.scale = int(lo), int(hi), int(scale)
        self.mass = None if mass is None else int(mass)

    @property
    def span(self) -> int:
        return self.hi - self.lo

    @property
    def abs_max(self) -> int:
        return max(abs(self.lo), abs(self.hi))

    def union(self, other: "IVal") -> "IVal":
        """Value union with additive mass: sound for concatenation
        (UNION arms) and per-row selection (CASE/COALESCE arms) alike;
        conservative for intersect/except (true mass only shrinks)."""
        s = max(self.scale, other.scale)
        a, b = self.at_scale(s), other.at_scale(s)
        mass = a.mass + b.mass \
            if a.mass is not None and b.mass is not None else None
        return IVal(min(a.lo, b.lo), max(a.hi, b.hi), s, mass)

    def at_scale(self, s: int) -> "IVal":
        """Rescaled interval (×10^Δ, Δ ≥ 0) — caller checks int64 fit."""
        if s == self.scale:
            return self
        m = 10 ** (s - self.scale)
        return IVal(self.lo * m, self.hi * m, s,
                    None if self.mass is None else self.mass * m)

    def __repr__(self):
        return f"IVal({self.lo}, {self.hi}, s={self.scale})"


# value domains the dsdgen generator fixes but the schema types do not
# express (customer_demographics is the full cartesian product; each
# dependents counter is generated in 0..6). Interval-only knowledge —
# deliberately NOT added to mem_audit.SPEC_INT_DOMAINS, which also
# prices encoded widths; kept slack by an order of magnitude.
NUM_INT_DOMAINS = {
    "cd_dep_count": 100,
    "cd_dep_employed_count": 100,
    "cd_dep_college_count": 100,
    "c_birth_year": 10_000,          # calendar year (generator: 1924-92)
    "c_birth_month": 100,
    "c_birth_day": 100,
}

# sequential-surrogate FK columns whose value domain is the referenced
# dimension's row bound (dsdgen generates dimension surrogate keys as
# 1..N): the ROW_BOUND_DOMAINS mechanism, extended num-audit-locally for
# group keys that appear WITHOUT their dimension joined (query77 groups
# catalog sales/returns by call-center key alone)
NUM_FK_DOMAINS = {
    "cs_call_center_sk": "call_center",
    "cr_call_center_sk": "call_center",
}


def column_interval(col: str, t: str, row_bounds: dict) -> IVal | None:
    """The static seed interval of one catalog column, or None when the
    type carries no provable bound (plain int64, strings, doubles). The
    SAME static knowledge :func:`mem_audit.encoded_type_width` prices
    from — by construction the two can only drift if one changes."""
    if is_decimal(t):
        p, s = decimal_precision_scale(t)
        m = 10 ** p - 1
        return IVal(-m, m, s)
    if is_string(t) or t == "double":
        return None
    dom = SPEC_INT_DOMAINS.get(col)
    if dom is None:
        dom = NUM_INT_DOMAINS.get(col)
    if dom is None and col in ROW_BOUND_DOMAINS:
        dom = row_bounds.get(ROW_BOUND_DOMAINS[col])
    if dom is None and col in NUM_FK_DOMAINS:
        dom = row_bounds.get(NUM_FK_DOMAINS[col])
    if dom is not None:
        return IVal(0, int(dom), 0)
    if t in ("int32", "date"):
        # storage-sound: the device lowering is int32
        return IVal(-(1 << 31), (1 << 31) - 1, 0)
    return None                    # plain int64: unbounded


def codec_width_verdict(iv: IVal | None, logical_bytes: int):
    """``(code_bytes, mode)`` the FOR codec provably chooses for a column
    whose whole-table values sit inside ``iv`` — the static mirror of the
    ``plan_column_codec`` width rules (span < 2^15 ⇒ int16 codes;
    span < 2^31 - 1 on an 8-byte logical ⇒ int32) — or None when no
    narrow width is provable without data (the dict codec needs a
    distinct count only the runtime has)."""
    if iv is None:
        return None
    if iv.span < FOR16_SPAN:
        return 2, "for-int16"
    if iv.span < FOR32_SPAN and logical_bytes == 8:
        return 4, "for-int32"
    return None


# ---------------------------------------------------------------------------
# report types
# ---------------------------------------------------------------------------


@dataclass
class NumCheck:
    """One discharged (or failed) numeric-safety obligation."""

    kind: str                  # codec | rebase | agg | arith | scale | hash-bits | claim
    subject: str               # column / expression / site description
    proven: bool
    rule: str = "num-overflow"  # finding rule when unproven
    detail: str = ""

    def to_dict(self) -> dict:
        return {"kind": self.kind, "subject": self.subject,
                "proven": self.proven, "rule": self.rule,
                "detail": self.detail}


@dataclass
class NumReport:
    """All numeric-safety checks of one template statement."""

    file: str
    query: str
    classification: str
    checks: tuple = ()
    detail: str = ""

    @property
    def proven(self) -> bool:
        return all(c.proven for c in self.checks)

    @property
    def proven_safe(self) -> bool:
        """Statement is compiled-stream AND every check proved: the static
        verdict the runtime overflow-flag evidence must agree with (a
        proven-safe statement showing an overflow rerun — or an unproven
        one that the differential arms never trip — is model drift)."""
        return self.classification == CLASS_COMPILED and self.proven

    def to_dict(self) -> dict:
        return {"file": self.file, "query": self.query,
                "classification": self.classification,
                "proven": self.proven, "proven_safe": self.proven_safe,
                "checks": [c.to_dict() for c in self.checks],
                "detail": self.detail}


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


class _NRel:
    """One FROM part of the interval walk: per-alias column intervals plus
    the row bound / source / uniqueness metadata the shared join-bound
    formula needs. ``uniq`` holds frozensets of bare column names each of
    which is a unique key of the relation (base-table PK, a plain GROUP
    BY key set, DISTINCT output, or the empty frozenset for a single-row
    relation); ``mass_safe`` is set per SELECT once the join shape proves
    this part's rows are never replicated (see ``_mark_mass_safety``)."""

    __slots__ = ("cols", "rows", "source", "chunked", "single_row",
                 "uniq", "mass_safe")

    def __init__(self, alias: str, cols: dict, rows: int, source=None,
                 chunked=False, single_row=False, uniq=None):
        self.cols = {alias.lower(): dict(cols)}
        self.rows = max(int(rows), 1)
        self.source = source
        self.chunked = chunked
        self.single_row = single_row
        self.uniq = set(uniq or ())
        self.mass_safe = False

    @property
    def alias(self) -> str:
        return next(iter(self.cols))

    def colset(self) -> set:
        return {f"{a}.{c}" for a, cols in self.cols.items() for c in cols}

    def lookup(self, ref: A.ColumnRef):
        """(found, ival) — found distinguishes a known column with an
        unbounded interval (None) from an unresolved reference."""
        name = ref.name.lower()
        if ref.table:
            cols = self.cols.get(ref.table.lower())
            if cols is not None and name in cols:
                return True, cols[name]
            return False, None
        for cols in self.cols.values():
            if name in cols:
                return True, cols[name]
        return False, None


class NumAuditor:
    """Host-only value-range/precision interpreter.

    Composes :class:`ExecAuditor` (routing classification) and
    :class:`MemAuditor` (partition/shard choices per streamed scan) over
    the same decomposition — the perf_audit pattern — and walks the AST
    once more carrying interval + scale abstractions. ``streamed`` /
    ``model`` / ``base_tables`` follow the sibling auditors."""

    def __init__(self, streamed=None, model: MemModel | None = None,
                 base_tables=None, catalog: dict | None = None):
        self.model = model or MemModel()
        self.mem = MemAuditor(streamed=streamed, model=self.model,
                              base_tables=base_tables)
        self.exec = ExecAuditor(catalog=catalog, streamed=streamed,
                                base_tables=base_tables,
                                mem_model=self.model)
        self.streamed = self.mem.streamed
        self.base_tables = self.mem.base_tables
        self.ivals = {
            t: {f.name.lower(): column_interval(
                f.name.lower(), f.type, self.model.row_bounds)
                for f in fields}
            for t, fields in get_schemas(use_decimal=True).items()}
        # device f64 lanes: doubles and every column with no int seed
        # still EXIST in the scope (interval None = unbounded int lane;
        # doubles are tracked as the f64 marker)
        self.kinds = {
            t: {f.name.lower(): f.type for f in fields}
            for t, fields in get_schemas(use_decimal=True).items()}

    # -- entry point --------------------------------------------------------

    def audit_sql(self, sql: str, file: str = "<sql>",
                  query: str = "<sql>") -> NumReport:
        er = self.exec.audit_sql(sql, file=file, query=query)
        if er.classification == CLASS_UNKNOWN:
            return NumReport(file, query, er.classification,
                             detail=er.detail)
        mr = self.mem.audit_sql(sql, file=file, query=query)
        try:
            stmt = parse(sql)
        except ParseError as e:
            return NumReport(file, query, CLASS_UNKNOWN, detail=str(e))
        self._checks: list = []
        self._seen: set = set()
        self._needed = statement_needed_names(stmt)
        try:
            if isinstance(stmt, A.Query):
                self._walk_query(stmt, self._base_env())
            elif isinstance(stmt, (A.InsertInto, A.CreateTempView)):
                self._walk_query(stmt.query, self._base_env())
            # DeleteFrom: no narrow arithmetic — nothing to prove
        except RecursionError:
            return NumReport(file, query, er.classification,
                             detail="recursion limit")
        for s in mr.scans:
            self._check_hash_bits(s.table, s.partitions, s.shards)
        return NumReport(file, query, er.classification,
                         checks=tuple(self._checks))

    # -- check plumbing -----------------------------------------------------

    def _check(self, kind: str, subject: str, proven: bool,
               detail: str = "", rule: str = "num-overflow") -> None:
        key = (kind, subject, proven, detail)
        if key in self._seen:
            return
        self._seen.add(key)
        self._checks.append(NumCheck(kind, subject, proven, rule, detail))

    def _check_hash_bits(self, table: str, partitions: int,
                         shards: int) -> None:
        p_bits = max(int(partitions).bit_length() - 1, 0)
        s_bits = max(int(shards).bit_length() - 1, 0)
        ok = p_bits + s_bits <= HASH_BITS
        self._check(
            "hash-bits", f"{table} P={partitions} S={shards}", ok,
            f"route bits {p_bits}+{s_bits} "
            + ("fit" if ok else "EXCEED") + f" the mixed {HASH_BITS}-bit "
            "hash (disjoint windows: pids = h & (P-1), "
            "dest = (h >> log2 P) & (S-1))")

    # -- environment --------------------------------------------------------

    def _base_env(self) -> dict:
        env = {}
        for name, cols in self.kinds.items():
            ivs = {}
            for c, t in cols.items():
                if t == "double":
                    ivs[c] = F64
                else:
                    ivs[c] = self.ivals[name].get(c)
            rows = self.model.table_rows(name) or 1
            pk = _table_pk(name)
            uniq = {frozenset(pk)} if pk else set()
            env[name] = (ivs, rows, name in self.base_tables, name, uniq)
        return env

    # -- query / set-op walk ------------------------------------------------

    def _walk_query(self, q: A.Query, env: dict):
        env = dict(env)
        for cname, cq in q.ctes:
            cols, rows, uniq = self._walk_query(cq, env)
            env[cname.lower()] = (cols, rows, False, None, uniq)
        cols, rows, uniq = self._walk_body(q.body, env)
        if q.limit is not None:
            rows = min(rows, max(int(q.limit), 0))
        return cols, max(rows, 1), uniq

    def _walk_body(self, body, env: dict):
        if isinstance(body, A.SetOp):
            lcols, lrows, _lu = self._walk_body(body.left, env)
            rcols, rrows, _ru = self._walk_body(body.right, env)
            rows = lrows if body.op in ("intersect", "except") \
                else lrows + rrows
            # positional interval union (set-op columns align by position;
            # a length mismatch would have failed plan_audit already);
            # concatenation voids any uniqueness, masses add
            cols = {}
            rvals = list(rcols.values())
            for i, (name, liv) in enumerate(lcols.items()):
                riv = rvals[i] if i < len(rvals) else None
                if isinstance(liv, IVal) and isinstance(riv, IVal):
                    cols[name] = liv.union(riv)
                elif liv == F64 and riv == F64:
                    cols[name] = F64
                else:
                    cols[name] = None
            return cols, rows, set()
        if isinstance(body, A.Query):
            return self._walk_query(body, env)
        return self._walk_select(body, env)

    # -- SELECT -------------------------------------------------------------

    def _walk_select(self, sel: A.Select, env: dict):
        where = _conjuncts_of(sel.where)
        parts, preds, outer_mult = self._flatten_from(sel.from_, env)
        conjuncts = list(preds) + list(where)
        for c in conjuncts:
            self._walk_subqueries(c, env)
        if sel.having is not None:
            self._walk_subqueries(sel.having, env)
        for item in sel.items:
            if not isinstance(item.expr, A.Star):
                self._walk_subqueries(item.expr, env)

        if parts:
            self._mark_mass_safety(parts, conjuncts)
            rows = self._join_rows(parts, conjuncts) * outer_mult
            self._check_conjuncts(parts, conjuncts)
        else:
            rows = 1
        preagg_rows = max(rows, 1)

        # aggregate accumulator proofs at THIS select's pre-agg row bound
        agg_exprs = list(i.expr for i in sel.items
                         if not isinstance(i.expr, A.Star))
        if sel.having is not None:
            agg_exprs.append(sel.having)
        has_agg = False
        for e in agg_exprs:
            for call in self._agg_calls(e):
                has_agg = True
                self._check_agg(call, parts, preagg_rows)

        if sel.group_by is not None:
            gb = sel.group_by
            dom = 1
            for e in gb.exprs:
                d = self._domain(e, parts, rows)
                dom = min(dom * max(d, 1), max(rows, 1))
            n_sets = max(len(gb.sets), 1) if gb.kind != "plain" else 1
            rows = min(rows, max(dom, 1)) * n_sets
        elif has_agg and all(self._agg_only(i.expr) for i in sel.items
                             if not isinstance(i.expr, A.Star)):
            rows = 1

        # projection: output intervals
        cols: dict = {}
        for i, item in enumerate(sel.items):
            e = item.expr
            if isinstance(e, A.Star):
                qual = e.table and e.table.lower()
                for p in parts:
                    for a, pc in p.cols.items():
                        if qual is None or a == qual:
                            cols.update(pc)
                continue
            if item.alias:
                name = item.alias.lower()
            elif isinstance(e, A.ColumnRef):
                name = e.name.lower()
            else:
                name = f"_c{i}"
            cols[name] = self._ival(e, parts, preagg_rows)
        if sel.distinct and cols:
            d = 1
            for iv in cols.values():
                card = iv.span + 1 if isinstance(iv, IVal) else rows
                d = min(d * max(card, 1), max(rows, 1))
            rows = min(rows, max(d, 1))

        # output uniqueness: a plain GROUP BY whose keys survive the
        # projection is a unique key set; DISTINCT makes the whole row
        # unique; a keyless aggregate yields the single-row frozenset()
        uniq: set = set()
        if sel.group_by is not None and sel.group_by.kind == "plain":
            names = [e.name.lower() for e in sel.group_by.exprs
                     if isinstance(e, A.ColumnRef)]
            if len(names) == len(sel.group_by.exprs) \
                    and all(n in cols for n in names):
                uniq.add(frozenset(names))
        elif sel.group_by is None and has_agg and rows == 1:
            uniq.add(frozenset())
        if sel.distinct and cols:
            uniq.add(frozenset(cols))
        return cols, max(rows, 1), uniq

    def _agg_only(self, e) -> bool:
        """True when every value path of the item flows through an
        aggregate (keyless aggregate ⇒ single output row, mirroring
        ``mem_audit._has_aggregate_items``)."""
        if isinstance(e, A.FuncCall) and e.name.lower() in AGG_FUNCS:
            return True
        if isinstance(e, A.ColumnRef):
            return False
        kids = [c for c in vars(e).values() if isinstance(c, A.Expr)] \
            if hasattr(e, "__dataclass_fields__") else []
        return all(self._agg_only(c) for c in kids) if kids else True

    def _domain(self, e, parts, rows: int) -> int:
        """Distinct-value bound of one group key: at most the key's
        interval width AND the producing part's rows (a dimension column
        cannot take more values than the dimension has rows)."""
        if isinstance(e, A.ColumnRef):
            for p in parts:
                found, iv = p.lookup(e)
                if found:
                    if isinstance(iv, IVal):
                        return min(iv.span + 1, p.rows, max(rows, 1))
                    return p.rows
        return rows

    # -- FROM flattening ----------------------------------------------------

    def _flatten_from(self, node, env: dict, outer_mult: int = 1):
        """(parts, join conjuncts, outer multiplier). Outer joins flatten
        into the same part list with their ON conjuncts as edges plus a
        sound row multiplier: ×2 covers the null-extended extras of a
        LEFT/RIGHT join even when its batch is PK-unique (pairs + extras
        ≤ 2 × max side), ×4 covers FULL (pairs + both extras)."""
        if node is None:
            return [], [], outer_mult
        if isinstance(node, A.TableRef):
            return [self._table_rel(node, env)], [], outer_mult
        if isinstance(node, A.SubqueryRef):
            cols, rows, uniq = self._walk_query(node.query, env)
            return [_NRel(node.alias, cols, rows, single_row=rows == 1,
                          uniq=uniq)], [], outer_mult
        if isinstance(node, A.Join):
            lp, lj, outer_mult = self._flatten_from(node.left, env,
                                                    outer_mult)
            rp, rj, outer_mult = self._flatten_from(node.right, env,
                                                    outer_mult)
            conjs = _conjuncts_of(node.condition)
            if node.kind == "full":
                outer_mult *= 4
            elif node.kind in ("left", "right"):
                outer_mult *= 2
            # semi/anti never grow the left side; flattening both sides
            # with the ON edges keeps the bound sound (result ≤ joined)
            return lp + rp, lj + rj + conjs, outer_mult
        if isinstance(node, A.Query):          # parenthesized join tree
            return self._flatten_from(getattr(node.body, "from_", None),
                                      env, outer_mult)
        return [], [], outer_mult

    def _table_rel(self, node: A.TableRef, env: dict) -> _NRel:
        name = node.name.lower()
        alias = (node.alias or node.name).lower()
        ivs, rows, is_base, source, uniq = env.get(
            name, ({}, 1, False, None, set()))
        return _NRel(alias, ivs, rows, source=source if is_base else None,
                     chunked=is_base and name in self.streamed,
                     single_row=rows == 1 and not is_base, uniq=uniq)

    # -- the shared join-row bound (mem_audit._audit_graph formula) ---------

    def _join_rows(self, parts, conjuncts) -> int:
        """UNCLAMPED joined-row bound of one flattened graph: per
        component the largest member row bound, times the enforced
        ``bucket × fanout`` for every equi batch with no PK-unique side;
        components multiply. Identical arithmetic to
        ``mem_audit._audit_graph`` via the shared helpers — but without
        the accumulator clamp, because an overflow-rerun statement
        re-aggregates the SAME rows eagerly, so the accumulator ceiling
        never bounds what a SUM can see."""
        part_cols = [p.colset() for p in parts]
        sources = [p.source for p in parts]
        batches: dict = {}
        for c in conjuncts:
            if _has_subquery(c):
                continue
            e = _equi_sides(c, part_cols)
            if e is not None:
                batches.setdefault(tuple(sorted(e[:2])), []).append(e)
        parent = list(range(len(parts)))

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for (a, b) in batches:
            parent[find(a)] = find(b)
        comp_rows: dict = {}
        for i, p in enumerate(parts):
            r = find(i)
            base = 1 if p.single_row else max(p.rows, 1)
            comp_rows[r] = max(comp_rows.get(r, 1), base)
        chunked_idx = [i for i, p in enumerate(parts) if p.chunked]
        keep = max(chunked_idx, key=lambda i: parts[i].rows) \
            if chunked_idx else -1
        for (a, b), batch in batches.items():
            if not (_batch_unique_side(part_cols, sources, keep, a, b,
                                       batch)
                    or self._subq_unique_side(parts, a, b, batch)):
                r = find(a)
                comp_rows[r] = _bucket(comp_rows[r]) * self.model.fanout
        rows = 1
        for r in comp_rows.values():
            rows *= r
        return rows

    @staticmethod
    def _batch_keys(side: int, batch) -> set:
        keys = set()
        for (li, ri, lk, rk) in batch:
            k = lk if li == side else (rk if ri == side else None)
            if k is not None:
                keys.add(k)
        return keys

    def _side_unique(self, part: _NRel, keys: set) -> bool:
        """True when ``keys`` (bare column names) cover a unique key of
        the relation: a declared uniqueness set (GROUP BY keys, DISTINCT
        output, frozenset() for single-row) or the base-table PK — so a
        join on those keys matches each opposite row at most once."""
        if part.single_row:
            return True
        if any(u <= keys for u in part.uniq):
            return True
        pk = _table_pk(part.source)
        return pk is not None and set(pk) <= keys

    def _subq_unique_side(self, parts, a: int, b: int, batch) -> bool:
        """The derived-relation extension of ``_batch_unique_side``: a
        subquery part unique on its batch keys (its GROUP BY output)
        bounds the edge's multiplicity exactly like a base PK. Chunked
        parts are excluded for the same masked-PK-plan reason."""
        for side in (a, b):
            p = parts[side]
            if p.chunked or p.source:
                continue               # base tables: _batch_unique_side
            if self._side_unique(p, self._batch_keys(side, batch)):
                return True
        return False

    def _mark_mass_safety(self, parts, conjuncts) -> None:
        """Mark the parts whose rows provably appear at most once in the
        joined relation, so their columns' ``mass`` bounds survive: a
        single part trivially; in a two-part graph, a part is safe when
        the OPPOSITE side is unique on its join keys (each row matches
        ≤ 1 opposite row; the join conjunction can only filter further),
        including the no-edge cross join against a single-row relation.
        Wider graphs conservatively strip mass."""
        for p in parts:
            p.mass_safe = len(parts) == 1
        if len(parts) != 2:
            return
        part_cols = [p.colset() for p in parts]
        batch = []
        for c in conjuncts:
            if _has_subquery(c):
                continue
            e = _equi_sides(c, part_cols)
            if e is not None:
                batch.append(e)
        for i in (0, 1):
            other = parts[1 - i]
            parts[i].mass_safe = self._side_unique(
                other, self._batch_keys(1 - i, batch))

    # -- conjunct checks: codec fit, literal rebase, compare rescale --------

    def _check_conjuncts(self, parts, conjuncts) -> None:
        for p in parts:
            if p.chunked and p.source:
                self._check_codecs(p)
        for c in conjuncts:
            if _has_subquery(c):
                continue
            if isinstance(c, A.BinaryOp) and c.op in ("=", "<>", "<",
                                                      "<=", ">", ">="):
                self._check_compare(c, parts)
            elif isinstance(c, A.Between):
                self._check_between(c, parts)
            elif isinstance(c, A.InList):
                self._check_inlist(c, parts)

    def _check_codecs(self, rel: _NRel) -> None:
        table = rel.source
        kinds = self.kinds.get(table, {})
        enc = self.model.enc_widths.get(table, {}) if self.model.encoded \
            else {}
        for col, t in kinds.items():
            if self._needed is not None and col not in self._needed:
                continue
            iv = self.ivals.get(table, {}).get(col)
            logical = 4 if t in ("int32", "date") else 8
            verdict = codec_width_verdict(iv, logical)
            if verdict is None:
                continue
            width, mode = verdict
            # codes = value - lo ∈ [0, span] fit the chosen dtype by the
            # span rule itself; the obligation left is that the model's
            # priced encoded width never UNDER-prices the provable codec
            priced = enc.get(col)
            ok = priced is None or priced >= width + 1
            self._check(
                "codec", f"{table}.{col}", ok,
                f"{mode}: span {iv.span} codes fit {width} B"
                + ("" if ok else
                   f" but the model prices {priced} B — encoded width "
                   "model under-prices the provable codec"))

    def _chunk_for_col(self, ref: A.ColumnRef, parts):
        """(table, col, interval, verdict) when ``ref`` resolves to a
        streamed chunk column with a provable FOR width."""
        for p in parts:
            found, iv = p.lookup(ref)
            if not found:
                continue
            if not (p.chunked and p.source) or not isinstance(iv, IVal):
                return None
            t = self.kinds.get(p.source, {}).get(ref.name.lower())
            logical = 4 if t in ("int32", "date") else 8
            v = codec_width_verdict(iv, logical)
            return (p.source, ref.name.lower(), iv, v) if v else None
        return None

    def _lit_fraction(self, lit, scale: int):
        """Scaled-space Fraction of a literal (the exact boundary the
        kernel lowering rebases), or None for non-numeric literals."""
        if isinstance(lit, A.DateLiteral):
            d = parse_days(lit.text)
            return None if d is None else Fraction(d) * 10 ** scale
        if not isinstance(lit, A.Literal):
            return None
        v = lit.value
        if isinstance(v, bool) or v is None:
            return None
        if isinstance(v, str):
            d = parse_days(v)
            return None if d is None else Fraction(d) * 10 ** scale
        try:
            return Fraction(v) * 10 ** scale
        except (TypeError, ValueError):
            return None

    def _check_rebase(self, table: str, col: str, iv: IVal, op: str,
                      q: Fraction) -> None:
        """Prove the FOR-rebased threshold arithmetic exact: the
        value-space threshold (kernel_spec.value_cmp) and its worst-case
        rebase ``T - base`` (base ∈ [lo, hi]) must fit int64 — the scalar
        the fused kernel compares int64-widened codes against, and the
        bound under which the saturating trace-time fold
        (``exprs._encoded_compare_views``) is exact."""
        entry = value_cmp(op, q)
        if entry[0] in ("true", "false"):
            self._check("rebase", f"{table}.{col} {op} {q}", True,
                        f"degenerate: folds to {entry[0]}")
            return
        t = entry[1]
        worst = max(abs(t - iv.lo), abs(t - iv.hi), abs(t))
        ok = worst <= I64_MAX
        self._check(
            "rebase", f"{table}.{col} {op} {q}", ok,
            f"threshold {t}, rebased |T - base| ≤ {worst} "
            + ("fits int64" if ok else "OVERFLOWS int64"))

    def _check_compare(self, c: A.BinaryOp, parts) -> None:
        sides = ((c.left, c.right, c.op),
                 (c.right, c.left,
                  {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                   "=": "=", "<>": "<>"}[c.op]))
        for ref, other, op in sides:
            if not isinstance(ref, A.ColumnRef):
                continue
            got = self._chunk_for_col(ref, parts)
            if got is not None and isinstance(other,
                                              (A.Literal, A.DateLiteral)):
                table, col, iv, _v = got
                q = self._lit_fraction(other, iv.scale)
                if q is not None:
                    self._check_rebase(table, col, iv, op, q)
            break
        # decimal-scale unification of a column-column compare: the
        # smaller-scale side multiplies by 10^Δ in int64
        # (exprs._align_decimals) — prove it cannot wrap
        if isinstance(c.left, A.ColumnRef) and \
                isinstance(c.right, A.ColumnRef):
            la = self._ival(c.left, parts, 1)
            ra = self._ival(c.right, parts, 1)
            if isinstance(la, IVal) and isinstance(ra, IVal) \
                    and la.scale != ra.scale:
                s = max(la.scale, ra.scale)
                worst = max(la.at_scale(s).abs_max, ra.at_scale(s).abs_max)
                ok = worst <= I64_MAX
                self._check(
                    "scale",
                    f"{c.left.name.lower()} {c.op} {c.right.name.lower()}",
                    ok,
                    f"rescale to s={s}: |v| ≤ {worst} "
                    + ("fits int64" if ok else "OVERFLOWS int64"))

    def _check_between(self, c: A.Between, parts) -> None:
        if not isinstance(c.expr, A.ColumnRef):
            return
        got = self._chunk_for_col(c.expr, parts)
        if got is None:
            return
        table, col, iv, _v = got
        for lit, op in ((c.low, ">="), (c.high, "<=")):
            q = self._lit_fraction(lit, iv.scale)
            if q is not None:
                self._check_rebase(table, col, iv, op, q)

    def _check_inlist(self, c: A.InList, parts) -> None:
        if not isinstance(c.expr, A.ColumnRef):
            return
        got = self._chunk_for_col(c.expr, parts)
        if got is None:
            return
        table, col, iv, _v = got
        for it in c.items:
            q = self._lit_fraction(it, iv.scale)
            if q is not None:
                self._check_rebase(table, col, iv, "=", q)

    # -- aggregates ---------------------------------------------------------

    def _agg_calls(self, e):
        """Aggregate FuncCalls of one expression tree, not descending
        into subqueries (those run their own select walk)."""
        if isinstance(e, (A.InSubquery, A.ScalarSubquery, A.Exists,
                          A.QuantifiedCompare)):
            return
        if isinstance(e, A.FuncCall) and e.name.lower() in AGG_FUNCS:
            yield e
            return                     # engine rejects nested aggregates
        if hasattr(e, "__dataclass_fields__"):
            for v in vars(e).values():
                if isinstance(v, A.Expr):
                    yield from self._agg_calls(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, A.Expr):
                            yield from self._agg_calls(x)

    def _check_agg(self, call: A.FuncCall, parts, rows: int) -> None:
        name = call.name.lower()
        subject = self._edesc(call)
        if name == "count":
            ok = rows <= I64_MAX
            self._check("agg", subject, ok,
                        f"≤ {rows:,} rows in an int64 count")
            return
        if name not in ("sum", "avg"):
            return                     # min/max/stddev: no exact-integer
            #                            accumulation to prove
        arg = call.args[0] if call.args else None
        if arg is None:
            return
        iv = self._ival(arg, parts, rows)
        if iv == F64:
            # engine f64 lane (doubles, divisions): approximate by the
            # tolerance contract (ops.agg_sum f64 path) — nothing exact
            # to prove, and nothing silently wrong to gate
            return
        if not isinstance(iv, IVal):
            self._check("agg", subject, False,
                        "argument interval unprovable: accumulator range "
                        "cannot be bounded at the audited scale")
            return
        bound = rows * iv.abs_max
        if iv.mass is not None:
            # mass is a bound on Σ|v| over ALL producing rows, and it
            # only survives subset/concat paths — so it bounds the
            # accumulator directly, without the row multiplication
            bound = min(bound, iv.mass)
        if name == "sum" or iv.scale > 0:
            # exact int64 accumulation (ops._agg_sum_impl; the decimal
            # AVG divides the exact int64 sum once in f64)
            ok = bound <= I64_MAX
            self._check(
                "agg", subject, ok,
                f"{rows:,} rows × |v| ≤ {iv.abs_max:,} (s={iv.scale}) "
                + ("fits int64" if ok else "OVERFLOWS int64"))
        else:
            # integer AVG accumulates f64 terms (ops._agg_avg_impl):
            # exact only inside the f64 integer range
            ok = bound < F64_EXACT
            self._check(
                "agg", subject, ok,
                f"{rows:,} rows × |v| ≤ {iv.abs_max:,} "
                + ("within" if ok else "EXCEEDS")
                + " the f64-exact integer range (2^53)",
                rule="num-precision")

    # -- expression intervals ------------------------------------------------

    def _edesc(self, e) -> str:
        if isinstance(e, A.ColumnRef):
            return e.name.lower()
        if isinstance(e, A.Literal):
            return repr(e.value)
        if isinstance(e, A.FuncCall):
            inner = "*" if e.star else ", ".join(
                self._edesc(a) for a in e.args[:2])
            return f"{e.name.lower()}({inner})"
        if isinstance(e, A.Cast):
            return self._edesc(e.expr)
        if isinstance(e, A.BinaryOp):
            return (f"{self._edesc(e.left)} {e.op} "
                    f"{self._edesc(e.right)}")
        if isinstance(e, A.Case):
            return "case"
        return type(e).__name__.lower()

    def _ival(self, e, parts, rows: int):
        """Abstract value of one expression: IVal (int lane), F64 (float
        lane) or None (unbounded int lane). Each int64 arithmetic site is
        itself checked — the engine computes +,-,× in int64 and WRAPS."""
        if isinstance(e, A.Literal):
            v = e.value
            if isinstance(v, bool) or v is None or isinstance(v, str):
                return None
            if isinstance(v, int):
                # a zero literal has zero mass (Σ|0| = 0 over any rows):
                # keeps COALESCE(x, 0) / CASE ... ELSE 0 mass-bounded
                return IVal(v, v, 0, mass=0 if v == 0 else None)
            if isinstance(v, float):
                return F64
            # Decimal: exact scaled integer
            q = Fraction(v)
            s = 0
            while q.denominator != 1 and s < MAX_DEC_SCALE:
                q *= 10
                s += 1
            return IVal(int(q), int(q), s) if q.denominator == 1 else F64
        if isinstance(e, A.DateLiteral):
            d = parse_days(e.text)
            return None if d is None else IVal(d, d, 0)
        if isinstance(e, A.IntervalLiteral):
            return IVal(e.amount, e.amount, 0) if e.unit == "day" else None
        if isinstance(e, A.ColumnRef):
            for p in parts:
                found, iv = p.lookup(e)
                if found:
                    if isinstance(iv, IVal) and iv.mass is not None \
                            and not p.mass_safe:
                        # the join shape could replicate this part's
                        # rows — Σ|v| over the joined rows is unbounded
                        # by the source mass, so strip it
                        return IVal(iv.lo, iv.hi, iv.scale)
                    return iv
            return None
        if isinstance(e, A.UnaryOp):
            iv = self._ival(e.operand, parts, rows)
            if e.op == "-" and isinstance(iv, IVal):
                return IVal(-iv.hi, -iv.lo, iv.scale, iv.mass)
            return iv if e.op == "-" else None
        if isinstance(e, A.Cast):
            t = e.target.lower()
            iv = self._ival(e.expr, parts, rows)
            if t in ("double", "float"):
                return F64
            if is_decimal(t):
                _p, s = decimal_precision_scale(t)
                if isinstance(iv, IVal) and s >= iv.scale:
                    out = iv.at_scale(s)
                    ok = out.abs_max <= I64_MAX
                    self._check("scale", self._edesc(e), ok,
                                f"cast rescale to s={s}: |v| ≤ "
                                f"{out.abs_max:,} "
                                + ("fits int64" if ok
                                   else "OVERFLOWS int64"))
                    return out
                return None            # down-scale / unbounded: unknown
            return iv
        if isinstance(e, A.Case):
            # a null arm (explicit ELSE NULL or missing ELSE) contributes
            # no value: nulls are excluded from aggregates and compares
            arms = [r for _c, r in e.branches]
            if e.else_ is not None:
                arms.append(e.else_)
            arms = [a for a in arms
                    if not (isinstance(a, A.Literal) and a.value is None)]
            out = None
            for iv in (self._ival(a, parts, rows) for a in arms):
                if iv == F64:
                    return F64
                if not isinstance(iv, IVal):
                    return None
                out = iv if out is None else out.union(iv)
            return out
        if isinstance(e, A.FuncCall):
            return self._func_ival(e, parts, rows)
        if isinstance(e, A.BinaryOp):
            return self._arith_ival(e, parts, rows)
        if isinstance(e, A.ScalarSubquery):
            return None                # walked separately; value unknown
        return None

    def _func_ival(self, e: A.FuncCall, parts, rows: int):
        name = e.name.lower()
        if name == "count":
            return IVal(0, max(rows, 1), 0)
        if name in ("sum", "min", "max"):
            arg = self._ival(e.args[0], parts, rows) if e.args else None
            if not isinstance(arg, IVal):
                return arg
            if name in ("min", "max"):
                return arg
            # a (possibly windowed) SUM over these rows: |any partial
            # sum| ≤ Σ|v| — the argument's mass when it has one, else
            # rows × max|v|; that same quantity is the result's mass
            mass = arg.mass if arg.mass is not None \
                else rows * arg.abs_max
            return IVal(-mass if arg.lo < 0 else 0,
                        mass if arg.hi > 0 else 0, arg.scale, mass)
        if name in ("avg", "stddev", "stddev_samp", "var_samp",
                    "variance"):
            return F64
        if name == "coalesce":
            out = None
            for a in e.args:
                iv = self._ival(a, parts, rows)
                if iv == F64:
                    return F64
                if not isinstance(iv, IVal):
                    return None
                out = iv if out is None else out.union(iv)
            return out
        if name == "abs" and e.args:
            iv = self._ival(e.args[0], parts, rows)
            if isinstance(iv, IVal):
                return IVal(0, iv.abs_max, iv.scale, iv.mass)
            return iv
        return None

    def _arith_ival(self, e: A.BinaryOp, parts, rows: int):
        if e.op not in ("+", "-", "*", "/", "%"):
            return None                # comparison / boolean: not numeric
        a = self._ival(e.left, parts, rows)
        b = self._ival(e.right, parts, rows)
        if e.op == "/":
            return F64                 # engine divides on the f64 lane
        if a == F64 or b == F64:
            return F64
        if not isinstance(a, IVal) or not isinstance(b, IVal):
            return None
        subject = self._edesc(e)
        if e.op in ("+", "-"):
            s = max(a.scale, b.scale)
            ra, rb = a.at_scale(s), b.at_scale(s)
            ok = max(ra.abs_max, rb.abs_max) <= I64_MAX
            if s > max(a.scale, b.scale) or a.scale != b.scale:
                self._check("scale", subject, ok,
                            f"unify to s={s}: operands "
                            + ("fit int64" if ok else "OVERFLOW int64"))
            # triangle inequality: Σ|a ± b| ≤ Σ|a| + Σ|b|
            mass = ra.mass + rb.mass \
                if ra.mass is not None and rb.mass is not None else None
            if e.op == "+":
                out = IVal(ra.lo + rb.lo, ra.hi + rb.hi, s, mass)
            else:
                out = IVal(ra.lo - rb.hi, ra.hi - rb.lo, s, mass)
            ok2 = out.abs_max <= I64_MAX
            self._check("arith", subject, ok2,
                        f"|result| ≤ {out.abs_max:,} "
                        + ("fits int64" if ok2 else "OVERFLOWS int64"))
            return out
        if e.op == "*":
            s = a.scale + b.scale
            if s > MAX_DEC_SCALE:
                return F64             # engine falls to the float lane
            prods = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
            # Σ|a·b| ≤ max|a| × Σ|b| (and symmetrically)
            mcands = [x for x in
                      (a.abs_max * b.mass if b.mass is not None else None,
                       b.abs_max * a.mass if a.mass is not None else None)
                      if x is not None]
            out = IVal(min(prods), max(prods), s,
                       min(mcands) if mcands else None)
            ok = out.abs_max <= I64_MAX
            self._check("arith", subject, ok,
                        f"|product| ≤ {out.abs_max:,} (s={s}) "
                        + ("fits int64" if ok else "OVERFLOWS int64"))
            return out
        # %: bounded by the divisor magnitude (dividend sign)
        m = b.abs_max
        return IVal(-m, m, 0) if a.scale == b.scale == 0 else None

    # -- subqueries ---------------------------------------------------------

    def _walk_subqueries(self, e, env: dict) -> None:
        def walk(node):
            if isinstance(node, (A.InSubquery, A.ScalarSubquery, A.Exists,
                                 A.QuantifiedCompare)):
                self._walk_query(node.query, env)
                return
            if hasattr(node, "__dataclass_fields__"):
                for v in vars(node).values():
                    if isinstance(v, A.Expr):
                        walk(v)
                    elif isinstance(v, (list, tuple)):
                        for x in v:
                            if isinstance(x, A.Expr):
                                walk(x)

        walk(e)


# ---------------------------------------------------------------------------
# claim checks: every numeric comment in io/columnar.py + engine/kernels.py
# ---------------------------------------------------------------------------


def kernel_claim_checks() -> list:
    """Executable versions of ``engine/kernels.py``'s numeric-safety
    claims (host arithmetic only — no jax import). Each failed check is a
    ``num-claim`` finding: the comment would be lying."""
    import numpy as np
    checks = []

    def claim(subject, ok, detail):
        checks.append(NumCheck("claim", subject, bool(ok), "num-claim",
                               detail))

    # K1 — limb kernel: "a per-cell partial is <= 512*255 < 2^17 so the
    # f32 dot is exact" (f32 integers are exact below 2^24)
    claim("limb-partial-exact", 512 * 255 < (1 << 17) < (1 << 24),
          "per-cell limb partial 512×255 stays f32-exact")
    # K2 — "cross-tile accumulation happens in an i32 output ref (exact
    # while n*255 < 2^31 => n < 2^23 rows — the one gate)", and
    # exact_sum_supported gates at n_rows < 2^23
    claim("limb-i32-accumulator", ((1 << 23) - 1) * 255 < (1 << 31) - 1,
          "i32 limb accumulation exact under the n < 2^23 row gate")
    # K3 — two's-complement limb recombination is the identity for ANY
    # int64 (7 unsigned byte limbs + signed arithmetic-shift top limb)
    ok3 = True
    for v in (0, 1, -1, 255, 256, -256, (1 << 62) + 12345,
              -(1 << 62) - 999, (1 << 63) - 1, -(1 << 63)):
        x = np.int64(v)
        limbs = [int((x >> np.int64(8 * k)) & np.int64(255))
                 for k in range(7)]
        limbs.append(int(x >> np.int64(56)))       # signed top limb
        total = sum(l << (8 * k) for k, l in enumerate(limbs))
        ok3 = ok3 and total == v
    claim("limb-recombination", ok3,
          "sum_l limb_l << 8l reproduces every int64 bit-exactly")
    # K4 — "the f32 MXU kernel above cannot carry [exact int64]
    # (24-bit mantissa)": 2^24 + 1 is the first unrepresentable int
    claim("f32-mantissa-limit",
          int(np.float32((1 << 24) + 1)) != (1 << 24) + 1
          and int(np.float32(1 << 24)) == (1 << 24),
          "2^24 + 1 is not f32-representable; 2^24 is")
    # K5 — "counts are exactly representable in f32 below 2^24 rows"
    # (ops.agg_count's kernel gate)
    claim("count-f32-gate",
          int(np.float32((1 << 24) - 1)) == (1 << 24) - 1,
          "every count below the 2^24 row gate is f32-exact")
    # K6 — hash route-bit budget at the max LEGAL (P, S): the shared env
    # readers clamp both knobs to the partition search ceiling, so the
    # disjoint bit windows always fit the mixed 32-bit hash
    os_p = os.environ.get("NDS_TPU_STREAM_PARTITIONS")
    os_s = os.environ.get("NDS_TPU_STREAM_SHARDS")
    try:
        os.environ["NDS_TPU_STREAM_PARTITIONS"] = str(1 << 40)
        os.environ["NDS_TPU_STREAM_SHARDS"] = str(1 << 40)
        p_max = stream_partitions_env()
        s_max = stream_shards_env()
    finally:
        for k, v in (("NDS_TPU_STREAM_PARTITIONS", os_p),
                     ("NDS_TPU_STREAM_SHARDS", os_s)):
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    bits = (p_max.bit_length() - 1) + (s_max.bit_length() - 1)
    claim("hash-route-bits",
          bits <= HASH_BITS and p_max.bit_length() - 1 < HASH_BITS,
          f"clamped max P={p_max}, S={s_max}: {bits} route bits ≤ "
          f"{HASH_BITS}, pshift < {HASH_BITS}")
    return checks


def codec_claim_checks() -> list:
    """Executable versions of ``io/columnar.py``'s codec claims, driven
    through the REAL ``plan_column_codec`` on boundary-value arrays."""
    import numpy as np
    import pyarrow as pa

    from nds_tpu.io.columnar import DICT_MAX_VALUES, plan_column_codec
    checks = []

    def claim(subject, ok, detail):
        checks.append(NumCheck("claim", subject, bool(ok), "num-claim",
                               detail))

    def plan(values, t="int64", arrow_type=None):
        arr = pa.array(values, type=arrow_type or pa.int64())
        return plan_column_codec(arr, t)

    # C1 — "decimal(7,2) always fits int32 by type": scaled span
    # 2×(10^7 - 1) < 2^31 - 1; p=9 is the widest int32-provable precision
    claim("decimal-int32-by-type",
          2 * (10 ** 7 - 1) < FOR32_SPAN and 2 * (10 ** 9 - 1) < FOR32_SPAN
          and 2 * (10 ** 10 - 1) >= FOR32_SPAN,
          "p ≤ 9 scaled decimals always FOR-encode int32; p = 10 does not")
    from decimal import Decimal
    ext = Decimal(10 ** 7 - 1) / 100
    got = plan([-ext, ext], "decimal(7,2)", pa.decimal128(7, 2))
    claim("decimal-extremes-int32",
          got is not None and got[0].dtype == np.int32
          and got[2].mode == "for"
          and int(got[0][1]) + got[2].base == 10 ** 7 - 1,
          "full-range decimal(7,2) extremes FOR-encode as int32 and "
          "round-trip the scaled value bit-exactly")
    # C2 — FOR int16 edge: span 2^15 - 1 fits, span 2^15 does not
    lo = 5_000_000
    got = plan([lo, lo + FOR16_SPAN - 1])
    ok = got is not None and got[0].dtype == np.int16 \
        and int(got[0][1]) + got[2].base == lo + FOR16_SPAN - 1
    claim("for-int16-edge-fits", ok,
          "span 2^15 - 1 FOR-encodes int16 and round-trips bit-exactly")
    got = plan([lo, lo + FOR16_SPAN])
    claim("for-int16-edge-refuses",
          got is not None and got[0].dtype == np.int32,
          "span 2^15 widens to int32 (int16 refused)")
    # int32 edge on an 8-byte logical: span 2^31 - 2 fits, 2^31 - 1 spills
    got = plan([0, FOR32_SPAN - 1])
    claim("for-int32-edge-fits",
          got is not None and got[0].dtype == np.int32,
          "span 2^31 - 2 FOR-encodes int32")
    got = plan([0, FOR32_SPAN])
    claim("for-int32-edge-refuses",
          got is None or got[2].mode == "dict",
          "span 2^31 - 1 refuses FOR (narrow-width overflow guard)")
    # C3 — dict edge: 4096 distinct wide-span values encode int16 codes
    # clipped into [0, 4096); 4097 distinct refuse (overflow guard)
    vals = [v * (1 << 40) for v in range(DICT_MAX_VALUES)]
    got = plan(vals)
    ok = got is not None and got[2].mode == "dict" \
        and got[0].dtype == np.int16 \
        and int(got[0].max()) == DICT_MAX_VALUES - 1 \
        and int(got[0].min()) == 0
    claim("dict-4096-fits", ok,
          "4096 distinct values dict-encode; top code 4095 is a valid "
          "value-table index (take mode='clip' cannot read past it)")
    got = plan(vals + [(DICT_MAX_VALUES + 7) * (1 << 40)])
    claim("dict-4097-refuses", got is None,
          "4097 distinct values exceed DICT_MAX_VALUES (overflow guard)")
    # C4 — all-null / empty: trivial FOR int16 zeros (never under-priced)
    got = plan([None, None, None])
    claim("all-null-trivial-for",
          got is not None and got[2].mode == "for" and got[2].base == 0
          and got[0].dtype == np.int16 and int(got[0].max()) == 0,
          "all-null column FOR-encodes as int16 zeros")
    # C5 — order preservation: FOR and dict codes sort like their values
    got = plan([40, 10, 30, 20])
    ok = got is not None and got[2].mode == "for" \
        and list(np.argsort(got[0])) == [1, 3, 2, 0]
    vals = [-3, 5, 99, 10 ** 12]
    got2 = plan([vals[i] for i in (3, 0, 2, 1)])
    ok2 = got2 is not None and got2[2].mode == "dict" \
        and list(np.argsort(got2[0])) == [1, 3, 2, 0]
    claim("order-preserving", ok and ok2,
          "FOR and dict codes preserve value order (encoded-space "
          "compares and min/max stay exact)")
    return checks


# ---------------------------------------------------------------------------
# corpus driver + lint-gate findings
# ---------------------------------------------------------------------------


def audit_num_template_text(text: str, file: str,
                            auditor: NumAuditor | None = None) -> list:
    """Instantiate one template (pinned seed, shared with the other
    auditors) and prove each statement; returns NumReports."""
    import numpy as np
    auditor = auditor or NumAuditor()
    sql = instantiate_template(text, np.random.default_rng(_AUDIT_SEED))
    stmts = [s for s in sql.split(";") if s.strip()]
    base = os.path.basename(file)
    out = []
    for i, stmt in enumerate(stmts):
        qname = base[:-4] if base.endswith(".tpl") else base
        if len(stmts) > 1:
            qname = f"{qname}_part{i + 1}"
        out.append(auditor.audit_sql(stmt, file=base, query=qname))
    return out


def audit_num_corpus(template_dir: str | None = None,
                     streamed=None, model: MemModel | None = None) -> list:
    """NumReports for every template in templates.lst order."""
    template_dir = template_dir or TEMPLATE_DIR
    auditor = NumAuditor(streamed=streamed, model=model)
    reports: list = []
    for name in list_templates(template_dir):
        reports.extend(audit_num_template_text(
            load_template(name, template_dir), name, auditor))
    return reports


def reports_to_findings(reports) -> list:
    """Lint-gate findings: every unproven check is a ``num-overflow`` /
    ``num-precision`` finding (rule per check); proven checks are a
    report (``--num-report``), not findings."""
    findings = []
    for r in reports:
        for c in r.checks:
            if c.proven:
                continue
            findings.append(Finding(
                r.file, r.query, c.rule, "error",
                f"{c.kind} {c.subject}: {c.detail}"))
    return findings


def claim_findings() -> list:
    """``num-claim`` findings from the executable claim checks — empty
    while every numeric comment in io/columnar.py + engine/kernels.py
    tells the truth."""
    findings = []
    for c, file in ([(c, "engine/kernels.py")
                     for c in kernel_claim_checks()]
                    + [(c, "io/columnar.py")
                       for c in codec_claim_checks()]):
        if not c.proven:
            findings.append(Finding(
                file, "<claims>", c.rule, "error",
                f"{c.subject}: {c.detail}"))
    return findings


def num_audit_findings(template_dir: str | None = None) -> list:
    """The lint pass entry point (tools/lint.py eighth pass): corpus
    interval proofs plus the codec/kernel claim checks."""
    return reports_to_findings(audit_num_corpus(template_dir)) \
        + claim_findings()


def check_counts(reports) -> dict:
    """``check kind -> (proven, total)`` histogram over the corpus."""
    counts: dict = {}
    for r in reports:
        for c in r.checks:
            p, t = counts.get(c.kind, (0, 0))
            counts[c.kind] = (p + (1 if c.proven else 0), t + 1)
    return counts


def format_num_report(reports) -> str:
    """The per-template proof table (``tools/lint.py --num-report``)."""
    lines = ["# num-audit: per-statement value-range/precision proofs",
             "# checks: codec fit, literal rebase, accumulator range, "
             "arith/scale sites, hash route bits",
             f"{'template':<18} {'class':<16} {'checks':>7} "
             f"{'proven':>7}  worst unproven"]
    for r in reports:
        bad = [c for c in r.checks if not c.proven]
        worst = f"{bad[0].kind} {bad[0].subject}" if bad else "-"
        lines.append(f"{r.query:<18} {r.classification:<16} "
                     f"{len(r.checks):>7} "
                     f"{sum(1 for c in r.checks if c.proven):>7}  {worst}")
    counts = check_counts(reports)
    summary = ", ".join(f"{k}: {p}/{t}"
                        for k, (p, t) in sorted(counts.items()))
    n_safe = sum(1 for r in reports if r.proven_safe)
    lines.append(f"# {len(reports)} statements — {summary}; "
                 f"{n_safe} proven-safe compiled-stream")
    return "\n".join(lines)
