# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Parameterization auditor: literal-bindability proofs over the corpus.

NDS throughput streams are the SAME 99 templates with per-stream literal
permutations (``nds_gen_query_stream.py``), yet every permutation gets its
own recorded graph and its own XLA compile — THROUGHPUT_r05 measured
34.9 s of compile on query78 alone.  The fix (Flare's whole-plan
compilation, the Execution-Templates install-once/patch-parameters model)
rests on knowing WHICH literals can become runtime operands of the one
compiled per-chunk program without changing it.  This module is that
knowledge, as the repo's seventh abstract interpreter over the planner's
decomposition (the ninth ``tools/lint.py`` pass).

A literal occurrence is **BINDABLE** when hoisting it into a jit operand
provably leaves every compiled artifact invariant to its value:

* the recorded host-read log — a bindable literal lives in a WHERE
  conjunct owned solely by the streamed (chunked) alias, so its
  evaluation is pure traced jnp over chunk columns.  The record phase ran
  under ``ops.stream_bounds()``: any chunk-side host decision would have
  raised ``StreamSyncError``, so the log cannot embed the value;
* chunk shapes, codec selection and stream bounds — chunk encodings are
  fixed by the ``ChunkedTable`` before any predicate runs; the
  FOR-encoded compare rebases the PLAIN side in-trace with a saturating
  clamp (``engine/exprs._encoded_compare_views``), so even out-of-window
  operand values keep exact comparison semantics;
* partition/shard counts and accumulator sizing — ``_proved_plan`` is
  structural (row counts, PK edges, equi-key NAMES), never value-driven;
* residual keys — a bindable conjunct contains no subquery, so no
  ``expr_key`` of a residual replan can embed it.

Everything else is **FOLD-REQUIRED**, with a machine-readable reason:

``shape-affecting``
    LIMIT row counts and IN-list members: ``_eval_in_list`` makes a HOST
    value decision (fractional decimal members are dropped before
    ``jnp.asarray``), so the baked device array's length depends on the
    values.
``codec-threshold``
    string literals — ``exprs.literal`` builds the one-value dictionary
    ON HOST (``_str_literal_dicts``) and the sorted-dict merge folds at
    trace time.  The tag doubles as domain PROVENANCE on bindable
    numeric slots whose partner column carries a num_audit interval (the
    encoded-compare span the saturating rebase was proven over).
``partition-count-dependent``
    literals inside join ON conjuncts: equi-key structure feeds the
    grace-partition/shard routing plan.
``residual-key``
    the conjunct contains a subquery — the residual registry keys on
    ``expr_key``, which serializes the literal value.
``date-parse-at-plan``
    DATE/INTERVAL literals: parsed to host ints at plan time
    (``X.parse_date_literal``), baked into the trace.
``replayed-host-read``
    numeric comparand in a conjunct NOT owned solely by the streamed
    alias: dimension-side evaluation may fold into recorded host reads
    (dense key maps, key ranges), which the cached program replays.
``non-comparand``
    a literal that is not one whole side of a compare/BETWEEN reachable
    through AND/OR/NOT only (arithmetic operands, CASE results,
    function arguments — ``Planner._const_int`` reads those on host).
``non-streamed-statement``
    the enclosing statement (or this scan) does not execute through the
    compiled chunk pipeline — there is no cached program to bind into.

The runtime half lands in lockstep in ``engine/stream.py``: for
audited-bindable slots the pipeline-cache key canonicalizes each
conjunct to its template SKELETON (literal values become typed ``?p``
placeholders, see :func:`skeleton_conjunct_key`), the values ride as jit
operands appended to the replay-operand tuple, and ``NDS_TPU_PARAM_BIND=0``
is the escape hatch (bind mode is a cache-key member).  The shared
comparand walker below (:func:`conjunct_bind_slots`) is the ONE rule
both sides consult; ``tools/param_audit_diff.py`` proves the lockstep
against the real engine (one compile serving K parameter vectors
bit-for-bit, fold-required slots changing the key, ``--inject-drift``
misclassifying a slot and failing both directions).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from nds_tpu.analysis import Finding
from nds_tpu.analysis.exec_audit import (CLASS_COMPILED, DEFAULT_STREAMED,
                                         ExecAuditor, _conjuncts_of,
                                         _has_subquery)
from nds_tpu.queries import (TEMPLATE_DIR, instantiate_template,
                             list_templates, load_template)
from nds_tpu.sql import ast as A
from nds_tpu.sql.parser import ParseError, expr_key, parse

# the shared corpus-instantiation seed (exec/mem/perf/num use the same)
_AUDIT_SEED = 20260117

VERDICT_BINDABLE = "bindable"
R_SHAPE = "shape-affecting"
R_CODEC = "codec-threshold"
R_PARTITION = "partition-count-dependent"
R_RESIDUAL = "residual-key"
R_DATE = "date-parse-at-plan"
R_REPLAYED = "replayed-host-read"
R_NON_COMPARAND = "non-comparand"
R_NON_STREAMED = "non-streamed-statement"

REASONS = (R_SHAPE, R_CODEC, R_PARTITION, R_RESIDUAL, R_DATE, R_REPLAYED,
           R_NON_COMPARAND, R_NON_STREAMED)

# proven-safe int magnitude for a bound operand: the encoded-compare
# rebase subtracts a host base before the saturating clamp, so one
# sign-bit of margin keeps |lit - base| inside int64 for every codec
# base the FOR encoder can emit (num_audit's rebase proof).
SAFE_INT_ABS = 1 << 62

_COMPARE_OPS = frozenset(("=", "<>", "<", "<=", ">", ">="))


# ---------------------------------------------------------------------------
# the shared bindability rule (static auditor AND engine/stream.py)
# ---------------------------------------------------------------------------


def literal_typetag(value) -> str | None:
    """Operand type tag of a bindable literal value, or None when the
    value class can never bind (str/bool/None/date — host-folded).
    Decimal tags pin the EXACT scale: a scale change re-plans decimal
    alignment, so it must produce a different skeleton."""
    if value is None or isinstance(value, bool):
        return None
    if isinstance(value, int):
        return "i64"
    if isinstance(value, float):
        return "f64"
    if type(value).__name__ == "Decimal":
        s = max(0, -value.as_tuple().exponent)
        return f"dec:{s}"
    return None


def safe_domain(typetag: str) -> tuple:
    """Closed proven-safe value domain ``(lo, hi)`` for one type tag, in
    LITERAL units (unscaled decimals).  f64 slots admit any finite value
    (comparisons never leave f64), signalled as ``(None, None)``."""
    if typetag == "i64":
        return (-SAFE_INT_ABS, SAFE_INT_ABS)
    if typetag == "f64":
        return (None, None)
    s = int(typetag.split(":")[1])
    lim = SAFE_INT_ABS // (10 ** s)
    return (-lim, lim)


def domain_contains(typetag: str, value) -> bool:
    lo, hi = safe_domain(typetag)
    if lo is None:
        try:
            v = float(value)
        except (TypeError, ValueError):
            return False
        return v == v and v not in (float("inf"), float("-inf"))
    return lo <= value <= hi


def slot_param_value(value, typetag: str):
    """The host value a bound slot passes as its jit operand: ints stay
    ints, floats floats, decimals pre-scale to their pinned-scale int
    (exactly what ``exprs.literal`` bakes)."""
    if typetag == "i64":
        return int(value)
    if typetag == "f64":
        return float(value)
    s = int(typetag.split(":")[1])
    return int(value.scaleb(s))


def _comparand_literals(conj, drift: bool = False):
    """Yield ``(path, literal_node, partner_expr)`` for every literal in a
    direct-comparand position of one WHERE conjunct: one whole side of a
    compare BinaryOp or a BETWEEN bound, reachable from the conjunct root
    through AND/OR/NOT only.  ``path`` is the dataclass-field DFS path —
    slot identity inside the skeleton (two statements sharing a skeleton
    share conjunct tree shape, so the path addresses the same node in
    both).  ``drift=True`` is the deliberate misclassification for the
    differential self-test: IN-list members are yielded as if they were
    comparands (they are ``shape-affecting``: ``_eval_in_list`` bakes
    them into a host-built device array)."""
    out = []

    def walk(e, path):
        if isinstance(e, A.BinaryOp) and e.op in ("and", "or"):
            walk(e.left, path + ("left",))
            walk(e.right, path + ("right",))
            return
        if isinstance(e, A.UnaryOp) and e.op == "not":
            walk(e.operand, path + ("operand",))
            return
        if isinstance(e, A.BinaryOp) and e.op in _COMPARE_OPS:
            if isinstance(e.left, A.Literal):
                out.append((path + ("left",), e.left, e.right))
            if isinstance(e.right, A.Literal):
                out.append((path + ("right",), e.right, e.left))
            return
        if isinstance(e, A.Between):
            if isinstance(e.low, A.Literal):
                out.append((path + ("low",), e.low, e.expr))
            if isinstance(e.high, A.Literal):
                out.append((path + ("high",), e.high, e.expr))
            return
        if drift and isinstance(e, A.InList):
            for i, item in enumerate(e.items):
                if isinstance(item, A.Literal):
                    out.append((path + (("items", i),), item, e.expr))

    walk(conj, ())
    return out


def conjunct_bind_slots(conj, owned: bool, has_subquery: bool,
                        drift: bool = False) -> list:
    """THE shared bindability rule over one WHERE conjunct: the list of
    ``(path, literal_node, typetag)`` slots that are safe to hoist into
    jit operands.  ``owned`` — the caller's verdict that the conjunct
    references ONLY the streamed alias (static: catalog resolution;
    runtime: the planner's ``_expr_tables`` ownership, the same test
    ``_build_pipeline`` pushes conjuncts down by).  Non-owned or
    subquery-bearing conjuncts bind nothing; neither do string / date /
    bool / None literals or non-comparand positions."""
    if has_subquery or not owned:
        return []
    slots = []
    for path, lit, _partner in _comparand_literals(conj, drift=drift):
        tag = literal_typetag(lit.value)
        if tag is None:
            continue
        if not domain_contains(tag, lit.value):
            continue                     # outside the proven safe domain
        slots.append((path, lit, tag))
    return slots


def skeleton_conjunct_key(conj, slots) -> str:
    """``expr_key`` of the conjunct with every bindable slot's VALUE
    replaced by a typed placeholder — the canonical template-skeleton key
    member.  The AST nodes are plain mutable dataclasses, so the swap is
    a temporary in-place edit restored under ``finally``.  Placeholders
    are impossible literal collisions: a ``?p:<tag>`` STRING literal in a
    real statement would sit in a slot-free conjunct, and the slot
    signature tuple rides the cache key next to these strings."""
    saved = [(lit, lit.value) for (_p, lit, _t) in slots]
    try:
        for (_p, lit, tag) in slots:
            lit.value = f"?p:{tag}"
        return expr_key(conj)
    finally:
        for lit, v in saved:
            lit.value = v


def drift_active() -> bool:
    """NDS_TPU_PARAM_DRIFT=1: the deliberate shared-rule misclassification
    (IN-list members treated as bindable comparands) both halves consume,
    so ``tools/param_audit_diff.py --inject-drift`` proves the harness
    would catch a real drift.  Never set outside the self-tests."""
    return os.environ.get("NDS_TPU_PARAM_DRIFT") == "1"


# ---------------------------------------------------------------------------
# static corpus auditor
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ParamSlot:
    """One audited-bindable parameter slot of a statement."""

    conjunct: int           # index into the block's WHERE conjunct list
    path: tuple             # dataclass-field DFS path to the Literal
    typetag: str            # "i64" | "f64" | "dec:<scale>"
    column: str             # partner expression key (provenance)
    domain: tuple           # proven safe (lo, hi); (None, None) = finite f64
    provenance: str = ""    # "codec-threshold" when the partner column
    #                         carries a num_audit interval (FOR-encodable)
    value: object = None    # the audit-seed instantiation's literal value

    def to_dict(self) -> dict:
        return {"conjunct": self.conjunct, "path": list(self.path),
                "typetag": self.typetag, "column": self.column,
                "domain": [None if d is None else int(d)
                           for d in self.domain],
                "provenance": self.provenance,
                "value": repr(self.value)}


@dataclass
class ParamReport:
    """Bindability classification of one template statement: the
    parameter signature (bindable slots + proven safe value domains) and
    the fold-required census by reason."""

    file: str
    query: str
    classification: str
    n_literals: int = 0
    slots: tuple = ()                    # ParamSlots
    folds: dict = field(default_factory=dict)   # reason -> count

    @property
    def n_bindable(self) -> int:
        return len(self.slots)

    def signature(self) -> str:
        """The per-template parameter signature: ordered bindable slots
        with their type tags (the plan-bank key shape)."""
        return ", ".join(f"{s.column}:{s.typetag}" for s in self.slots)

    def to_dict(self) -> dict:
        return {"file": self.file, "query": self.query,
                "classification": self.classification,
                "n_literals": self.n_literals,
                "slots": [s.to_dict() for s in self.slots],
                "folds": dict(sorted(self.folds.items())),
                "signature": self.signature()}


class _Census:
    """Accumulator for one statement's walk."""

    def __init__(self):
        self.slots: list = []
        self.folds: dict = {}
        self.n = 0

    def fold(self, reason: str, k: int = 1) -> None:
        if k:
            self.n += k
            self.folds[reason] = self.folds.get(reason, 0) + k


def _iter_literals(e):
    """Every Literal/DateLiteral/IntervalLiteral node under ``e``,
    WITHOUT descending into subqueries (their blocks are walked as
    statements of their own)."""
    if isinstance(e, (A.Literal, A.DateLiteral, A.IntervalLiteral)):
        yield e
        return
    if isinstance(e, (A.InSubquery, A.Exists, A.ScalarSubquery,
                      A.QuantifiedCompare)):
        for f in ("expr",):
            sub = getattr(e, f, None)
            if sub is not None:
                yield from _iter_literals(sub)
        return
    if not hasattr(e, "__dataclass_fields__"):
        return
    for f in e.__dataclass_fields__:
        v = getattr(e, f)
        if isinstance(v, (list, tuple)):
            for item in v:
                if hasattr(item, "__dataclass_fields__"):
                    yield from _iter_literals(item)
        elif hasattr(v, "__dataclass_fields__"):
            yield from _iter_literals(v)


def _classify_literal(lit, in_list: bool) -> str:
    """Fold reason of one non-bindable literal inside an owned streamed
    conjunct (shared precedence with the runtime's skip rules)."""
    if isinstance(lit, (A.DateLiteral, A.IntervalLiteral)):
        return R_DATE
    if in_list:
        return R_SHAPE
    if isinstance(lit.value, str):
        return R_CODEC
    return R_NON_COMPARAND


def _in_list_literals(conj) -> set:
    ids = set()

    def walk(e):
        if isinstance(e, A.InList):
            for item in e.items:
                if isinstance(item, A.Literal):
                    ids.add(id(item))
        if isinstance(e, (A.InSubquery, A.Exists, A.ScalarSubquery,
                          A.QuantifiedCompare)):
            return
        if hasattr(e, "__dataclass_fields__"):
            for f in e.__dataclass_fields__:
                v = getattr(e, f)
                if isinstance(v, (list, tuple)):
                    for it in v:
                        if hasattr(it, "__dataclass_fields__"):
                            walk(it)
                elif hasattr(v, "__dataclass_fields__"):
                    walk(v)

    walk(conj)
    return ids


class ParamAuditor:
    """Host-only bindability interpreter over the planner decomposition.

    Composes :class:`ExecAuditor` for statement classification (a slot
    can only bind into a COMPILED chunk pipeline) and mirrors the
    planner's ownership resolution (``_expr_tables``) over the catalog —
    the same single-ownership test ``_build_pipeline`` pushes conjuncts
    down by.  ``drift=True`` routes the shared rule's deliberate
    misclassification (the differential self-test)."""

    def __init__(self, catalog: dict | None = None, streamed=None,
                 base_tables=None, drift: bool = False):
        self._exec = ExecAuditor(catalog=catalog, streamed=streamed,
                                 base_tables=base_tables)
        self.catalog = self._exec.catalog
        self.streamed = set(DEFAULT_STREAMED if streamed is None
                            else streamed)
        self.drift = drift

    # -- entry point --------------------------------------------------------

    def audit_sql(self, sql: str, file: str = "<sql>",
                  query: str = "<sql>") -> ParamReport:
        rep = self._exec.audit_sql(sql, file, query)
        census = _Census()
        try:
            stmt = parse(sql)
        except ParseError:
            return ParamReport(file, query, rep.classification)
        compiled = {s.alias for s in rep.scans if s.compiled}
        q = stmt.query if isinstance(stmt, (A.InsertInto,
                                            A.CreateTempView)) else stmt
        if isinstance(q, A.Query):
            try:
                self._walk_query(q, set(), compiled, census)
            except RecursionError:
                pass
        return ParamReport(file, query, rep.classification,
                           n_literals=census.n,
                           slots=tuple(census.slots),
                           folds=census.folds)

    # -- statement walk -----------------------------------------------------

    def _walk_query(self, q: A.Query, cte_names: set, compiled: set,
                    census: _Census) -> None:
        cte_names = set(cte_names)
        for cname, cq in q.ctes:
            self._walk_query(cq, cte_names, compiled, census)
            cte_names.add(cname.lower())
        self._walk_body(q.body, cte_names, compiled, census)
        if q.limit is not None:
            census.fold(R_SHAPE)         # LIMIT sizes the output shaping
        for e, _d, _nl in q.order_by:
            census.fold(R_NON_COMPARAND, _count_literals(e))

    def _walk_body(self, body, cte_names, compiled, census) -> None:
        if isinstance(body, A.SetOp):
            self._walk_body(body.left, cte_names, compiled, census)
            self._walk_body(body.right, cte_names, compiled, census)
            return
        if isinstance(body, A.Query):
            self._walk_query(body, cte_names, compiled, census)
            return
        if isinstance(body, A.Select):
            self._walk_select(body, cte_names, compiled, census)

    def _flatten_rels(self, node, cte_names, compiled, census,
                      rels: list) -> None:
        """FROM flattening for ownership: ``rels`` collects
        ``(alias, qualified-col set | None, streamed-compiled)``.  ON
        conjunct literals census as partition-count-dependent (equi-key
        structure routes the grace partition/shard plan)."""
        if node is None:
            return
        if isinstance(node, A.TableRef):
            name = node.name.lower()
            alias = (node.alias or node.name).lower()
            if name in cte_names or name not in self.catalog:
                rels.append((alias, None, False))
                return
            cols = {f"{alias}.{c}" for c in self.catalog[name]}
            rels.append((alias, cols,
                         name in self.streamed and alias in compiled))
            return
        if isinstance(node, A.SubqueryRef):
            self._walk_query(node.query, cte_names, compiled, census)
            rels.append((node.alias.lower(), None, False))
            return
        if isinstance(node, A.Join):
            self._flatten_rels(node.left, cte_names, compiled, census,
                               rels)
            self._flatten_rels(node.right, cte_names, compiled, census,
                               rels)
            for c in _conjuncts_of(node.condition):
                if _has_subquery(c):
                    census.fold(R_RESIDUAL, _count_literals(c))
                else:
                    census.fold(R_PARTITION, _count_literals(c))
            return
        if isinstance(node, A.Query):    # parenthesized join tree
            self._flatten_rels(getattr(node.body, "from_", None),
                               cte_names, compiled, census, rels)

    def _ref_owners(self, ref: A.ColumnRef, rels) -> set:
        """Aliases that can answer for ``ref`` — the static mirror of the
        planner's ``_resolve_name`` suffix match.  Unknown-column rels
        (CTEs, subqueries) own every unqualified name conservatively."""
        name = ref.name.lower()
        if ref.table:
            t = ref.table.lower()
            return {a for (a, _cols, _s) in rels if a == t}
        owners = set()
        for (a, cols, _s) in rels:
            if cols is None or any(c.split(".")[-1] == name for c in cols):
                owners.add(a)
        return owners

    def _conjunct_refs(self, e, out: list) -> None:
        if isinstance(e, A.ColumnRef):
            out.append(e)
        if isinstance(e, (A.InSubquery, A.Exists, A.ScalarSubquery,
                          A.QuantifiedCompare)):
            return
        if hasattr(e, "__dataclass_fields__"):
            for f in e.__dataclass_fields__:
                v = getattr(e, f)
                if isinstance(v, (list, tuple)):
                    for it in v:
                        if hasattr(it, "__dataclass_fields__"):
                            self._conjunct_refs(it, out)
                elif hasattr(v, "__dataclass_fields__"):
                    self._conjunct_refs(v, out)

    def _walk_select(self, sel: A.Select, cte_names, compiled,
                     census) -> None:
        rels: list = []
        self._flatten_rels(sel.from_, cte_names, compiled, census, rels)
        streamed_aliases = {a for (a, _c, s) in rels if s}
        for ci, conj in enumerate(_conjuncts_of(sel.where)):
            self._walk_conjunct(ci, conj, rels, streamed_aliases,
                                cte_names, compiled, census)
        # non-conjunct positions: projections, grouping, HAVING — their
        # subquery blocks still walk (the q9 scalar-subquery shape)
        for item in sel.items:
            self._census_other(item.expr, cte_names, compiled, census)
        if sel.group_by is not None:
            for e in sel.group_by.exprs:
                self._census_other(e, cte_names, compiled, census)
        if sel.having is not None:
            self._census_other(sel.having, cte_names, compiled, census)

    def _census_other(self, e, cte_names, compiled, census) -> None:
        census.fold(R_NON_COMPARAND, _count_literals(e))
        for sub in _subqueries_of(e):
            self._walk_query(sub, cte_names, compiled, census)

    def _walk_conjunct(self, ci, conj, rels, streamed_aliases,
                       cte_names, compiled, census) -> None:
        lits = list(_iter_literals(conj))
        if _has_subquery(conj):
            census.fold(R_RESIDUAL, len(lits))
            for sub in _subqueries_of(conj):
                self._walk_query(sub, cte_names, compiled, census)
            return
        if not streamed_aliases:
            census.fold(R_NON_STREAMED, len(lits))
            return
        refs: list = []
        self._conjunct_refs(conj, refs)
        owners = set()
        for r in refs:
            owners |= self._ref_owners(r, rels)
        owned = bool(refs) and owners and owners <= streamed_aliases
        if not owned:
            for lit in lits:
                if isinstance(lit, (A.DateLiteral, A.IntervalLiteral)):
                    census.fold(R_DATE)
                elif isinstance(lit.value, str):
                    census.fold(R_CODEC)
                else:
                    census.fold(R_REPLAYED)
            return
        slots = conjunct_bind_slots(conj, owned=True, has_subquery=False,
                                    drift=self.drift)
        bound_ids = {id(lit) for (_p, lit, _t) in slots}
        in_list = _in_list_literals(conj)
        keep_alias = next(iter(streamed_aliases))
        for path, lit, tag in slots:
            census.n += 1
            partner = self._slot_partner(conj, path)
            prov = R_CODEC if self._for_encodable(partner, rels) else ""
            census.slots.append(ParamSlot(
                conjunct=ci, path=path, typetag=tag,
                column=partner or keep_alias, domain=safe_domain(tag),
                provenance=prov, value=lit.value))
        for lit in lits:
            if id(lit) in bound_ids:
                continue
            census.fold(_classify_literal(lit, id(lit) in in_list))

    def _slot_partner(self, conj, path) -> str:
        """Readable partner-column key of one slot (provenance only)."""
        for p, _lit, partner in _comparand_literals(conj, drift=True):
            if p == path:
                if isinstance(partner, A.ColumnRef):
                    return (f"{partner.table.lower()}.{partner.name.lower()}"
                            if partner.table else partner.name.lower())
                try:
                    return expr_key(partner)
                except Exception:
                    return "<expr>"
        return "<expr>"

    def _for_encodable(self, partner: str, rels) -> bool:
        """True when the slot's partner column carries a known num_audit
        interval — the FOR-encodable case whose in-trace rebase the
        saturating-clamp proof covers (domain provenance tag)."""
        if not partner or "." not in partner:
            return False
        bare = partner.split(".")[-1]
        try:
            from nds_tpu.analysis.mem_audit import SPEC_INT_DOMAINS
            from nds_tpu.analysis.num_audit import (NUM_FK_DOMAINS,
                                                    NUM_INT_DOMAINS)
            return (bare in SPEC_INT_DOMAINS or bare in NUM_INT_DOMAINS
                    or bare in NUM_FK_DOMAINS or bare.endswith("_sk"))
        except Exception:
            return False


def _count_literals(e) -> int:
    return sum(1 for _ in _iter_literals(e))


def _subqueries_of(e) -> list:
    out = []

    def walk(node):
        if isinstance(node, (A.InSubquery, A.Exists, A.ScalarSubquery,
                             A.QuantifiedCompare)):
            out.append(node.query)
            return
        if not hasattr(node, "__dataclass_fields__"):
            return
        for f in node.__dataclass_fields__:
            v = getattr(node, f)
            if isinstance(v, (list, tuple)):
                for it in v:
                    if hasattr(it, "__dataclass_fields__"):
                        walk(it)
            elif hasattr(v, "__dataclass_fields__"):
                walk(v)

    walk(e)
    return out


# ---------------------------------------------------------------------------
# corpus drivers (tools/lint.py ninth pass)
# ---------------------------------------------------------------------------


def audit_param_template_text(text: str, file: str,
                              auditor: ParamAuditor | None = None) -> list:
    """Instantiate one template (pinned seed, shared with the other
    auditors) and classify each statement; returns ParamReports."""
    import numpy as np
    auditor = auditor or ParamAuditor()
    sql = instantiate_template(text, np.random.default_rng(_AUDIT_SEED))
    stmts = [s for s in sql.split(";") if s.strip()]
    base = os.path.basename(file)
    out = []
    for i, stmt in enumerate(stmts):
        qname = base[:-4] if base.endswith(".tpl") else base
        if len(stmts) > 1:
            qname = f"{qname}_part{i + 1}"
        out.append(auditor.audit_sql(stmt, file=base, query=qname))
    return out


def audit_param_corpus(template_dir: str | None = None,
                       streamed=None, drift: bool = False) -> list:
    """ParamReports for every template in templates.lst order."""
    template_dir = template_dir or TEMPLATE_DIR
    auditor = ParamAuditor(streamed=streamed, drift=drift)
    reports: list = []
    for name in list_templates(template_dir):
        reports.extend(audit_param_template_text(
            load_template(name, template_dir), name, auditor))
    return reports


def reports_to_findings(reports) -> list:
    """Lint-gate findings.  The signatures themselves are a report
    (``--param-report``), not findings; the gate catches the two ways
    the bindability model can contradict itself:

    * ``param-unproven-bind`` — a bindable slot on a statement that is
      not classified compiled-stream: there is no cached per-chunk
      program its operand could patch, so the proof is vacuous (model
      drift between the param and exec decompositions);
    * ``param-domain-escape`` — the audit-seed instantiation's own
      literal value sits outside the slot's proven safe domain: the
      domain arithmetic stopped covering the corpus the other passes
      audit.
    """
    findings = []
    for r in reports:
        for s in r.slots:
            if r.classification != CLASS_COMPILED:
                findings.append(Finding(
                    r.file, r.query, "param-unproven-bind", "error",
                    f"bindable slot {s.column}:{s.typetag} on a "
                    f"{r.classification} statement: no compiled chunk "
                    "pipeline exists to bind its operand into"))
            if s.value is not None and \
                    not domain_contains(s.typetag, s.value):
                findings.append(Finding(
                    r.file, r.query, "param-domain-escape", "error",
                    f"slot {s.column}:{s.typetag} instantiated at "
                    f"{s.value!r}, outside its proven safe domain "
                    f"{s.domain}"))
    return findings


def param_audit_findings(template_dir: str | None = None) -> list:
    """The lint pass entry point (tools/lint.py ninth pass)."""
    return reports_to_findings(audit_param_corpus(template_dir))


def bindability_counts(reports) -> dict:
    """``verdict -> literal-occurrence count`` over the corpus (the
    pinned bindability story), plus the bindable-statement count."""
    counts = {VERDICT_BINDABLE: 0}
    statements = 0
    for r in reports:
        counts[VERDICT_BINDABLE] += r.n_bindable
        if r.n_bindable:
            statements += 1
        for reason, k in r.folds.items():
            counts[reason] = counts.get(reason, 0) + k
    counts["statements-with-bindable"] = statements
    return counts


def format_param_report(reports) -> str:
    """The per-template signature table (``tools/lint.py
    --param-report``): literal census, bindable slot count, fold
    reasons, and the parameter signature a plan bank would key on."""
    lines = ["# param-audit: literal bindability / parameter signatures",
             f"{'template':<18} {'class':<16} {'lits':>5} {'bind':>5}  "
             "signature"]
    for r in reports:
        sig = r.signature()
        if len(sig) > 48:
            sig = sig[:45] + "..."
        lines.append(f"{r.query:<18} {r.classification:<16} "
                     f"{r.n_literals:>5} {r.n_bindable:>5}  {sig}")
    counts = bindability_counts(reports)
    summary = ", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
    lines.append(f"# {len(reports)} statements — {summary}")
    return "\n".join(lines)
