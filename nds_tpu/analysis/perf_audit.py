# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Static cost auditor: price every statement's data movement before it runs.

The mechanism era closed with four abstract interpreters proving syncs
(``exec_audit``), memory (``mem_audit``), plans (``plan_audit``) and
concurrency (``conc_audit``) — but none of them prices *data movement*,
so a measured campaign number can only be compared with other measured
numbers. This module is the fifth interpreter: composing the exec and
mem walks (one decomposition, zero new AST logic), it derives for every
statement

1. **Predicted h2d bytes** — what the streamed scan pipeline uploads.
   The compiled chunk path pads every chunk to ONE physical capacity
   and always carries a validity byte per column
   (``engine/table.py padded_chunks``), so the upload is a closed form::

       bytes_h2d = n_chunks x chunk_cap x sum(width_data + 1)

   over the pruned columns at their WIRE widths (encoded codes when the
   ``io/columnar.py`` codec plan narrows them). The formula is EXACT by
   construction whenever the model knows the real rows and wire widths
   (``tools/perf_audit_diff.py`` feeds both from the live toy session
   and requires equality with ``StreamEvent.bytes_h2d``); against the
   SF10 catalog widths it is an upper bound (the runtime may encode
   narrower than the static proof). Warm runs re-upload every chunk —
   the chunk store caches the ENCODING, not the device buffers — so the
   prediction is sight-invariant, and the prefetch ring moves the same
   bytes earlier, never different bytes. The eager chunk loop instead
   uploads unencoded bucket-padded chunks with validity only on
   null-bearing columns: priced as a [min, max] band, never exact.

2. **Per-stage HBM traffic** — the roofline denominator of the chunk
   program, stage by stage (scan / filter / partition / probe /
   exchange / accumulate). Each per-chunk dispatch re-reads the chunk
   (one mask+compact pass, one hash pass when partitioned, one read per
   extra partition dispatch); the fused-kernel arm collapses the filter
   and partition re-reads into the single VMEM scan pass (the PR 12
   stage model), which is why the arm exists. This is a *model* (XLA
   fusion may do better) — it feeds the roofline wall, not an equality
   check.

3. **Predicted ICI bytes** — exact from the collective budget's shapes
   (``parallel/exchange.py`` accounts trace-time aval bytes; this
   module reproduces the same arithmetic): the per-chunk hash-exchange
   moves ``S x cap_ex x (sum(width_data + 1) + 5)`` bytes (data +
   validity per column, the partition-id plane, the validity plane) and
   the one cross-shard reduce moves ``20 x P`` (count all-gather +
   overflow/histogram psums). Outer-build bitmap psums ride on top —
   priced zero (a lower bound) and flagged inexact.

4. **A roofline lower-bound wall** — ``max`` of the three byte totals
   over their link rates (``NDS_TPU_ROOFLINE_H2D_GBS`` /
   ``_HBM_GBS`` / ``_ICI_GBS``), with a ranked static bottleneck tag:
   ``h2d-bound`` / ``hbm-bound`` / ``ici-bound`` for the slowest wall,
   ``sync-bound`` when exec_audit reports no finite sync bound (the
   eager loop's O(chunks) host reads dominate any byte wall). The wall
   is a LOWER bound on the statement's wall time by construction:
   measured minus wall = named overhead, the number
   ``tools/trace_report.py`` surfaces as ``unexplained ms``.

Lockstep (the standing rule): every prediction that maps to runtime
evidence is differentially checked. ``tools/perf_audit_diff.py``
replays the ``tests/test_synccount.py`` A/B sweep — base, forced-
partition, 2-shard, fused-kernel and encoded-off arms — and fails when
measured ``StreamEvent.bytes_h2d`` / ``bytes_ici`` /
``kernel_launches`` disagree with the static prediction (equality for
exact predictions, band membership for bounds); ``--inject-drift``
must fail. ``tools/bench_compare.py --audit-perf`` re-checks a
campaign ledger's recorded evidence against the same predictions, so
every Power Run lands pre-wired to its static denominator.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field

from nds_tpu.analysis import Finding
from nds_tpu.analysis.exec_audit import (CLASS_COMPILED, CLASS_DEVICE,
                                         CLASS_EAGER, CLASS_UNKNOWN,
                                         ExecAuditor, ExecReport,
                                         ScanVerdict, _AUDIT_SEED)
from nds_tpu.analysis.mem_audit import (MemAuditor, MemModel, ScanBound,
                                        _bucket)
from nds_tpu.queries import (TEMPLATE_DIR, instantiate_template,
                             list_templates, load_template)

# ---------------------------------------------------------------------------
# roofline link rates
# ---------------------------------------------------------------------------

# Default sustained link rates (GB/s) the roofline walls divide by.
# HBM and ICI share their defaults with tools/trace_report.py's measured
# roofline columns (v5e-class: 819 GB/s HBM, 186 GB/s combined ICI);
# H2D is new here — a PCIe-class host link (the streamed upload path).
# All three are env knobs so a different part's numbers drop in without
# code changes, and the static and measured rooflines stay comparable
# because they read the SAME knobs.
DEFAULT_ROOFLINE_GBS = {"h2d": 32.0, "hbm": 819.0, "ici": 186.0}


def roofline_gbs() -> dict:
    """``{"h2d","hbm","ici"} -> GB/s`` from ``NDS_TPU_ROOFLINE_*_GBS``
    (read at call time; :class:`PerfAuditor` freezes a copy at
    construction, the same build-time env discipline every model
    follows)."""
    out = {}
    for k, dflt in DEFAULT_ROOFLINE_GBS.items():
        try:
            out[k] = float(os.environ.get(f"NDS_TPU_ROOFLINE_{k.upper()}_GBS",
                                          str(dflt)))
        except ValueError:
            out[k] = dflt
    return out


# the four static bottleneck tags (the corpus histogram is pinned in
# tier-1 by tests/test_analysis.py, like exec_audit's classification pin)
BOUND_H2D = "h2d-bound"
BOUND_HBM = "hbm-bound"
BOUND_ICI = "ici-bound"
BOUND_SYNC = "sync-bound"

# HBM stage names, pipeline order (DESIGN.md "Static cost model")
STAGES = ("scan", "filter", "partition", "probe", "exchange", "accumulate")


# ---------------------------------------------------------------------------
# report types
# ---------------------------------------------------------------------------


@dataclass
class ScanCost:
    """The priced data movement of one >HBM streamed scan."""

    alias: str
    table: str
    compiled: bool             # chunk pipeline (True) or eager loop
    rows: int                  # streamed row bound the chunks slice
    chunks: int                # ceil(rows / chunk_rows)
    chunk_cap: int             # uniform padded capacity per chunk
    n_cols: int = 0            # pruned column count on the wire
    width: int = 0             # wire bytes/row incl. validity (pruned)
    priced: bool = True        # False = unknown table, default width
    bytes_h2d: int = 0         # upload prediction (compiled: exact form)
    bytes_h2d_min: int = 0     # lower edge (eager band; == bytes_h2d
    #                            when the prediction is a point)
    h2d_exact: bool = False    # True only when rows AND wire widths are
    #                            the real ones (harness-supplied)
    partitions: int = 1        # grace partition count (mem model choice)
    shards: int = 1            # mesh shard count
    exchange: bool = False     # per-chunk hash-exchange pass active
    cap_ex: int = 0            # exchange bucket capacity per (shard,dest)
    bytes_ici: int = 0         # collective wire bytes (exchange + reduce)
    ici_exact: bool = False    # False when outer-build bitmaps ride the
    #                            reduce (priced 0: lower bound) or widths
    #                            are the static stand-ins
    kernel_min: int = 0        # fused-kernel launch band the measured
    kernel_max: int = 0        # StreamEvent.kernel_launches must fit
    stages: dict = field(default_factory=dict)  # stage -> HBM bytes

    @property
    def bytes_hbm(self) -> int:
        return sum(self.stages.values())

    def to_dict(self) -> dict:
        return {
            "alias": self.alias, "table": self.table,
            "compiled": self.compiled, "rows": int(self.rows),
            "chunks": int(self.chunks), "chunk_cap": int(self.chunk_cap),
            "n_cols": int(self.n_cols), "width": int(self.width),
            "priced": self.priced,
            "bytes_h2d": int(self.bytes_h2d),
            "bytes_h2d_min": int(self.bytes_h2d_min),
            "h2d_exact": self.h2d_exact,
            "partitions": int(self.partitions), "shards": int(self.shards),
            "exchange": self.exchange, "cap_ex": int(self.cap_ex),
            "bytes_ici": int(self.bytes_ici), "ici_exact": self.ici_exact,
            "kernel_min": int(self.kernel_min),
            "kernel_max": int(self.kernel_max),
            "stages": {k: int(v) for k, v in self.stages.items()},
            "bytes_hbm": int(self.bytes_hbm),
        }


@dataclass
class PerfReport:
    """Byte totals + roofline wall of one template statement."""

    file: str
    query: str
    classification: str        # exec_audit's routing classification
    bytes_h2d: int = 0
    bytes_h2d_min: int = 0
    h2d_exact: bool = False
    bytes_hbm: int = 0
    bytes_ici: int = 0
    ici_exact: bool = False
    wall_h2d_ms: float = 0.0
    wall_hbm_ms: float = 0.0
    wall_ici_ms: float = 0.0
    roofline_ms: float = 0.0   # max of the three walls: the static
    #                            lower bound on the statement's wall
    bound: str = BOUND_SYNC    # ranked bottleneck tag
    scans: tuple = ()          # ScanCosts, exec/mem walk order
    stages: dict = field(default_factory=dict)  # aggregated stage bytes
    detail: str = ""

    def to_dict(self) -> dict:
        return {
            "file": self.file, "query": self.query,
            "classification": self.classification,
            "bytes_h2d": int(self.bytes_h2d),
            "bytes_h2d_min": int(self.bytes_h2d_min),
            "h2d_exact": self.h2d_exact,
            "bytes_hbm": int(self.bytes_hbm),
            "bytes_ici": int(self.bytes_ici),
            "ici_exact": self.ici_exact,
            "wall_h2d_ms": round(self.wall_h2d_ms, 6),
            "wall_hbm_ms": round(self.wall_hbm_ms, 6),
            "wall_ici_ms": round(self.wall_ici_ms, 6),
            "roofline_ms": round(self.roofline_ms, 6),
            "bound": self.bound,
            "scans": [s.to_dict() for s in self.scans],
            "stages": {k: int(v) for k, v in self.stages.items()},
            "detail": self.detail,
        }


# ---------------------------------------------------------------------------
# live wire widths (the harness's exactness hook)
# ---------------------------------------------------------------------------


def wire_column_widths(table, canonical_types: dict | None = None) -> dict:
    """``{lowercase column -> wire bytes/row incl. validity}`` of the
    padded streamed chunks the engine actually uploads for ``table`` (an
    arrow Table or an engine ``ChunkedTable``) — the live twin of the
    :class:`MemModel` width tables, exact by construction because the
    dtype selection mirrors ``padded_chunks``: strings ride int32
    dictionary codes, int-path columns the SAME ``plan_column_codec``
    plan the runtime caches (narrow FOR/dict codes when the data
    proves them), everything else the plain device lowering
    (int32/date -> 4, int64/double/scaled decimal -> 8) — plus the
    always-present validity byte. ``tools/perf_audit_diff.py`` and
    ``tools/bench_compare.py --audit-perf`` feed these into
    :class:`PerfAuditor` as the ``wire_cols`` override, which is what
    upgrades the h2d/ICI predictions from bounds to equalities."""
    from nds_tpu import types as _t
    from nds_tpu.io.columnar import encoded_enabled, plan_column_codec
    arrow = getattr(table, "arrow", table)
    ctypes = dict(canonical_types
                  or getattr(table, "canonical_types", None) or {})
    enc = encoded_enabled()
    out = {}
    for name in arrow.column_names:
        ct = ctypes.get(name) or _t.arrow_to_canonical(
            arrow.schema.field(name).type)
        kind = _t.device_kind(ct)
        if kind == "str":
            w = 4                          # int32 dictionary codes
        else:
            got = plan_column_codec(arrow[name], ct) if enc else None
            if got is not None:
                w = got[0].dtype.itemsize  # narrow FOR/dict codes
            elif kind in ("i32", "date"):
                w = 4
            else:
                w = 8                      # i64 / f64 / scaled decimal
        out[name.lower()] = int(w) + 1     # + the validity byte
    return out


# ---------------------------------------------------------------------------
# the auditor
# ---------------------------------------------------------------------------


class PerfAuditor:
    """Host-only static cost model over the planner's decomposition.

    Composes :class:`ExecAuditor` (routing, shards, collective/kernel
    budgets) and :class:`MemAuditor` (row bounds, partition plan, widths)
    rather than walking the AST a third time: one decomposition, three
    interpretations. ``wire_cols`` optionally maps a table name to its
    REAL per-column wire widths (:func:`wire_column_widths`) — the
    differential harnesses pass it so the byte predictions become
    equalities; without it the model prices the conservative static
    widths and every prediction is an upper bound. Roofline link rates
    are frozen at construction from ``NDS_TPU_ROOFLINE_*_GBS``."""

    def __init__(self, streamed=None, model: MemModel | None = None,
                 base_tables=None, catalog: dict | None = None,
                 wire_cols: dict | None = None):
        self.model = model or MemModel()
        self.mem = MemAuditor(streamed=streamed, model=self.model,
                              base_tables=base_tables)
        self.exec = ExecAuditor(catalog=catalog, streamed=streamed,
                                base_tables=base_tables,
                                mem_model=self.model)
        self.streamed = self.mem.streamed
        self.wire_cols = {t.lower(): {c.lower(): int(w)
                                      for c, w in cols.items()}
                          for t, cols in (wire_cols or {}).items()}
        self.rates = roofline_gbs()
        # NDS_TPU_STREAM_EXCHANGE gate, frozen at construction like the
        # executor freezes it at pipeline build (the lockstep rule)
        self.exchange_on = os.environ.get("NDS_TPU_STREAM_EXCHANGE",
                                          "1") != "0"

    # -- entry point --------------------------------------------------------

    def audit_sql(self, sql: str, file: str = "<sql>",
                  query: str = "<sql>") -> PerfReport:
        """Price one SQL statement's data movement."""
        er = self.exec.audit_sql(sql, file=file, query=query)
        mr = self.mem.audit_sql(sql, file=file, query=query)
        if er.classification == CLASS_UNKNOWN:
            return PerfReport(file, query, er.classification,
                              detail=er.detail or mr.detail)
        costs = self._scan_costs(er, mr, self.mem.needed)
        return self._assemble(file, query, er, mr, costs)

    # -- per-scan pricing ---------------------------------------------------

    def _scan_costs(self, er: ExecReport, mr, needed) -> list:
        """Pair the exec verdicts with the mem bounds (both walk the
        same decomposition; pair by index, falling back to table-name
        matching) and price each streamed scan. EVERY pipeline of the
        statement — expression-subquery pipelines included — prunes at
        the STATEMENT-level needed set: the planner computes pruning
        once per statement, so the ab12-class scalar-subquery chain
        uploads the same columns in both of its store_sales pipelines
        (the differential harness pins this byte-exactly)."""
        bounds = list(mr.scans)
        pairs = []
        for i, sv in enumerate(er.scans):
            sb = None
            if i < len(bounds) and bounds[i] is not None \
                    and bounds[i].table == sv.table:
                sb = bounds[i]
                bounds[i] = None
            else:
                for j, b in enumerate(bounds):
                    if b is not None and b.table == sv.table:
                        sb = b
                        bounds[j] = None
                        break
            pairs.append((sv, sb))
        return [self._scan_cost(sv, sb, needed) for sv, sb in pairs]

    def _pruned_widths(self, table: str, needed):
        """``(cols, exact, priced)``: the pruned wire width per column.
        ``cols`` applies the planner's proper-subset pruning rule to the
        table's ACTUAL columns (the ``wire_cols`` override when the
        harness supplies real widths, the static encoded/plain catalog
        widths otherwise)."""
        exact = False
        cols = self.wire_cols.get(table)
        if cols is not None:
            exact = True
        else:
            cols = (self.model.enc_widths if self.model.encoded
                    else self.model.widths).get(table, {})
        if not cols:
            return {"?": 9}, False, False   # unknown table: one wide col
        if needed is not None:
            kept = {c: w for c, w in cols.items() if c in needed}
            if kept and len(kept) < len(cols):
                cols = kept
        return dict(cols), exact, True

    def _plain_width(self, table: str, needed):
        """``(width, n_cols)`` of the UNENCODED pruned row — what the
        eager chunk loop uploads (``from_arrow``: no narrow codecs,
        bucket-padded per chunk)."""
        cols = self.model.widths.get(table, {})
        if not cols:
            return 9, 1
        if needed is not None:
            kept = {c: w for c, w in cols.items() if c in needed}
            if kept and len(kept) < len(cols):
                cols = kept
        return sum(cols.values()), len(cols)

    def _scan_cost(self, sv: ScanVerdict, sb: ScanBound | None,
                   needed) -> ScanCost:
        model = self.model
        rows = sb.rows if sb is not None \
            else (model.table_rows(sv.table) or 1)
        n_chunks = max(1, math.ceil(rows / model.chunk_rows))
        cap = model.chunk_cap()
        P = max(1, sb.partitions if sb is not None else 1)
        S = max(1, sv.shards)
        cols, exact_w, priced = self._pruned_widths(sv.table, needed)
        width = sum(cols.values())
        n_cols = len(cols)
        cost = ScanCost(sv.alias, sv.table, sv.compiled, rows, n_chunks,
                        cap, n_cols=n_cols, width=width, priced=priced,
                        partitions=P, shards=S)

        chunk_bytes = cap * width
        if sv.compiled:
            # the closed form: every chunk at ONE capacity, every column
            # data + validity — identical cold/warm (the chunk store
            # caches the encoding, not the buffers) and prefetch-
            # invariant (the ring changes WHEN bytes move, not how many)
            cost.bytes_h2d = cost.bytes_h2d_min = n_chunks * chunk_bytes
            cost.h2d_exact = exact_w
        else:
            # eager loop: unencoded chunks, each bucket-padded to its own
            # length, validity only where nulls exist -> a [min,max] band
            pw, pn = self._plain_width(sv.table, needed)
            last = rows - (n_chunks - 1) * model.chunk_rows
            padded = (n_chunks - 1) * _bucket(model.chunk_rows) \
                + _bucket(max(last, 1))
            cost.bytes_h2d = padded * pw
            cost.bytes_h2d_min = padded * max(pw - pn, 1)

        # -- ICI: exchange (per chunk) + the one cross-shard reduce ------
        if sv.compiled and S > 1:
            exch = (P > 1 and sv.a2a_chunk > 0 and self.exchange_on)
            reduce_bytes = 20 * P          # all_gather counts (8P) +
            #                                psum flags (4P) + hist (8P)
            n_builds = max(0, sv.coll_final - 3)
            if exch:
                cost.exchange = True
                cost.cap_ex = _bucket(max((cap // S) // S, 1)
                                      * model.skew)
                exch_bytes = S * cost.cap_ex * (width + 5)
                cost.bytes_ici = n_chunks * exch_bytes + reduce_bytes
            else:
                cost.bytes_ici = reduce_bytes
            # outer-build bitmap psums ride the reduce; their padded
            # length is the build side's device table length, which the
            # composed walk does not surface — priced 0 (lower bound)
            cost.ici_exact = exact_w and n_builds == 0

        # -- fused-kernel launch band ------------------------------------
        if sv.compiled:
            cost.kernel_min = sv.kernel_scan_chunk * n_chunks
            cost.kernel_max = (sv.kernel_scan_chunk
                               + sv.kernel_probe_chunk * P) * n_chunks

        # -- HBM stage model (roofline denominator) ----------------------
        stages = dict.fromkeys(STAGES, 0)
        if sv.compiled:
            fused = sv.kernel_scan_chunk > 0
            stages["scan"] = n_chunks * chunk_bytes
            # mask + compact re-read per chunk, folded into the fused
            # VMEM pass on the Pallas arm (the PR 12 stage collapse)
            stages["filter"] = 0 if fused else n_chunks * chunk_bytes
            if P > 1:
                # radix hash pass re-reads the chunk (fused arm: the
                # hash stage rides the same VMEM pass)
                stages["partition"] = 0 if fused \
                    else n_chunks * chunk_bytes
                # every extra per-partition dispatch re-reads the chunk
                stages["probe"] = (P - 1) * n_chunks * chunk_bytes
            if cost.exchange:
                # pack write + exchanged read around the all-to-all
                stages["exchange"] = 2 * n_chunks * S * cost.cap_ex \
                    * (width + 5)
            if sb is not None:
                acc = sb.part_bytes * P if (sb.part_bytes is not None
                                            and P > 1) else sb.acc_bytes
                stages["accumulate"] = int(acc or 0)
        else:
            # eager loop: each uploaded chunk is read once; survivors
            # concatenate on host (no device accumulator to price)
            stages["scan"] = cost.bytes_h2d
        cost.stages = {k: v for k, v in stages.items() if v}
        return cost

    # -- statement assembly -------------------------------------------------

    def _assemble(self, file, query, er: ExecReport, mr,
                  costs: list) -> PerfReport:
        rep = PerfReport(file, query, er.classification)
        rep.scans = tuple(costs)
        rep.bytes_h2d = sum(c.bytes_h2d for c in costs)
        rep.bytes_h2d_min = sum(c.bytes_h2d_min for c in costs)
        rep.h2d_exact = bool(costs) and all(c.h2d_exact for c in costs)
        rep.bytes_ici = sum(c.bytes_ici for c in costs)
        rep.ici_exact = all(c.ici_exact for c in costs
                            if c.bytes_ici) if any(c.bytes_ici
                                                   for c in costs) else False
        stages: dict = {}
        for c in costs:
            for k, v in c.stages.items():
                stages[k] = stages.get(k, 0) + v
        if er.classification == CLASS_DEVICE:
            # device-resident statement: one pass over the resident peak
            # is the floor of its HBM traffic
            stages["scan"] = stages.get("scan", 0) + int(mr.peak_bytes)
        rep.stages = stages
        rep.bytes_hbm = sum(stages.values())
        # walls: bytes / (GB/s x 1e9) in ms == bytes / rate / 1e6
        rep.wall_h2d_ms = rep.bytes_h2d / self.rates["h2d"] / 1e6
        rep.wall_hbm_ms = rep.bytes_hbm / self.rates["hbm"] / 1e6
        rep.wall_ici_ms = rep.bytes_ici / self.rates["ici"] / 1e6
        rep.roofline_ms = max(rep.wall_h2d_ms, rep.wall_hbm_ms,
                              rep.wall_ici_ms)
        rep.bound = self._bound_tag(er, rep)
        return rep

    @staticmethod
    def _bound_tag(er: ExecReport, rep: PerfReport) -> str:
        """Ranked static bottleneck: ``sync-bound`` when exec_audit has
        no finite statement sync bound (the eager loop's O(chunks) host
        reads dominate any byte wall — routing is the bottleneck, not a
        link), else the slowest wall, ties resolved in pipeline order
        (h2d feeds HBM feeds ICI)."""
        if er.classification == CLASS_EAGER or er.sync_bound is None:
            return BOUND_SYNC
        walls = ((rep.wall_h2d_ms, BOUND_H2D),
                 (rep.wall_hbm_ms, BOUND_HBM),
                 (rep.wall_ici_ms, BOUND_ICI))
        best, tag = 0.0, BOUND_SYNC
        for w, t in walls:
            if w > best:
                best, tag = w, t
        return tag


# ---------------------------------------------------------------------------
# corpus driver + lint-gate findings
# ---------------------------------------------------------------------------


def audit_perf_template_text(text: str, file: str,
                             auditor: PerfAuditor | None = None) -> list:
    """Instantiate one template (pinned seed, shared with the other
    auditors) and price each statement; returns PerfReports."""
    import numpy as np
    auditor = auditor or PerfAuditor()
    sql = instantiate_template(text, np.random.default_rng(_AUDIT_SEED))
    stmts = [s for s in sql.split(";") if s.strip()]
    base = os.path.basename(file)
    out = []
    for i, stmt in enumerate(stmts):
        qname = base[:-4] if base.endswith(".tpl") else base
        if len(stmts) > 1:
            qname = f"{qname}_part{i + 1}"
        out.append(auditor.audit_sql(stmt, file=base, query=qname))
    return out


def audit_perf_corpus(template_dir: str | None = None,
                      streamed=None) -> list:
    """PerfReports for every template in templates.lst order."""
    template_dir = template_dir or TEMPLATE_DIR
    auditor = PerfAuditor(streamed=streamed)
    reports: list = []
    for name in list_templates(template_dir):
        reports.extend(audit_perf_template_text(
            load_template(name, template_dir), name, auditor))
    return reports


def reports_to_findings(reports) -> list:
    """Lint-gate findings from perf reports. The byte totals themselves
    are a report (``--perf-report``), not findings; the gate catches the
    two ways the cost model can silently stop modeling:

    * ``cost-model-gap`` — a compiled streamed scan priced at the
      unknown-table default width: the model cannot see the table's
      columns, so every byte prediction for the statement is fiction;
    * ``roofline-degenerate`` — a compiled-stream statement whose
      roofline wall is zero: nothing was priced at all, which means the
      composed walk and the routing drifted apart.
    """
    findings = []
    for r in reports:
        for s in r.scans:
            if s.compiled and not s.priced:
                findings.append(Finding(
                    r.file, r.query, "cost-model-gap", "error",
                    f"streamed scan {s.table!r} priced at the unknown-"
                    "table default width: the static cost model cannot "
                    "see its columns, so the statement's byte "
                    "predictions are unfounded"))
        if r.classification == CLASS_COMPILED and r.roofline_ms <= 0:
            findings.append(Finding(
                r.file, r.query, "roofline-degenerate", "error",
                "compiled-stream statement with a zero roofline wall: "
                "the cost model priced no data movement (model drift "
                "against the exec/mem decomposition)"))
    return findings


def perf_audit_findings(template_dir: str | None = None) -> list:
    """The lint pass entry point (tools/lint.py seventh pass)."""
    return reports_to_findings(audit_perf_corpus(template_dir))


def bottleneck_counts(reports) -> dict:
    """``tag -> statement count`` histogram of the static bottleneck
    tags (the pinned corpus cost story)."""
    counts: dict = {}
    for r in reports:
        counts[r.bound] = counts.get(r.bound, 0) + 1
    return counts


def corpus_walls(template_dir: str | None = None) -> dict:
    """``query -> (roofline_ms, bound)`` for the whole corpus — the
    static denominator ``tools/trace_report.py`` renders next to the
    measured roofline columns."""
    return {r.query: (r.roofline_ms, r.bound)
            for r in audit_perf_corpus(template_dir)}


def _mb(n: int) -> str:
    return f"{n / 1e6:,.1f}"


def format_perf_report(reports) -> str:
    """The per-template cost table (``tools/lint.py --perf-report``):
    predicted byte totals, the roofline wall and the bottleneck tag —
    what a measured campaign number is compared against."""
    rates = roofline_gbs()
    lines = ["# perf-audit: per-statement static cost model",
             "# rates GB/s: "
             + ", ".join(f"{k}={rates[k]:g}" for k in ("h2d", "hbm",
                                                       "ici")),
             f"{'template':<18} {'class':<16} {'h2d-MB':>10} "
             f"{'hbm-MB':>10} {'ici-MB':>9} {'roof-ms':>9}  bound"]
    for r in reports:
        lines.append(
            f"{r.query:<18} {r.classification:<16} "
            f"{_mb(r.bytes_h2d):>10} {_mb(r.bytes_hbm):>10} "
            f"{_mb(r.bytes_ici):>9} {r.roofline_ms:>9.2f}  {r.bound}")
    counts = bottleneck_counts(reports)
    summary = ", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
    lines.append(f"# {len(reports)} statements — {summary}")
    return "\n".join(lines)
