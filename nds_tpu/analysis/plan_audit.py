# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Static plan auditor: the analyzer pass the planner itself doesn't have.

Walks the parsed AST of every query template against the
:mod:`nds_tpu.schema` catalog — no device, no data — and reports the
plan-shape problems that would otherwise only surface at runtime deep
inside a Power Run:

* ``unknown-table`` / ``unresolved-column`` — a reference no relation in
  scope provides. Resolution mirrors the planner exactly
  (:meth:`Planner._resolve_name`): qualified refs need an exact
  ``alias.column`` match, unqualified refs resolve by bare-name suffix
  match across every relation in scope (then up the correlation chain).
* ``ambiguous-column`` — an unqualified ref matching several relations;
  the planner silently picks the first, so this is a warning, not an error.
* ``type-mismatch`` — comparisons / BETWEEN / IN whose operand type
  classes can't meet (numeric vs string, date vs numeric). String/date
  comparisons are allowed (Spark coerces date literals).
* ``agg-arg-type`` — sum/avg/stddev/variance over strings or dates.
* ``unknown-function`` — a function the planner has no lowering for.
* ``window-misuse`` — rank()/row_number()/... outside an OVER clause.
* ``nested-aggregate`` / ``agg-in-where`` — aggregate misuse Spark's
  analyzer would reject.
* ``grouping-misuse`` — grouping(x) without GROUP BY, or over an
  expression that is not a grouping expression.
* ``cartesian-join`` — a FROM clause whose join graph has unconnected
  components (no predicate of any kind links them). Guaranteed-single-row
  relations (aggregate-only subqueries, LIMIT 1) are exempt: broadcasting
  one row is a gather, not a pair explosion.
* ``setop-arity`` / ``subquery-arity`` — UNION/INTERSECT/EXCEPT branch or
  IN/scalar-subquery column-count mismatches.
"""

from __future__ import annotations

import os

import numpy as np

from nds_tpu.analysis import Finding
from nds_tpu.queries import (TEMPLATE_DIR, instantiate_template,
                             list_templates, load_template)
from nds_tpu.schema import get_schemas
from nds_tpu.sql import ast as A
from nds_tpu.sql.parser import (AGG_FUNCS, WINDOW_ONLY_FUNCS, ParseError,
                                expr_key, parse)

# ---------------------------------------------------------------------------
# type classes
# ---------------------------------------------------------------------------

# canonical schema type -> coarse class the audit compares on
def type_class(canonical: str | None) -> str | None:
    if canonical is None:
        return None
    t = canonical.lower()
    if t in ("int32", "int64", "double", "float", "bigint", "int",
             "integer", "smallint", "tinyint") or t.startswith("decimal"):
        return "num"
    if t == "date":
        return "date"
    if t == "string" or t.startswith(("char", "varchar")):
        return "str"
    if t in ("bool", "boolean"):
        return "bool"
    return None


# type-class pairs a comparison may legally meet on. str/date meets because
# Spark coerces string literals in date comparisons (the corpus does this
# in both directions); num/bool meets for grouping-flag arithmetic.
_COMPATIBLE = {
    frozenset(("num",)), frozenset(("str",)), frozenset(("date",)),
    frozenset(("bool",)), frozenset(("interval",)),
    frozenset(("str", "date")), frozenset(("num", "bool")),
    frozenset(("date", "interval")),
}


def _meet(a: str | None, b: str | None) -> bool:
    if a is None or b is None:
        return True
    return frozenset((a, b)) in _COMPATIBLE


SCALAR_FUNCS = {
    "substr", "substring", "coalesce", "nullif", "abs", "round", "floor",
    "ceil", "ceiling", "sqrt", "upper", "ucase", "lower", "lcase", "trim",
    "length", "char_length", "character_length", "concat", "year", "month",
    "day", "dayofmonth", "grouping",
}
KNOWN_FUNCS = SCALAR_FUNCS | set(AGG_FUNCS) | set(WINDOW_ONLY_FUNCS)

# aggregates whose argument must be orderable-numeric
_NUMERIC_AGGS = {"sum", "avg", "stddev_samp", "stddev", "var_samp",
                 "variance"}

_NUM_RESULT_FUNCS = ({"count", "approx_count_distinct", "length",
                      "char_length", "character_length", "year", "month",
                      "day", "dayofmonth", "grouping", "abs", "round",
                      "floor", "ceil", "ceiling", "sqrt"}
                     | _NUMERIC_AGGS | set(WINDOW_ONLY_FUNCS))
_STR_RESULT_FUNCS = {"substr", "substring", "upper", "ucase", "lower",
                     "lcase", "trim", "concat"}


# ---------------------------------------------------------------------------
# scopes
# ---------------------------------------------------------------------------


class Scope:
    """Columns visible to expressions of one SELECT: ``alias.column`` (all
    lowercase) -> type class, plus the enclosing scope for correlated
    subqueries. Resolution order mirrors the planner: innermost scope
    first, suffix match for unqualified names. ``env`` carries the relation
    environment (catalog + in-scope CTEs) so subqueries audited from inside
    expressions still see the statement's CTEs."""

    def __init__(self, columns: dict, parent: "Scope | None" = None,
                 env: dict | None = None):
        self.columns = columns
        self.parent = parent
        self.env = env if env is not None else (
            parent.env if parent is not None else None)

    def resolve(self, ref: A.ColumnRef):
        """-> (key, type class, ambiguous) or (None, None, False)."""
        name = ref.name.lower()
        scope: Scope | None = self
        while scope is not None:
            if ref.table:
                key = f"{ref.table.lower()}.{name}"
                if key in scope.columns:
                    return key, scope.columns[key], False
            else:
                matches = [c for c in scope.columns
                           if c.split(".")[-1] == name]
                if matches:
                    return (matches[0], scope.columns[matches[0]],
                            len(matches) > 1)
            scope = scope.parent
        return None, None, False


class _SelectInfo:
    """Join-graph bookkeeping for one SELECT."""

    def __init__(self):
        self.rels: dict = {}        # alias -> single_row flag
        self.edges: set = set()     # frozenset({alias_a, alias_b})


class _OutCols(dict):
    """Ordered ``{output name -> type class}`` of a query, carrying the
    TRUE projected arity: duplicate output names collapse as scope keys
    but still count as columns for set-op/subquery arity checks."""

    arity: int = 0


def _arity(out) -> int:
    return getattr(out, "arity", len(out))


class PlanAuditor:
    def __init__(self, catalog: dict | None = None):
        # table -> ordered {column -> type class}
        if catalog is None:
            catalog = {
                t: {f.name.lower(): type_class(f.type) for f in fields}
                for t, fields in get_schemas(use_decimal=True).items()
            }
        self.catalog = catalog
        self.findings: list = []
        self._file = "<sql>"
        self._query = "<sql>"

    # -- entry points -------------------------------------------------------

    def audit_sql(self, sql: str, file: str = "<sql>",
                  query: str = "<sql>") -> list:
        """Audit one SQL statement text; returns (and accumulates) findings."""
        self._file, self._query = file, query
        before = len(self.findings)
        try:
            stmt = parse(sql)
        except ParseError as e:
            self._emit("parse-error", "error", str(e))
            return self.findings[before:]
        env = dict(self.catalog)
        if isinstance(stmt, A.Query):
            self._audit_query(stmt, env, None)
        elif isinstance(stmt, (A.InsertInto, A.CreateTempView)):
            self._audit_query(stmt.query, env, None)
        elif isinstance(stmt, A.DeleteFrom):
            cols = env.get(stmt.table.lower())
            if cols is None:
                self._emit("unknown-table", "error",
                           f"DELETE target {stmt.table!r} not in catalog")
            elif stmt.where is not None:
                alias = stmt.table.lower()
                scope = Scope({f"{alias}.{c}": k for c, k in cols.items()})
                self._check_expr(stmt.where, scope, None)
        return self.findings[before:]

    def _emit(self, rule: str, severity: str, message: str) -> None:
        self.findings.append(Finding(self._file, self._query, rule,
                                     severity, message))

    def _env_of(self, scope: Scope | None) -> dict:
        """Relation environment for a subquery audited mid-expression: the
        enclosing statement's catalog + CTEs, carried on the scope chain."""
        if scope is not None and scope.env is not None:
            return scope.env
        return dict(self.catalog)

    # -- query / select -----------------------------------------------------

    def _audit_query(self, q: A.Query, env: dict, outer: Scope | None):
        """Audit one query expression; returns its output columns as an
        ordered {name -> type class}."""
        env = dict(env)
        for cname, cq in q.ctes:
            env[cname.lower()] = self._audit_query(cq, env, None)
        out = self._audit_body(q.body, env, outer)
        if q.order_by:
            from_scope, _ = self._body_scope(q.body, env, outer)
            # ORDER BY sees output aliases first (an alias shadowing the
            # column it projects is not an ambiguity), then FROM columns
            scope = Scope(dict(out), parent=from_scope, env=env)
            info = _SelectInfo()
            for e, _, _ in q.order_by:
                self._check_expr(e, scope, info,
                                 group=self._body_group(q.body))
        return out

    def _audit_body(self, body, env: dict, outer: Scope | None) -> dict:
        if isinstance(body, A.SetOp):
            left = self._audit_body(body.left, env, outer)
            right = self._audit_body(body.right, env, outer)
            if left and right and _arity(left) != _arity(right):
                self._emit("setop-arity", "error",
                           f"{body.op} branches project {_arity(left)} vs "
                           f"{_arity(right)} columns")
            return left
        if isinstance(body, A.Query):
            return self._audit_query(body, env, outer)
        return self._audit_select(body, env, outer)

    def _body_scope(self, body, env: dict, outer: Scope | None):
        """Scope + info of the leftmost SELECT (for ORDER BY resolution)."""
        while isinstance(body, (A.SetOp, A.Query)):
            body = body.left if isinstance(body, A.SetOp) else body.body
        return self._from_scope(body.from_, env, outer, audit=False)

    def _body_group(self, body):
        while isinstance(body, (A.SetOp, A.Query)):
            body = body.left if isinstance(body, A.SetOp) else body.body
        return body.group_by

    def _audit_select(self, sel: A.Select, env: dict,
                      outer: Scope | None) -> dict:
        scope, info = self._from_scope(sel.from_, env, outer, audit=True)
        group = sel.group_by

        if sel.where is not None:
            self._check_expr(sel.where, scope, info, group=None,
                             in_where=True)
        if group is not None:
            for e in group.exprs:
                self._check_expr(e, scope, info, group=None)
        out = _OutCols()
        arity = 0
        idx = 0
        for item in sel.items:
            if isinstance(item.expr, A.Star):
                alias = item.expr.table and item.expr.table.lower()
                for key, klass in scope.columns.items():
                    rel, col = key.split(".", 1)
                    if alias is None or rel == alias:
                        out[col] = klass
                        arity += 1
                if alias is not None and alias not in info.rels:
                    self._emit("unresolved-column", "error",
                               f"star over unknown relation {alias!r}")
                continue
            klass = self._check_expr(item.expr, scope, info, group=group)
            name = item.alias
            if name is None and isinstance(item.expr, A.ColumnRef):
                name = item.expr.name
            if name is None:
                name = f"_c{idx}"
            out[name.lower()] = klass
            arity += 1
            idx += 1
        out.arity = arity
        if sel.having is not None:
            having_scope = Scope(dict(out), parent=scope)
            self._check_expr(sel.having, having_scope, info, group=group)
        self._check_connectivity(sel, info)
        return out

    # -- FROM clause --------------------------------------------------------

    def _from_scope(self, from_, env: dict, outer: Scope | None,
                    audit: bool):
        """Build the SELECT's visible-column scope and relation graph."""
        info = _SelectInfo()
        columns: dict = {}
        on_conds: list = []

        def add_rel(alias: str, cols: dict, single_row: bool):
            alias = alias.lower()
            if audit and alias in info.rels:
                self._emit("duplicate-alias", "warning",
                           f"relation alias {alias!r} bound twice")
            info.rels[alias] = single_row
            for col, klass in cols.items():
                columns[f"{alias}.{col}"] = klass

        def walk(node):
            if node is None:
                return
            if isinstance(node, A.TableRef):
                cols = env.get(node.name.lower())
                if cols is None:
                    if audit:
                        self._emit("unknown-table", "error",
                                   f"unknown table {node.name!r}")
                    cols = {}
                add_rel(node.alias or node.name, cols, False)
            elif isinstance(node, A.SubqueryRef):
                if audit:
                    sub_out = self._audit_query(node.query, env, None)
                else:
                    sub_out = self._query_output_shape(node.query, env)
                add_rel(node.alias, sub_out,
                        _single_row_query(node.query))
            elif isinstance(node, A.Join):
                walk(node.left)
                walk(node.right)
                if node.condition is not None:
                    on_conds.append(node.condition)
            elif isinstance(node, A.Query):
                # parenthesized join tree parsed as bare query body
                walk(getattr(node.body, "from_", None))
        walk(from_)
        scope = Scope(columns, outer, env=env)
        if audit:
            for cond in on_conds:
                self._check_expr(cond, scope, info, group=None)
        return scope, info

    def _query_output_shape(self, q: A.Query, env: dict) -> dict:
        """Output columns of a query WITHOUT emitting findings (used when a
        scope is rebuilt for ORDER BY after the audit already ran)."""
        saved, self.findings = self.findings, []
        try:
            return self._audit_query(q, env, None)
        finally:
            self.findings = saved

    # -- join-graph connectivity -------------------------------------------

    def _check_connectivity(self, sel: A.Select, info: _SelectInfo) -> None:
        multi = [a for a, single in info.rels.items() if not single]
        if len(multi) <= 1:
            return
        parent = {a: a for a in multi}

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for edge in info.edges:
            pair = [a for a in edge if a in parent]
            if len(pair) == 2:
                parent[find(pair[0])] = find(pair[1])
        comps: dict = {}
        for a in multi:
            comps.setdefault(find(a), []).append(a)
        if len(comps) > 1:
            groups = sorted(sorted(c) for c in comps.values())
            self._emit("cartesian-join", "error",
                       "unconnected join components (true cartesian): "
                       + " x ".join("{" + ",".join(g) + "}" for g in groups))

    # -- expressions --------------------------------------------------------

    def _check_expr(self, e, scope: Scope, info: _SelectInfo | None,
                    group: A.GroupingSets | None = None,
                    in_where: bool = False, in_agg: bool = False):
        """Recursively validate one expression; returns its type class
        (None = unknown). Side effects: findings, join edges on ``info``."""
        if isinstance(e, A.Literal):
            v = e.value
            if isinstance(v, bool):
                return "bool"
            if isinstance(v, str):
                return "str"
            if v is None:
                return None
            return "num"
        if isinstance(e, A.DateLiteral):
            return "date"
        if isinstance(e, A.IntervalLiteral):
            return "interval"
        if isinstance(e, A.ColumnRef):
            key, klass, ambiguous = scope.resolve(e)
            if key is None:
                ref = f"{e.table}.{e.name}" if e.table else e.name
                self._emit("unresolved-column", "error",
                           f"column {ref!r} resolves to no relation in scope")
                return None
            if ambiguous:
                self._emit("ambiguous-column", "warning",
                           f"unqualified {e.name!r} matches several "
                           f"relations; planner picks {key.split('.')[0]!r}")
            return klass
        if isinstance(e, A.Star):
            return None
        if isinstance(e, A.UnaryOp):
            self._check_expr(e.operand, scope, info, group, in_where, in_agg)
            return "bool" if e.op == "not" else "num"
        if isinstance(e, A.BinaryOp):
            lk = self._check_expr(e.left, scope, info, group, in_where, in_agg)
            rk = self._check_expr(e.right, scope, info, group, in_where,
                                  in_agg)
            if e.op in ("=", "<>", "<", "<=", ">", ">="):
                if not _meet(lk, rk):
                    self._emit("type-mismatch", "error",
                               f"{e.op} compares {lk} with {rk}: "
                               f"{_describe(e.left)} {e.op} "
                               f"{_describe(e.right)}")
                self._note_edge(e, scope, info)
                return "bool"
            if e.op in ("and", "or"):
                # a disjunction spanning two relations is evaluated per
                # pair — it connects them; a conjunction decomposes into
                # independent conjuncts, which note their own edges
                if e.op == "or":
                    self._note_edge(e, scope, info)
                return "bool"
            if e.op == "||":
                return "str"
            # arithmetic: date +/- interval stays a date
            if "date" in (lk, rk) and "interval" in (lk, rk):
                return "date"
            if not _meet(lk, rk):
                self._emit("type-mismatch", "error",
                           f"arithmetic {e.op!r} combines {lk} with {rk}")
            return "num"
        if isinstance(e, A.Between):
            k = self._check_expr(e.expr, scope, info, group, in_where, in_agg)
            for bound in (e.low, e.high):
                bk = self._check_expr(bound, scope, info, group, in_where,
                                      in_agg)
                if not _meet(k, bk):
                    self._emit("type-mismatch", "error",
                               f"BETWEEN bound is {bk}, operand is {k}")
            self._note_edge(e, scope, info)
            return "bool"
        if isinstance(e, A.InList):
            k = self._check_expr(e.expr, scope, info, group, in_where, in_agg)
            for item in e.items:
                ik = self._check_expr(item, scope, info, group, in_where,
                                      in_agg)
                if not _meet(k, ik):
                    self._emit("type-mismatch", "error",
                               f"IN list item is {ik}, operand is {k}")
            self._note_edge(e, scope, info)
            return "bool"
        if isinstance(e, A.InSubquery):
            k = self._check_expr(e.expr, scope, info, group, in_where, in_agg)
            out = self._audit_query(e.query, self._env_of(scope), scope)
            if _arity(out) != 1:
                self._emit("subquery-arity", "error",
                           f"IN subquery projects {_arity(out)} columns")
            elif not _meet(k, next(iter(out.values()))):
                self._emit("type-mismatch", "error",
                           f"IN subquery column is "
                           f"{next(iter(out.values()))}, operand is {k}")
            self._note_edge(e, scope, info)
            return "bool"
        if isinstance(e, A.Exists):
            self._audit_query(e.query, self._env_of(scope), scope)
            return "bool"
        if isinstance(e, A.ScalarSubquery):
            out = self._audit_query(e.query, self._env_of(scope), scope)
            if _arity(out) != 1:
                self._emit("subquery-arity", "error",
                           f"scalar subquery projects {_arity(out)} columns")
                return None
            return next(iter(out.values()))
        if isinstance(e, A.QuantifiedCompare):
            k = self._check_expr(e.expr, scope, info, group, in_where, in_agg)
            out = self._audit_query(e.query, self._env_of(scope), scope)
            if _arity(out) == 1 and not _meet(k, next(iter(out.values()))):
                self._emit("type-mismatch", "error",
                           f"{e.quantifier.upper()} subquery column is "
                           f"{next(iter(out.values()))}, operand is {k}")
            self._note_edge(e, scope, info)
            return "bool"
        if isinstance(e, A.Like):
            k = self._check_expr(e.expr, scope, info, group, in_where, in_agg)
            if k is not None and k != "str":
                self._emit("type-mismatch", "error",
                           f"LIKE over non-string operand ({k})")
            self._note_edge(e, scope, info)
            return "bool"
        if isinstance(e, A.IsNull):
            self._check_expr(e.expr, scope, info, group, in_where, in_agg)
            self._note_edge(e, scope, info)
            return "bool"
        if isinstance(e, A.Case):
            if e.operand is not None:
                self._check_expr(e.operand, scope, info, group, in_where,
                                 in_agg)
            klass = None
            for cond, res in e.branches:
                self._check_expr(cond, scope, info, group, in_where, in_agg)
                rk = self._check_expr(res, scope, info, group, in_where,
                                      in_agg)
                klass = klass or rk
            if e.else_ is not None:
                rk = self._check_expr(e.else_, scope, info, group, in_where,
                                      in_agg)
                klass = klass or rk
            return klass
        if isinstance(e, A.Cast):
            self._check_expr(e.expr, scope, info, group, in_where, in_agg)
            return type_class(e.target)
        if isinstance(e, A.WindowFunc):
            for p in e.spec.partition_by:
                self._check_expr(p, scope, info, group, in_where, in_agg)
            for oe, _, _ in e.spec.order_by:
                self._check_expr(oe, scope, info, group, in_where, in_agg)
            # the wrapped call is exempt from the window-misuse check and
            # may itself be an aggregate (rank() over (order by sum(x)))
            return self._check_func(e.func, scope, info, group, in_where,
                                    in_agg, windowed=True)
        if isinstance(e, A.FuncCall):
            return self._check_func(e, scope, info, group, in_where, in_agg,
                                    windowed=False)
        return None

    def _check_func(self, e: A.FuncCall, scope, info, group, in_where,
                    in_agg, windowed: bool):
        name = e.name.lower()
        if name not in KNOWN_FUNCS:
            self._emit("unknown-function", "error",
                       f"function {name!r} has no planner lowering")
            for a in e.args:
                self._check_expr(a, scope, info, group, in_where, in_agg)
            return None
        if name in WINDOW_ONLY_FUNCS and not windowed:
            self._emit("window-misuse", "error",
                       f"window function {name}() used without OVER")
        is_agg = name in AGG_FUNCS
        if is_agg:
            if in_agg:
                self._emit("nested-aggregate", "error",
                           f"aggregate {name}() nested inside an aggregate")
            if in_where:
                self._emit("agg-in-where", "error",
                           f"aggregate {name}() in WHERE clause")
        if name == "grouping":
            if group is None:
                self._emit("grouping-misuse", "error",
                           "grouping() without GROUP BY")
            elif e.args:
                keys = {expr_key(g) for g in group.exprs}
                if expr_key(e.args[0]) not in keys:
                    self._emit("grouping-misuse", "error",
                               f"grouping({_describe(e.args[0])}) over a "
                               "non-grouping expression")
        # a windowed aggregate evaluates post-grouping, so its argument may
        # itself be a plain aggregate (q12-class sum(sum(x)) over (...))
        arg_in_agg = False if (windowed and is_agg) else (in_agg or is_agg)
        arg_classes = [self._check_expr(a, scope, info, group, in_where,
                                        arg_in_agg)
                       for a in e.args]
        if name in _NUMERIC_AGGS and arg_classes and \
                arg_classes[0] in ("str", "date"):
            self._emit("agg-arg-type", "error",
                       f"{name}() over a {arg_classes[0]} argument")
        if name in _NUM_RESULT_FUNCS:
            return "num"
        if name in _STR_RESULT_FUNCS:
            return "str"
        if name in ("min", "max", "coalesce", "nullif", "lag", "lead"):
            return arg_classes[0] if arg_classes else None
        return None

    # -- join edges ---------------------------------------------------------

    def _note_edge(self, e, scope: Scope, info: _SelectInfo | None) -> None:
        """Record which FROM relations a predicate links: ANY predicate
        referencing two relations connects them (the planner turns equi
        conjuncts into join keys and everything else into pair filters —
        either way the pair is not an accidental cartesian)."""
        if info is None:
            return
        rels = set()

        def walk(node):
            if isinstance(node, A.ColumnRef):
                key, _, _ = scope.resolve(node)
                # only count rels of THIS select's scope, not outer/corr
                if key is not None and key in scope.columns:
                    rels.add(key.split(".")[0])
            for c in _children(node):
                if not isinstance(c, A.Query):
                    walk(c)
        walk(e)
        for a in rels:
            for b in rels:
                if a < b:
                    info.edges.add(frozenset((a, b)))


def _children(e):
    if isinstance(e, A.BinaryOp):
        return (e.left, e.right)
    if isinstance(e, A.UnaryOp):
        return (e.operand,)
    if isinstance(e, A.Between):
        return (e.expr, e.low, e.high)
    if isinstance(e, (A.InList,)):
        return (e.expr, *e.items)
    if isinstance(e, (A.Like, A.IsNull)):
        return (e.expr,)
    if isinstance(e, A.Case):
        out = [c for b in e.branches for c in b]
        if e.operand is not None:
            out.append(e.operand)
        if e.else_ is not None:
            out.append(e.else_)
        return tuple(out)
    if isinstance(e, A.Cast):
        return (e.expr,)
    if isinstance(e, A.FuncCall):
        return tuple(e.args)
    if isinstance(e, A.WindowFunc):
        return (e.func, *e.spec.partition_by,
                *(oe for oe, _, _ in e.spec.order_by))
    if isinstance(e, (A.InSubquery, A.QuantifiedCompare)):
        return (e.expr,)
    return ()


def _single_row_query(q: A.Query) -> bool:
    """True when the derived table is guaranteed one row: LIMIT 1 or an
    ungrouped aggregate-only projection."""
    if q.limit == 1:
        return True
    body = q.body
    if not isinstance(body, A.Select) or body.group_by is not None:
        return False

    def aggregate_valued(e) -> bool:
        if isinstance(e, A.FuncCall):
            if e.name.lower() in AGG_FUNCS:
                return True
            return bool(e.args) and all(aggregate_valued(a)
                                        for a in e.args)
        if isinstance(e, (A.Literal, A.DateLiteral, A.IntervalLiteral)):
            return True
        if isinstance(e, A.BinaryOp):
            return aggregate_valued(e.left) and aggregate_valued(e.right)
        if isinstance(e, A.UnaryOp):
            return aggregate_valued(e.operand)
        if isinstance(e, A.Cast):
            return aggregate_valued(e.expr)
        return False

    def has_aggregate(e) -> bool:
        if isinstance(e, A.FuncCall) and e.name.lower() in AGG_FUNCS:
            return True
        return any(has_aggregate(c) for c in _children(e))

    # every item aggregate-valued is not enough: a constants-only
    # projection (select 1 from t) is one row PER INPUT ROW — at least one
    # real aggregate is what collapses the select to a single row
    items = body.items
    return bool(items) and all(
        not isinstance(i.expr, A.Star) and aggregate_valued(i.expr)
        for i in items) and any(has_aggregate(i.expr) for i in items)


def _describe(e) -> str:
    k = expr_key(e)
    return k if len(k) <= 60 else k[:57] + "..."


# ---------------------------------------------------------------------------
# corpus driver
# ---------------------------------------------------------------------------

# fixed seed: findings must not depend on sampled parameter values, but a
# pinned instantiation keeps the baseline and CI gate deterministic anyway
_AUDIT_SEED = 20260803


def audit_template_text(text: str, file: str,
                        auditor: PlanAuditor | None = None) -> list:
    """Instantiate one template (pinned seed) and audit each statement."""
    auditor = auditor or PlanAuditor()
    sql = instantiate_template(text, np.random.default_rng(_AUDIT_SEED))
    stmts = [s for s in sql.split(";") if s.strip()]
    out = []
    base = os.path.basename(file)
    for i, stmt in enumerate(stmts):
        qname = base[:-4] if base.endswith(".tpl") else base
        if len(stmts) > 1:
            qname = f"{qname}_part{i + 1}"
        out.extend(auditor.audit_sql(stmt, file=base, query=qname))
    return out


def audit_corpus(template_dir: str | None = None) -> list:
    """Audit every template in templates.lst order; returns all findings."""
    template_dir = template_dir or TEMPLATE_DIR
    auditor = PlanAuditor()
    findings: list = []
    for name in list_templates(template_dir):
        findings.extend(audit_template_text(
            load_template(name, template_dir), name, auditor))
    return findings
