# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Fail-fast precondition gates shared by every driver CLI.

Mirrors the reference's sanity toolbox (ref: nds/check.py:38-152): version
gate, build-artifact discovery, range/parallel argparse validators, and
output-folder protection — adapted to the TPU build (the native generator is
``native/ndsgen/ndsgen`` instead of the Hadoop jar + dsdgen pair, but the
user-supplied patched TPC-DS toolkit is honoured when present).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

MIN_PYTHON = (3, 8)


def check_version(min_version=MIN_PYTHON) -> None:
    """Abort on interpreters older than we support (ref: nds/check.py:38-44)."""
    if sys.version_info < min_version:
        raise RuntimeError(
            f"Python {min_version[0]}.{min_version[1]}+ required, "
            f"found {sys.version_info.major}.{sys.version_info.minor}"
        )


def repo_root() -> Path:
    return Path(__file__).resolve().parent.parent


def check_build_ndsgen() -> Path:
    """Locate the built native data generator (ref: nds/check.py:47-66).

    Looks for the in-tree C++ generator first, then a user-supplied TPC-DS
    toolkit via $TPCDS_HOME (the spec-mandated dsdgen, used when bit-parity
    with reference data is required).
    """
    native = repo_root() / "native" / "ndsgen" / "ndsgen"
    if not native.is_file():
        # build from the checked-in source on demand (no prebuilt binary
        # ships in the repo — it would be unreviewable and could drift);
        # a host without make falls through to the $TPCDS_HOME toolkit
        import subprocess
        build_failed = False
        try:
            build = subprocess.run(["make", "-C", str(native.parent)],
                                   capture_output=True, text=True)
            if build.returncode:
                # never run whatever a failed build left behind — fall
                # through to the $TPCDS_HOME toolkit instead
                build_failed = True
                print(f"ndsgen build failed (make exited {build.returncode}):\n"
                      f"{build.stderr.strip()}")
        except OSError:
            pass
        if build_failed:
            native = native / "unbuilt"  # guaranteed not a file
    if native.is_file() and os.access(native, os.X_OK):
        return native
    tpcds_home = os.environ.get("TPCDS_HOME")
    if tpcds_home:
        dsdgen = Path(tpcds_home) / "tools" / "dsdgen"
        if dsdgen.is_file():
            return dsdgen
    raise RuntimeError(
        "native data generator not built. Run `make -C native/ndsgen` "
        "(or set $TPCDS_HOME to a patched TPC-DS v3.2.0 toolkit)."
    )


def get_abs_path(p: str) -> str:
    """Driver args may be relative; all subprocess work uses absolute paths
    (ref: nds/check.py:69-78)."""
    return str(Path(p).expanduser().resolve())


def valid_range(range_str: str, parallel: int):
    """Validate ``--range a,b`` against ``--parallel`` (ref: nds/check.py:88-106)."""
    try:
        start, end = map(int, range_str.split(","))
    except Exception:
        raise argparse.ArgumentTypeError(
            f"invalid range: {range_str!r}; expected 'start,end'"
        )
    if not (1 <= start <= end <= parallel):
        raise argparse.ArgumentTypeError(
            f"range {range_str!r} out of bounds for parallel={parallel}"
        )
    return start, end


def parallel_value(v: str) -> int:
    """argparse type for ``--parallel`` (ref: nds/check.py:109-118)."""
    try:
        n = int(v)
    except ValueError:
        raise argparse.ArgumentTypeError(f"{v!r} is not an int")
    if n < 2:
        raise argparse.ArgumentTypeError("parallel must be >= 2")
    return n


def positive_int(v: str) -> int:
    try:
        n = int(v)
    except ValueError:
        raise argparse.ArgumentTypeError(f"{v!r} is not an int")
    if n <= 0:
        raise argparse.ArgumentTypeError("value must be positive")
    return n


def get_dir_size(d: str) -> int:
    """Recursive byte size of a directory (ref: nds/check.py:121-133)."""
    total = 0
    for root, _dirs, files in os.walk(d):
        for f in files:
            fp = os.path.join(root, f)
            if not os.path.islink(fp):
                total += os.path.getsize(fp)
    return total


def check_json_summary_folder(folder: str | None) -> None:
    """Refuse to mix new JSON summaries into a non-empty folder
    (ref: nds/check.py:136-145)."""
    if folder is None:
        return
    if os.path.exists(folder):
        if os.listdir(folder):
            raise RuntimeError(
                f"json_summary_folder {folder!r} is not empty. "
                "Use a clean folder per run."
            )
    else:
        os.makedirs(folder)


def check_query_subset_exists(query_dict, subset) -> bool:
    """Every requested --sub_queries name must exist in the parsed stream
    (ref: nds/check.py:147-152)."""
    for q in subset:
        if q not in query_dict:
            raise RuntimeError(f"query {q!r} not found in query stream")
    return True
