# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Columnar execution engine: the TPU-native replacement for the role the
RAPIDS SQL plugin plays in the reference stack (SURVEY.md §2.2 N4).

Tables live on device as JAX arrays — one array per column plus a validity
mask; strings are dictionary-encoded (int32 codes on device, values on host);
decimals are int64 scaled fixed point (exact arithmetic on the integer path);
dates are int32 days-since-epoch. Relational operators (filter, project,
hash/sort aggregate, join, sort, window) are built from XLA-friendly
primitives: lexsort, segment reductions, searchsorted probes, gathers.
"""

from nds_tpu.engine.column import Column, from_arrow, to_arrow  # noqa: F401
from nds_tpu.engine.table import DeviceTable  # noqa: F401
