# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Device column representation and arrow <-> device conversion.

Device kinds (see :func:`nds_tpu.types.device_kind`):

    i32 / i64   plain integers                  -> int32 / int64 arrays
    f64         doubles                         -> float64 arrays
    date        calendar dates                  -> int32 days-since-epoch
    dec(P,S)    decimals                        -> int64 scaled by 10**S
    str         char/varchar/string             -> int32 dictionary codes +
                                                   host-side value table
    bool        intermediate predicates         -> bool arrays

Null handling: every column optionally carries a ``valid`` bool mask; ``None``
means all-valid. Data under invalid slots is zeroed so reductions can run
unmasked where the zero is the identity.

Encoded columns: the streamed chunk path (``ChunkedTable.padded_chunks``)
may upload int/date/decimal columns in a NARROW encoded representation —
frame-of-reference offsets from a per-table base (``logical = base +
stored``) or sorted-dictionary codes (``logical = values[stored]``) —
carried by :class:`Encoding` on ``Column.enc``. Both encodings are
order-preserving, so predicates and join keys can evaluate directly on
encoded values (constants fold to encoded space at trace time); any
consumer that needs the logical values calls :meth:`Column.plain`, a
fused elementwise decode inside the jit program (zero host syncs).
Decode to arrow happens at materialize, mirroring ``dict_values[codes]``.
"""

from __future__ import annotations

import functools
import re
from dataclasses import dataclass, replace

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

_DEC_KIND_RE = re.compile(r"dec\((\d+),(\d+)\)")


@dataclass
class Encoding:
    """Narrow device representation of an int-path column.

    ``mode`` "for": stored codes are offsets from ``base`` — logical value
    = ``base + stored``. ``mode`` "dict": stored codes index the SORTED
    host-side ``values`` table — logical value = ``values[stored]``. Both
    are order-preserving (dict values are sorted ascending), which is
    what lets comparisons run in encoded space. Like a string column's
    ``dict_values``, the encoding is host metadata shared identically by
    every chunk of a table (chunk-invariant: a cache-key member)."""

    mode: str                        # "for" | "dict"
    base: int = 0                    # FOR: logical = base + stored
    values: np.ndarray | None = None  # dict: sorted logical values (host)


def encs_equal(a: Encoding | None, b: Encoding | None) -> bool:
    """Value equality of two encodings (identity fast path) — the test
    cached compiled programs apply before serving differently-encoded
    buffers (mirrors ``stream._dicts_equal`` for string dictionaries)."""
    if a is None or b is None:
        return a is b
    if a is b:
        return True
    if a.mode != b.mode or a.base != b.base:
        return False
    if a.values is None or b.values is None:
        return a.values is b.values
    return a.values is b.values or np.array_equal(a.values, b.values)


def enc_key(enc: Encoding | None):
    """Hashable cache-key signature of an encoding (value tables are
    validated separately by identity/content, like string dictionaries)."""
    if enc is None:
        return None
    return (enc.mode, enc.base,
            None if enc.values is None else len(enc.values))


# logical (decoded) dtype per device kind — what plain() widens to
_WIDE_DTYPES = {"i32": "int32", "date": "int32", "i64": "int64",
                "bool": "bool", "f64": "float64"}


def _wide_dtype(kind: str):
    if kind.startswith("dec("):
        return np.dtype("int64")
    return np.dtype(_WIDE_DTYPES.get(kind, "int64"))


def dec_scale(kind: str) -> int:
    m = _DEC_KIND_RE.match(kind)
    if not m:
        raise ValueError(f"not a decimal kind: {kind}")
    return int(m.group(2))


def dec_precision(kind: str) -> int:
    return int(_DEC_KIND_RE.match(kind).group(1))


def is_dec(kind: str) -> bool:
    return kind.startswith("dec(")


@dataclass
class Column:
    kind: str
    data: jnp.ndarray
    valid: jnp.ndarray | None = None          # bool mask; None = all valid
    dict_values: np.ndarray | None = None     # host-side strings for kind 'str'
    enc: Encoding | None = None               # narrow encoded representation

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def plain(self) -> "Column":
        """Decode an encoded column to its logical (wide) representation.
        A fused elementwise device op — inside a jit program this costs
        nothing extra and never syncs. Invalid slots are re-zeroed to
        preserve the zero-under-null invariant (an encoded 0 decodes to
        ``base``, not 0)."""
        if self.enc is None:
            return self
        wide = _wide_dtype(self.kind)
        if self.enc.mode == "for":
            data = self.data.astype(wide) + jnp.asarray(self.enc.base,
                                                        dtype=wide)
        else:                                  # "dict": sorted value table
            data = jnp.take(jnp.asarray(self.enc.values.astype(wide)),
                            self.data, mode="clip")
        if self.valid is not None:
            data = jnp.where(self.valid, data, jnp.zeros((), dtype=wide))
        return replace(self, data=data, enc=None)

    @property
    def scale(self) -> int:
        return dec_scale(self.kind) if is_dec(self.kind) else 0

    def valid_mask(self) -> jnp.ndarray:
        """Materialized validity mask."""
        if self.valid is None:
            return jnp.ones(self.data.shape[0], dtype=bool)
        return self.valid

    def null_count(self, nrows=None) -> int:
        """Nulls among the first ``nrows`` rows (pass the table's logical
        count — host int or DeviceCount — for a bucket-padded column; pad
        slots carry garbage validity). This is a host read: it syncs, and
        the sync is counted. Inside a stream-bounds region the value is a
        RECORDED scalar with a device-side staleness guard
        (:func:`ops.guarded_scalar_read`): the first chunk's count replays
        for every chunk, and any chunk whose live count differs flips the
        pipeline's overflow flag (eager rerun) instead of silently using a
        stale decision — the `chunk-dependent-host-read` conversion."""
        if self.valid is None:
            return 0
        from nds_tpu.engine import ops as _ops
        invalid = ~self.valid
        # mask pads whenever the actual count may be below the physical
        # length (always for a device count: its bound can equal plen while
        # the true count is lower — pad slots carry cloned garbage validity)
        if nrows is not None and (
                isinstance(nrows, _ops.DeviceCount)
                or int(nrows) < int(self.data.shape[0])):
            invalid = invalid & (
                jnp.arange(self.data.shape[0]) < _ops.count_arr(nrows))
        return _ops.guarded_scalar_read("null_count", jnp.sum(invalid))

    def take(self, indices) -> "Column":
        # clip mode: out-of-range pad indices duplicate a real row, so pad
        # slots never hold values outside the column's domain (dict codes
        # stay in range, host-side conversions stay safe)
        return replace(
            self,
            data=jnp.take(self.data, indices, axis=0, mode="clip"),
            valid=None if self.valid is None else jnp.take(
                self.valid, indices, axis=0, mode="clip"),
        )

    def with_valid(self, valid) -> "Column":
        """Attach a (possibly combined) validity mask, zeroing masked slots."""
        if valid is None:
            return self
        data = jnp.where(valid, self.data, jnp.zeros((), dtype=self.data.dtype))
        return replace(self, data=data, valid=valid)


# ---------------------------------------------------------------------------
# arrow -> device
# ---------------------------------------------------------------------------

_NUMERIC_DTYPES = {
    "i32": jnp.int32,
    "i64": jnp.int64,
    "f64": jnp.float64,
    "date": jnp.int32,
    "bool": jnp.bool_,
}


def _decimal_to_int64(arr: pa.ChunkedArray, s: int, target_scale: int) -> np.ndarray:
    """decimal128(p, s) -> int64 of value * 10**target_scale, exactly.

    Reads the unscaled int128 values straight out of the arrow buffer (low
    word is the exact value while it fits in int64, which every schema decimal
    does) and rescales in integer arithmetic.
    """
    out = np.empty(len(arr), dtype=np.int64)
    pos = 0
    for chunk in arr.chunks:
        n = len(chunk)
        buf = chunk.buffers()[1]
        raw = np.frombuffer(buf, dtype="<i8")
        lo = raw[2 * chunk.offset: 2 * (chunk.offset + n): 2]
        out[pos:pos + n] = lo
        pos += n
    if target_scale > s:
        out = out * (10 ** (target_scale - s))
    elif target_scale < s:
        out = out // (10 ** (s - target_scale))
    return out


def _bucket_pad(a: np.ndarray, cap: int):
    """Zero-pad a host array to the bucket capacity (the padded-prefix
    invariant: rows past the logical count are ignored garbage). Padding on
    host keeps raw table lengths out of the device shape universe, so every
    XLA executable is keyed by a power-of-two bucket."""
    n = a.shape[0]
    if n >= cap:
        return a
    return np.concatenate([a, np.zeros(cap - n, dtype=a.dtype)])


def from_arrow_array(arr, canonical_type: str, cap: int | None = None) -> Column:
    """One arrow column (Array or ChunkedArray) -> device Column, physically
    padded to ``cap`` rows when given."""
    from nds_tpu import types as _t

    if isinstance(arr, pa.Array):
        arr = pa.chunked_array([arr])
    kind = _t.device_kind(canonical_type)
    n = len(arr)
    if cap is None:
        cap = n
    null_count = arr.null_count
    valid_np = None
    if null_count:
        valid_np = _bucket_pad(
            ~np.asarray(pc.is_null(arr).combine_chunks().to_numpy(zero_copy_only=False)),
            cap)

    if kind == "str":
        if not pa.types.is_dictionary(arr.type):
            arr = pc.dictionary_encode(arr)
        combined = arr.combine_chunks()
        if isinstance(combined, pa.ChunkedArray):
            combined = combined.chunk(0) if combined.num_chunks else pa.array(
                [], type=combined.type)
        codes_arr = combined.indices
        if null_count:
            codes_arr = pc.fill_null(codes_arr, 0)
        codes = np.asarray(codes_arr.to_numpy(zero_copy_only=False), dtype=np.int32)
        values = np.asarray(combined.dictionary.to_pylist(), dtype=object)
        if values.size == 0:
            values = np.asarray([""], dtype=object)
            codes = np.zeros(n, dtype=np.int32)
        col = Column("str", jnp.asarray(_bucket_pad(codes, cap)),
                     None if valid_np is None else jnp.asarray(valid_np), values)
        return col

    if kind.startswith("dec("):
        s = dec_scale(kind)
        if pa.types.is_decimal(arr.type):
            filled = pc.fill_null(arr, pa.scalar(0, arr.type)) if null_count else arr
            data_np = _decimal_to_int64(filled, arr.type.scale, s)
        else:  # e.g. float column being treated as decimal
            data_np = np.asarray(pc.fill_null(arr, 0).combine_chunks().to_numpy(
                zero_copy_only=False))
            data_np = np.round(data_np * (10 ** s)).astype(np.int64)
        return Column(kind, jnp.asarray(_bucket_pad(data_np, cap)),
                      None if valid_np is None else jnp.asarray(valid_np))

    # plain numeric / date / bool
    if kind == "date":
        arr = pc.cast(arr, pa.int32())
    filled = pc.fill_null(arr, 0) if null_count else arr
    np_arr = np.asarray(filled.combine_chunks().to_numpy(zero_copy_only=False))
    data = jnp.asarray(_bucket_pad(np_arr.astype(_NUMERIC_DTYPES[kind]), cap))
    return Column(kind, data, None if valid_np is None else jnp.asarray(valid_np))


def from_arrow(table: pa.Table, canonical_types: dict | None = None):
    """arrow Table -> {name: Column}. ``canonical_types`` overrides the
    per-column canonical type (defaults to inference from arrow types).
    Columns are physically padded to the power-of-two bucket (padded-prefix
    invariant) so base-table shapes reuse the same XLA executables as every
    intermediate."""
    from nds_tpu import types as _t
    from nds_tpu.engine.ops import bucket_len
    from nds_tpu.engine.table import DeviceTable

    cap = bucket_len(table.num_rows)
    cols = {}
    for name in table.column_names:
        ct = (canonical_types or {}).get(name) or _t.arrow_to_canonical(
            table.schema.field(name).type)
        cols[name] = from_arrow_array(table[name], ct, cap)
    return DeviceTable(cols, table.num_rows)


# ---------------------------------------------------------------------------
# device -> arrow
# ---------------------------------------------------------------------------

def _slice_col(col: Column, nrows: int | None) -> Column:
    """Slice off the bucket-padding suffix (padded-prefix invariant)."""
    if nrows is None or nrows >= col.data.shape[0]:
        return col
    return replace(col, data=col.data[:nrows],
                   valid=None if col.valid is None else col.valid[:nrows])


def slice_col_prefix(col: Column, cap: int) -> Column:
    """Public prefix slice — re-bucketing a lazily-compacted column down to
    a resolved tight capacity (see ``ops.resolve_table``)."""
    return _slice_col(col, cap)


def _decode_host(col: Column) -> Column:
    """Host-side decode of an encoded column whose data is already a
    fetched numpy array (materialize path): the device->host transfer
    moved the NARROW codes, and the widening happens here — the exact
    analogue of ``dict_values[codes]`` for strings."""
    if col.enc is None:
        return col
    wide = _wide_dtype(col.kind)
    codes = np.asarray(col.data)
    if col.enc.mode == "for":
        data = codes.astype(wide) + wide.type(col.enc.base)
    else:
        data = col.enc.values.astype(wide)[
            np.clip(codes, 0, len(col.enc.values) - 1)]
    if col.valid is not None:
        data = np.where(np.asarray(col.valid), data,
                        np.zeros((), dtype=wide))
    return replace(col, data=data, enc=None)


def column_to_arrow(col: Column, nrows: int | None = None) -> pa.Array:
    """Device -> arrow; ``nrows`` drops the padding before the transfer.
    Encoded columns decode on HOST after the fetch, so the transfer moves
    the narrow representation."""
    col = _slice_col(col, nrows)
    if not isinstance(col.data, np.ndarray):     # not already fetched
        col = _fetch_columns([col])[0]
    col = _decode_host(col)
    valid_np = None if col.valid is None else np.asarray(col.valid)

    if col.kind == "str":
        codes = np.asarray(col.data)
        out = col.dict_values[codes]
        mask = None if valid_np is None else ~valid_np
        return pa.array(out, type=pa.string(), mask=mask)

    data_np = np.asarray(col.data)
    mask = None if valid_np is None else ~valid_np
    if col.kind == "date":
        return pa.array(data_np.astype("int32"), type=pa.int32(), mask=mask).cast(pa.date32())
    if is_dec(col.kind):
        s = dec_scale(col.kind)
        # reinterpret the int64 fixed-point values as decimal128(38, s) by
        # building the 128-bit little-endian buffer directly (a cast would
        # multiply by 10**s instead of reinterpreting)
        lo = data_np.astype(np.int64)
        n = lo.shape[0]
        buf = np.empty((n, 2), dtype=np.int64)
        buf[:, 0] = lo
        buf[:, 1] = np.where(lo < 0, -1, 0)
        arr = pa.Array.from_buffers(
            pa.decimal128(38, s), n, [None, pa.py_buffer(buf.tobytes())])
        if valid_np is not None:
            arr = pc.if_else(pa.array(valid_np), arr, pa.scalar(None, arr.type))
        return arr
    pa_type = {
        "i32": pa.int32(), "i64": pa.int64(), "f64": pa.float64(), "bool": pa.bool_(),
    }[col.kind]
    return pa.array(data_np, type=pa_type, mask=mask)


@functools.lru_cache(maxsize=8)
def _replicator(mesh):
    """One cached jitted identity-with-replicated-output per mesh, so
    multi-host fetches retrace once instead of per column per collect."""
    import jax

    from jax.sharding import NamedSharding, PartitionSpec
    rep = NamedSharding(mesh, PartitionSpec())
    return jax.jit(lambda a: a, out_shardings=rep)


def _fetch_columns(cols):
    """Materialize device buffers on host in ONE transfer round trip
    (``jax.device_get`` of the whole tree), returning Columns whose
    data/valid are host numpy arrays. Blocked time and bytes feed the
    per-query roofline accounting (ops.sync_wait_ns / fetch_bytes)."""
    import time as _time

    import jax

    from nds_tpu.engine import ops as _ops

    tree = [(c.data, c.valid) for c in cols]
    t0 = _time.perf_counter_ns()

    def _addressable(x):
        if x is None or isinstance(x, np.ndarray):
            return x
        if getattr(x, "is_fully_addressable", True):
            return x
        # multi-controller federation: shards live on other processes'
        # devices; an explicit replicate (all-gather over DCN) makes the
        # value locally readable — the multi-host leg of collect()
        return _replicator(x.sharding.mesh)(x)

    tree = [(_addressable(d), _addressable(v)) for d, v in tree]
    fetched = jax.device_get(tree)
    _ops.add_sync_wait(_time.perf_counter_ns() - t0)
    _ops.add_fetch_bytes(sum(
        d.nbytes + (0 if v is None else v.nbytes) for d, v in fetched))
    return [replace(c, data=d, valid=v)
            for c, (d, v) in zip(cols, fetched)]


def to_arrow(dt) -> pa.Table:
    """DeviceTable -> arrow Table. Crossing to host is THE legitimate
    resolve point for a lazy count (DESIGN.md item 1)."""
    from nds_tpu.engine import ops as _ops
    nrows = _ops.count_int(dt.nrows)
    cols = [_slice_col(c, nrows) for c in dt.columns.values()]
    cols = _fetch_columns(cols)   # one device->host round trip for the table
    arrays = [column_to_arrow(c) for c in cols]
    return pa.table(arrays, names=list(dt.columns.keys()))
