# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Scalar expression kernels over device columns.

SQL three-valued logic: every kernel combines operand validity into the
result's validity; AND/OR implement Kleene logic. Decimal arithmetic stays on
the exact int64 fixed-point path (scales align for +/-, add for *), spilling
to float64 for division and for scale overflow. String predicates evaluate
once per distinct dictionary value on host, then map through the device codes
— the dictionary is orders of magnitude smaller than the column.
"""

from __future__ import annotations

import re
import threading

import jax.numpy as jnp
import numpy as np

from nds_tpu.engine.column import Column, encs_equal, is_dec
from nds_tpu.engine.ops import ordered_codes_merged, plain_col

_MAX_DEC_SCALE = 10
# dictionary memos (literal dictionaries + per-tag _map_dict caches):
# concurrent Throughput streams evaluate expressions at once, and
# identity-keyed caches downstream need ONE winner per key — mutations
# take the dedicated lock, setdefault keeps the first insert.
_str_literal_dicts: dict = {}
_DICT_MEMO_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# literals / lifting
# ---------------------------------------------------------------------------

# Parameter-binding context: inside a compiled replay, audited-bindable
# WHERE literals are served from jit operands instead of trace constants
# (one compile, many parameter vectors — see analysis/param_audit).  The
# map is keyed by id(Literal AST node): stream.StreamPipeline keeps the
# build statement's slot nodes alive for the life of the cached program.
_PARAM_TL = threading.local()


class param_binding:
    """Context manager installing ``{id(node): (typetag, operand)}`` for
    the planner's Literal arm to consult (thread-local, nestable)."""

    def __init__(self, bindings: dict):
        self._bindings = bindings

    def __enter__(self):
        prev = getattr(_PARAM_TL, "bindings", None)
        self._prev = prev
        _PARAM_TL.bindings = self._bindings
        return self

    def __exit__(self, *exc):
        _PARAM_TL.bindings = self._prev
        return False


def param_bindings_active() -> bool:
    """True inside a compiled replay that carries bound-literal operands.
    The planner's expression-fusion caches must stand down then: a fused
    program is keyed by ``expr_key`` (which serializes literal VALUES)
    and traced once — serving it inside the pipeline trace would inline
    the RECORD phase's baked constants past the binding. Inside the
    pipeline's jit the fused dispatch is inlined anyway, so evaluating
    eagerly there costs nothing at drive time."""
    return bool(getattr(_PARAM_TL, "bindings", None))


def bound_literal(e, n: int) -> Column | None:
    """The operand-backed Column for a bound Literal node, or None when
    no binding is active for it (the planner then bakes the value as a
    trace constant, today's behaviour)."""
    bindings = getattr(_PARAM_TL, "bindings", None)
    if not bindings:
        return None
    hit = bindings.get(id(e))
    if hit is None:
        return None
    tag, arr = hit
    if tag == "i64":
        return Column("i64", jnp.broadcast_to(
            jnp.asarray(arr, dtype=jnp.int64), (n,)))
    if tag == "f64":
        return Column("f64", jnp.broadcast_to(
            jnp.asarray(arr, dtype=jnp.float64), (n,)))
    s = int(tag.split(":")[1])           # "dec:<scale>" pre-scaled int
    return Column(f"dec(38,{s})", jnp.broadcast_to(
        jnp.asarray(arr, dtype=jnp.int64), (n,)))


def literal(value, n: int) -> Column:
    """Python literal -> broadcast Column of length n."""
    if value is None:
        return Column("i32", jnp.zeros(n, dtype=jnp.int32), jnp.zeros(n, dtype=bool))
    if isinstance(value, bool):
        return Column("bool", jnp.full(n, value, dtype=bool))
    if isinstance(value, int):
        return Column("i64", jnp.full(n, value, dtype=jnp.int64))
    if isinstance(value, float):
        return Column("f64", jnp.full(n, value, dtype=jnp.float64))
    if isinstance(value, str):
        # per-value dictionary cache: identity-keyed caches downstream
        # (expression fusion) need the same host object on every execution.
        # Bounded FIFO like the engine's other dictionary caches.
        d = _str_literal_dicts.get(value)
        if d is None:
            built = np.asarray([value], dtype=object)
            with _DICT_MEMO_LOCK:
                if len(_str_literal_dicts) >= 4096:
                    _str_literal_dicts.pop(next(iter(_str_literal_dicts)))
                d = _str_literal_dicts.setdefault(value, built)
        return Column("str", jnp.zeros(n, dtype=jnp.int32), None, d)
    if type(value).__name__ == "Decimal":
        s = -value.as_tuple().exponent
        s = max(0, s)
        return Column(f"dec(38,{s})",
                      jnp.full(n, int(value.scaleb(s)), dtype=jnp.int64))
    raise TypeError(f"unsupported literal: {value!r}")


# ---------------------------------------------------------------------------
# numeric coercion
# ---------------------------------------------------------------------------


# the scalar kernels funnel value consumption through the ONE decode
# choke point (ops.plain_col); comparisons keep a fast path that stays
# in encoded space (see compare)
_plain = plain_col


def _as_f64(col: Column) -> jnp.ndarray:
    col = _plain(col)
    d = col.data.astype(jnp.float64)
    if is_dec(col.kind):
        d = d / (10.0 ** col.scale)
    return d


def _combine_valid(a: Column, b: Column):
    if a.valid is None and b.valid is None:
        return None
    return a.valid_mask() & b.valid_mask()


def _align_decimals(a: Column, b: Column):
    """Bring two int-path numeric columns to a common scale."""
    a, b = _plain(a), _plain(b)
    sa, sb = a.scale, b.scale
    s = max(sa, sb)
    da = a.data.astype(jnp.int64) * (10 ** (s - sa))
    db = b.data.astype(jnp.int64) * (10 ** (s - sb))
    return da, db, s


def _int_path(col: Column) -> bool:
    return col.kind in ("i32", "i64", "date", "bool") or is_dec(col.kind)


def arith(op: str, a: Column, b: Column) -> Column:
    a, b = _plain(a), _plain(b)        # arithmetic needs logical values
    valid = _combine_valid(a, b)
    if op == "/":
        num, den = _as_f64(a), _as_f64(b)
        zero = den == 0
        out = jnp.where(zero, 0.0, num / jnp.where(zero, 1.0, den))
        v = valid if valid is not None else jnp.ones(len(a), dtype=bool)
        return Column("f64", out, v & ~zero)  # SQL: x/0 -> null (Spark semantics)
    if _int_path(a) and _int_path(b):
        if op in ("+", "-"):
            da, db, s = _align_decimals(a, b)
            out = da + db if op == "+" else da - db
            if s:
                kind = f"dec(38,{s})"
            elif (a.kind == "date") != (b.kind == "date"):
                kind = "date"       # date +/- integer days
                out = out.astype(jnp.int32)
            else:
                kind = "i64"        # incl. date - date = day count
            return Column(kind, out, valid)
        if op == "*":
            s = a.scale + b.scale
            if s <= _MAX_DEC_SCALE:
                out = a.data.astype(jnp.int64) * b.data.astype(jnp.int64)
                kind = f"dec(38,{s})" if s else "i64"
                return Column(kind, out, valid)
        if op == "%":
            da, db = a.data.astype(jnp.int64), b.data.astype(jnp.int64)
            zero = db == 0
            safe_db = jnp.where(zero, 1, db)
            out = jnp.where(zero, 0, da % safe_db)
            # SQL/Spark remainder takes the dividend's sign, not the divisor's
            out = jnp.where((out != 0) & ((out < 0) != (da < 0)),
                            out - safe_db, out)
            v = valid if valid is not None else jnp.ones(len(a), dtype=bool)
            return Column("i64", out, v & ~zero)
    # float path
    fa, fb = _as_f64(a), _as_f64(b)
    if op == "+":
        out = fa + fb
    elif op == "-":
        out = fa - fb
    elif op == "*":
        out = fa * fb
    elif op == "%":
        zero = fb == 0
        # fmod (C semantics: dividend's sign) matches Spark's % on doubles
        out = jnp.where(zero, 0.0, jnp.fmod(fa, jnp.where(zero, 1.0, fb)))
        v = valid if valid is not None else jnp.ones(len(a), dtype=bool)
        return Column("f64", out, v & ~zero)
    else:
        raise ValueError(f"unknown arith op {op}")
    return Column("f64", out, valid)


def negate(a: Column) -> Column:
    a = _plain(a)
    if a.kind == "f64":
        return Column("f64", -a.data, a.valid)
    return Column(a.kind if is_dec(a.kind) else "i64",
                  -a.data.astype(jnp.int64), a.valid)


# ---------------------------------------------------------------------------
# comparisons
# ---------------------------------------------------------------------------


def _encoded_compare_views(a: Column, b: Column):
    """Encoded-space comparison views, or None when the pair must decode.

    Both FOR and sorted-dict encodings are order-preserving, so two sides
    sharing ONE encoding compare by raw codes. For a FOR side against a
    plain int-path side at the same scale, the comparison rebases the
    PLAIN side into the encoded space (``code op (other - base)``) — when
    the other side is a broadcast literal the subtraction folds to a
    constant at trace time, so the predicate runs entirely on the narrow
    encoded column."""
    if a.enc is not None and b.enc is not None:
        # same encoding AND same scale: codes of a dec(7,2) and an int
        # column can share (mode, base) while meaning values 100x apart,
        # so scale must align exactly like _align_decimals would
        if encs_equal(a.enc, b.enc) and a.scale == b.scale:
            return a.data.astype(jnp.int64), b.data.astype(jnp.int64)
        return None
    enc_side, plain_side = (a, b) if a.enc is not None else (b, a)
    if enc_side.enc.mode != "for" or plain_side.enc is not None or \
            enc_side.scale != plain_side.scale or plain_side.kind == "f64":
        return None
    base_i = int(enc_side.enc.base)
    ev = enc_side.data.astype(jnp.int64)
    raw = plain_side.data.astype(jnp.int64)
    diff = raw - jnp.int64(base_i)
    # The rebase must SATURATE, not wrap: a plain value near ±2^63 with an
    # opposite-signed base overflows int64 and lands back inside the code
    # window with every comparison inverted. The base is a host int, so
    # only one wrap direction is possible per trace: with base < 0 the
    # subtraction can only wrap upward (raw > 0 yet diff < 0), with
    # base > 0 only downward (raw < 0 yet diff > 0). Wrapped values and
    # all out-of-window values pin to the sentinels -1 / code_max + 1,
    # strictly outside the code range [0, span] — every comparison
    # against any code keeps its exact truth value.
    code_max = jnp.int64((1 << 15) - 1 if enc_side.data.dtype == jnp.int16
                         else (1 << 31) - 1)
    if base_i < 0:
        diff = jnp.where((raw > 0) & (diff < 0), code_max + 1, diff)
    elif base_i > 0:
        diff = jnp.where((raw < 0) & (diff > 0), jnp.int64(-1), diff)
    pv = jnp.clip(diff, jnp.int64(-1), code_max + 1)
    return (ev, pv) if enc_side is a else (pv, ev)


def compare(op: str, a: Column, b: Column) -> Column:
    valid = _combine_valid(a, b)
    if a.kind == "str" or b.kind == "str":
        if a.kind == "str" and b.kind == "str":
            la, lb = ordered_codes_merged(a, b)
        else:
            raise TypeError("cannot compare string with non-string")
        da, db = la, lb
    elif _int_path(a) and _int_path(b):
        views = _encoded_compare_views(a, b) \
            if (a.enc is not None or b.enc is not None) else None
        if views is not None:
            da, db = views
        else:
            da, db, _ = _align_decimals(a, b)
    else:
        da, db = _as_f64(a), _as_f64(b)
    out = {
        "=": lambda: da == db,
        "<>": lambda: da != db,
        "<": lambda: da < db,
        "<=": lambda: da <= db,
        ">": lambda: da > db,
        ">=": lambda: da >= db,
    }[op]()
    return Column("bool", out, valid)


def is_null(a: Column, negate_: bool = False) -> Column:
    m = ~a.valid_mask() if not negate_ else a.valid_mask()
    return Column("bool", m)


# ---------------------------------------------------------------------------
# Kleene boolean logic
# ---------------------------------------------------------------------------


def logical_and(a: Column, b: Column) -> Column:
    av, bv = a.valid_mask(), b.valid_mask()
    ad, bd = a.data.astype(bool), b.data.astype(bool)
    data = ad & bd
    false_a = av & ~ad
    false_b = bv & ~bd
    valid = (av & bv) | false_a | false_b
    return Column("bool", data, valid)


def logical_or(a: Column, b: Column) -> Column:
    av, bv = a.valid_mask(), b.valid_mask()
    ad, bd = a.data.astype(bool), b.data.astype(bool)
    data = (av & ad) | (bv & bd)
    true_a = av & ad
    true_b = bv & bd
    valid = (av & bv) | true_a | true_b
    return Column("bool", data, valid)


def logical_not(a: Column) -> Column:
    return Column("bool", ~a.data.astype(bool), a.valid)


# ---------------------------------------------------------------------------
# conditionals
# ---------------------------------------------------------------------------


def _unify(cols):
    """Bring branch results to one kind (for CASE/COALESCE/IF)."""
    cols = [_plain(c) for c in cols]
    kinds = {c.kind for c in cols}
    if len(kinds) == 1 and "str" not in kinds:
        return cols, cols[0].kind
    if kinds == {"str"}:
        return cols, "str"
    if "str" in kinds:
        # null literals come through as i32; rewrite them as empty-string nulls
        fixed = []
        str_dict = next(c.dict_values for c in cols if c.kind == "str")
        for c in cols:
            if c.kind == "str":
                fixed.append(c)
            else:
                fixed.append(Column("str", jnp.zeros(len(c), dtype=jnp.int32),
                                    jnp.zeros(len(c), dtype=bool), str_dict))
        return fixed, "str"
    scales = {c.scale for c in cols if is_dec(c.kind)}
    if scales and all(_int_path(c) for c in cols):
        s = max(scales)
        fixed = [Column(f"dec(38,{s})",
                        c.data.astype(jnp.int64) * (10 ** (s - c.scale)), c.valid)
                 for c in cols]
        return fixed, f"dec(38,{s})"
    if kinds <= {"i32", "i64", "date", "bool"}:
        fixed = [Column("i64", c.data.astype(jnp.int64), c.valid) for c in cols]
        return fixed, "i64"
    fixed = [Column("f64", _as_f64(c), c.valid) for c in cols]
    return fixed, "f64"


def unify_columns(cols):
    """Public alias of :func:`_unify` for cross-module use (set operations
    align operand columns with it)."""
    return _unify(cols)


def case_when(branches, else_col: Column) -> Column:
    """branches: [(cond Column, value Column)], evaluated first-match-wins."""
    vals = [v for _, v in branches] + [else_col]
    vals, kind = _unify(vals)
    branch_vals, else_v = vals[:-1], vals[-1]
    n = len(else_v)
    if kind == "str":
        # merge dictionaries across branches
        from nds_tpu.engine.ops import concat_columns
        merged = concat_columns([v for v in vals])
        dict_values = merged.dict_values
        datas = [merged.data[i * n:(i + 1) * n] for i in range(len(vals))]
        branch_datas, else_data = datas[:-1], datas[-1]
    else:
        dict_values = None
        branch_datas = [v.data for v in branch_vals]
        else_data = else_v.data
    out = else_data
    out_valid = else_v.valid_mask()
    taken = jnp.zeros(n, dtype=bool)
    for (cond, _), val, vdata in zip(branches, branch_vals, branch_datas):
        c = cond.data.astype(bool) & cond.valid_mask() & ~taken
        out = jnp.where(c, vdata, out)
        out_valid = jnp.where(c, val.valid_mask(), out_valid)
        taken = taken | c
    return Column(kind, out, out_valid, dict_values)


def coalesce(cols) -> Column:
    n = len(cols[0])
    branches = [(is_null(c, negate_=True), c) for c in cols[:-1]]
    return case_when(branches, cols[-1]) if len(cols) > 1 else cols[0]


# ---------------------------------------------------------------------------
# casts
# ---------------------------------------------------------------------------


def cast(col: Column, target: str) -> Column:
    """target: canonical-ish SQL type name (int, bigint, double, decimal(p,s),
    date, string, char(n), varchar(n))."""
    col = _plain(col)
    t = target.lower().replace(" ", "")
    if t in ("int", "integer", "i32"):
        if col.kind == "str":
            vals = np.asarray(
                [int(v) if _is_intstr(v) else 0 for v in col.dict_values])
            ok = np.asarray([_is_intstr(v) for v in col.dict_values])
            data = jnp.take(jnp.asarray(vals), col.data)
            valid = col.valid_mask() & jnp.take(jnp.asarray(ok), col.data)
            return Column("i64", data, valid)
        return Column("i64", _as_f64(col).astype(jnp.int64) if col.kind == "f64"
                      else (col.data.astype(jnp.int64) // (10 ** col.scale)), col.valid)
    if t in ("bigint", "long", "i64"):
        return cast(col, "int")
    if t in ("double", "float", "f64", "real"):
        return Column("f64", _as_f64(col) if col.kind != "str" else _str_to_f64(col)[0],
                      col.valid if col.kind != "str" else _str_to_f64(col)[1])
    if t.startswith("decimal("):
        p, s = t[len("decimal("):-1].split(",")
        s = int(s)
        if is_dec(col.kind) or col.kind in ("i32", "i64", "bool"):
            cs = col.scale
            if s >= cs:
                data = col.data.astype(jnp.int64) * (10 ** (s - cs))
            else:
                # round half away from zero on the dropped digits
                f = 10 ** (cs - s)
                d = col.data.astype(jnp.int64)
                half = f // 2
                data = jnp.where(d >= 0, (d + half) // f, -((-d + half) // f))
            return Column(f"dec({p},{s})", data, col.valid)
        f64 = _as_f64(col)
        data = jnp.round(f64 * (10 ** s)).astype(jnp.int64)
        return Column(f"dec({p},{s})", data, col.valid)
    if t == "date":
        if col.kind == "date":
            return col
        if col.kind == "str":
            days = np.asarray([_parse_date(v) for v in col.dict_values])
            ok = days >= -(10 ** 8)
            data = jnp.take(jnp.asarray(days.astype(np.int32)), col.data)
            valid = col.valid_mask() & jnp.take(jnp.asarray(ok), col.data)
            return Column("date", data, valid)
    if t in ("string", "varchar", "char") or t.startswith(("char(", "varchar(")):
        if col.kind == "str":
            return col

        def fetch():
            # host-side dictionary build from the column values — a whole-
            # column fetch, so it routes through the trace-replay log
            vals = np.asarray(col.data)
            if is_dec(col.kind):
                s = col.scale
                strs = np.asarray([_dec_str(int(v), s) for v in vals],
                                  dtype=object)
            elif col.kind == "date":
                strs = np.asarray([_date_str(int(v)) for v in vals],
                                  dtype=object)
            else:
                strs = np.asarray([str(v) for v in vals], dtype=object)
            uniq, inv = np.unique(strs, return_inverse=True)
            return inv.astype(np.int32), uniq.astype(object)

        from nds_tpu.engine.ops import timed_read
        inv, uniq = timed_read("cast_str", fetch)
        return Column("str", jnp.asarray(inv), col.valid, uniq)
    raise ValueError(f"unsupported cast target: {target}")


def _is_intstr(v) -> bool:
    try:
        int(str(v))
        return True
    except ValueError:
        return False


def _str_to_f64(col: Column):
    def conv(v):
        try:
            return float(v)
        except ValueError:
            return np.nan
    vals = np.asarray([conv(v) for v in col.dict_values])
    data = jnp.take(jnp.asarray(vals), col.data)
    valid = col.valid_mask() & ~jnp.isnan(data)
    return data, valid


_EPOCH = np.datetime64("1970-01-01", "D")


def _parse_date(v) -> int:
    try:
        return int((np.datetime64(str(v), "D") - _EPOCH).astype(int))
    except Exception:
        return -(10 ** 9)


def _date_str(days: int) -> str:
    return str(_EPOCH + np.timedelta64(days, "D"))


def _dec_str(v: int, s: int) -> str:
    if s == 0:
        return str(v)
    sign = "-" if v < 0 else ""
    v = abs(v)
    return f"{sign}{v // 10**s}.{v % 10**s:0{s}d}"


def parse_date_literal(text: str) -> int:
    d = _parse_date(text)
    if d <= -(10 ** 8):
        raise ValueError(f"bad date literal: {text!r}")
    return d


# ---------------------------------------------------------------------------
# string functions (host-side on dictionaries)
# ---------------------------------------------------------------------------


_map_dict_cache: dict = {}


def _map_dict(col: Column, fn, tag=None) -> Column:
    """Apply a str->str function to the dictionary, re-uniquing the result.
    ``tag`` (a hashable description of ``fn``) enables caching per input
    dictionary, so repeated executions return the SAME output dictionary
    object — identity-keyed caches downstream (expression fusion) depend on
    stable dictionary identities across runs."""
    def compute():
        new_vals = np.asarray([fn(str(v)) for v in col.dict_values],
                              dtype=object)
        uniq, inv = np.unique(new_vals.astype(str), return_inverse=True)
        # cache HOST arrays only: a device constant created inside a jit
        # trace is a tracer, and caching one leaks it across traces
        return inv.astype(np.int32), uniq.astype(object)

    if tag is None:
        remap, uniq = compute()
    else:
        from nds_tpu.engine.ops import _identity_cache
        sub = _map_dict_cache.get(tag)
        if sub is None:
            with _DICT_MEMO_LOCK:
                sub = _map_dict_cache.setdefault(tag, {})
        remap, uniq = _identity_cache(sub, 256, (col.dict_values,), compute)
    return Column("str", jnp.take(jnp.asarray(remap), col.data),
                  col.valid, uniq)


def _dict_predicate(col: Column, fn) -> Column:
    mask = np.asarray([bool(fn(str(v))) for v in col.dict_values])
    data = jnp.take(jnp.asarray(mask), col.data)
    return Column("bool", data, col.valid)


def fn_substr(col: Column, start: int, length: int | None = None) -> Column:
    def f(s):
        i = start - 1 if start > 0 else len(s) + start
        return s[i:i + length] if length is not None else s[i:]
    return _map_dict(col, f, tag=("substr", start, length))


def fn_upper(col: Column) -> Column:
    return _map_dict(col, str.upper, tag=("upper",))


def fn_lower(col: Column) -> Column:
    return _map_dict(col, str.lower, tag=("lower",))


def fn_trim(col: Column) -> Column:
    return _map_dict(col, str.strip, tag=("trim",))


def fn_length(col: Column) -> Column:
    lens = np.asarray([len(str(v)) for v in col.dict_values], dtype=np.int64)
    return Column("i64", jnp.take(jnp.asarray(lens), col.data), col.valid)


def fn_concat(cols) -> Column:
    """String || concatenation; distinct combinations resolved on host
    (a whole-column fetch — routed through the trace-replay log)."""
    cols = [c if c.kind == "str" else cast(c, "string") for c in cols]

    def fetch():
        parts = [np.asarray(c.dict_values.astype(str))[np.asarray(c.data)]
                 for c in cols]
        combined = parts[0].astype(object)
        for p in parts[1:]:
            combined = combined + p.astype(object)
        uniq, inv = np.unique(combined.astype(str), return_inverse=True)
        return inv.astype(np.int32), uniq.astype(object)

    from nds_tpu.engine.ops import timed_read
    inv, uniq = timed_read("concat", fetch)
    valid = None
    vs = [c.valid for c in cols if c.valid is not None]
    if vs:
        valid = vs[0]
        for v in vs[1:]:
            valid = valid & v
    return Column("str", jnp.asarray(inv), valid, uniq.astype(object))


def like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def fn_like(col: Column, pattern: str, negate_: bool = False) -> Column:
    rx = re.compile(like_to_regex(pattern), re.DOTALL)
    res = _dict_predicate(col, lambda s: rx.match(s) is not None)
    return logical_not(res) if negate_ else res


def fn_in_strings(col: Column, values) -> Column:
    vs = set(values)
    return _dict_predicate(col, lambda s: s in vs)


# ---------------------------------------------------------------------------
# numeric functions
# ---------------------------------------------------------------------------


def fn_abs(col: Column) -> Column:
    col = _plain(col)
    if col.kind == "f64":
        return Column("f64", jnp.abs(col.data), col.valid)
    return Column(col.kind, jnp.abs(col.data), col.valid)


def fn_round(col: Column, digits: int = 0) -> Column:
    col = _plain(col)
    if is_dec(col.kind):
        s = col.scale
        if digits >= s:
            return col
        f = 10 ** (s - digits)
        half = f // 2
        data = jnp.where(col.data >= 0,
                         (col.data + half) // f,
                         -((-col.data + half) // f)) * f
        return Column(col.kind, data, col.valid)
    scale = 10.0 ** digits
    d = _as_f64(col) * scale
    # SQL ROUND: half away from zero (jnp.round is half-to-even)
    out = jnp.where(d >= 0, jnp.floor(d + 0.5), jnp.ceil(d - 0.5)) / scale
    return Column("f64", out, col.valid)


def fn_floor(col: Column) -> Column:
    return Column("i64", jnp.floor(_as_f64(col)).astype(jnp.int64), col.valid)


def fn_ceil(col: Column) -> Column:
    return Column("i64", jnp.ceil(_as_f64(col)).astype(jnp.int64), col.valid)


def fn_sqrt(col: Column) -> Column:
    return Column("f64", jnp.sqrt(jnp.maximum(_as_f64(col), 0.0)), col.valid)


# ---------------------------------------------------------------------------
# fused chunk-scan predicate lowering (engine half of the shared rule in
# analysis/kernel_spec.py — see DESIGN.md "Fused chunk kernels")
# ---------------------------------------------------------------------------
#
# The streamed pipeline extracts, ONCE at record time, a chunk-invariant
# spec of its chunk-local WHERE conjuncts for engine/kernels.fused_chunk_
# scan: ordered comparisons rebase into ENCODED space (FOR codes shift by
# the base, sorted-dict values map to code indexes through bisect — the
# exact rational threshold math lives in analysis/kernel_spec.py), string
# equality resolves against the whole-table dictionary, and anything the
# shared eligibility rule declines stays in the recorded XLA graph —
# per-conjunct fallback, never all-or-nothing. The lowered semantics are
# bit-for-bit the eager kernels above (compare/_encoded_compare_views/
# _eval_in_list/is_null): any drift fails the strict A/B sweep.


def scan_class(kind: str) -> str | None:
    """Device kind -> the coarse class the shared eligibility rule
    speaks (mirrors plan_audit.type_class on schema types, so the static
    auditor and the runtime judge the same conjunct identically)."""
    if kind == "str":
        return "str"
    if kind == "date":
        return "date"
    if kind in ("i32", "i64", "f64") or is_dec(kind):
        return "num"
    if kind == "bool":
        return "bool"
    return None


def _scan_resolve(cols_meta):
    """ref -> column position resolver over the chunk's aliased columns
    (planner suffix-match scoping: bare names must match exactly one)."""
    def resolve(ref):
        name = ref.name.lower()
        if ref.table:
            key = f"{ref.table.lower()}.{name}"
            hits = [i for i, m in enumerate(cols_meta)
                    if m["name"] == key]
        else:
            hits = [i for i, m in enumerate(cols_meta)
                    if m["name"].split(".")[-1] == name]
        return hits[0] if len(hits) == 1 else None
    return resolve


def _scan_float_meta(meta):
    """(fmode, base, values-or-None, sdiv) of one column's float-lane
    decode — exactly ``_as_f64(plain(col))``."""
    kind, enc = meta["kind"], meta["enc"]
    sdiv = float(10 ** dec_scale(kind)) if is_dec(kind) else 1.0
    if enc is None:
        return "id", 0, None, sdiv
    if enc.mode == "for":
        return "for", int(enc.base), None, sdiv
    return "dict", 0, enc.values, sdiv


def _scan_int_entry(entry, meta):
    """Map a VALUE-space integer entry into the column's STORED space
    (raw uploaded codes) — the encoded-space evaluation."""
    from nds_tpu.analysis.kernel_spec import dict_map, shift_for
    enc = meta["enc"]
    if enc is None:
        return entry
    if enc.mode == "for":
        return shift_for(entry, int(enc.base))
    return dict_map(entry, [int(v) for v in enc.values])


def _scan_frac(value, scale: int):
    """Exact rational of a numeric literal at the column's stored scale
    (the engine's _align_decimals arithmetic, as a Fraction — Fraction
    is exact for int, Decimal AND float inputs)."""
    from fractions import Fraction
    return Fraction(value) * (10 ** scale)


def _lower_compare(op, lit, ci, meta):
    from fractions import Fraction

    from nds_tpu.analysis import kernel_spec as KS
    from nds_tpu.sql import ast as A
    kind = meta["kind"]
    cls = scan_class(kind)
    if cls == "str":
        if not isinstance(lit, A.Literal) or lit.value is None:
            return [("false", ci)]
        vals = [str(v) for v in meta["dict_values"]] \
            if meta["dict_values"] is not None else []
        # the whole-table dictionary is np.unique-sorted, so an equality
        # maps to one code index (absent literal -> constant False/True)
        ent = KS.dict_map(("ieq" if op == "=" else "ine",
                           str(lit.value)), vals) if vals else ("false",)
        return [_with_ci(ent, ci)]
    # date column vs date-ish literal -> integer days
    if isinstance(lit, A.DateLiteral) or (cls == "date"
                                          and isinstance(lit, A.Literal)
                                          and isinstance(lit.value, str)):
        text = lit.text if isinstance(lit, A.DateLiteral) else lit.value
        days = KS.parse_days(text)
        if isinstance(lit, A.DateLiteral) and days is None:
            return None            # eager arm raises on a bad DateLiteral
        if days is None:
            return [("false", ci)]  # str cast -> invalid literal (engine)
        ent = _scan_int_entry(KS.value_cmp(op, Fraction(days)), meta)
        return [_with_ci(ent, ci)]
    if lit.value is None:
        return [("false", ci)]     # NULL literal: comparison never true
    v = lit.value
    if kind == "f64" or isinstance(v, float):
        # the eager engine float-compares whenever either side is f64
        # (_as_f64 both); the kernel's float lane decodes identically
        fop = {"=": "feq", "<>": "fne", "<": "flt", "<=": "fle",
               ">": "fgt", ">=": "fge"}[op]
        return [(fop, ci, _f64_literal(v))]
    q = _scan_frac(v, dec_scale(kind) if is_dec(kind) else 0)
    ent = _scan_int_entry(KS.value_cmp(op, q), meta)
    return [_with_ci(ent, ci)]


def _f64_literal(v) -> float:
    """float64 value of a numeric literal exactly as X.literal +
    _as_f64 would produce it (Decimal: scaled int divided by 10**s)."""
    from decimal import Decimal
    if isinstance(v, Decimal):
        s = max(0, -v.as_tuple().exponent)
        return int(v.scaleb(s)) / (10.0 ** s)
    return float(v)


def _with_ci(ent, ci):
    """Insert the column index into a kernel_spec entry tuple."""
    kind = ent[0]
    if kind in ("true", "false"):
        return (kind, ci)
    if kind in ("ieq", "ine", "ile", "ige"):
        return (kind, ci, ent[1])
    if kind in ("irange", "nrange"):
        return (kind, ci, ent[1], ent[2])
    raise ValueError(f"unexpected entry {ent!r}")


def _lower_between(c, ci, meta):
    """Total over the eligible shapes (analysis/kernel_spec.py rejects
    unparseable date bounds and negated-with-float-bounds up front):
    int-lane bounds fuse into one (n)range entry in encoded space;
    an f64 column (or a float bound) takes the float lane — a mixed
    pair lowers to TWO entries under one conjunct (the engine
    evaluates each side in its own lane; the entries AND exactly
    like logical_and of the two compares)."""
    from fractions import Fraction

    from nds_tpu.analysis import kernel_spec as KS
    from nds_tpu.sql import ast as A
    kind = meta["kind"]

    def bound_days(b):
        text = b.text if isinstance(b, A.DateLiteral) else b.value
        return KS.parse_days(text)

    def is_float_bound(b):
        return kind == "f64" or (isinstance(b, A.Literal)
                                 and isinstance(b.value, float))

    def bound_frac(b):
        if isinstance(b, A.DateLiteral) or isinstance(b.value, str):
            d = bound_days(b)
            return None if d is None else Fraction(d)
        return _scan_frac(b.value, dec_scale(kind) if is_dec(kind) else 0)

    flo, fhi = is_float_bound(c.low), is_float_bound(c.high)
    if flo and fhi:
        lo = _f64_literal(c.low.value)
        hi = _f64_literal(c.high.value)
        return [("fnrange" if c.negated else "frange", ci, lo, hi)]
    if flo or fhi:
        if c.negated:
            return None       # mixed-lane negation is not expressible
        ents = []
        for b, fl, fop, iop in ((c.low, flo, "fge", ">="),
                                (c.high, fhi, "fle", "<=")):
            if fl:
                ents.append((fop, ci, _f64_literal(b.value)))
            else:
                q = bound_frac(b)
                if q is None:
                    return None
                ents.append(_with_ci(
                    _scan_int_entry(KS.value_cmp(iop, q), meta), ci))
        return ents
    qlo, qhi = bound_frac(c.low), bound_frac(c.high)
    if qlo is None or qhi is None:
        return None           # eligibility pre-checks parseability
    ge = KS.value_cmp(">=", qlo)
    le = KS.value_cmp("<=", qhi)
    ent = _scan_int_entry(("irange", ge[1], le[1]), meta)
    if c.negated:
        # both codecs are order-preserving, so value BETWEEN [lo,hi]
        # <=> code in the mapped range — negation flips in code space
        ent = ("nrange", ent[1], ent[2])
    return [_with_ci(ent, ci)]


def _lower_in_list(c, ci, meta):
    """Mirror Planner._eval_in_list exactly (Decimal scaling, fractional
    drop, ANSI NOT IN with NULL, string dictionary membership)."""
    import bisect
    from decimal import Decimal
    kind = meta["kind"]
    vals = [it.value for it in c.items]
    has_null = any(v is None for v in vals)
    vals = [v for v in vals if v is not None]
    if c.negated and has_null:
        return [("false", ci)]     # ANSI: NOT IN with NULL never true
    enc = meta["enc"]
    if kind == "str":
        dv = [str(v) for v in meta["dict_values"]] \
            if meta["dict_values"] is not None else []
        codes = []
        for v in vals:
            i = bisect.bisect_left(dv, str(v))
            if i < len(dv) and dv[i] == str(v):
                codes.append(i)
        if not codes:
            # no literal occurs in the dictionary: membership is
            # all-false, so NOT IN is true for every non-null row
            return [("true" if c.negated else "false", ci)]
        return [("inotin" if c.negated else "iin", ci, tuple(codes))]
    if kind == "f64":
        fl = tuple(float(v) for v in vals)
        if not fl:
            return [("true" if c.negated else "false", ci)]
        return [("fnotin" if c.negated else "fin", ci, fl)]
    scale = dec_scale(kind) if is_dec(kind) else 0
    nums = []
    for v in vals:
        if not isinstance(v, Decimal):
            v = Decimal(str(v))
        scaled = v.scaleb(scale)
        if scaled == scaled.to_integral_value():
            nums.append(int(scaled))
    if not nums:
        # every literal is fractional at this scale: membership is
        # all-false (engine drops them), so NOT IN keeps non-null rows
        return [("true" if c.negated else "false", ci)]
    if enc is not None and enc.mode == "for":
        stored = tuple(n - int(enc.base) for n in nums)
    elif enc is not None:
        tv = [int(x) for x in enc.values]
        stored = []
        for n in nums:
            i = bisect.bisect_left(tv, n)
            if i < len(tv) and tv[i] == n:
                stored.append(i)
        if not stored and not c.negated:
            return [("false", ci)]
        if not stored and c.negated:
            return [("true", ci)]
        stored = tuple(stored)
    else:
        stored = tuple(nums)
    return [("inotin" if c.negated else "iin", ci, stored)]


def lower_scan_spec(conjuncts, cols_meta, owned):
    """(ScanSpec | None, kept conjuncts): lower every eligible
    chunk-owned conjunct into the fused scan pass and return the rest
    for the recorded XLA graph. ``cols_meta`` describes the chunk's
    columns in flattened-buffer order (dicts with name/kind/enc/
    dict_values/data_slot/valid_slot); ``owned(c)`` is the planner's
    single-ownership test for the streamed part.

    None means NO fused pass (nothing eligible, or an eligible conjunct
    failed to lower — the latter disables the whole pass so the static
    launch prediction can flag the drift loudly instead of silently
    splitting)."""
    from nds_tpu.analysis.kernel_spec import eligible_conjunct
    from nds_tpu.engine.kernels import ScanSpec

    resolve = _scan_resolve(cols_meta)

    def class_of(ref):
        i = resolve(ref)
        return None if i is None else scan_class(cols_meta[i]["kind"])

    kept, entries, used = [], [], {}
    tables = []
    spec_cols = []

    def col_index(i):
        if i in used:
            return used[i]
        meta = cols_meta[i]
        fmode, base, values, sdiv = _scan_float_meta(meta)
        tbl = -1
        if fmode == "dict":
            tbl = len(tables)
            tables.append(np.asarray(values).astype(np.int64))
        spec_cols.append((meta["data_slot"], meta["valid_slot"],
                          fmode, base, tbl, sdiv))
        used[i] = len(spec_cols) - 1
        return used[i]

    n_lowered = 0
    for c in conjuncts:
        if not owned(c) or not eligible_conjunct(c, class_of):
            kept.append(c)
            continue
        try:
            ents = _lower_one(c, resolve, cols_meta, col_index)
        except Exception:
            ents = None
        if ents is None:
            return None, list(conjuncts)
        entries.extend(ents)
        n_lowered += 1
    if not n_lowered:
        return None, list(conjuncts)
    return ScanSpec(entries, spec_cols, tables=tables,
                    n_conjuncts=n_lowered), kept


def _lower_one(c, resolve, cols_meta, col_index):
    from nds_tpu.analysis.kernel_spec import _ref_lit
    from nds_tpu.sql import ast as A
    got = _ref_lit(c)
    if got is not None:
        ref, lit, op = got
        i = resolve(ref)
        return _lower_compare(op, lit, col_index(i), cols_meta[i])
    if isinstance(c, A.Between):
        i = resolve(c.expr)
        return _lower_between(c, col_index(i), cols_meta[i])
    if isinstance(c, A.InList):
        i = resolve(c.expr)
        return _lower_in_list(c, col_index(i), cols_meta[i])
    if isinstance(c, A.IsNull):
        i = resolve(c.expr)
        ci = col_index(i)
        if cols_meta[i]["valid_slot"] < 0 and not c.negated:
            return [("false", ci)]   # no mask: nothing is null
        return [("notnull" if c.negated else "isnull", ci)]
    return None
