# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Scalar expression kernels over device columns.

SQL three-valued logic: every kernel combines operand validity into the
result's validity; AND/OR implement Kleene logic. Decimal arithmetic stays on
the exact int64 fixed-point path (scales align for +/-, add for *), spilling
to float64 for division and for scale overflow. String predicates evaluate
once per distinct dictionary value on host, then map through the device codes
— the dictionary is orders of magnitude smaller than the column.
"""

from __future__ import annotations

import re

import jax.numpy as jnp
import numpy as np

from nds_tpu.engine.column import Column, encs_equal, is_dec
from nds_tpu.engine.ops import ordered_codes_merged, plain_col

_MAX_DEC_SCALE = 10
_str_literal_dicts: dict = {}


# ---------------------------------------------------------------------------
# literals / lifting
# ---------------------------------------------------------------------------


def literal(value, n: int) -> Column:
    """Python literal -> broadcast Column of length n."""
    if value is None:
        return Column("i32", jnp.zeros(n, dtype=jnp.int32), jnp.zeros(n, dtype=bool))
    if isinstance(value, bool):
        return Column("bool", jnp.full(n, value, dtype=bool))
    if isinstance(value, int):
        return Column("i64", jnp.full(n, value, dtype=jnp.int64))
    if isinstance(value, float):
        return Column("f64", jnp.full(n, value, dtype=jnp.float64))
    if isinstance(value, str):
        # per-value dictionary cache: identity-keyed caches downstream
        # (expression fusion) need the same host object on every execution.
        # Bounded FIFO like the engine's other dictionary caches.
        d = _str_literal_dicts.get(value)
        if d is None:
            if len(_str_literal_dicts) >= 4096:
                _str_literal_dicts.pop(next(iter(_str_literal_dicts)))
            d = _str_literal_dicts[value] = np.asarray([value], dtype=object)
        return Column("str", jnp.zeros(n, dtype=jnp.int32), None, d)
    if type(value).__name__ == "Decimal":
        s = -value.as_tuple().exponent
        s = max(0, s)
        return Column(f"dec(38,{s})",
                      jnp.full(n, int(value.scaleb(s)), dtype=jnp.int64))
    raise TypeError(f"unsupported literal: {value!r}")


# ---------------------------------------------------------------------------
# numeric coercion
# ---------------------------------------------------------------------------


# the scalar kernels funnel value consumption through the ONE decode
# choke point (ops.plain_col); comparisons keep a fast path that stays
# in encoded space (see compare)
_plain = plain_col


def _as_f64(col: Column) -> jnp.ndarray:
    col = _plain(col)
    d = col.data.astype(jnp.float64)
    if is_dec(col.kind):
        d = d / (10.0 ** col.scale)
    return d


def _combine_valid(a: Column, b: Column):
    if a.valid is None and b.valid is None:
        return None
    return a.valid_mask() & b.valid_mask()


def _align_decimals(a: Column, b: Column):
    """Bring two int-path numeric columns to a common scale."""
    a, b = _plain(a), _plain(b)
    sa, sb = a.scale, b.scale
    s = max(sa, sb)
    da = a.data.astype(jnp.int64) * (10 ** (s - sa))
    db = b.data.astype(jnp.int64) * (10 ** (s - sb))
    return da, db, s


def _int_path(col: Column) -> bool:
    return col.kind in ("i32", "i64", "date", "bool") or is_dec(col.kind)


def arith(op: str, a: Column, b: Column) -> Column:
    a, b = _plain(a), _plain(b)        # arithmetic needs logical values
    valid = _combine_valid(a, b)
    if op == "/":
        num, den = _as_f64(a), _as_f64(b)
        zero = den == 0
        out = jnp.where(zero, 0.0, num / jnp.where(zero, 1.0, den))
        v = valid if valid is not None else jnp.ones(len(a), dtype=bool)
        return Column("f64", out, v & ~zero)  # SQL: x/0 -> null (Spark semantics)
    if _int_path(a) and _int_path(b):
        if op in ("+", "-"):
            da, db, s = _align_decimals(a, b)
            out = da + db if op == "+" else da - db
            if s:
                kind = f"dec(38,{s})"
            elif (a.kind == "date") != (b.kind == "date"):
                kind = "date"       # date +/- integer days
                out = out.astype(jnp.int32)
            else:
                kind = "i64"        # incl. date - date = day count
            return Column(kind, out, valid)
        if op == "*":
            s = a.scale + b.scale
            if s <= _MAX_DEC_SCALE:
                out = a.data.astype(jnp.int64) * b.data.astype(jnp.int64)
                kind = f"dec(38,{s})" if s else "i64"
                return Column(kind, out, valid)
        if op == "%":
            da, db = a.data.astype(jnp.int64), b.data.astype(jnp.int64)
            zero = db == 0
            safe_db = jnp.where(zero, 1, db)
            out = jnp.where(zero, 0, da % safe_db)
            # SQL/Spark remainder takes the dividend's sign, not the divisor's
            out = jnp.where((out != 0) & ((out < 0) != (da < 0)),
                            out - safe_db, out)
            v = valid if valid is not None else jnp.ones(len(a), dtype=bool)
            return Column("i64", out, v & ~zero)
    # float path
    fa, fb = _as_f64(a), _as_f64(b)
    if op == "+":
        out = fa + fb
    elif op == "-":
        out = fa - fb
    elif op == "*":
        out = fa * fb
    elif op == "%":
        zero = fb == 0
        # fmod (C semantics: dividend's sign) matches Spark's % on doubles
        out = jnp.where(zero, 0.0, jnp.fmod(fa, jnp.where(zero, 1.0, fb)))
        v = valid if valid is not None else jnp.ones(len(a), dtype=bool)
        return Column("f64", out, v & ~zero)
    else:
        raise ValueError(f"unknown arith op {op}")
    return Column("f64", out, valid)


def negate(a: Column) -> Column:
    a = _plain(a)
    if a.kind == "f64":
        return Column("f64", -a.data, a.valid)
    return Column(a.kind if is_dec(a.kind) else "i64",
                  -a.data.astype(jnp.int64), a.valid)


# ---------------------------------------------------------------------------
# comparisons
# ---------------------------------------------------------------------------


def _encoded_compare_views(a: Column, b: Column):
    """Encoded-space comparison views, or None when the pair must decode.

    Both FOR and sorted-dict encodings are order-preserving, so two sides
    sharing ONE encoding compare by raw codes. For a FOR side against a
    plain int-path side at the same scale, the comparison rebases the
    PLAIN side into the encoded space (``code op (other - base)``) — when
    the other side is a broadcast literal the subtraction folds to a
    constant at trace time, so the predicate runs entirely on the narrow
    encoded column."""
    if a.enc is not None and b.enc is not None:
        # same encoding AND same scale: codes of a dec(7,2) and an int
        # column can share (mode, base) while meaning values 100x apart,
        # so scale must align exactly like _align_decimals would
        if encs_equal(a.enc, b.enc) and a.scale == b.scale:
            return a.data.astype(jnp.int64), b.data.astype(jnp.int64)
        return None
    enc_side, plain_side = (a, b) if a.enc is not None else (b, a)
    if enc_side.enc.mode != "for" or plain_side.enc is not None or \
            enc_side.scale != plain_side.scale or plain_side.kind == "f64":
        return None
    base = jnp.asarray(enc_side.enc.base, dtype=jnp.int64)
    ev = enc_side.data.astype(jnp.int64)
    pv = plain_side.data.astype(jnp.int64) - base
    return (ev, pv) if enc_side is a else (pv, ev)


def compare(op: str, a: Column, b: Column) -> Column:
    valid = _combine_valid(a, b)
    if a.kind == "str" or b.kind == "str":
        if a.kind == "str" and b.kind == "str":
            la, lb = ordered_codes_merged(a, b)
        else:
            raise TypeError("cannot compare string with non-string")
        da, db = la, lb
    elif _int_path(a) and _int_path(b):
        views = _encoded_compare_views(a, b) \
            if (a.enc is not None or b.enc is not None) else None
        if views is not None:
            da, db = views
        else:
            da, db, _ = _align_decimals(a, b)
    else:
        da, db = _as_f64(a), _as_f64(b)
    out = {
        "=": lambda: da == db,
        "<>": lambda: da != db,
        "<": lambda: da < db,
        "<=": lambda: da <= db,
        ">": lambda: da > db,
        ">=": lambda: da >= db,
    }[op]()
    return Column("bool", out, valid)


def is_null(a: Column, negate_: bool = False) -> Column:
    m = ~a.valid_mask() if not negate_ else a.valid_mask()
    return Column("bool", m)


# ---------------------------------------------------------------------------
# Kleene boolean logic
# ---------------------------------------------------------------------------


def logical_and(a: Column, b: Column) -> Column:
    av, bv = a.valid_mask(), b.valid_mask()
    ad, bd = a.data.astype(bool), b.data.astype(bool)
    data = ad & bd
    false_a = av & ~ad
    false_b = bv & ~bd
    valid = (av & bv) | false_a | false_b
    return Column("bool", data, valid)


def logical_or(a: Column, b: Column) -> Column:
    av, bv = a.valid_mask(), b.valid_mask()
    ad, bd = a.data.astype(bool), b.data.astype(bool)
    data = (av & ad) | (bv & bd)
    true_a = av & ad
    true_b = bv & bd
    valid = (av & bv) | true_a | true_b
    return Column("bool", data, valid)


def logical_not(a: Column) -> Column:
    return Column("bool", ~a.data.astype(bool), a.valid)


# ---------------------------------------------------------------------------
# conditionals
# ---------------------------------------------------------------------------


def _unify(cols):
    """Bring branch results to one kind (for CASE/COALESCE/IF)."""
    cols = [_plain(c) for c in cols]
    kinds = {c.kind for c in cols}
    if len(kinds) == 1 and "str" not in kinds:
        return cols, cols[0].kind
    if kinds == {"str"}:
        return cols, "str"
    if "str" in kinds:
        # null literals come through as i32; rewrite them as empty-string nulls
        fixed = []
        str_dict = next(c.dict_values for c in cols if c.kind == "str")
        for c in cols:
            if c.kind == "str":
                fixed.append(c)
            else:
                fixed.append(Column("str", jnp.zeros(len(c), dtype=jnp.int32),
                                    jnp.zeros(len(c), dtype=bool), str_dict))
        return fixed, "str"
    scales = {c.scale for c in cols if is_dec(c.kind)}
    if scales and all(_int_path(c) for c in cols):
        s = max(scales)
        fixed = [Column(f"dec(38,{s})",
                        c.data.astype(jnp.int64) * (10 ** (s - c.scale)), c.valid)
                 for c in cols]
        return fixed, f"dec(38,{s})"
    if kinds <= {"i32", "i64", "date", "bool"}:
        fixed = [Column("i64", c.data.astype(jnp.int64), c.valid) for c in cols]
        return fixed, "i64"
    fixed = [Column("f64", _as_f64(c), c.valid) for c in cols]
    return fixed, "f64"


def unify_columns(cols):
    """Public alias of :func:`_unify` for cross-module use (set operations
    align operand columns with it)."""
    return _unify(cols)


def case_when(branches, else_col: Column) -> Column:
    """branches: [(cond Column, value Column)], evaluated first-match-wins."""
    vals = [v for _, v in branches] + [else_col]
    vals, kind = _unify(vals)
    branch_vals, else_v = vals[:-1], vals[-1]
    n = len(else_v)
    if kind == "str":
        # merge dictionaries across branches
        from nds_tpu.engine.ops import concat_columns
        merged = concat_columns([v for v in vals])
        dict_values = merged.dict_values
        datas = [merged.data[i * n:(i + 1) * n] for i in range(len(vals))]
        branch_datas, else_data = datas[:-1], datas[-1]
    else:
        dict_values = None
        branch_datas = [v.data for v in branch_vals]
        else_data = else_v.data
    out = else_data
    out_valid = else_v.valid_mask()
    taken = jnp.zeros(n, dtype=bool)
    for (cond, _), val, vdata in zip(branches, branch_vals, branch_datas):
        c = cond.data.astype(bool) & cond.valid_mask() & ~taken
        out = jnp.where(c, vdata, out)
        out_valid = jnp.where(c, val.valid_mask(), out_valid)
        taken = taken | c
    return Column(kind, out, out_valid, dict_values)


def coalesce(cols) -> Column:
    n = len(cols[0])
    branches = [(is_null(c, negate_=True), c) for c in cols[:-1]]
    return case_when(branches, cols[-1]) if len(cols) > 1 else cols[0]


# ---------------------------------------------------------------------------
# casts
# ---------------------------------------------------------------------------


def cast(col: Column, target: str) -> Column:
    """target: canonical-ish SQL type name (int, bigint, double, decimal(p,s),
    date, string, char(n), varchar(n))."""
    col = _plain(col)
    t = target.lower().replace(" ", "")
    if t in ("int", "integer", "i32"):
        if col.kind == "str":
            vals = np.asarray(
                [int(v) if _is_intstr(v) else 0 for v in col.dict_values])
            ok = np.asarray([_is_intstr(v) for v in col.dict_values])
            data = jnp.take(jnp.asarray(vals), col.data)
            valid = col.valid_mask() & jnp.take(jnp.asarray(ok), col.data)
            return Column("i64", data, valid)
        return Column("i64", _as_f64(col).astype(jnp.int64) if col.kind == "f64"
                      else (col.data.astype(jnp.int64) // (10 ** col.scale)), col.valid)
    if t in ("bigint", "long", "i64"):
        return cast(col, "int")
    if t in ("double", "float", "f64", "real"):
        return Column("f64", _as_f64(col) if col.kind != "str" else _str_to_f64(col)[0],
                      col.valid if col.kind != "str" else _str_to_f64(col)[1])
    if t.startswith("decimal("):
        p, s = t[len("decimal("):-1].split(",")
        s = int(s)
        if is_dec(col.kind) or col.kind in ("i32", "i64", "bool"):
            cs = col.scale
            if s >= cs:
                data = col.data.astype(jnp.int64) * (10 ** (s - cs))
            else:
                # round half away from zero on the dropped digits
                f = 10 ** (cs - s)
                d = col.data.astype(jnp.int64)
                half = f // 2
                data = jnp.where(d >= 0, (d + half) // f, -((-d + half) // f))
            return Column(f"dec({p},{s})", data, col.valid)
        f64 = _as_f64(col)
        data = jnp.round(f64 * (10 ** s)).astype(jnp.int64)
        return Column(f"dec({p},{s})", data, col.valid)
    if t == "date":
        if col.kind == "date":
            return col
        if col.kind == "str":
            days = np.asarray([_parse_date(v) for v in col.dict_values])
            ok = days >= -(10 ** 8)
            data = jnp.take(jnp.asarray(days.astype(np.int32)), col.data)
            valid = col.valid_mask() & jnp.take(jnp.asarray(ok), col.data)
            return Column("date", data, valid)
    if t in ("string", "varchar", "char") or t.startswith(("char(", "varchar(")):
        if col.kind == "str":
            return col

        def fetch():
            # host-side dictionary build from the column values — a whole-
            # column fetch, so it routes through the trace-replay log
            vals = np.asarray(col.data)
            if is_dec(col.kind):
                s = col.scale
                strs = np.asarray([_dec_str(int(v), s) for v in vals],
                                  dtype=object)
            elif col.kind == "date":
                strs = np.asarray([_date_str(int(v)) for v in vals],
                                  dtype=object)
            else:
                strs = np.asarray([str(v) for v in vals], dtype=object)
            uniq, inv = np.unique(strs, return_inverse=True)
            return inv.astype(np.int32), uniq.astype(object)

        from nds_tpu.engine.ops import timed_read
        inv, uniq = timed_read("cast_str", fetch)
        return Column("str", jnp.asarray(inv), col.valid, uniq)
    raise ValueError(f"unsupported cast target: {target}")


def _is_intstr(v) -> bool:
    try:
        int(str(v))
        return True
    except ValueError:
        return False


def _str_to_f64(col: Column):
    def conv(v):
        try:
            return float(v)
        except ValueError:
            return np.nan
    vals = np.asarray([conv(v) for v in col.dict_values])
    data = jnp.take(jnp.asarray(vals), col.data)
    valid = col.valid_mask() & ~jnp.isnan(data)
    return data, valid


_EPOCH = np.datetime64("1970-01-01", "D")


def _parse_date(v) -> int:
    try:
        return int((np.datetime64(str(v), "D") - _EPOCH).astype(int))
    except Exception:
        return -(10 ** 9)


def _date_str(days: int) -> str:
    return str(_EPOCH + np.timedelta64(days, "D"))


def _dec_str(v: int, s: int) -> str:
    if s == 0:
        return str(v)
    sign = "-" if v < 0 else ""
    v = abs(v)
    return f"{sign}{v // 10**s}.{v % 10**s:0{s}d}"


def parse_date_literal(text: str) -> int:
    d = _parse_date(text)
    if d <= -(10 ** 8):
        raise ValueError(f"bad date literal: {text!r}")
    return d


# ---------------------------------------------------------------------------
# string functions (host-side on dictionaries)
# ---------------------------------------------------------------------------


_map_dict_cache: dict = {}


def _map_dict(col: Column, fn, tag=None) -> Column:
    """Apply a str->str function to the dictionary, re-uniquing the result.
    ``tag`` (a hashable description of ``fn``) enables caching per input
    dictionary, so repeated executions return the SAME output dictionary
    object — identity-keyed caches downstream (expression fusion) depend on
    stable dictionary identities across runs."""
    def compute():
        new_vals = np.asarray([fn(str(v)) for v in col.dict_values],
                              dtype=object)
        uniq, inv = np.unique(new_vals.astype(str), return_inverse=True)
        # cache HOST arrays only: a device constant created inside a jit
        # trace is a tracer, and caching one leaks it across traces
        return inv.astype(np.int32), uniq.astype(object)

    if tag is None:
        remap, uniq = compute()
    else:
        from nds_tpu.engine.ops import _identity_cache
        remap, uniq = _identity_cache(
            _map_dict_cache.setdefault(tag, {}), 256,
            (col.dict_values,), compute)
    return Column("str", jnp.take(jnp.asarray(remap), col.data),
                  col.valid, uniq)


def _dict_predicate(col: Column, fn) -> Column:
    mask = np.asarray([bool(fn(str(v))) for v in col.dict_values])
    data = jnp.take(jnp.asarray(mask), col.data)
    return Column("bool", data, col.valid)


def fn_substr(col: Column, start: int, length: int | None = None) -> Column:
    def f(s):
        i = start - 1 if start > 0 else len(s) + start
        return s[i:i + length] if length is not None else s[i:]
    return _map_dict(col, f, tag=("substr", start, length))


def fn_upper(col: Column) -> Column:
    return _map_dict(col, str.upper, tag=("upper",))


def fn_lower(col: Column) -> Column:
    return _map_dict(col, str.lower, tag=("lower",))


def fn_trim(col: Column) -> Column:
    return _map_dict(col, str.strip, tag=("trim",))


def fn_length(col: Column) -> Column:
    lens = np.asarray([len(str(v)) for v in col.dict_values], dtype=np.int64)
    return Column("i64", jnp.take(jnp.asarray(lens), col.data), col.valid)


def fn_concat(cols) -> Column:
    """String || concatenation; distinct combinations resolved on host
    (a whole-column fetch — routed through the trace-replay log)."""
    cols = [c if c.kind == "str" else cast(c, "string") for c in cols]

    def fetch():
        parts = [np.asarray(c.dict_values.astype(str))[np.asarray(c.data)]
                 for c in cols]
        combined = parts[0].astype(object)
        for p in parts[1:]:
            combined = combined + p.astype(object)
        uniq, inv = np.unique(combined.astype(str), return_inverse=True)
        return inv.astype(np.int32), uniq.astype(object)

    from nds_tpu.engine.ops import timed_read
    inv, uniq = timed_read("concat", fetch)
    valid = None
    vs = [c.valid for c in cols if c.valid is not None]
    if vs:
        valid = vs[0]
        for v in vs[1:]:
            valid = valid & v
    return Column("str", jnp.asarray(inv), valid, uniq.astype(object))


def like_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def fn_like(col: Column, pattern: str, negate_: bool = False) -> Column:
    rx = re.compile(like_to_regex(pattern), re.DOTALL)
    res = _dict_predicate(col, lambda s: rx.match(s) is not None)
    return logical_not(res) if negate_ else res


def fn_in_strings(col: Column, values) -> Column:
    vs = set(values)
    return _dict_predicate(col, lambda s: s in vs)


# ---------------------------------------------------------------------------
# numeric functions
# ---------------------------------------------------------------------------


def fn_abs(col: Column) -> Column:
    col = _plain(col)
    if col.kind == "f64":
        return Column("f64", jnp.abs(col.data), col.valid)
    return Column(col.kind, jnp.abs(col.data), col.valid)


def fn_round(col: Column, digits: int = 0) -> Column:
    col = _plain(col)
    if is_dec(col.kind):
        s = col.scale
        if digits >= s:
            return col
        f = 10 ** (s - digits)
        half = f // 2
        data = jnp.where(col.data >= 0,
                         (col.data + half) // f,
                         -((-col.data + half) // f)) * f
        return Column(col.kind, data, col.valid)
    scale = 10.0 ** digits
    d = _as_f64(col) * scale
    # SQL ROUND: half away from zero (jnp.round is half-to-even)
    out = jnp.where(d >= 0, jnp.floor(d + 0.5), jnp.ceil(d - 0.5)) / scale
    return Column("f64", out, col.valid)


def fn_floor(col: Column) -> Column:
    return Column("i64", jnp.floor(_as_f64(col)).astype(jnp.int64), col.valid)


def fn_ceil(col: Column) -> Column:
    return Column("i64", jnp.ceil(_as_f64(col)).astype(jnp.int64), col.valid)


def fn_sqrt(col: Column) -> Column:
    return Column("f64", jnp.sqrt(jnp.maximum(_as_f64(col), 0.0)), col.valid)
