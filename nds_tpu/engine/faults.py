# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Fault registry + recovery-policy layer: every failure seam named,
bounded, and proven recoverable (DESIGN.md "Fault-tolerance contract").

The engine grew a large IMPLICIT failure surface — a prefetch worker
re-raising at the driver's next fetch, a Mosaic refusal degrading to the
XLA arm, an accumulator overflow rerunning eagerly, a chunk-store
checksum refusing an entry — none of it enumerated, injected, or proven
to recover. This module makes that surface a CHECKED contract, the same
discipline exec/mem/conc audit apply to syncs, memory and locks:

* **Registry** — :data:`SEAMS` names every failure seam with its
  classification (``transient`` / ``degradable`` / ``fatal``) and its
  recovery policy. A seam that is not registered cannot be injected; a
  registered seam without a tier-1 injection fails
  ``tests/test_faults.py``'s coverage check.
* **Deterministic injection** — ``NDS_TPU_FAULT=seam:kind:nth`` (read at
  USE time, never frozen at import — the PR 6/13 env-knob discipline)
  makes the ``nth`` occurrence of :func:`fault_point` at ``seam`` raise
  :class:`FaultInjected` (``kind=error``) or sleep
  ``NDS_TPU_FAULT_HANG_S`` seconds first (``kind=hang`` — the hung-sync /
  stuck-peer simulation the watchdog must beat). Exactly ONE injection
  fires per process per spec: occurrence counting is process-global
  under a lock, deterministic under threads.
* **Recovery policies** — ``transient`` seams recover through
  :func:`with_retry` (bounded attempts, deterministic backoff — no
  randomness, so the diff harness's wall bound holds); ``degradable``
  seams ride the existing degradation ladder (Pallas→XLA,
  sharded→single-device, compiled→eager, partitioned rerun), now
  evidence-recorded; ``fatal`` seams raise a classified
  :class:`FaultError` promptly instead of hanging or corrupting.
* **FaultEvent evidence** — every recovery records a
  :class:`FaultEvent` into a thread-scoped bounded ring (mirroring
  ``listener.StreamEvent``), drained per query by the drivers into
  ``faultEvents`` next to ``streamedScans`` and into the campaign
  ledger — so a fallback that fired in production is benchmark
  evidence, not log noise (the reference suite's TaskFailureListener
  idea, applied to the engine's own recovery paths). The
  ``swallowed-fault`` jax_lint rule (error) statically requires any
  except-clause catching a :class:`FaultError` to record an event or
  re-raise.
* **Statement watchdog** — ``NDS_TPU_STATEMENT_DEADLINE_S`` arms an
  in-process per-statement deadline: :func:`bounded_call` runs a
  blocking device->host fetch (or a peer wait) on a daemon helper
  thread and raises :class:`StatementTimeout` — a classified error the
  drivers map to status ``timeout`` — when the statement's remaining
  budget runs out, instead of hanging the process. Unset (the default)
  the call runs inline: zero threads, zero overhead, bit-for-bit
  today's path.

The runtime half is ``tools/fault_diff.py`` (tier-1 via
``tests/test_faults.py``): it sweeps the injection matrix over the A/B
subset and proves every seam either recovers bit-for-bit against the
fault-free run or raises its classified error within the deadline —
never hangs, never silently wrong rows — with FaultEvent counts matching
injections exactly, and ``--inject-drift`` (suppress the recovery
machinery via ``NDS_TPU_FAULT_DRIFT``) MUST fail.

Deliberately STDLIB-ONLY (no jax, no nds_tpu imports): the bench.py
parent and ``obs/ledger.py`` — both barred from the jax-importing
package root — load this file by path (``nds_tpu.obs.ledger._faults_mod``)
for the driver-side seams (``ledger-write``, ``bench-child``).

Concurrency contract (analysis/conc_audit.py entry point): the
occurrence counters are ONE dict under ONE dedicated lock
(``_FAULT_LOCK``); the event ring is thread-local ``deque(maxlen)``;
the statement scope is thread-local. Nothing else is shared.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass

TRANSIENT = "transient"
DEGRADABLE = "degradable"
FATAL = "fatal"


@dataclass(frozen=True)
class Seam:
    """One registered failure seam: where it lives, how it is classified,
    and the recovery policy the diff harness proves. ``retries`` is the
    bounded attempt allowance of a transient seam's :func:`with_retry`
    (total attempts = retries + 1); ``retry_on`` names the exception
    types the retry treats as transient — anything else propagates
    unchanged, so a genuine engine bug is never masked by a retry loop."""

    name: str
    where: str
    classify: str
    recovery: str
    retries: int = 0
    retry_on: tuple = ()


# THE registry: every fault_point() call names one of these. Order is
# documentation order (DESIGN.md's seam table mirrors it; tests assert
# the mirror).
SEAMS = {s.name: s for s in (
    Seam("prefetch", "engine/prefetch.py worker (slice+encode+upload)",
         TRANSIENT,
         "bounded retry of the prepare step on the worker; exhausted or "
         "non-transient errors re-raise at the driver's next fetch "
         "exactly like the inline path",
         retries=2, retry_on=("FaultInjected", "OSError")),
    Seam("device-put", "engine/stream.py _prepare_chunk[_sharded] "
         "(host->device upload)",
         TRANSIENT,
         "covered by the prefetch seam's bounded retry (prepare wraps "
         "the upload); inline/depth-0 paths retry on the driver",
         retries=2, retry_on=("FaultInjected", "OSError")),
    Seam("pipeline-compile", "engine/stream.py _build_pipeline / "
         "StreamPipeline.compile",
         DEGRADABLE,
         "degrade compiled->eager: the statement reruns through the "
         "eager chunk loop, bit-for-bit (the existing ladder, now "
         "evidence-recorded)"),
    Seam("exchange", "engine/stream.py _run_sharded collective dispatch "
         "(parallel/exchange.py all-to-alls)",
         DEGRADABLE,
         "degrade sharded->single-device eager rerun, bit-for-bit"),
    Seam("chunk-store-read", "io/chunk_store.py load_plan (mmap + CRC)",
         TRANSIENT,
         "corrupt entry (checksum/torn write): delete + re-encode from "
         "the source arrow once; version drift stays a loud fatal "
         "refusal (operator action)",
         retries=1, retry_on=("FaultInjected",)),
    Seam("chunk-store-write", "io/chunk_store.py save_plan (lock-file + "
         "atomic rename)",
         DEGRADABLE,
         "best-effort persist: a failed/contended/killed write degrades "
         "to the in-memory wire plan; a killed writer leaves old-valid "
         "or none (lock-file steal by pid liveness)"),
    Seam("sync", "engine/ops.py timed_read/host_sync (materializing "
         "device->host fetch)",
         TRANSIENT,
         "bounded retry of the idempotent fetch; under "
         "NDS_TPU_STATEMENT_DEADLINE_S a hung fetch raises "
         "StatementTimeout (status 'timeout') instead of hanging",
         retries=1, retry_on=("FaultInjected", "OSError")),
    Seam("ledger-write", "obs/ledger.py Ledger.write (flush+fsync)",
         TRANSIENT,
         "one bounded retry, then degrade: the write is skipped with a "
         "stderr note and a write_failures count — evidence loss is "
         "recorded, the campaign continues",
         retries=1, retry_on=("FaultInjected", "OSError")),
    Seam("bench-child", "bench.py ChildServer.start (persistent serving "
         "child)",
         TRANSIENT,
         "restart with deterministic-jittered backoff; 2 consecutive "
         "setup failures trip the circuit breaker into a labeled "
         "partial artifact (fail fast, never a burned round)"),
    Seam("peer", "parallel/multihost.py maybe_initialize (federation "
         "coordinator/peer attach)",
         FATAL,
         "classified FaultError raised promptly (no silent retry loop: "
         "a half-formed federation must never run a collective); under "
         "a deadline a stuck attach raises StatementTimeout"),
)}


class FaultError(RuntimeError):
    """Base classified error of the fault layer: carries the seam name so
    drivers and the diff harness can attribute it without string
    matching. Every path out of a failed recovery raises one of these
    (or re-raises the original, non-transient exception unchanged)."""

    def __init__(self, seam: str, message: str):
        super().__init__(message)
        self.seam = seam


class FaultInjected(FaultError):
    """The deterministic injected fault (``NDS_TPU_FAULT``). Recovery
    paths treat it exactly like the real fault it simulates; the diff
    harness asserts they do."""


class StatementTimeout(FaultError):
    """The statement's ``NDS_TPU_STATEMENT_DEADLINE_S`` budget ran out
    inside a blocking wait: the watchdog's classified error. Drivers map
    it to status ``timeout``; the helper thread stays blocked (daemon)
    but the process — and the campaign — moves on."""


@dataclass
class FaultEvent:
    """One recovery (or classified failure) at a registered seam — the
    evidence record the drivers drain per query next to StreamEvents.
    ``action``: ``recovered`` (transient retry succeeded) | ``degrade``
    (ladder step taken) | ``timeout`` (watchdog fired) | ``fatal``
    (classified error raised) | ``note`` (diagnostic, e.g. heartbeat
    survival)."""

    seam: str
    action: str
    attempt: int = 0
    detail: str = ""


def fault_event_json(e: FaultEvent) -> dict:
    """The ONE JSON shape of a FaultEvent in driver summaries
    (``faultEvents`` next to ``streamedScans``) and the campaign
    ledger."""
    out = {"seam": e.seam, "action": e.action}
    if e.attempt:
        out["attempt"] = e.attempt
    if e.detail:
        out["detail"] = str(e.detail)[:200]
    return out


_fault_tls = threading.local()


def record_fault_event(seam: str, action: str, attempt: int = 0,
                       detail: str = "") -> None:
    """Record one recovery event, thread-scoped like the sync counters
    and StreamEvents (concurrent Throughput streams account their own
    recoveries). Suppressed under ``NDS_TPU_FAULT_DRIFT`` — the
    harness-only knob ``tools/fault_diff.py --inject-drift`` uses to
    prove its event-count check can fail."""
    if _drift():
        return
    lst = getattr(_fault_tls, "events", None)
    if lst is None:
        # deque(maxlen): diagnostics ring, never unbounded, O(1) evict
        lst = _fault_tls.events = deque(maxlen=1000)
    lst.append(FaultEvent(seam, action, attempt, detail))


def drain_fault_events() -> list:
    """Return and clear the calling thread's fault events (oldest first;
    the ring keeps the newest 1000) — the per-query drain the drivers
    run, mirroring ``listener.drain_stream_events``."""
    lst = getattr(_fault_tls, "events", None)
    if not lst:
        return []
    out = list(lst)
    lst.clear()
    return out


# ---------------------------------------------------------------------------
# deterministic injection
# ---------------------------------------------------------------------------

# process-global occurrence counters: seam -> times fault_point() was
# reached while an injection spec targeted it. ONE dict, ONE dedicated
# lock (the conc-audit classification), reset by the diff harness
# between matrix entries.
_FAULT_COUNTS: dict = {}
_FAULT_LOCK = threading.Lock()


def _drift() -> bool:
    """``NDS_TPU_FAULT_DRIFT``: harness-only recovery suppression —
    with_retry stops retrying and event recording stops, so every
    fault_diff recovery check MUST fail (the --inject-drift self-test).
    Never set outside the harness."""
    return bool(os.environ.get("NDS_TPU_FAULT_DRIFT"))


def fault_spec():
    """Parse ``NDS_TPU_FAULT=seam:kind:nth`` (read at USE time). Returns
    ``(seam, kind, nth)`` or None. Unknown seams raise: a typo'd
    injection silently never firing would make the diff harness pass
    vacuously."""
    env = os.environ.get("NDS_TPU_FAULT", "").strip()
    if not env:
        return None
    parts = env.split(":")
    seam = parts[0]
    kind = parts[1] if len(parts) > 1 and parts[1] else "error"
    try:
        nth = int(parts[2]) if len(parts) > 2 else 1
    except ValueError:
        nth = 1
    if seam not in SEAMS:
        raise ValueError(f"NDS_TPU_FAULT names unregistered seam "
                         f"{seam!r} (known: {sorted(SEAMS)})")
    if kind not in ("error", "hang"):
        raise ValueError(f"NDS_TPU_FAULT kind {kind!r} not in "
                         "('error', 'hang')")
    return seam, kind, max(nth, 1)


def hang_seconds() -> float:
    """``NDS_TPU_FAULT_HANG_S`` (default 30): how long a ``hang``-kind
    injection blocks before raising — long enough that an un-watchdogged
    statement visibly hangs, bounded so nothing wedges forever."""
    try:
        return float(os.environ.get("NDS_TPU_FAULT_HANG_S", "30"))
    except ValueError:
        return 30.0


def reset_fault_counts() -> None:
    """Zero the occurrence counters (diff-harness helper: each matrix
    entry starts from a known state so ``nth`` is deterministic)."""
    with _FAULT_LOCK:
        _FAULT_COUNTS.clear()


def fired_count(seam: str) -> int:
    """How many fault_point() occurrences the seam has seen since the
    last reset while targeted — the harness's injection-actually-fired
    check."""
    with _FAULT_LOCK:
        return _FAULT_COUNTS.get(seam, 0)


def fault_point(seam: str, detail: str = "") -> None:
    """The injection seam: a no-op unless ``NDS_TPU_FAULT`` targets
    ``seam``, in which case the ``nth`` occurrence raises
    :class:`FaultInjected` (``kind=hang`` sleeps ``NDS_TPU_FAULT_HANG_S``
    first — the hung-sync simulation). Exactly one injection fires per
    spec per process; occurrences are counted under the lock so
    concurrent threads agree on ``nth``. Callers place this at the TOP
    of the seam's real work, so the simulated fault interrupts exactly
    where a real one would."""
    spec = fault_spec()
    if spec is None or spec[0] != seam:
        return
    _seam, kind, nth = spec
    with _FAULT_LOCK:
        n = _FAULT_COUNTS[seam] = _FAULT_COUNTS.get(seam, 0) + 1
    if n != nth:
        return
    if kind == "hang":
        time.sleep(hang_seconds())
    raise FaultInjected(seam, f"injected fault at seam {seam!r}"
                        + (f" ({detail})" if detail else ""))


# ---------------------------------------------------------------------------
# recovery: bounded deterministic retry
# ---------------------------------------------------------------------------

# deterministic backoff schedule base (seconds): attempt k sleeps
# base * 2^k — no randomness, so the diff harness's wall bound holds
_BACKOFF_BASE_S = 0.02


def _is_transient(exc: BaseException, seam: Seam) -> bool:
    names = {t.__name__ for t in type(exc).__mro__}
    return bool(names & set(seam.retry_on))


def with_retry(seam_name: str, fn, record=record_fault_event):
    """Run ``fn`` under the seam's bounded-retry policy: an exception in
    the seam's ``retry_on`` set retries up to ``retries`` times with
    deterministic backoff; success after k>0 failures records ONE
    ``recovered`` FaultEvent (via ``record`` — ring workers pass a
    sink that re-records on the driver thread); exhaustion re-raises
    the last transient error unchanged (already classified when it is a
    FaultError). Non-transient exceptions propagate untouched on the
    FIRST attempt — a retry loop must never mask an engine bug.
    ``NDS_TPU_FAULT_DRIFT`` suppresses the retries entirely (the
    --inject-drift self-test)."""
    seam = SEAMS[seam_name]
    attempts = 1 + (0 if _drift() else max(seam.retries, 0))
    last = None
    for k in range(attempts):
        try:
            out = fn()
        except BaseException as exc:
            if not _is_transient(exc, seam) or k + 1 >= attempts:
                raise
            last = exc
            time.sleep(_BACKOFF_BASE_S * (1 << k))
            continue
        if k > 0:
            ev_seam = last.seam if isinstance(last, FaultError) \
                else seam_name
            record(ev_seam, "recovered", attempt=k,
                   detail=f"{type(last).__name__}: {last}")
        return out
    raise AssertionError("unreachable")  # pragma: no cover


# ---------------------------------------------------------------------------
# statement watchdog
# ---------------------------------------------------------------------------


def statement_deadline_s() -> float | None:
    """``NDS_TPU_STATEMENT_DEADLINE_S`` (read at use; unset/<=0 = off):
    the per-statement wall budget the watchdog enforces at every
    bounded wait."""
    env = os.environ.get("NDS_TPU_STATEMENT_DEADLINE_S", "").strip()
    if not env:
        return None
    try:
        v = float(env)
    except ValueError:
        return None
    return v if v > 0 else None


_stmt_tls = threading.local()


class statement_scope:
    """Thread-scoped statement clock (entered by ``Session.sql``): the
    watchdog charges every bounded wait against ONE per-statement
    budget, so N slow fetches cannot each consume a fresh deadline.
    Re-entrant statements (a view definition executing a query) keep the
    OUTER clock — the statement the user is waiting on."""

    def __enter__(self):
        self._outer = getattr(_stmt_tls, "start", None)
        if self._outer is None:
            _stmt_tls.start = time.monotonic()
        return self

    def __exit__(self, *exc):
        if self._outer is None:
            _stmt_tls.start = None
        return False


def _remaining_s() -> float | None:
    """Remaining statement budget, or None when the watchdog is off.
    Outside any statement scope the full deadline applies per wait."""
    deadline = statement_deadline_s()
    if deadline is None:
        return None
    start = getattr(_stmt_tls, "start", None)
    if start is None:
        return deadline
    return deadline - (time.monotonic() - start)


def bounded_call(seam_name: str, fn):
    """Run a blocking wait under the statement watchdog. Watchdog off
    (the default): call inline — zero threads, zero overhead,
    bit-for-bit today's path. Armed: the call runs on a daemon helper
    thread and the driver waits at most the statement's REMAINING
    budget; expiry records a ``timeout`` FaultEvent and raises
    :class:`StatementTimeout` (the helper stays blocked — an
    interruptible hang does not exist in-process; the classified error
    is the contract). A helper-thread exception re-raises on the
    driver unchanged."""
    remaining = _remaining_s()
    if remaining is None:
        return fn()
    if remaining <= 0:
        record_fault_event(seam_name, "timeout",
                           detail="statement budget exhausted")
        raise StatementTimeout(
            seam_name, f"statement deadline "
            f"({statement_deadline_s()}s) already exhausted before the "
            f"{seam_name!r} wait")
    box: list = []
    done = threading.Event()

    def runner():
        try:
            box.append(("ok", fn()))
        except BaseException as exc:  # propagate to the driver, always
            box.append(("err", exc))
        finally:
            done.set()

    t = threading.Thread(target=runner, daemon=True,
                         name=f"nds-watchdog-{seam_name}")
    t.start()
    if not done.wait(timeout=remaining):
        record_fault_event(seam_name, "timeout",
                           detail=f"blocked > {remaining:.1f}s remaining")
        raise StatementTimeout(
            seam_name, f"{seam_name!r} wait exceeded the statement "
            f"deadline (NDS_TPU_STATEMENT_DEADLINE_S="
            f"{statement_deadline_s()}); statement marked timeout")
    kind, val = box[0]
    if kind == "err":
        raise val
    return val
