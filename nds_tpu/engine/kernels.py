# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Pallas TPU kernels for the hot aggregation path.

The reference delegates its hot operators to the RAPIDS plugin's CUDA
kernels (SURVEY.md §2.2 N4). Here the hottest device pattern — masked
grouped aggregation, the inner loop of every GROUP BY query — gets a
TPU-native Pallas kernel that rides the MXU: a segment-sum is a matmul
against a one-hot membership matrix, so each (row-tile × group-tile) grid
cell builds its one-hot block in VMEM with ``broadcasted_iota`` compares and
accumulates ``w @ onehot`` partial sums on the systolic array. For the group
counts the same trick runs with unit weights, so one kernel emits both.

This beats a scatter-add lowering when groups are modest (TPC-DS group-bys:
brands, categories, states — hundreds to tens of thousands of groups) because
the MXU does 128×128 MACs/cycle while scatter serializes on HBM.

Use :func:`segment_sum_fused` — it picks the Pallas path on TPU (or when
``NDS_TPU_PALLAS=interpret`` for tests) and falls back to
``jax.ops.segment_sum`` elsewhere. Values are accumulated in float32 on the
MXU; the engine's exact int64 decimal path keeps using the XLA fallback
(int64 matmul does not map to the MXU), mirroring the reference's
``--floats`` fast path vs exact-decimal split (ref: nds/nds_transcode.py
--floats, nds/README.md decimal notes).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

try:  # pallas is TPU/experimental; keep the engine importable without it
    from jax.experimental import pallas as pl
    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    pl = None
    _HAVE_PALLAS = False

# row tile: sublane-friendly multiple; group tile: one lane width
_TR = 512
_TG = 128


def _pallas_mode() -> str:
    """'tpu' | 'interpret' | 'off'."""
    env = os.environ.get("NDS_TPU_PALLAS", "auto")
    if env == "off" or not _HAVE_PALLAS:
        return "off"
    if env == "interpret":
        return "interpret"
    if env in ("auto", "1", "tpu"):
        try:
            if jax.default_backend() == "tpu":
                return "tpu"
        except RuntimeError:  # pragma: no cover
            pass
        return "off"
    return "off"


def _seg_kernel(gid_ref, w_ref, sum_ref, cnt_ref):
    """One (group-tile j, row-tile i) cell: accumulate this row tile's
    contribution to this group tile's sums and counts via MXU matmuls.

    The row (reduction) dimension is the INNERMOST grid dim so each output
    block sees its row tiles on consecutive grid steps — Pallas only keeps an
    output block's VMEM buffer live across consecutive steps mapping to the
    same block, so accumulation across a non-innermost reduction dim would
    read stale buffers on real hardware."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)

    gid = gid_ref[:]                      # (1, TR) int32, -1 = masked row
    w = w_ref[:].astype(jnp.float32)      # (1, TR)
    j = pl.program_id(0)
    gbase = j * _TG
    # one-hot membership block (TR, TG): rows vs this tile's group ids
    groups = gbase + jax.lax.broadcasted_iota(jnp.int32, (_TR, _TG), 1)
    onehot = (gid.reshape(_TR, 1) == groups).astype(jnp.float32)
    sum_ref[:] += jnp.dot(w, onehot, preferred_element_type=jnp.float32)
    live = (gid.reshape(1, _TR) >= 0).astype(jnp.float32)
    cnt_ref[:] += jnp.dot(live, onehot, preferred_element_type=jnp.float32)


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnums=(2, 3))
def _segment_sum_pallas(gids, weights, num_segments: int, interpret: bool):
    n = gids.shape[0]
    n_pad = max(_ceil_to(n, _TR), _TR)
    g_pad = max(_ceil_to(num_segments, _TG), _TG)
    # pad rows with gid -1 (matches no group) and zero weight
    gid_p = jnp.full(n_pad, -1, dtype=jnp.int32).at[:n].set(
        gids.astype(jnp.int32))
    w_p = jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
        weights.astype(jnp.float32))
    grid = (g_pad // _TG, n_pad // _TR)   # rows innermost (see kernel doc)
    sums, counts = pl.pallas_call(
        _seg_kernel,
        grid=grid,
        # the leading block index must stay i32: a literal 0 weak-types to
        # i64 under the engine's jax_enable_x64, and Mosaic refuses the
        # mixed (i64, i32) index-map return (seen on the v5e attachment as
        # "failed to legalize operation 'func.return'"); j - j keeps the
        # zero in the grid index's own dtype
        in_specs=[
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, _TG), lambda j, i: (i - i, j)),
            pl.BlockSpec((1, _TG), lambda j, i: (i - i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, g_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, g_pad), jnp.float32),
        ],
        interpret=interpret,
    )(gid_p.reshape(1, n_pad), w_p.reshape(1, n_pad))
    return sums[0, :num_segments], counts[0, :num_segments]


_pallas_broken = False

# the one-hot matmul does O(rows x groups) MACs — MXU throughput makes that
# a win over scatter only while the group tile count stays small. Measured
# on v5e (n=16M): 1.8x faster at 1k groups, 12x SLOWER at 64k groups.
_MAX_GROUPS = int(os.environ.get("NDS_TPU_PALLAS_MAX_GROUPS", "2048"))


def pallas_active(num_segments: int | None = None) -> bool:
    """True when :func:`segment_sum_fused` will take the Pallas path for
    this group count. Callers must gate on this (not the raw env var) so the
    exact XLA path is used whenever the kernel itself would fall back."""
    if num_segments is not None and num_segments > _MAX_GROUPS:
        return False
    return not _pallas_broken and _pallas_mode() != "off"


def segment_sum_fused(weights, gids, num_segments: int):
    """(sums f32[G], counts f32[G]) of ``weights`` grouped by ``gids``.

    Rows with gid < 0 are excluded (pre-masked nulls / filtered rows).
    Pallas MXU path on TPU (small group counts — see ``_MAX_GROUPS``), XLA
    segment ops elsewhere. Some TPU attachment paths (e.g. tunneled
    remote-compile backends) cannot compile Mosaic kernels at all; the first
    such failure permanently flips to the XLA fallback for the process
    instead of failing the query.
    """
    global _pallas_broken
    mode = _pallas_mode()
    if mode != "off" and not _pallas_broken and \
            num_segments <= _MAX_GROUPS:
        try:
            return _segment_sum_pallas(gids, weights, num_segments,
                                       mode == "interpret")
        except Exception as e:  # Mosaic unsupported on this attachment
            _pallas_broken = True
            from nds_tpu.listener import report_task_failure
            report_task_failure("pallas segment-sum kernel "
                                "(permanent XLA fallback)", e)
            import sys
            print(f"# pallas kernels disabled ({type(e).__name__}); "
                  f"using XLA fallback", file=sys.stderr)
    live = gids >= 0
    safe = jnp.where(live, gids, 0)
    w = jnp.where(live, weights.astype(jnp.float32), 0.0)
    sums = jax.ops.segment_sum(w, safe, num_segments=num_segments)
    counts = jax.ops.segment_sum(live.astype(jnp.float32), safe,
                                 num_segments=num_segments)
    return sums, counts




# ---------------------------------------------------------------------------
# EXACT int64 segment-sum via limb-split MXU matmuls (the decimal path)
# ---------------------------------------------------------------------------
#
# The default bench runs exact decimals (scaled int64), which the f32 MXU
# kernel above cannot carry (24-bit mantissa). Two's-complement limb
# decomposition makes it exact for ANY int64 — no trust in declared value
# bounds: limbs 0-6 are unsigned bytes, the top limb is the SIGNED
# arithmetic shift (v >> 56, in [-128, 127]), so v = sum_l limb_l << 8l
# identically. All 8 limbs plus the count row ride ONE (9, TR) x (TR, TG)
# MXU matmul per grid cell (the systolic array processes the 9-row operand
# in the same tile pass as the 1-row f32 kernel's). A per-cell partial is
# <= 512*255 < 2^17 so the f32 dot is exact; cross-tile accumulation
# happens in an i32 output ref (exact while n*255 < 2^31 => n < 2^23 rows
# — the one gate), and the i64 recombination runs in XLA on the tiny
# (9, G) result, wrapping on true-sum overflow exactly like the XLA
# segment-sum it replaces.

_LIMB_BITS = 8
_N_LIMBS = 8            # full int64 coverage: 7 unsigned bytes + signed top


def _seg_exact_kernel(gid_ref, w_ref, acc_ref):
    """One (group-tile j, row-tile i) cell: (9, TR) limb rows (+count
    row) hit the one-hot membership block in a single MXU matmul; the f32
    partial (exact, < 2^17) accumulates into the i32 output ref."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    gid = gid_ref[:]                      # (1, TR) i32, -1 = masked row
    j = pl.program_id(0)
    groups = j * _TG + jax.lax.broadcasted_iota(jnp.int32, (_TR, _TG), 1)
    onehot = (gid.reshape(_TR, 1) == groups).astype(jnp.float32)
    part = jnp.dot(w_ref[:], onehot, preferred_element_type=jnp.float32)
    acc_ref[:] += part.astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _segment_sum_exact_pallas(gids, values, num_segments: int,
                              interpret: bool):
    n = gids.shape[0]
    k = _N_LIMBS
    live = gids >= 0
    v = jnp.where(live, values, 0)
    n_pad = max(_ceil_to(n, _TR), _TR)
    g_pad = max(_ceil_to(num_segments, _TG), _TG)
    gid_p = jnp.full(n_pad, -1, dtype=jnp.int32).at[:n].set(
        gids.astype(jnp.int32))
    rows = []
    for l in range(k - 1):
        limb = (v >> (_LIMB_BITS * l)) & jnp.int64(255)
        rows.append(jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
            limb.astype(jnp.float32)))
    top = v >> (_LIMB_BITS * (k - 1))              # signed, [-128, 127]
    rows.append(jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
        top.astype(jnp.float32)))
    rows.append(jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
        live.astype(jnp.float32)))                 # count row
    w = jnp.stack(rows)                            # (k+1, n_pad)
    grid = (g_pad // _TG, n_pad // _TR)            # rows innermost
    acc = pl.pallas_call(
        _seg_exact_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
            pl.BlockSpec((k + 1, _TR), lambda j, i: (j - j, i)),
        ],
        out_specs=pl.BlockSpec((k + 1, _TG), lambda j, i: (i - i, j)),
        out_shape=jax.ShapeDtypeStruct((k + 1, g_pad), jnp.int32),
        interpret=interpret,
    )(gid_p.reshape(1, n_pad), w)
    acc = acc[:, :num_segments].astype(jnp.int64)
    sums = jnp.zeros(num_segments, dtype=jnp.int64)
    for l in range(k):
        sums = sums + (acc[l] << (_LIMB_BITS * l))
    return sums, acc[k]


# measured crossover on v5e (min-of-5, hard device->host sync; n x G):
#   1M x 256:  pallas 60.5ms  vs XLA  83.6ms   (pallas 1.38x)
#   4M x 1024: pallas 132.5ms vs XLA 102.2ms   (XLA 1.30x)
#  16M x 1024: pallas 187.8ms vs XLA  97.4ms   (XLA 1.93x)
#  16M x 2048: pallas 352.4ms vs XLA 107.5ms   (XLA 3.28x)
# the one-hot matmul does O(n*G) MACs while XLA's scatter is O(n), so the
# exact kernel engages only below the measured n*G break-even
_EXACT_ONEHOT_BUDGET = int(float(os.environ.get(
    "NDS_TPU_EXACT_ONEHOT_BUDGET", "3e8")))


def exact_sum_supported(num_segments: int, n_rows: int) -> bool:
    """True when the exact limb-split kernel will engage: Pallas active
    for this group count, per-limb i32 accumulation cannot overflow, and
    the O(n*G) one-hot work sits below the measured XLA-scatter
    break-even (table above)."""
    return (pallas_active(num_segments) and n_rows < (1 << 23)
            and n_rows * max(num_segments, 1) <= _EXACT_ONEHOT_BUDGET)


def segment_sum_exact(values, gids, num_segments: int):
    """EXACT (sums i64[G], counts i64[G]) of any int64 ``values`` grouped
    by ``gids`` (rows with gid < 0 excluded). MXU limb path on TPU under
    the same gates as :func:`segment_sum_fused`; XLA segment ops
    elsewhere. Unlike the f32 kernel this is bit-exact — it serves the
    DEFAULT decimal bench path."""
    global _pallas_broken
    mode = _pallas_mode()
    if mode != "off" and not _pallas_broken and \
            exact_sum_supported(num_segments, int(values.shape[0])):
        try:
            sums, counts = _segment_sum_exact_pallas(
                gids, values, num_segments, mode == "interpret")
            return sums, counts.astype(jnp.int64)
        except Exception as e:  # Mosaic unsupported on this attachment
            _pallas_broken = True
            from nds_tpu.listener import report_task_failure
            report_task_failure("pallas exact segment-sum kernel "
                                "(permanent XLA fallback)", e)
            import sys
            print("# pallas kernels disabled; using XLA fallback",
                  file=sys.stderr)
    live = gids >= 0
    safe = jnp.where(live, gids, 0)
    v = jnp.where(live, values, 0)
    sums = jax.ops.segment_sum(v, safe, num_segments=num_segments)
    counts = jax.ops.segment_sum(live.astype(jnp.int64), safe,
                                 num_segments=num_segments)
    return sums, counts


# ---------------------------------------------------------------------------
# segment min/max (VPU tiled reduce over the same one-hot membership tiling)
# ---------------------------------------------------------------------------

_F32_MAX = 3.4e38


def _seg_minmax_kernel(gid_ref, v_ref, min_ref, max_ref):
    """One (group-tile j, row-tile i) cell: masked row-tile min and max per
    group. Same grid discipline as :func:`_seg_kernel` (rows innermost)."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        min_ref[:] = jnp.full_like(min_ref, _F32_MAX)
        max_ref[:] = jnp.full_like(max_ref, -_F32_MAX)

    gid = gid_ref[:]                     # (1, TR) int32, -1 = masked row
    v = v_ref[:].astype(jnp.float32)     # (1, TR)
    j = pl.program_id(0)
    gbase = j * _TG
    groups = gbase + jax.lax.broadcasted_iota(jnp.int32, (_TR, _TG), 1)
    member = gid.reshape(_TR, 1) == groups              # (TR, TG) bool
    vb = v.reshape(_TR, 1)
    # sentinels must be f32 CONSTANTS: a bare Python float weak-types to
    # f64 under jax_enable_x64 and Mosaic cannot legalize the tpu.truncf
    # the promotion would need
    big = jnp.float32(_F32_MAX)
    lo = jnp.where(member, vb, big)
    hi = jnp.where(member, vb, -big)
    min_ref[:] = jnp.minimum(min_ref[:], jnp.min(lo, axis=0).reshape(1, _TG))
    max_ref[:] = jnp.maximum(max_ref[:], jnp.max(hi, axis=0).reshape(1, _TG))


@functools.partial(jax.jit, static_argnums=(2, 3))
def _segment_minmax_pallas(gids, values, num_segments: int, interpret: bool):
    n = gids.shape[0]
    n_pad = max(_ceil_to(n, _TR), _TR)
    g_pad = max(_ceil_to(num_segments, _TG), _TG)
    gid_p = jnp.full(n_pad, -1, dtype=jnp.int32).at[:n].set(
        gids.astype(jnp.int32))
    v_p = jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
        values.astype(jnp.float32))
    grid = (g_pad // _TG, n_pad // _TR)
    mins, maxs = pl.pallas_call(
        _seg_minmax_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, _TG), lambda j, i: (i - i, j)),
            pl.BlockSpec((1, _TG), lambda j, i: (i - i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, g_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, g_pad), jnp.float32),
        ],
        interpret=interpret,
    )(gid_p.reshape(1, n_pad), v_p.reshape(1, n_pad))
    return mins[0, :num_segments], maxs[0, :num_segments]


def segment_minmax_fused(values, gids, num_segments: int):
    """(mins f32[G], maxs f32[G]) of ``values`` grouped by ``gids`` (rows
    with gid < 0 excluded; empty groups come back as +/-_F32_MAX). Pallas
    VPU path on TPU under the same small-group-count gate as
    :func:`segment_sum_fused`; XLA segment ops elsewhere.

    f32 precision note: like the sum kernel this is the opt-in float path —
    the engine's exact decimal/int64 min/max stays on XLA (f32 rounding
    would corrupt exact comparisons).
    """
    global _pallas_broken
    mode = _pallas_mode()
    if mode != "off" and not _pallas_broken and \
            num_segments <= _MAX_GROUPS:
        try:
            return _segment_minmax_pallas(gids, values, num_segments,
                                          mode == "interpret")
        except Exception as e:  # Mosaic unsupported on this attachment
            _pallas_broken = True
            from nds_tpu.listener import report_task_failure
            report_task_failure("pallas segment-min/max kernel "
                                "(permanent XLA fallback)", e)
            import sys
            print("# pallas kernels disabled; using XLA fallback",
                  file=sys.stderr)
    live = gids >= 0
    safe = jnp.where(live, gids, 0)
    v = values.astype(jnp.float32)
    mins = jax.ops.segment_min(jnp.where(live, v, _F32_MAX), safe,
                               num_segments=num_segments)
    maxs = jax.ops.segment_max(jnp.where(live, v, -_F32_MAX), safe,
                               num_segments=num_segments)
    return mins, maxs
