# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Pallas TPU kernels for the hot aggregation path.

The reference delegates its hot operators to the RAPIDS plugin's CUDA
kernels (SURVEY.md §2.2 N4). Here the hottest device pattern — masked
grouped aggregation, the inner loop of every GROUP BY query — gets a
TPU-native Pallas kernel that rides the MXU: a segment-sum is a matmul
against a one-hot membership matrix, so each (row-tile × group-tile) grid
cell builds its one-hot block in VMEM with ``broadcasted_iota`` compares and
accumulates ``w @ onehot`` partial sums on the systolic array. For the group
counts the same trick runs with unit weights, so one kernel emits both.

This beats a scatter-add lowering when groups are modest (TPC-DS group-bys:
brands, categories, states — hundreds to tens of thousands of groups) because
the MXU does 128×128 MACs/cycle while scatter serializes on HBM.

Use :func:`segment_sum_fused` — it picks the Pallas path on TPU (or when
``NDS_TPU_PALLAS=interpret`` for tests) and falls back to
``jax.ops.segment_sum`` elsewhere. Values are accumulated in float32 on the
MXU; the engine's exact int64 decimal path keeps using the XLA fallback
(int64 matmul does not map to the MXU), mirroring the reference's
``--floats`` fast path vs exact-decimal split (ref: nds/nds_transcode.py
--floats, nds/README.md decimal notes).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading

import jax
import jax.numpy as jnp

try:  # pallas is TPU/experimental; keep the engine importable without it
    from jax.experimental import pallas as pl
    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    pl = None
    _HAVE_PALLAS = False

# row tile: sublane-friendly multiple; group tile: one lane width
_TR = 512
_TG = 128


def _pallas_mode() -> str:
    """'tpu' | 'interpret' | 'off'."""
    env = os.environ.get("NDS_TPU_PALLAS", "auto")
    if env == "off" or not _HAVE_PALLAS:
        return "off"
    if env == "interpret":
        return "interpret"
    if env in ("auto", "1", "tpu"):
        try:
            if jax.default_backend() == "tpu":
                return "tpu"
        except RuntimeError:  # pragma: no cover
            pass
        return "off"
    return "off"


def _seg_kernel(gid_ref, w_ref, sum_ref, cnt_ref):
    """One (group-tile j, row-tile i) cell: accumulate this row tile's
    contribution to this group tile's sums and counts via MXU matmuls.

    The row (reduction) dimension is the INNERMOST grid dim so each output
    block sees its row tiles on consecutive grid steps — Pallas only keeps an
    output block's VMEM buffer live across consecutive steps mapping to the
    same block, so accumulation across a non-innermost reduction dim would
    read stale buffers on real hardware."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)

    gid = gid_ref[:]                      # (1, TR) int32, -1 = masked row
    w = w_ref[:].astype(jnp.float32)      # (1, TR)
    j = pl.program_id(0)
    gbase = j * _TG
    # one-hot membership block (TR, TG): rows vs this tile's group ids
    groups = gbase + jax.lax.broadcasted_iota(jnp.int32, (_TR, _TG), 1)
    onehot = (gid.reshape(_TR, 1) == groups).astype(jnp.float32)
    sum_ref[:] += jnp.dot(w, onehot, preferred_element_type=jnp.float32)
    live = (gid.reshape(1, _TR) >= 0).astype(jnp.float32)
    cnt_ref[:] += jnp.dot(live, onehot, preferred_element_type=jnp.float32)


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.jit, static_argnums=(2, 3))
def _segment_sum_pallas(gids, weights, num_segments: int, interpret: bool):
    n = gids.shape[0]
    n_pad = max(_ceil_to(n, _TR), _TR)
    g_pad = max(_ceil_to(num_segments, _TG), _TG)
    # pad rows with gid -1 (matches no group) and zero weight
    gid_p = jnp.full(n_pad, -1, dtype=jnp.int32).at[:n].set(
        gids.astype(jnp.int32))
    w_p = jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
        weights.astype(jnp.float32))
    grid = (g_pad // _TG, n_pad // _TR)   # rows innermost (see kernel doc)
    sums, counts = pl.pallas_call(
        _seg_kernel,
        grid=grid,
        # the leading block index must stay i32: a literal 0 weak-types to
        # i64 under the engine's jax_enable_x64, and Mosaic refuses the
        # mixed (i64, i32) index-map return (seen on the v5e attachment as
        # "failed to legalize operation 'func.return'"); j - j keeps the
        # zero in the grid index's own dtype
        in_specs=[
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, _TG), lambda j, i: (i - i, j)),
            pl.BlockSpec((1, _TG), lambda j, i: (i - i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, g_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, g_pad), jnp.float32),
        ],
        interpret=interpret,
    )(gid_p.reshape(1, n_pad), w_p.reshape(1, n_pad))
    return sums[0, :num_segments], counts[0, :num_segments]


_pallas_broken = False

# the one-hot matmul does O(rows x groups) MACs — MXU throughput makes that
# a win over scatter only while the group tile count stays small. Measured
# on v5e (n=16M): 1.8x faster at 1k groups, 12x SLOWER at 64k groups.
# Read at USE time (not import): the ceiling picks which segment
# implementation TRACES, so it is a pipeline-cache key member
# (engine/stream.py _cache_key) and a post-import change must retrace.
def max_groups() -> int:
    return int(os.environ.get("NDS_TPU_PALLAS_MAX_GROUPS", "2048"))


def pallas_active(num_segments: int | None = None) -> bool:
    """True when :func:`segment_sum_fused` will take the Pallas path for
    this group count. Callers must gate on this (not the raw env var) so the
    exact XLA path is used whenever the kernel itself would fall back."""
    if num_segments is not None and num_segments > max_groups():
        return False
    return not _pallas_broken and _pallas_mode() != "off"


def segment_sum_fused(weights, gids, num_segments: int):
    """(sums f32[G], counts f32[G]) of ``weights`` grouped by ``gids``.

    Rows with gid < 0 are excluded (pre-masked nulls / filtered rows).
    Pallas MXU path on TPU (small group counts — see ``max_groups()``), XLA
    segment ops elsewhere. Some TPU attachment paths (e.g. tunneled
    remote-compile backends) cannot compile Mosaic kernels at all; the first
    such failure permanently flips to the XLA fallback for the process
    instead of failing the query.
    """
    global _pallas_broken
    mode = _pallas_mode()
    if mode != "off" and not _pallas_broken and \
            num_segments <= max_groups():
        try:
            return _segment_sum_pallas(gids, weights, num_segments,
                                       mode == "interpret")
        except Exception as e:  # Mosaic unsupported on this attachment
            _pallas_broken = True
            from nds_tpu.listener import report_task_failure
            report_task_failure("pallas segment-sum kernel "
                                "(permanent XLA fallback)", e)
            import sys
            print(f"# pallas kernels disabled ({type(e).__name__}); "
                  f"using XLA fallback", file=sys.stderr)
    live = gids >= 0
    safe = jnp.where(live, gids, 0)
    w = jnp.where(live, weights.astype(jnp.float32), 0.0)
    sums = jax.ops.segment_sum(w, safe, num_segments=num_segments)
    counts = jax.ops.segment_sum(live.astype(jnp.float32), safe,
                                 num_segments=num_segments)
    return sums, counts




# ---------------------------------------------------------------------------
# EXACT int64 segment-sum via limb-split MXU matmuls (the decimal path)
# ---------------------------------------------------------------------------
#
# The default bench runs exact decimals (scaled int64), which the f32 MXU
# kernel above cannot carry (24-bit mantissa). Two's-complement limb
# decomposition makes it exact for ANY int64 — no trust in declared value
# bounds: limbs 0-6 are unsigned bytes, the top limb is the SIGNED
# arithmetic shift (v >> 56, in [-128, 127]), so v = sum_l limb_l << 8l
# identically. All 8 limbs plus the count row ride ONE (9, TR) x (TR, TG)
# MXU matmul per grid cell (the systolic array processes the 9-row operand
# in the same tile pass as the 1-row f32 kernel's). A per-cell partial is
# <= 512*255 < 2^17 so the f32 dot is exact; cross-tile accumulation
# happens in an i32 output ref (exact while n*255 < 2^31 => n < 2^23 rows
# — the one gate), and the i64 recombination runs in XLA on the tiny
# (9, G) result, wrapping on true-sum overflow exactly like the XLA
# segment-sum it replaces. Each arithmetic claim in this paragraph (limb
# identity, f32-exact partials, the i32 gate, the f32 mantissa limit) is
# an executable check in ``analysis/num_audit.kernel_claim_checks``; the
# per-statement accumulator-range proofs that make the wrap-on-overflow
# caveat unreachable at the audited scale live in the same module.

_LIMB_BITS = 8
_N_LIMBS = 8            # full int64 coverage: 7 unsigned bytes + signed top


def _seg_exact_kernel(gid_ref, w_ref, acc_ref):
    """One (group-tile j, row-tile i) cell: (9, TR) limb rows (+count
    row) hit the one-hot membership block in a single MXU matmul; the f32
    partial (exact, < 2^17) accumulates into the i32 output ref."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    gid = gid_ref[:]                      # (1, TR) i32, -1 = masked row
    j = pl.program_id(0)
    groups = j * _TG + jax.lax.broadcasted_iota(jnp.int32, (_TR, _TG), 1)
    onehot = (gid.reshape(_TR, 1) == groups).astype(jnp.float32)
    part = jnp.dot(w_ref[:], onehot, preferred_element_type=jnp.float32)
    acc_ref[:] += part.astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _segment_sum_exact_pallas(gids, values, num_segments: int,
                              interpret: bool):
    n = gids.shape[0]
    k = _N_LIMBS
    live = gids >= 0
    v = jnp.where(live, values, 0)
    n_pad = max(_ceil_to(n, _TR), _TR)
    g_pad = max(_ceil_to(num_segments, _TG), _TG)
    gid_p = jnp.full(n_pad, -1, dtype=jnp.int32).at[:n].set(
        gids.astype(jnp.int32))
    rows = []
    for l in range(k - 1):
        limb = (v >> (_LIMB_BITS * l)) & jnp.int64(255)
        rows.append(jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
            limb.astype(jnp.float32)))
    top = v >> (_LIMB_BITS * (k - 1))              # signed, [-128, 127]
    rows.append(jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
        top.astype(jnp.float32)))
    rows.append(jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
        live.astype(jnp.float32)))                 # count row
    w = jnp.stack(rows)                            # (k+1, n_pad)
    grid = (g_pad // _TG, n_pad // _TR)            # rows innermost
    acc = pl.pallas_call(
        _seg_exact_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
            pl.BlockSpec((k + 1, _TR), lambda j, i: (j - j, i)),
        ],
        out_specs=pl.BlockSpec((k + 1, _TG), lambda j, i: (i - i, j)),
        out_shape=jax.ShapeDtypeStruct((k + 1, g_pad), jnp.int32),
        interpret=interpret,
    )(gid_p.reshape(1, n_pad), w)
    acc = acc[:, :num_segments].astype(jnp.int64)
    sums = jnp.zeros(num_segments, dtype=jnp.int64)
    for l in range(k):
        sums = sums + (acc[l] << (_LIMB_BITS * l))
    return sums, acc[k]


# measured crossover on v5e (min-of-5, hard device->host sync; n x G):
#   1M x 256:  pallas 60.5ms  vs XLA  83.6ms   (pallas 1.38x)
#   4M x 1024: pallas 132.5ms vs XLA 102.2ms   (XLA 1.30x)
#  16M x 1024: pallas 187.8ms vs XLA  97.4ms   (XLA 1.93x)
#  16M x 2048: pallas 352.4ms vs XLA 107.5ms   (XLA 3.28x)
# the one-hot matmul does O(n*G) MACs while XLA's scatter is O(n), so the
# exact kernel engages only below the measured n*G break-even.
# Read at USE time for the same reason as max_groups() above.
def exact_onehot_budget() -> int:
    return int(float(os.environ.get("NDS_TPU_EXACT_ONEHOT_BUDGET", "3e8")))


def exact_sum_supported(num_segments: int, n_rows: int) -> bool:
    """True when the exact limb-split kernel will engage: Pallas active
    for this group count, per-limb i32 accumulation cannot overflow, and
    the O(n*G) one-hot work sits below the measured XLA-scatter
    break-even (table above)."""
    return (pallas_active(num_segments) and n_rows < (1 << 23)
            and n_rows * max(num_segments, 1) <= exact_onehot_budget())


def segment_sum_exact(values, gids, num_segments: int):
    """EXACT (sums i64[G], counts i64[G]) of any int64 ``values`` grouped
    by ``gids`` (rows with gid < 0 excluded). MXU limb path on TPU under
    the same gates as :func:`segment_sum_fused`; XLA segment ops
    elsewhere. Unlike the f32 kernel this is bit-exact — it serves the
    DEFAULT decimal bench path."""
    global _pallas_broken
    mode = _pallas_mode()
    if mode != "off" and not _pallas_broken and \
            exact_sum_supported(num_segments, int(values.shape[0])):
        try:
            sums, counts = _segment_sum_exact_pallas(
                gids, values, num_segments, mode == "interpret")
            return sums, counts.astype(jnp.int64)
        except Exception as e:  # Mosaic unsupported on this attachment
            _pallas_broken = True
            from nds_tpu.listener import report_task_failure
            report_task_failure("pallas exact segment-sum kernel "
                                "(permanent XLA fallback)", e)
            import sys
            print("# pallas kernels disabled; using XLA fallback",
                  file=sys.stderr)
    live = gids >= 0
    safe = jnp.where(live, gids, 0)
    v = jnp.where(live, values, 0)
    sums = jax.ops.segment_sum(v, safe, num_segments=num_segments)
    counts = jax.ops.segment_sum(live.astype(jnp.int64), safe,
                                 num_segments=num_segments)
    return sums, counts


# ---------------------------------------------------------------------------
# segment min/max (VPU tiled reduce over the same one-hot membership tiling)
# ---------------------------------------------------------------------------

_F32_MAX = 3.4e38


def _seg_minmax_kernel(gid_ref, v_ref, min_ref, max_ref):
    """One (group-tile j, row-tile i) cell: masked row-tile min and max per
    group. Same grid discipline as :func:`_seg_kernel` (rows innermost)."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        min_ref[:] = jnp.full_like(min_ref, _F32_MAX)
        max_ref[:] = jnp.full_like(max_ref, -_F32_MAX)

    gid = gid_ref[:]                     # (1, TR) int32, -1 = masked row
    v = v_ref[:].astype(jnp.float32)     # (1, TR)
    j = pl.program_id(0)
    gbase = j * _TG
    groups = gbase + jax.lax.broadcasted_iota(jnp.int32, (_TR, _TG), 1)
    member = gid.reshape(_TR, 1) == groups              # (TR, TG) bool
    vb = v.reshape(_TR, 1)
    # sentinels must be f32 CONSTANTS: a bare Python float weak-types to
    # f64 under jax_enable_x64 and Mosaic cannot legalize the tpu.truncf
    # the promotion would need
    big = jnp.float32(_F32_MAX)
    lo = jnp.where(member, vb, big)
    hi = jnp.where(member, vb, -big)
    min_ref[:] = jnp.minimum(min_ref[:], jnp.min(lo, axis=0).reshape(1, _TG))
    max_ref[:] = jnp.maximum(max_ref[:], jnp.max(hi, axis=0).reshape(1, _TG))


@functools.partial(jax.jit, static_argnums=(2, 3))
def _segment_minmax_pallas(gids, values, num_segments: int, interpret: bool):
    n = gids.shape[0]
    n_pad = max(_ceil_to(n, _TR), _TR)
    g_pad = max(_ceil_to(num_segments, _TG), _TG)
    gid_p = jnp.full(n_pad, -1, dtype=jnp.int32).at[:n].set(
        gids.astype(jnp.int32))
    v_p = jnp.zeros(n_pad, dtype=jnp.float32).at[:n].set(
        values.astype(jnp.float32))
    grid = (g_pad // _TG, n_pad // _TR)
    mins, maxs = pl.pallas_call(
        _seg_minmax_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
            pl.BlockSpec((1, _TR), lambda j, i: (j - j, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, _TG), lambda j, i: (i - i, j)),
            pl.BlockSpec((1, _TG), lambda j, i: (i - i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, g_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, g_pad), jnp.float32),
        ],
        interpret=interpret,
    )(gid_p.reshape(1, n_pad), v_p.reshape(1, n_pad))
    return mins[0, :num_segments], maxs[0, :num_segments]


def segment_minmax_fused(values, gids, num_segments: int):
    """(mins f32[G], maxs f32[G]) of ``values`` grouped by ``gids`` (rows
    with gid < 0 excluded; empty groups come back as +/-_F32_MAX). Pallas
    VPU path on TPU under the same small-group-count gate as
    :func:`segment_sum_fused`; XLA segment ops elsewhere.

    f32 precision note: like the sum kernel this is the opt-in float path —
    the engine's exact decimal/int64 min/max stays on XLA (f32 rounding
    would corrupt exact comparisons).
    """
    global _pallas_broken
    mode = _pallas_mode()
    if mode != "off" and not _pallas_broken and \
            num_segments <= max_groups():
        try:
            return _segment_minmax_pallas(gids, values, num_segments,
                                          mode == "interpret")
        except Exception as e:  # Mosaic unsupported on this attachment
            _pallas_broken = True
            from nds_tpu.listener import report_task_failure
            report_task_failure("pallas segment-min/max kernel "
                                "(permanent XLA fallback)", e)
            import sys
            print("# pallas kernels disabled; using XLA fallback",
                  file=sys.stderr)
    live = gids >= 0
    safe = jnp.where(live, gids, 0)
    v = values.astype(jnp.float32)
    mins = jax.ops.segment_min(jnp.where(live, v, _F32_MAX), safe,
                               num_segments=num_segments)
    maxs = jax.ops.segment_max(jnp.where(live, v, -_F32_MAX), safe,
                               num_segments=num_segments)
    return mins, maxs


# ---------------------------------------------------------------------------
# fused chunk-scan pass (decode -> filter -> hash -> partition/shard ids)
# ---------------------------------------------------------------------------
#
# The streamed per-chunk program used to evaluate its chunk-local
# predicates, the _hash_mix partition hash, and the survivor mask as a
# chain of generic XLA elementwise ops — each stage re-reading the chunk
# from HBM. fused_chunk_scan makes ONE VMEM-resident pass over each
# padded chunk tile: FOR/sorted-dict decode stays IMPLICIT (ordered
# predicates are rebased into encoded space at lower time, so the kernel
# compares raw stored codes; only the float lane decodes), every lowered
# conjunct evaluates on the tile in VMEM, and the same pass folds the
# partition hash whose low bits pick the partition and next bits the
# destination shard — the ids the exchange consumes unchanged. The
# TPU-native analogue of operating directly on compressed data inside
# the kernel ("GPU Acceleration of SQL Analytics on Compressed Data",
# PAPERS.md).
#
# The spec (engine/exprs.lower_scan_spec) is extracted ONCE at pipeline
# record time from the chunk-local WHERE conjuncts, so the kernel is
# chunk-invariant and pipeline-cacheable; eligibility is the shared rule
# in analysis/kernel_spec.py (the exec_audit lockstep). The XLA op chain
# stays the always-available fallback (NDS_TPU_PALLAS=off, non-lowerable
# conjuncts fall back per-conjunct), bit-for-bit A/B'd under
# NDS_TPU_STREAM_STRICT=1.
#
# Entry opcodes (one entry per lowered conjunct; thresholds already in
# STORED space — analysis/kernel_spec.py does the exact rational math):
#
#   ("ieq"|"ine"|"ile"|"ige", ci, T)     int lane, raw stored codes
#   ("irange", ci, lo, hi)               BETWEEN (negated: "nrange")
#   ("iin"|"inotin", ci, values)         IN-list membership
#   ("isnull"|"notnull", ci)             validity only
#   ("true"|"false", ci)                 constant contribution (& valid)
#   ("feq"|"fne"|"flt"|"fle"|"fgt"|"fge", ci, L)
#                                        float lane: decode per col meta
#                                        (_as_f64 semantics), compare f64
#   ("fin"|"fnotin", ci, values)         float-lane IN-list membership
#   ("frange"|"fnrange", ci, lo, hi)     float-lane BETWEEN (f64 columns
#                                        / float bounds)


class ScanSpec:
    """Chunk-invariant description of one fused scan pass.

    ``cols`` holds per-referenced-column metadata
    ``(data_slot, valid_slot, fmode, base, tbl_idx, sdiv)`` — slots index
    the pipeline's flattened chunk buffers (valid_slot -1 = no mask);
    ``fmode``/``base``/``tbl_idx``/``sdiv`` describe the float-lane
    decode ("id" | "for" | "dict", FOR base, dict table index, the
    10**scale divisor). ``tables`` are the sorted dict value tables the
    float lane gathers (host arrays, chunk-invariant like string
    dictionaries). ``key_slots`` are the chunk buffers the partition
    hash folds (empty = no hash output)."""

    __slots__ = ("entries", "n_conjuncts", "cols", "tables", "key_slots")

    def __init__(self, entries, cols, tables=(), key_slots=(),
                 n_conjuncts=None):
        self.entries = tuple(entries)
        # a conjunct may lower to SEVERAL entries (mixed-lane BETWEEN),
        # so the stage count tracks CONJUNCTS, matching the static
        # prediction's count_eligible
        self.n_conjuncts = len(self.entries) if n_conjuncts is None \
            else n_conjuncts
        self.cols = tuple(cols)
        self.tables = tuple(tables)
        self.key_slots = tuple(key_slots)

    def stages(self) -> int:
        """Fused stage count of one launch: one per lowered conjunct
        plus the hash stage — the number ``StreamEvent.kernel_fused_
        stages`` reports and exec_audit predicts."""
        return self.n_conjuncts + (1 if self.key_slots else 0)


def hash_mix(h, data):
    """Fold one key column into the per-row partition hash (uint32) —
    THE partition/shard routing hash (moved here from engine/stream.py
    so the fused kernel and the XLA partition pass share one
    definition; any drift would route rows differently per arm).
    Dictionary codes hash as their int32 codes (the whole-table encoding
    makes them value-stable across chunks); floats hash their bit
    pattern. Multiplicative mixing — any chunk-row partitioning keeps
    the per-partition bound valid, the hash only evens the shares.
    The 32 mixed bits are split into DISJOINT route windows (low
    ``log2(P)`` bits pick the partition, the next ``log2(S)`` bits the
    shard — engine/stream.py); both env knobs are clamped so the two
    windows always fit: checked per statement (``hash-bits``) and at the
    clamp itself by ``analysis/num_audit.kernel_claim_checks``."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        data = jax.lax.bitcast_convert_type(
            data, jnp.int64 if data.dtype.itemsize == 8 else jnp.int32)
    x = data.astype(jnp.int64)
    lo = (x & jnp.int64(0xffffffff)).astype(jnp.uint32)
    hi = ((x >> 32) & jnp.int64(0xffffffff)).astype(jnp.uint32)
    h = (h ^ lo) * jnp.uint32(2654435761)
    h = h ^ (h >> 16)
    h = (h ^ hi) * jnp.uint32(2246822519)
    return h ^ (h >> 13)


def _eval_entries(spec: ScanSpec, datas, valids, tables):
    """Survivor mask of one tile (or whole buffer): AND of every lowered
    conjunct's contribution. Shared by the Pallas kernel body and the
    pure-jnp reference (scan_reference), so the two arms cannot drift.
    ``datas``/``valids`` are per-spec-col arrays (valids[i] None when the
    column has no mask); all boolean logic mirrors the eager engine's
    ``mask & data & valid_mask`` WHERE contract exactly."""
    shape = datas[0].shape
    m = jnp.ones(shape, dtype=bool)

    def vmask(ci):
        v = valids[ci]
        return jnp.ones(shape, dtype=bool) if v is None else v

    for e in spec.entries:
        kind, ci = e[0], e[1]
        if kind == "false":
            m = jnp.zeros(shape, dtype=bool)
            continue
        if kind == "true":
            m = m & vmask(ci)
            continue
        if kind == "isnull":
            m = m & ~vmask(ci)
            continue
        if kind == "notnull":
            m = m & vmask(ci)
            continue
        if kind[0] == "i":
            x = datas[ci].astype(jnp.int64)
            if kind == "ieq":
                c = x == e[2]
            elif kind == "ine":
                c = x != e[2]
            elif kind == "ile":
                c = x <= e[2]
            elif kind == "ige":
                c = x >= e[2]
            elif kind == "irange":
                c = (x >= e[2]) & (x <= e[3])
            elif kind == "iin":
                c = jnp.zeros(shape, dtype=bool)
                for v in e[2]:
                    c = c | (x == v)
            elif kind == "inotin":
                c = jnp.ones(shape, dtype=bool)
                for v in e[2]:
                    c = c & (x != v)
            else:
                raise ValueError(f"unknown scan entry {kind!r}")
            m = m & c & vmask(ci)
            continue
        if kind == "nrange":
            x = datas[ci].astype(jnp.int64)
            m = m & ~((x >= e[2]) & (x <= e[3])) & vmask(ci)
            continue
        if kind[0] == "f":
            _ds, _vs, fmode, base, tbl, sdiv = spec.cols[ci]
            d = datas[ci]
            if fmode == "for":
                val = (d.astype(jnp.int64) + base).astype(jnp.float64)
            elif fmode == "dict":
                val = jnp.take(tables[tbl], d, mode="clip").astype(
                    jnp.float64)
            else:
                val = d.astype(jnp.float64)
            if sdiv != 1.0:
                val = val / sdiv
            if kind == "fin" or kind == "fnotin":
                c = jnp.zeros(shape, dtype=bool)
                for v in e[2]:
                    c = c | (val == v)
                if kind == "fnotin":
                    c = ~c
                m = m & c & vmask(ci)
                continue
            if kind == "frange" or kind == "fnrange":
                c = (val >= e[2]) & (val <= e[3])
                if kind == "fnrange":
                    c = ~c
                m = m & c & vmask(ci)
                continue
            L = e[2]
            if kind == "feq":
                c = val == L
            elif kind == "fne":
                c = val != L
            elif kind == "flt":
                c = val < L
            elif kind == "fle":
                c = val <= L
            elif kind == "fgt":
                c = val > L
            else:
                c = val >= L
            m = m & c & vmask(ci)
            continue
        raise ValueError(f"unknown scan entry {kind!r}")
    return m


def _fold_hash(keybufs):
    h = jnp.full(keybufs[0].shape, 2166136261, dtype=jnp.uint32)
    for kb in keybufs:
        h = hash_mix(h, kb)
    return h


# scan-pass row tile (lane-width multiple; the pass is pure VPU)
_TR_SCAN = 512


def _scan_inputs(chunk_flat, spec: ScanSpec):
    """(datas, valids, keybufs, tables) pulled from the pipeline's
    flattened chunk buffers per the spec's slots."""
    datas = [chunk_flat[c[0]] for c in spec.cols]
    valids = [None if c[1] < 0 else chunk_flat[c[1]] for c in spec.cols]
    keybufs = [chunk_flat[s] for s in spec.key_slots]
    import numpy as np
    tables = [jnp.asarray(np.asarray(t)) for t in spec.tables]
    return datas, valids, keybufs, tables


def scan_reference(chunk_flat, n_dev, spec: ScanSpec):
    """Pure-jnp twin of :func:`fused_chunk_scan` (same shared entry
    evaluation, no Pallas): the parity oracle the kernel unit tests pin,
    and the documentation of exactly what the kernel computes."""
    datas, valids, keybufs, tables = _scan_inputs(chunk_flat, spec)
    plen = datas[0].shape[0]
    mask = _eval_entries(spec, datas, valids, tables)
    mask = mask & (jnp.arange(plen) < n_dev)
    h = _fold_hash(keybufs) if keybufs else None
    return mask, h


def fused_chunk_scan(chunk_flat, n_dev, spec: ScanSpec, interpret: bool):
    """ONE Pallas pass over the padded chunk: every referenced buffer
    crosses HBM->VMEM once, the lowered conjuncts and the partition hash
    evaluate on the resident tile, and the survivor mask (+ uint32 hash
    when the graph partitions/exchanges) come back for the compaction
    scatter. Traced inside the pipeline's jitted pre-pass — zero host
    syncs by construction (the `host-read-in-pallas` lint rule polices
    the kernel bodies).

    Mosaic caveat: like the segment kernels, some attachment paths
    cannot compile Pallas at all, and the int64 lanes here lean on the
    x64 emulation; ``scan_spec_ready`` smoke-compiles the spec at
    pipeline-build time so a refusing backend flips the process to the
    XLA chain instead of failing mid-drive."""
    datas, valids, keybufs, tables = _scan_inputs(chunk_flat, spec)
    plen = datas[0].shape[0]
    n_pad = max(_ceil_to(plen, _TR_SCAN), _TR_SCAN)

    def pad(x):
        if x is None:
            return None
        y = jnp.zeros(n_pad, dtype=x.dtype).at[:plen].set(x)
        return y.reshape(1, n_pad)

    datas_p = [pad(d) for d in datas]
    valids_p = [pad(v) for v in valids if v is not None]
    valid_pos = {}
    j = 0
    for i, v in enumerate(valids):
        if v is not None:
            valid_pos[i] = j
            j += 1
    keybufs_p = [pad(k) for k in keybufs]
    tabs_p = []
    for t in tables:
        t_pad = max(_ceil_to(t.shape[0], 128), 128)
        tabs_p.append(jnp.zeros(t_pad, dtype=t.dtype).at[:t.shape[0]]
                      .set(t).reshape(1, t_pad))
    emit_hash = bool(keybufs)
    nd, nv, nk, nt = (len(datas_p), len(valids_p), len(keybufs_p),
                      len(tabs_p))

    def kernel(*refs):
        ins = refs[:nd + nv + nk + nt]
        outs = refs[nd + nv + nk + nt:]
        d_tiles = [ins[i][:] for i in range(nd)]
        v_tiles = [None if i not in valid_pos
                   else ins[nd + valid_pos[i]][:] for i in range(nd)]
        k_tiles = [ins[nd + nv + i][:] for i in range(nk)]
        t_full = [ins[nd + nv + nk + i][:].reshape(-1) for i in range(nt)]
        outs[0][:] = _eval_entries(spec, d_tiles, v_tiles, t_full)
        if emit_hash:
            outs[1][:] = _fold_hash(k_tiles)

    grid = (n_pad // _TR_SCAN,)
    tile = lambda i: (i - i, i)          # noqa: E731 — i32 grid index
    whole = lambda i: (i - i, i - i)     # noqa: E731
    in_specs = [pl.BlockSpec((1, _TR_SCAN), tile)
                for _ in range(nd + nv + nk)]
    in_specs += [pl.BlockSpec((1, int(t.shape[1])), whole) for t in tabs_p]
    out_specs = [pl.BlockSpec((1, _TR_SCAN), tile)]
    out_shape = [jax.ShapeDtypeStruct((1, n_pad), jnp.bool_)]
    if emit_hash:
        out_specs.append(pl.BlockSpec((1, _TR_SCAN), tile))
        out_shape.append(jax.ShapeDtypeStruct((1, n_pad), jnp.uint32))
    got = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=interpret,
    )(*datas_p, *valids_p, *keybufs_p, *tabs_p)
    mask = got[0][0, :plen] & (jnp.arange(plen) < n_dev)
    h = got[1][0, :plen] if emit_hash else None
    note_launch(spec.stages())
    return mask, h


def scan_kernels_active() -> bool:
    """True when pipeline builds should extract a scan spec and route
    the per-chunk hot path through :func:`fused_chunk_scan`. Same
    contract as :func:`pallas_active`: callers gate on this, and the
    first backend refusal flips the process to the XLA chain."""
    return not _pallas_broken and _pallas_mode() != "off"


def scan_spec_ready(spec: ScanSpec, chunk_flat, plen: int) -> bool:
    """Smoke-run one fused scan over zeroed buffers of the real chunk
    shapes at pipeline-BUILD time (eager, one tile's work, result
    discarded — no host read). A Mosaic refusal here flips the
    permanent XLA fallback BEFORE any compiled pipeline bakes the
    kernel in, so a refusing attachment degrades at build time, never
    mid-drive."""
    global _pallas_broken
    mode = _pallas_mode()
    if mode == "off" or _pallas_broken:
        return False
    try:
        dummy = tuple(
            None if x is None else jnp.zeros((plen,), dtype=x.dtype)
            for x in chunk_flat)
        fused_chunk_scan(dummy, jnp.asarray(plen, dtype=jnp.int64), spec,
                         mode == "interpret")
        return True
    except Exception as e:  # Mosaic unsupported on this attachment
        _pallas_broken = True
        from nds_tpu.listener import report_task_failure
        report_task_failure("pallas fused chunk-scan kernel "
                            "(permanent XLA fallback)", e)
        import sys
        print(f"# pallas kernels disabled ({type(e).__name__}); "
              f"using XLA fallback", file=sys.stderr)
        return False


# ---------------------------------------------------------------------------
# fused bound-bucket join probe
# ---------------------------------------------------------------------------
#
# The stream-bounds join's probe phase hashes the chunk side's key
# columns and binary-searches the hash-sorted dimension side — under XLA
# that is one HBM pass per key column plus one per searchsorted. The
# fused probe replicates ops._key_hash_impl BITWISE on the resident tile
# (same _mix64 constants, same null/pad/exclusion sentinels) and runs
# both searchsorted sides against the dimension hash table held whole in
# VMEM, emitting the (lo, counts) pair the bound-bucket expansion
# consumes unchanged — candidate counts are identical to the XLA path's
# by construction, so overflow accounting cannot move between arms.

# dimension buckets past this stay on XLA: the whole sorted hash table
# rides VMEM per grid cell (8B/row)
_PROBE_MAX_R = 1 << 15


def _probe_hash_tile(views, valids, excluded, rows, n_valid):
    """uint64 key hash of one tile — ops._key_hash_impl, restated on
    resident arrays (int views only; f64 keys stay on XLA). ``rows`` are
    the tile's global row indices (pad/side sentinels must be per-ROW
    unique exactly like the XLA hash so nothing collides)."""
    import numpy as np
    _C1 = jnp.uint64(0x9E3779B97F4A7C15)
    _C2 = jnp.uint64(0xBF58476D1CE4E5B9)
    _C3 = jnp.uint64(0x94D049BB133111EB)

    def mix64(x):
        x = x.astype(jnp.uint64)
        x = (x ^ (x >> 30)) * _C2
        x = (x ^ (x >> 27)) * _C3
        return x ^ (x >> 31)

    shape = views[0].shape
    h = jnp.full(shape, jnp.uint64(0x243F6A8885A308D3), dtype=jnp.uint64)
    any_null = jnp.zeros(shape, dtype=bool)
    for v, valid in zip(views, valids):
        w = v.astype(jnp.uint64)
        if valid is not None:
            w = jnp.where(valid, w, jnp.uint64(0))
            marker = jnp.where(valid, jnp.uint64(0),
                               jnp.uint64(0xA5A5A5A5A5A5A5A5))
            any_null = any_null | ~valid
        else:
            marker = jnp.zeros(shape, dtype=jnp.uint64)
        h = mix64(h ^ marker)
        h = mix64(h ^ w * _C1)
    unmatchable = any_null | (rows >= n_valid)
    if excluded is not None:
        unmatchable = unmatchable | excluded
    # side_salt 0 (probe side): sentinel = 2 + (row << 3); REAL bit 4
    sentinel = jnp.uint64(2) + (rows.astype(jnp.uint64) << jnp.uint64(3))
    return jnp.where(unmatchable, sentinel, h | jnp.uint64(4))


def probe_reference(views, valids, n_valid, excluded, rh_sorted):
    """Pure-jnp twin of :func:`fused_probe` (parity oracle)."""
    n = views[0].shape[0]
    rows = jnp.arange(n)
    lh = _probe_hash_tile(views, valids, excluded, rows, n_valid)
    lo = jnp.searchsorted(rh_sorted, lh, side="left")
    hi = jnp.searchsorted(rh_sorted, lh, side="right")
    return hi - lo, lo


def probe_kernel_active(views, valids, plen_r: int) -> bool:
    """Gate for the fused probe: Pallas on, int key views only, and the
    dimension hash table small enough to hold whole in VMEM. Callers
    fall back to the XLA probe whenever this says no."""
    if _pallas_broken or _pallas_mode() == "off":
        return False
    if plen_r > _PROBE_MAX_R:
        return False
    return all(v.dtype != jnp.float64 for v in views)


def fused_probe(views, valids, n_valid, excluded, rh_sorted,
                interpret: bool):
    """(counts, lo) of the bound-bucket probe in ONE VMEM pass per chunk
    tile: key hash (bitwise ops._key_hash_impl) + both binary-search
    sides against the resident dimension hash table."""
    n = views[0].shape[0]
    n_pad = max(_ceil_to(n, _TR_SCAN), _TR_SCAN)
    r = rh_sorted.shape[0]
    r_pad = max(_ceil_to(r, 128), 128)
    # pads sort above every real hash (max uint64): searchsorted of any
    # real probe value lands below them
    rh_p = jnp.full(r_pad, jnp.uint64(0xFFFFFFFFFFFFFFFF),
                    dtype=jnp.uint64).at[:r].set(rh_sorted).reshape(
        1, r_pad)

    def pad(x, fill=0):
        return jnp.full(n_pad, fill, dtype=x.dtype).at[:n].set(x).reshape(
            1, n_pad)

    views_p = [pad(v) for v in views]
    valid_list = [v for v in valids if v is not None]
    valids_p = [pad(v) for v in valid_list]
    vpos = {}
    j = 0
    for i, v in enumerate(valids):
        if v is not None:
            vpos[i] = j
            j += 1
    excl_p = None if excluded is None else pad(excluded, True)
    nviews, nvalid = len(views_p), len(valids_p)
    nv_arr = jnp.asarray(n_valid, dtype=jnp.int64).reshape(1, 1)

    def kernel(*refs):
        i = pl.program_id(0)
        k = 0
        v_tiles = [refs[k + j][:] for j in range(nviews)]
        k += nviews
        valid_tiles = [None if j not in vpos
                       else refs[k + vpos[j]][:] for j in range(nviews)]
        k += nvalid
        if excl_p is not None:
            excl_tile = refs[k][:]
            k += 1
        else:
            excl_tile = None
        rh_full = refs[k][:].reshape(-1)
        k += 1
        nv = refs[k][0, 0]
        k += 1
        cnt_ref, lo_ref = refs[k], refs[k + 1]
        rows = i * _TR_SCAN + jax.lax.broadcasted_iota(
            jnp.int64, (1, _TR_SCAN), 1)
        lh = _probe_hash_tile(v_tiles, valid_tiles, excl_tile, rows, nv)
        lo = jnp.searchsorted(rh_full, lh.reshape(-1), side="left")
        hi = jnp.searchsorted(rh_full, lh.reshape(-1), side="right")
        cnt_ref[:] = (hi - lo).reshape(1, _TR_SCAN).astype(jnp.int64)
        lo_ref[:] = lo.reshape(1, _TR_SCAN).astype(jnp.int64)

    grid = (n_pad // _TR_SCAN,)
    tile = lambda i: (i - i, i)          # noqa: E731
    whole = lambda i: (i - i, i - i)     # noqa: E731
    in_specs = [pl.BlockSpec((1, _TR_SCAN), tile)
                for _ in range(nviews + nvalid)]
    if excl_p is not None:
        in_specs.append(pl.BlockSpec((1, _TR_SCAN), tile))
    in_specs.append(pl.BlockSpec((1, r_pad), whole))
    in_specs.append(pl.BlockSpec((1, 1), whole))
    args = [*views_p, *valids_p]
    if excl_p is not None:
        args.append(excl_p)
    args += [rh_p, nv_arr]
    counts, lo = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, _TR_SCAN), tile),
                   pl.BlockSpec((1, _TR_SCAN), tile)],
        out_shape=[jax.ShapeDtypeStruct((1, n_pad), jnp.int64),
                   jax.ShapeDtypeStruct((1, n_pad), jnp.int64)],
        interpret=interpret,
    )(*args)
    note_probe()
    return counts[0, :n], lo[0, :n]


_probe_smoke_ok: bool | None = None


def try_fused_probe(left_keys, lviews, lvalids, n_valid, excluded,
                    rh_sorted):
    """The ops.py seam: (counts, lo) through the fused probe, or None
    when the gate declines / the backend refuses (first refusal flips
    the permanent XLA fallback via a one-time eager smoke run, so a
    Mosaic error can never surface mid-pipeline-drive)."""
    global _probe_smoke_ok, _pallas_broken
    if not probe_kernel_active(lviews, lvalids, int(rh_sorted.shape[0])):
        return None
    if any(lk.kind == "f64" for lk in left_keys):
        return None
    mode = _pallas_mode()
    if _probe_smoke_ok is None:
        try:
            v = jnp.zeros(4, dtype=jnp.int64)
            rh = jnp.zeros(4, dtype=jnp.uint64)
            fused_probe((v,), (None,), jnp.asarray(4, dtype=jnp.int64),
                        None, rh, mode == "interpret")
            _probe_smoke_ok = True
        except Exception as e:  # Mosaic unsupported on this attachment
            _probe_smoke_ok = False
            _pallas_broken = True
            from nds_tpu.listener import report_task_failure
            report_task_failure("pallas fused join-probe kernel "
                                "(permanent XLA fallback)", e)
            import sys
            print(f"# pallas kernels disabled ({type(e).__name__}); "
                  f"using XLA fallback", file=sys.stderr)
    if not _probe_smoke_ok:
        return None
    return fused_probe(lviews, lvalids, n_valid, excluded, rh_sorted,
                       mode == "interpret")


# ---------------------------------------------------------------------------
# trace-time kernel accounting + the Pallas-vs-XLA arm surface
# ---------------------------------------------------------------------------

_kern_tls = threading.local()


@contextlib.contextmanager
def kernel_trace():
    """Count fused-kernel launches while tracing one compiled program —
    the same trace-time pattern as parallel.exchange.collective_trace:
    a kernel traced into a jit program launches once per dispatch, so
    counting at trace time gives exact per-dispatch evidence at zero
    runtime cost. ``counts``: {"launches", "stages", "probes"}."""
    prev = getattr(_kern_tls, "counts", None)
    _kern_tls.counts = {"launches": 0, "stages": 0, "probes": 0}
    try:
        yield _kern_tls.counts
    finally:
        _kern_tls.counts = prev


def note_launch(stages: int) -> None:
    c = getattr(_kern_tls, "counts", None)
    if c is not None:
        c["launches"] += 1
        c["stages"] += stages


def note_probe() -> None:
    c = getattr(_kern_tls, "counts", None)
    if c is not None:
        c["launches"] += 1
        c["probes"] += 1


def active_arm() -> str:
    """"pallas" | "xla": the arm the segment/scan kernels take for this
    process right now — NDS_TPU_PALLAS plus the permanent-fallback flip
    (_pallas_broken), which until now was only visible through the
    listener's task-failure report. Surfaced as the ``kernelArm``
    annotation on every ``stream`` span so tools/trace_report.py can
    attribute kernel coverage (and price fused-vs-XLA) per query."""
    return "pallas" if (not _pallas_broken and _pallas_mode() != "off") \
        else "xla"
