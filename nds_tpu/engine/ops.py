# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Structural relational operators over DeviceTable.

The TPU-native analogs of the physical operators the reference delegates to
Spark+RAPIDS (Parquet scan, filter/project, hash join, hash aggregate, sort,
window; SURVEY.md §2.2 N4). All grouping and joining is sort-based on device:
lexsort + run boundaries + segment reductions — collision-free and
XLA-friendly (fixed dtypes, gathers, segment ops), with searchsorted probes
for the join build/probe phases.

Shape discipline: every materialization pads its row count up to a
power-of-two bucket (:func:`bucket_len`), with valid rows in a prefix
(``DeviceTable.nrows`` logical rows out of ``plen`` physical). Data past the
logical count is garbage that every operator ignores: joins hash pad rows to
unmatchable sentinels, grouping gives them a discardable trailing group, and
sorts order them last. XLA sees a handful of distinct shapes instead of one
per intermediate cardinality, so compiled executables are reused across
queries and across Power Runs via the persistent compilation cache — the
compile-once-run-many analog of the reference's warmed JVM+plugin
(ref: nds/nds_power.py:125-135, SURVEY.md §6 hard parts: bucketed padding).
"""

from __future__ import annotations

import functools
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from nds_tpu.engine import faults as _faults
from nds_tpu.engine.column import Column, encs_equal, is_dec
from nds_tpu.engine.table import DeviceTable
from nds_tpu.obs import trace as _trace

# ---------------------------------------------------------------------------
# bucketed shapes
# ---------------------------------------------------------------------------

# Floor of every physical bucket. Meshes shard buckets row-wise, so a mesh
# wider than the floor needs it raised (NDS_TPU_MIN_BUCKET) at process
# start — it is a process-wide shape contract, never mutated at run time.
# Rounded up to a power of two so every bucket divides any power-of-two
# mesh up to the floor.
def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 1).bit_length() if n > 2 else 2


# deliberate import freeze: the bucket floor is a process-wide shape
# contract (session.py refuses mid-process changes by construction), so
# the conc-audit freeze rule is waived on the next line.
# nds-lint: ignore[env-freeze]
_MIN_BUCKET = _pow2_ceil(int(os.environ.get("NDS_TPU_MIN_BUCKET", "16")))


def bucket_len(n: int) -> int:
    """Smallest power-of-two capacity >= n (floor ``_MIN_BUCKET``)."""
    if n <= _MIN_BUCKET:
        return _MIN_BUCKET
    return 1 << (int(n) - 1).bit_length()


# host-sync accounting: every device->host scalar read blocks the dispatch
# queue (and under GSPMD is a full-mesh barrier through the host), so the
# count per query is THE scalability number to watch (DESIGN.md). Read
# around a query by the drivers. Thread-local, matching the thread-scoped
# listener: concurrent Throughput streams each count their own syncs.
_sync_tls = threading.local()


def add_syncs(n: int = 1) -> None:
    """Charge ``n`` host syncs to the calling thread's stream."""
    _sync_tls.count = getattr(_sync_tls, "count", 0) + n


def sync_count() -> int:
    """Host syncs counted on the calling thread so far."""
    return getattr(_sync_tls, "count", 0)


def add_sync_wait(ns: int) -> None:
    """Charge nanoseconds spent BLOCKED on a device->host read (sync
    stalls + result fetches) to the calling thread — the host side of the
    roofline decomposition (everything else in a query's wall time is
    dispatch + device compute overlap)."""
    _sync_tls.wait_ns = getattr(_sync_tls, "wait_ns", 0) + ns


def sync_wait_ns() -> int:
    return getattr(_sync_tls, "wait_ns", 0)


def add_fetch_bytes(n: int) -> None:
    """Record device->host result bytes (collect()/to_arrow transfers)."""
    _sync_tls.fetch_bytes = getattr(_sync_tls, "fetch_bytes", 0) + n


def fetch_bytes() -> int:
    return getattr(_sync_tls, "fetch_bytes", 0)


# compile-time accounting: XLA compilation is the dominant first-sight cost
# at scale (SF1 Power: 70% of the official wall was shape-universe compile)
# and the reports must split it from execution to be optimizable. JAX's
# monitoring stream reports every backend compile synchronously on the
# compiling thread, so thread-local accumulation composes with concurrent
# Throughput streams exactly like the sync counters above.
_compile_meter_on = False


def _compile_event(event: str, secs: float, **kw) -> None:
    if event == "/jax/core/compile/backend_compile_duration":
        _sync_tls.compile_ns = (getattr(_sync_tls, "compile_ns", 0)
                                + int(secs * 1e9))


def enable_compile_meter() -> None:
    """Register the global compile-duration listener (idempotent)."""
    global _compile_meter_on
    if _compile_meter_on:
        return
    from jax import monitoring
    monitoring.register_event_duration_secs_listener(_compile_event)
    _compile_meter_on = True


def compile_ns() -> int:
    """Nanoseconds of XLA backend compilation on the calling thread."""
    return getattr(_sync_tls, "compile_ns", 0)


# --------------------------------------------------------------------------
# trace-replay: every host read the engine performs routes through
# host_read(), so a query can be RECORDED once (eager run, log of host
# decisions) and then RE-TRACED under jax.jit with the log answering every
# host read — compiling the entire query pipeline into ONE XLA program
# (the Spark whole-stage-codegen analog; engine/replay.py drives this).
# --------------------------------------------------------------------------


class ReplayMismatch(RuntimeError):
    """The replay trace consumed host reads in a different order than the
    recording — the query is not replay-safe; callers fall back eager."""


# --------------------------------------------------------------------------
# stream-bounds mode: the compiled streaming executor (engine/stream.py)
# traces ONE per-chunk program and replays it for every chunk of a >HBM
# ChunkedTable, so the program must be CHUNK-INVARIANT — no host decision
# may depend on a chunk's data. Inside a stream-bounds region:
#   * host scalar syncs raise StreamSyncError (the executor falls back to
#     the eager chunk loop — correctness never depends on streamability);
#   * joins size their pair buckets from STATIC bounds instead of a
#     data-dependent sizing sync, registering a device-side overflow
#     predicate via stream_overflow() that the executor checks once at the
#     pipeline's single materializing sync (overflow => rerun eager);
#   * lazy compaction never takes the adaptive resolve (counts stay on
#     device for the pipeline's whole life).
# Host reads against NON-streamed inputs (dimension key maps/ranges) stay
# legal: they are chunk-invariant and ride the replay log.
# --------------------------------------------------------------------------


class StreamSyncError(RuntimeError):
    """A chunk-data-dependent host sync was reached inside a stream-bounds
    region; the query's join graph is not streamable through the compiled
    chunk pipeline."""


def stream_bounds_on() -> bool:
    return getattr(_sync_tls, "stream_bounds", False)


class _StreamBoundsSession:
    def __enter__(self):
        self._prev = (stream_bounds_on(),
                      getattr(_sync_tls, "stream_flags", None))
        _sync_tls.stream_bounds = True
        self.flags: list = []
        _sync_tls.stream_flags = self.flags
        return self

    def __exit__(self, *exc):
        _sync_tls.stream_bounds, _sync_tls.stream_flags = self._prev


def stream_bounds():
    """Context: execute with chunk-invariant (bound-derived) shape
    decisions; ``.flags`` collects the device-side overflow predicates the
    region registered."""
    return _StreamBoundsSession()


def stream_overflow(pred) -> None:
    """Register a device bool scalar that is True when a bound-sized
    bucket overflowed (rows silently dropped). The streaming executor ORs
    every flag into its accumulated overflow bit; outside a stream-bounds
    region this is a no-op."""
    flags = getattr(_sync_tls, "stream_flags", None)
    if flags is not None:
        flags.append(pred)


class _OuterMatchCollector:
    """Collects the per-dispatch matched-build-row masks an outer-build
    join registers (multi-pass streaming, engine/stream.py): the streamed
    pipeline ORs them into a device-resident unmatched-key accumulator so
    the outer-extras rows can be emitted once, at materialize time."""

    def __enter__(self):
        self._prev = getattr(_sync_tls, "stream_outer", None)
        self.masks: list = []
        _sync_tls.stream_outer = self.masks
        return self

    def __exit__(self, *exc):
        _sync_tls.stream_outer = self._prev


def outer_match_collector():
    return _OuterMatchCollector()


def stream_outer_matched(mask) -> None:
    """Register the device bool vector of build-side rows the current
    outer-build join dispatch matched. No-op outside a collector region
    (plain device-resident outer joins resolve their extras inline)."""
    lst = getattr(_sync_tls, "stream_outer", None)
    if lst is not None:
        lst.append(mask)


class _SuspendStreamRecord:
    """Escape hatch for CHUNK-INVARIANT inner plans reached from inside a
    streamed pipeline's record phase (subquery residuals): restores plain
    eager execution — replay log detached (inner host reads must never
    interleave with the outer recording, which the trace would then fail
    to consume), stream-bounds off (the inner plan may sync freely; it
    runs ONCE, not per chunk), and a FRESH pending-count/check list so the
    inner's batched resolutions never drain counts the outer record phase
    still owes its log."""

    def __enter__(self):
        t = _sync_tls
        self._saved = (
            replay_mode(), getattr(t, "replay_log", None),
            getattr(t, "replay_cursor", 0),
            getattr(t, "replay_operands", None),
            stream_bounds_on(), getattr(t, "stream_flags", None),
            getattr(t, "stream_outer", None),
            getattr(t, "pending", None), getattr(t, "checks", None))
        t.replay_mode = "off"
        t.replay_log = None
        t.replay_cursor = 0
        t.replay_operands = None
        t.stream_bounds = False
        t.stream_flags = None
        t.stream_outer = None
        t.pending = []
        t.checks = []
        return self

    def __exit__(self, *exc):
        t = _sync_tls
        (t.replay_mode, t.replay_log, t.replay_cursor, t.replay_operands,
         t.stream_bounds, t.stream_flags, t.stream_outer,
         t.pending, t.checks) = self._saved


def suspend_stream_record():
    return _SuspendStreamRecord()


def guarded_scalar_read(tag: str, dev_scalar) -> int:
    """Mechanism for CHUNK-DERIVED host scalars inside the streamed
    pipeline (the `chunk-dependent-host-read` conversion): outside a
    stream-bounds region this is an ordinary counted host read. Inside
    one, the value read on the FIRST chunk is recorded and replayed for
    every later chunk — with a device-side STALENESS GUARD registered on
    the overflow channel, so any chunk for which the recorded value's
    validity predicate fails (the live value differs) flips the pipeline's
    overflow flag and the statement re-runs eagerly, bit-for-bit. The
    guard is what makes replaying a recorded scalar SOUND rather than
    hopeful."""
    import jax.numpy as _jnp

    def fetch():
        add_syncs()
        t0 = time.perf_counter_ns()
        out = int(jax.device_get(dev_scalar))
        add_sync_wait(time.perf_counter_ns() - t0)
        return out

    if not stream_bounds_on():
        return host_read(tag, fetch)
    # replay serves the recorded value without touching fetch; record
    # fetches (one counted sync, first chunk only) and logs it
    val = host_read(tag, fetch)
    stream_overflow(_jnp.asarray(dev_scalar) != val)
    return val


def replay_mode() -> str:
    return getattr(_sync_tls, "replay_mode", "off")


class _ReplaySession:
    def __init__(self, mode: str, log, operands=None):
        self.mode, self.log = mode, log
        self.operands = operands

    def __enter__(self):
        self._prev = (replay_mode(), getattr(_sync_tls, "replay_log", None),
                      getattr(_sync_tls, "replay_cursor", 0))
        # snapshot ENTRIES (not an index): resolve_counts clears the list
        # mid-trace, so positions shift — restoration must be by identity
        self._pend_snapshot = list(_pending_counts())
        self._prev_ops = getattr(_sync_tls, "replay_operands", None)
        _sync_tls.replay_mode = self.mode
        _sync_tls.replay_log = self.log
        _sync_tls.replay_cursor = 0
        _sync_tls.replay_operands = self.operands
        return self.log

    def __exit__(self, *exc):
        if self.mode == "replay":
            # counts created while TRACING hold tracer scalars; they must
            # never reach a later eager device_get — keep only the entries
            # that already existed when the trace began. Same for deferred
            # checks registered against tracer counts: left in place they
            # could never resolve and would force a spurious resolve at
            # every later statement's flush.
            lst = _pending_counts()
            keep = [c for c in lst
                    if any(c is s for s in self._pend_snapshot)]
            lst[:] = keep
            checks = getattr(_sync_tls, "checks", None)
            if checks:
                _sync_tls.checks = [
                    (c, f) for c, f in checks
                    if any(c is s for s in self._pend_snapshot)
                    or c._host is not None]
        (_sync_tls.replay_mode, _sync_tls.replay_log,
         _sync_tls.replay_cursor) = self._prev
        _sync_tls.replay_operands = self._prev_ops


def recording(log=None):
    """Context: run eagerly while logging every host read."""
    return _ReplaySession("record", [] if log is None else log)


def replaying(log, operands=None):
    """Context: serve every host read from ``log`` (device untouched);
    ``operands`` resolves any lifted :class:`ArgRef` entries to traced
    jit arguments."""
    return _ReplaySession("replay", log, operands)


class ArgRef:
    """Placeholder in a replay log for a large array lifted into a jit
    ARGUMENT (baking fact-sized host reads as jaxpr constants bloats the
    compiled program; see replay.py). The replaying context resolves it to
    the corresponding traced operand."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index


def _resolve_refs(val):
    ops = getattr(_sync_tls, "replay_operands", None)
    if isinstance(val, ArgRef):
        return ops[val.index]
    if isinstance(val, tuple) and any(isinstance(x, ArgRef) for x in val):
        return tuple(ops[x.index] if isinstance(x, ArgRef) else x
                     for x in val)
    return val


def _sync_site() -> str:
    """First non-ops engine frame above the fetch — the call-site tag
    every sync-charging host read carries into the trace layer (the
    first-class form of tools/sync_profile.py's old monkeypatch). Frame
    walk only, no source reads; runs only when a sync was charged."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if "nds_tpu" in fn and not fn.endswith("ops.py"):
            return (f"{os.path.basename(fn)}:{f.f_lineno}:"
                    f"{f.f_code.co_name}")
        f = f.f_back
    return "?"


def host_read(tag: str, fetch):
    """The single host-read chokepoint. Off: just fetch. Record: fetch and
    log. Replay: pop the recorded value — no device contact (large arrays
    come back as traced jit operands via :class:`ArgRef`).

    With tracing on (nds_tpu/obs), a fetch that charged host syncs emits
    a sync-site event naming its engine call site. Attribution is
    re-entrancy-exact: a fetch that re-enters host_read (nested reads —
    e.g. a count fallback inside a span fetch) charges each site only its
    OWN syncs, which the old monkeypatch double-counted. Pure counter
    arithmetic — zero additional syncs."""
    mode = replay_mode()
    if mode == "replay":
        log = _sync_tls.replay_log
        i = _sync_tls.replay_cursor
        if i >= len(log) or log[i][0] != tag:
            got = log[i][0] if i < len(log) else "<end>"
            raise ReplayMismatch(f"expected {got!r}, hit {tag!r} at {i}")
        _sync_tls.replay_cursor = i + 1
        return _resolve_refs(log[i][1])
    if not _trace.on():
        val = fetch()
        if mode == "record":
            _sync_tls.replay_log.append((tag, val))
        return val
    s0, w0 = sync_count(), sync_wait_ns()
    a_s0, a_w0 = _trace.attributed()
    val = fetch()
    a_s1, a_w1 = _trace.attributed()
    own = (sync_count() - s0) - (a_s1 - a_s0)
    if own > 0:
        own_wait = max((sync_wait_ns() - w0) - (a_w1 - a_w0), 0)
        _trace.note_sync(tag, own, own_wait, _sync_site())
    if mode == "record":
        _sync_tls.replay_log.append((tag, val))
    return val


def _guarded_blocking_fetch(tag: str, fetch):
    """The ``sync`` fault seam around one blocking device->host fetch:
    bounded deterministic retry of the idempotent read (transient
    tunnel/device flakes and injected faults recover in place — the
    retry RE-CHARGES the same sync accounting, never re-budgets it:
    exec_audit's retry-paths row), and the statement watchdog
    (``NDS_TPU_STATEMENT_DEADLINE_S``): a hung fetch raises a classified
    :class:`faults.StatementTimeout` instead of hanging the process.
    Watchdog unset (the default): the fetch runs inline — zero threads,
    bit-for-bit today's path."""
    return _faults.with_retry(
        "sync",
        lambda: _faults.bounded_call(
            "sync",
            lambda: (_faults.fault_point("sync", tag), fetch())[1]))


def timed_read(tag: str, fetch):
    """host_read() with the fetch charged to the thread's sync/wait
    accounting — for blocking device->host reads that are not simple
    scalar syncs (chunk spans, exchange overflow counters, whole-column
    string/date fetches), so PERF.md's roofline sees them too. The raw
    fetch runs behind the ``sync`` fault seam (retry + watchdog); the
    sync counters stay charged on the CALLING thread either way."""

    def timed():
        add_syncs()
        t0 = time.perf_counter_ns()
        out = _guarded_blocking_fetch(tag, fetch)
        add_sync_wait(time.perf_counter_ns() - t0)
        return out

    return host_read(tag, timed)


def host_sync(value) -> int:
    """Read a device scalar on host, counting the sync."""
    if stream_bounds_on():
        raise StreamSyncError(
            "host scalar sync inside a stream-bounds region")

    def fetch():
        add_syncs()
        t0 = time.perf_counter_ns()
        out = _guarded_blocking_fetch("sync", lambda: int(value))
        add_sync_wait(time.perf_counter_ns() - t0)
        return out

    return host_read("sync", fetch)


class DeviceCount:
    """A logical row count that stays on device (DESIGN.md reduction items
    1+3: no-shrink capacity propagation with batched sync points).

    Operators that merely need the count inside a traced computation
    (liveness masks, hash-pad thresholds, segment routing) consume ``dev``
    and never block. ``bound`` is the static upper bound — a filter or
    inner join can never grow its input, so the producer's bucket is a
    valid capacity for every consumer — used for all physical-shape
    choices. Only a consumer that truly needs the host integer (ORDER
    BY+LIMIT output, scalar subqueries, ``collect()``) resolves, and
    resolution drains EVERY pending count of the calling thread in one
    transfer: a join that would have cost three round trips (pairs + two
    outer-extra counts) costs one.
    """

    __slots__ = ("dev", "bound", "_host")

    def __init__(self, dev, bound: int):
        self.dev = dev
        self.bound = int(bound)
        self._host: int | None = None
        _pending_counts().append(self)

    def to_int(self) -> int:
        if self._host is None and stream_bounds_on():
            # a chunk-data-dependent count must never reach host inside
            # the compiled per-chunk program (engine/stream.py)
            raise StreamSyncError(
                "DeviceCount resolution inside a stream-bounds region")
        if self._host is None:
            resolve_counts()
        if self._host is None:
            # not in the calling thread's pending list (created on another
            # stream's thread) or an earlier drain failed mid-transfer:
            # fetch directly rather than returning a poisoned None
            def fetch():
                add_syncs()
                t0 = time.perf_counter_ns()
                out = int(jax.device_get(self.dev))
                add_sync_wait(time.perf_counter_ns() - t0)
                return out

            self._host = host_read("count1", fetch)
            _run_deferred_checks()
        return self._host

    def __repr__(self):
        state = self._host if self._host is not None else "?"
        return f"DeviceCount({state}/{self.bound})"

    # implicit coercions raise so every host consumer is an EXPLICIT,
    # counted choice between count_int (syncs, batched) and count_bound
    # (free): a silent int() here would be an uncounted round trip
    def _no_host(self, *_a, **_k):
        raise TypeError(
            "DeviceCount is not a host value; use ops.count_int (syncs, "
            "batched) or ops.count_bound (free upper bound)")

    __bool__ = __index__ = __int__ = __eq__ = __lt__ = __le__ = __gt__ = \
        __ge__ = __add__ = __radd__ = __mul__ = __rmul__ = _no_host
    __hash__ = None


def _pending_counts() -> list:
    lst = getattr(_sync_tls, "pending", None)
    if lst is None:
        lst = _sync_tls.pending = []
    return lst


def resolve_counts() -> None:
    """Fetch every pending device count of this thread in ONE transfer
    (counted as one host sync — the batching is the point)."""
    lst = _pending_counts()
    pend = [c for c in lst if c._host is None]
    if not pend:
        lst.clear()
        _run_deferred_checks()   # checks on already-resolved counts
        return

    def fetch():
        t0 = time.perf_counter_ns()
        # on a failed transfer (device preemption) the list survives
        # untouched, so a retry drains it instead of stranding counts —
        # the ``sync`` fault seam (bounded retry + statement watchdog)
        # wraps the raw transfer, accounting stays on this thread
        vals = _guarded_blocking_fetch(
            "counts", lambda: jax.device_get([c.dev for c in pend]))
        add_sync_wait(time.perf_counter_ns() - t0)
        add_syncs()
        return [int(v) for v in vals]

    vals = host_read(f"counts{len(pend)}", fetch)
    for c, v in zip(pend, vals):
        c._host = v
    lst.clear()
    _run_deferred_checks()


def defer_check(count: DeviceCount, fn) -> None:
    """Register a validation against a count's eventual host value; it
    runs at whichever batched resolution produces the value. Keeps SQL
    runtime-error semantics (e.g. 'scalar subquery returned more than one
    row') without spending a dedicated sync on the check."""
    lst = getattr(_sync_tls, "checks", None)
    if lst is None:
        lst = _sync_tls.checks = []
    lst.append((count, fn))


def _run_deferred_checks() -> None:
    lst = getattr(_sync_tls, "checks", None)
    if not lst:
        return
    ready = [(c, f) for c, f in lst if c._host is not None]
    _sync_tls.checks = [(c, f) for c, f in lst if c._host is None]
    first_err = None
    for c, f in ready:          # every ready check runs even if one raises
        try:
            f(c._host)
        except Exception as e:
            first_err = first_err or e
    if first_err is not None:
        raise first_err


def flush_deferred_checks() -> None:
    """Statement-end barrier: resolve any counts that deferred checks are
    waiting on so SQL runtime errors surface inside the statement that
    caused them, never attributed to a later one."""
    if getattr(_sync_tls, "checks", None):
        resolve_counts()


def discard_deferred_checks() -> None:
    """Drop pending deferred checks — called when a statement aborts
    with its own exception, so its half-registered checks neither mask
    the real error nor leak into the next statement."""
    _sync_tls.checks = []


def count_int(n) -> int:
    """Host integer of a count (resolves a DeviceCount, batched)."""
    return n.to_int() if isinstance(n, DeviceCount) else int(n)


def count_bound(n) -> int:
    """Static upper bound of a count — valid for capacity decisions, free
    of any sync. Exact when already host-resolved."""
    if isinstance(n, DeviceCount):
        return n.bound if n._host is None else n._host
    return int(n)


def count_arr(n):
    """Traced-use form: the device scalar (or the plain int — both are
    valid jit arguments)."""
    return n.dev if isinstance(n, DeviceCount) else n


def live_mask(plen: int, nrows) -> jnp.ndarray:
    """Bool mask of the logical (non-pad) prefix of a physical array.
    ``nrows`` may be a host int or a :class:`DeviceCount` (no sync)."""
    return jnp.arange(plen) < count_arr(nrows)


def compact_indices(mask: jnp.ndarray, n: int) -> jnp.ndarray:
    """Indices of the first ``n`` True rows of ``mask``, padded to
    ``bucket_len(n)`` with an out-of-range fill (gathers clip, scatters
    drop)."""
    cap = bucket_len(n)
    plen = int(mask.shape[0])
    return jnp.nonzero(mask, size=cap, fill_value=max(plen, 1))[0]


# lazy-compaction bucket ceiling: below it, carrying the un-shrunk bucket
# is cheaper than a device->host round trip (the round trip dominates on a
# tunneled chip and is a full-mesh barrier under GSPMD); above it, the
# resolve-and-slice pays for itself in downstream sort width.
# Read at USE time (not import) like stream_fanout(): setting
# NDS_TPU_LAZY_SHRINK_ROWS after import must not be silently ignored.
def lazy_shrink_rows() -> int:
    return int(os.environ.get("NDS_TPU_LAZY_SHRINK_ROWS", str(1 << 20)))


def compact_table(table: DeviceTable, mask: jnp.ndarray,
                  shrink: bool = False) -> DeviceTable:
    """Keep rows where ``mask`` is true, as a prefix-padded table.

    Default (``shrink=False``, DESIGN.md item 1): NO host sync — live rows
    gather to the prefix of a bucket sized from the producer's bound (a
    filter never grows its input) and the logical count rides along as a
    :class:`DeviceCount`. Downstream joins/aggregations are pad-tolerant,
    so only an output-shaping consumer ever resolves it, batched.

    ``shrink=True`` is the legacy eager mode — one (batched) host sync,
    re-bucketing to the tight capacity — for callers about to hold many
    compacted tables at once (load-time filters, chunk accumulation)."""
    m = mask & live_mask(table.plen, table.nrows)
    if shrink:
        n = host_sync(jnp.sum(m))
        return take_padded(table, compact_indices(m, n), n)
    cap = min(bucket_len(count_bound(table.nrows)), bucket_len(table.plen))
    idx = jnp.nonzero(m, size=cap, fill_value=max(table.plen, 1))[0]
    n = DeviceCount(jnp.sum(m), min(count_bound(table.nrows), cap))
    out = take_padded(table, idx, n)
    if cap > lazy_shrink_rows() and not stream_bounds_on():
        # adaptive: past this bucket size the downstream sorts/segment ops a
        # fat bucket drags through cost more than one (batched) round trip,
        # so resolve now — the transfer still drains the whole pending batch
        return resolve_table(out)
    return out


def resolve_table(table: DeviceTable, shrink: bool = True) -> DeviceTable:
    """Resolve a table's lazy count to a host int (batched — one transfer
    drains every pending count of the thread) and, by default, slice the
    physical bucket down to the tight capacity. Lazy compaction kept live
    rows in the prefix, so shrinking is a metadata-cheap device slice."""
    n = table.nrows
    if not isinstance(n, DeviceCount):
        return table
    ni = n.to_int()
    cap = bucket_len(ni)
    if not shrink or cap >= table.plen:
        return DeviceTable(table.columns, ni, plen=table.plen)
    from nds_tpu.engine.column import slice_col_prefix
    cols = {nm: slice_col_prefix(c, cap) for nm, c in table.columns.items()}
    return DeviceTable(cols, ni, plen=cap)


@jax.jit
def _gather_cols_impl(idx, datas, valids):
    """One fused gather of every column (and validity mask) of a table —
    a single device dispatch where a per-column loop costs 2 x ncols round
    trips to a remote attachment."""
    outs = tuple(jnp.take(d, idx, axis=0, mode="clip") for d in datas)
    vouts = tuple(None if v is None else jnp.take(v, idx, axis=0, mode="clip")
                  for v in valids)
    return outs, vouts


def gather_table_rows(table: DeviceTable, idx: jnp.ndarray,
                      nrows: int) -> DeviceTable:
    """Fused whole-table row gather (clip mode); logical length ``nrows``."""
    from dataclasses import replace as _replace
    names = table.column_names
    cols = [table.columns[n] for n in names]
    datas, valids = _gather_cols_impl(
        idx, tuple(c.data for c in cols), tuple(c.valid for c in cols))
    out = {n: _replace(c, data=d, valid=v)
           for n, c, d, v in zip(names, cols, datas, valids)}
    return DeviceTable(out, nrows, plen=int(idx.shape[0]))


def take_padded(table: DeviceTable, idx: jnp.ndarray, nrows: int) -> DeviceTable:
    """Gather rows by (possibly out-of-range padded) ``idx``; logical length
    ``nrows``. The physical length follows ``idx`` (already bucketed by the
    callers), including for column-less tables, so the plen floor survives
    compaction."""
    cap = int(idx.shape[0])
    if table.plen == 0:
        cols = {n: _null_column_like(c, cap)
                for n, c in table.columns.items()}
        return DeviceTable(cols, 0, plen=cap)
    if not table.columns:
        return DeviceTable({}, nrows, plen=cap)
    return gather_table_rows(table, idx, nrows)


# ---------------------------------------------------------------------------
# sort-key preparation
# ---------------------------------------------------------------------------


# ONE dedicated lock for every _identity_cache-managed dict (_rank_cache,
# _merged_cache, _dense_dim_cache, _dim_span_cache, _union_cache, and
# exprs.py's dictionary memos): all of their mutations funnel through
# _identity_cache, so guarding the insert/evict here guards them all.
# compute() stays OFF-lock — it may sync or trace, and the lock-discipline
# audit (analysis/conc_audit.py) forbids either under a lock. Losing a
# concurrent-insert race just recomputes one idempotent value.
_IDENTITY_LOCK = threading.Lock()


def _identity_cache(cache: dict, max_size: int, key_arrays: tuple, compute,
                    static_key=()):
    """Bounded FIFO cache keyed by the identity of host arrays (plus an
    optional hashable ``static_key`` for non-array parameters the cached
    value depends on). The entry holds references to the keyed arrays so a
    recycled id() can never alias a freed object; evicts oldest-first past
    ``max_size``. Thread-safe: lock-free GIL-atomic read, mutations under
    :data:`_IDENTITY_LOCK`.

    Under trace-replay the cache is BYPASSED: record and replay must
    consume the same host-read sequence, and a record-time cache hit
    (from an earlier query) would skip a read the replay trace performs
    (tracer ids are always fresh)."""
    if replay_mode() != "off":
        return compute()
    key = (static_key,) + tuple(id(a) for a in key_arrays)
    hit = cache.get(key)
    if hit is not None and all(h is a for h, a in zip(hit[0], key_arrays)):
        return hit[1]
    value = compute()
    with _IDENTITY_LOCK:
        # single winner per key: a concurrent miss that landed first
        # keeps its entry and THIS caller adopts it — identity-keyed
        # consumers downstream must see ONE host object per logical key
        hit = cache.get(key)
        if hit is not None and all(h is a
                                   for h, a in zip(hit[0], key_arrays)):
            return hit[1]
        if len(cache) >= max_size:
            cache.pop(next(iter(cache)))
        cache[key] = (key_arrays, value)
    return value


_rank_cache: dict = {}


def _dict_ranks(dict_values) -> tuple:
    """(code -> lexicographic rank, rank -> code) maps for one string
    dictionary, cached per dictionary (sorts repeat the same dictionaries
    every query). Cached as HOST arrays: a device array built inside a jit
    trace is a constant tracer, and caching one leaks it into later eager
    calls (UnexpectedTracerError)."""
    def compute():
        order = np.argsort(dict_values.astype(str), kind="stable")
        ranks = np.empty(len(order), dtype=np.int64)
        ranks[order] = np.arange(len(order))
        return ranks, order.astype(np.int64)
    return _identity_cache(_rank_cache, 512, (dict_values,), compute)


def ordered_codes(col: Column) -> jnp.ndarray:
    """For a string column, map dictionary codes to lexicographic ranks so
    integer comparisons order like string comparisons."""
    return jnp.take(_dict_ranks(col.dict_values)[0], col.data)


def plain_col(col: Column) -> Column:
    """Decoded (logical-representation) view of a possibly-encoded column
    — the one choke point value-consuming ops funnel through. A fused
    elementwise device op, zero host syncs (see Column.plain)."""
    return col.plain() if col.enc is not None else col


def plain_data(col: Column) -> jnp.ndarray:
    """Decoded data array of a possibly-encoded column."""
    return col.plain().data if col.enc is not None else col.data


def sortable_view(col: Column) -> jnp.ndarray:
    """Numeric view of a column that sorts in SQL ascending order.
    FOR/dict int encodings are order-preserving, so encoded codes sort
    exactly like the logical values — no decode needed."""
    if col.kind == "str":
        return ordered_codes(col)
    if col.kind == "bool":
        return col.data.astype(jnp.int32)
    return col.data


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _lexsort_impl(views, valids, descending, nulls_last, pad_key, n_valid):
    """Jit-fused multi-key sort by iterative order-preserving re-coding.

    ``views`` are numeric sortable views (host-side string ranking already
    applied); ``valids`` is a tuple of masks-or-None (structure is static);
    flag tuples are static.

    Instead of one variadic sort over up to 2k+1 operands — whose XLA:TPU
    comparator compile time grows superlinearly in operand count and has
    hung the remote compiler outright on ORDER BY clauses with many keys
    (the same failure mode iterative re-coding fixed for q4-class GROUP
    BYs) — each key folds into one combined int64 code via
    :func:`_dense_codes` (codes are assigned in ascending value order, so
    folding ``dense(combined)*fold + code`` preserves lexicographic order),
    and a single stable single-key argsort finishes. Every fold reuses the
    same single-key sort executable.
    """
    n = views[0].shape[0]
    fold = jnp.int64(2 * n + 4)
    combined = None
    if pad_key:
        combined = (jnp.arange(n) >= n_valid).astype(jnp.int64)  # live first
    for v, valid, desc, nl in zip(views, valids, descending, nulls_last):
        # _dense_codes sorts the key in its own dtype (f64 keys sort as
        # floats — no s64 bitcast, which the TPU x64-emulation pass cannot
        # compile) and yields int64 codes in ascending value order
        if desc:
            v = -v.astype(jnp.int64) if v.dtype != jnp.float64 else -v
        if v.dtype == jnp.float64:
            # NaNs must compare EQUAL (one code, greatest — Spark's float
            # ordering) so later keys can still break their ties; boundary
            # detection via != would give every NaN its own code
            nan = jnp.isnan(v)
            c = _dense_codes(jnp.where(nan, jnp.inf, v))
            code = 2 * c + nan.astype(jnp.int64) + 1      # 1..2n
        else:
            code = _dense_codes(v) + 1                    # 1..n
        if valid is not None:
            # null sentinels sit outside every real code (max 2n < 2n+3)
            code = jnp.where(valid, code,
                             jnp.int64(2 * n + 3) if nl else jnp.int64(0))
        combined = code if combined is None else \
            _dense_codes(combined) * fold + code
    if combined is None:
        return jnp.arange(n)
    return jnp.argsort(combined, stable=True)


def lexsort_indices(cols, descending=None, nulls_last=None,
                    n_valid: int | None = None) -> jnp.ndarray:
    """Stable multi-key sort. ``cols`` primary-first; per-key descending and
    nulls-last flags (SQL default: asc, nulls first — Spark semantics).
    With ``n_valid``, rows past the logical count sort after every live row
    (the padded-table invariant is preserved by any reorder)."""
    n = len(cols[0])
    if descending is None:
        descending = [False] * len(cols)
    if nulls_last is None:
        nulls_last = [False] * len(cols)
    # a device count may sit below the physical length; the pad sort key is
    # harmless when they happen to be equal, so lazily-counted tables always
    # take it (no sync)
    pad_key = n_valid is not None and (
        isinstance(n_valid, DeviceCount) or n_valid < n)
    views = tuple(sortable_view(c) for c in cols)
    valids = tuple(c.valid for c in cols)
    return _lexsort_impl(views, valids, tuple(descending), tuple(nulls_last),
                         pad_key, 0 if n_valid is None else count_arr(n_valid))


# ---------------------------------------------------------------------------
# grouping
# ---------------------------------------------------------------------------


def _dense_codes(v: jnp.ndarray) -> jnp.ndarray:
    """Dense group codes of a single 1-D key array (exact, via one
    single-key stable sort). Codes are < len(v); no host sync."""
    n = v.shape[0]
    order = jnp.argsort(v, stable=True)
    sv = jnp.take(v, order)
    boundary = jnp.concatenate([jnp.ones(1, dtype=bool), sv[1:] != sv[:-1]])
    code_sorted = jnp.cumsum(boundary.astype(jnp.int64)) - 1
    return jnp.zeros(n, dtype=jnp.int64).at[order].set(code_sorted)


_PAD_GROUP_KEY = jnp.iinfo(jnp.int64).max // 2


@functools.partial(jax.jit, static_argnums=(2,))
def _group_rep_impl(gids, n_valid, cap):
    """First-occurrence row index of each live group, bucket-padded to
    ``cap`` (static); the pad group scatters out of range and is dropped."""
    plen = gids.shape[0]
    live = jnp.arange(plen) < n_valid
    scatter_ids = jnp.where(live, gids, cap)
    return jnp.full(cap, plen, dtype=jnp.int64).at[scatter_ids].min(
        jnp.arange(plen, dtype=jnp.int64), mode="drop")


@jax.jit
def _group_ids_impl(views, valids, n_valid):
    """Jit-fused iterative dense re-coding (see :func:`group_ids`). One XLA
    program per (arity, null pattern, bucket); returns per-row dense group
    ids with pads in one trailing group, plus the live group count as a
    device scalar (the caller's single host sync)."""
    plen = views[0].shape[0]
    live = jnp.arange(plen) < n_valid
    fold = jnp.int64(2 * plen + 2)
    combined = None
    for v, valid in zip(views, valids):
        if valid is not None:
            # zero data under nulls: all-null rows must compare equal
            v = jnp.where(valid, v, jnp.zeros((), dtype=v.dtype))
        codes = _dense_codes(v)
        if valid is not None:
            codes = 2 * codes + (~valid).astype(jnp.int64)
        if combined is None:
            combined = codes
        else:
            # fold and immediately re-densify: both operands stay < 2*plen+2,
            # so the product below never overflows int64
            combined = _dense_codes(combined) * fold + codes
    # pad rows form one trailing group (the sort key exceeds any real code)
    combined = jnp.where(live, combined, _PAD_GROUP_KEY)
    gids = _dense_codes(combined)
    ngroups = jnp.max(jnp.where(live, gids, -1)) + 1
    return gids, ngroups


# pack multi-key groupings into one sort key when the combined bit-width
# fits: saves K sorts on the K+1-sort iterative fold. Only worth the extra
# range-probe sync on big tables; small-table groupings are latency-bound.
# Read at USE time: the threshold feeds the traced per-chunk program, so
# it is a pipeline-cache key member (engine/stream.py _cache_key) and an
# import freeze would let a post-import change serve a stale pipeline.
def group_pack_min() -> int:
    return int(os.environ.get("NDS_TPU_GROUP_PACK_MIN", str(1 << 20)))


@jax.jit
def _int_key_ranges(views, n_valid):
    """Fused (min, max) of every integer key view over live rows — one
    dispatch, one host transfer for the whole key set."""
    plen = views[0].shape[0]
    live = jnp.arange(plen) < n_valid
    mins = jnp.stack([jnp.min(jnp.where(live, v.astype(jnp.int64), _I64_MAX))
                      for v in views])
    maxs = jnp.stack([jnp.max(jnp.where(live, v.astype(jnp.int64), _I64_MIN))
                      for v in views])
    return mins, maxs


@functools.partial(jax.jit, static_argnums=(3,))
def _group_ids_packed(views, valids, offsets, widths, n_valid):
    """Single-sort grouping: every key's offset code (null flag folded)
    packs into one int64, so ONE :func:`_dense_codes` sort replaces the
    K+1 sorts of the iterative fold (the SF1 q22/q78 scaling axis:
    4-key groupings over 10M+ rows)."""
    plen = views[0].shape[0]
    combined = jnp.zeros(plen, dtype=jnp.int64)
    for v, valid, off, width in zip(views, valids, offsets, widths):
        code = (v.astype(jnp.int64) - off)
        if valid is not None:
            code = 2 * jnp.where(valid, code, 0) + (~valid).astype(jnp.int64)
        combined = (combined << width) | code
    live = jnp.arange(plen) < n_valid
    combined = jnp.where(live, combined, _PAD_GROUP_KEY)
    gids = _dense_codes(combined)
    ngroups = jnp.max(jnp.where(live, gids, -1)) + 1
    return gids, ngroups


def _packed_group_plan(key_cols, views, n_valid):
    """(offsets, widths) when the combined key fits 62 bits, else None.
    String/bool key spans are host-known (dictionary sizes); integer keys
    cost ONE fused range sync — only attempted past ``group_pack_min()``."""
    int_idx = [i for i, c in enumerate(key_cols)
               if c.kind not in ("str", "bool")]
    spans = [None] * len(key_cols)
    for i, c in enumerate(key_cols):
        if c.kind == "str":
            spans[i] = (0, max(len(c.dict_values) - 1, 0))
        elif c.kind == "bool":
            spans[i] = (0, 1)
    if int_idx:
        def fetch():
            mins, maxs = _int_key_ranges(
                tuple(views[i] for i in int_idx), n_valid)
            add_syncs()
            t0 = time.perf_counter_ns()
            out = (np.asarray(mins), np.asarray(maxs))
            add_sync_wait(time.perf_counter_ns() - t0)
            return out

        mins, maxs = host_read("group_ranges", fetch)
        for k, i in enumerate(int_idx):
            if mins[k] > maxs[k]:              # no live rows
                spans[i] = (0, 0)
            else:
                spans[i] = (int(mins[k]), int(maxs[k]))
    offsets, widths, total = [], [], 0
    for (lo, hi), c in zip(spans, key_cols):
        span = hi - lo
        if c.valid is not None:
            span = 2 * span + 1                # null flag folded in
        width = max(int(span).bit_length(), 1)
        offsets.append(lo)
        widths.append(width)
        total += width
    if total > 62:
        return None
    return tuple(offsets), tuple(widths)


def group_ids(key_cols, n_valid: int | None = None):
    """Grouping by iterative dense re-coding.

    Returns ``(gids, ngroups, rep_indices, cap)``: per-row dense group id
    (pad rows land in one trailing, discardable group), the live group
    count, the (bucket-padded, ``cap``-long) row index of each group's first
    occurrence, and the bucket capacity every grouped output should be
    allocated with (``num_segments=cap`` keeps segment-op shapes canonical;
    pad-group contributions land in output slots past ``ngroups`` or are
    dropped).

    One single-key sort per key column (+1 to densify each fold) instead of a
    single k-key lexsort: XLA:TPU compile time for a sort comparator grows
    superlinearly in operand count, and TPC-DS group-bys reach 8+ key columns
    (q4's 8-column customer rollup hung the remote compiler outright).
    SQL GROUP BY treats nulls as equal; each column's code folds its null
    flag in (``2*value_code + is_null``), so all-null rows share a code
    distinct from any real value's. The fold multiplier is the static bound
    ``2*plen+2`` (codes are < plen), so no per-fold host sync is needed.
    """
    plen = len(key_cols[0])
    if n_valid is None:
        n_valid = plen
    if plen == 0:
        cap = bucket_len(0)
        return (jnp.zeros(0, dtype=jnp.int64), 0,
                jnp.full(cap, 1, dtype=jnp.int64), cap)
    views = tuple(sortable_view(c) for c in key_cols)
    valids = tuple(c.valid for c in key_cols)
    nv = count_arr(n_valid)
    plan = None
    if len(key_cols) > 1 and plen >= group_pack_min():
        plan = _packed_group_plan(key_cols, views, nv)
    if plan is not None:
        gids, ng_dev = _group_ids_packed(views, valids, plan[0], plan[1],
                                         nv)
    else:
        gids, ng_dev = _group_ids_impl(views, valids, nv)
    # the one host sync — routed through the pending batch, so any lazy
    # counts the query accumulated upstream (filter compactions, inner-join
    # pair counts) resolve in the SAME transfer
    ngroups = DeviceCount(ng_dev, count_bound(n_valid)).to_int()
    cap = bucket_len(ngroups)
    rep = _group_rep_impl(gids, nv, cap)
    return gids, ngroups, rep, cap


# ---------------------------------------------------------------------------
# aggregation kernels
# ---------------------------------------------------------------------------

_F64_MIN = jnp.finfo(jnp.float64).min
_F64_MAX = jnp.finfo(jnp.float64).max
_I64_MIN = jnp.iinfo(jnp.int64).min
_I64_MAX = jnp.iinfo(jnp.int64).max


@functools.partial(jax.jit, static_argnums=(2,))
def _agg_count_impl(valid, gids, ngroups):
    ones = (jnp.ones(gids.shape[0], dtype=jnp.int64) if valid is None
            else valid.astype(jnp.int64))
    return jax.ops.segment_sum(ones, gids, num_segments=ngroups)


def agg_count(col: Column | None, gids, ngroups) -> Column:
    """count(*) when col is None else count(col) (non-null). Pad rows need
    no masking here: grouping routes them to a trailing group that lands
    past the logical group count or is dropped by the segment op.

    Counts are exactly representable in f32 below 2^24 rows, so unlike the
    decimal sums this EXACT aggregate can ride the Pallas MXU kernel —
    count appears in nearly every query (count(*), avg validity), which is
    what makes the kernel hot on the default exact-decimal bench. (The
    2^24 exactness claim and this gate are checked by
    ``analysis/num_audit.kernel_claim_checks``.)"""
    valid = None if col is None else col.valid
    if int(gids.shape[0]) < (1 << 24):
        from nds_tpu.engine.kernels import pallas_active, segment_sum_fused
        if pallas_active(ngroups):
            g = gids if valid is None else jnp.where(valid, gids, -1)
            _, counts = segment_sum_fused(
                jnp.zeros(gids.shape[0], dtype=jnp.float32), g, ngroups)
            return Column("i64", counts.astype(jnp.int64))
    return Column("i64", _agg_count_impl(valid, gids, ngroups))


@functools.partial(jax.jit, static_argnums=(3, 4))
def _agg_sum_impl(data, valid, gids, ngroups, as_f64):
    v = (jnp.ones(data.shape[0], dtype=bool) if valid is None else valid)
    d = jnp.where(v, data, 0)
    d = d if as_f64 else d.astype(jnp.int64)
    out = jax.ops.segment_sum(d, gids, num_segments=ngroups)
    cnt = jax.ops.segment_sum(v.astype(jnp.int32), gids, num_segments=ngroups)
    return out, cnt > 0


def agg_sum(col: Column, gids, ngroups) -> Column:
    col = plain_col(col)           # sums need logical values (fused decode)
    if col.kind == "f64":
        from nds_tpu.engine.kernels import pallas_active, segment_sum_fused
        if pallas_active(ngroups):
            # opt-in MXU fast path (f32 accumulation; the exact path below is
            # the default because validation compares at decimal tolerance).
            # The kernel's counts are per-group valid counts (gid -1 = null),
            # so they double as the result validity mask.
            valid = col.valid_mask()
            g = jnp.where(valid, gids, -1)
            sums, counts = segment_sum_fused(
                jnp.where(valid, col.data, 0), g, ngroups)
            return Column("f64", sums.astype(jnp.float64), counts > 0)
        out, nonempty = _agg_sum_impl(col.data, col.valid, gids, ngroups, True)
        return Column("f64", out, nonempty)
    if is_dec(col.kind):
        # EXACT MXU path for the default decimal bench: two's-complement
        # limb accumulation (kernels.segment_sum_exact), bit-exact for any
        # int64 — no reliance on the declared precision.
        from nds_tpu.engine.kernels import (exact_sum_supported,
                                            segment_sum_exact)
        if exact_sum_supported(ngroups, int(gids.shape[0])):
            valid = col.valid_mask()
            g = jnp.where(valid, gids, -1)
            sums, counts = segment_sum_exact(
                jnp.where(valid, col.data, 0), g, ngroups)
            return Column(f"dec(38,{col.scale})", sums, counts > 0)
    out, nonempty = _agg_sum_impl(col.data, col.valid, gids, ngroups, False)
    kind = f"dec(38,{col.scale})" if is_dec(col.kind) else "i64"
    return Column(kind, out, nonempty)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _agg_min_impl(view, valid, gids, ngroups, is_max):
    v = (jnp.ones(view.shape[0], dtype=bool) if valid is None else valid)
    if view.dtype == jnp.float64:
        sentinel = _F64_MIN if is_max else _F64_MAX
        work = view
    else:
        sentinel = _I64_MIN if is_max else _I64_MAX
        work = view.astype(jnp.int64)
    data = jnp.where(v, work, sentinel)
    seg = jax.ops.segment_max if is_max else jax.ops.segment_min
    out = seg(data, gids, num_segments=ngroups)
    cnt = jax.ops.segment_sum(v.astype(jnp.int32), gids, num_segments=ngroups)
    return out, cnt > 0


def agg_min(col: Column, gids, ngroups, is_max=False) -> Column:
    if col.kind == "f64":
        from nds_tpu.engine.kernels import pallas_active, \
            segment_minmax_fused
        if pallas_active(ngroups):
            # float min/max rides the tiled one-hot kernel; exact kinds
            # (int/decimal/string ranks) stay on the XLA path below
            valid = col.valid_mask()
            g = jnp.where(valid, gids, -1)
            mins, maxs = segment_minmax_fused(col.data, g, ngroups)
            cnt = jax.ops.segment_sum(valid.astype(jnp.int32),
                                      jnp.where(valid, gids, 0),
                                      num_segments=ngroups)
            out = (maxs if is_max else mins).astype(jnp.float64)
            return Column("f64", jnp.where(cnt > 0, out, 0.0), cnt > 0)
    out, out_valid = _agg_min_impl(sortable_view(col), col.valid, gids,
                                   ngroups, bool(is_max))
    if col.kind == "str":
        # min/max of strings: map the winning rank back to a dictionary code
        # (the rank<->code maps are cached per dictionary)
        rank_to_code = _dict_ranks(col.dict_values)[1]
        codes = jnp.take(rank_to_code,
                         jnp.clip(out, 0, rank_to_code.shape[0] - 1))
        return Column("str", codes.astype(jnp.int32), out_valid, col.dict_values)
    if col.kind == "f64":
        return Column("f64", out, out_valid)
    # order-preserving encodings: min/max of codes IS the code of the
    # min/max value, so the result stays encoded (decode at materialize)
    return Column(col.kind, out.astype(col.data.dtype), out_valid,
                  enc=col.enc)


@functools.partial(jax.jit, static_argnums=(3,))
def _agg_avg_impl(data, valid, gids, ngroups):
    v = (jnp.ones(data.shape[0], dtype=bool) if valid is None else valid)
    d = jnp.where(v, data, 0.0)
    s = jax.ops.segment_sum(d, gids, num_segments=ngroups)
    c = jax.ops.segment_sum(v.astype(jnp.float64), gids, num_segments=ngroups)
    return jnp.where(c > 0, s / jnp.maximum(c, 1.0), 0.0), c > 0


def agg_avg(col: Column, gids, ngroups) -> Column:
    col = plain_col(col)
    if is_dec(col.kind):
        # exact MXU sum first (same gate as agg_sum), then one f64 divide:
        # better than accumulating rounded f64 terms AND rides the hardware
        from nds_tpu.engine.kernels import (exact_sum_supported,
                                            segment_sum_exact)
        if exact_sum_supported(ngroups, int(gids.shape[0])):
            valid = col.valid_mask()
            g = jnp.where(valid, gids, -1)
            sums, counts = segment_sum_exact(
                jnp.where(valid, col.data, 0), g, ngroups)
            out = jnp.where(
                counts > 0,
                (sums.astype(jnp.float64) / (10.0 ** col.scale)) /
                jnp.maximum(counts, 1).astype(jnp.float64), 0.0)
            return Column("f64", out, counts > 0)
    data = col.data.astype(jnp.float64)
    if is_dec(col.kind):
        data = data / (10.0 ** col.scale)
    if col.kind == "f64":
        # avg is exactly the kernel's (sums, counts) pair in one MXU pass;
        # decimal avgs stay on the exact XLA path like decimal sums
        from nds_tpu.engine.kernels import pallas_active, segment_sum_fused
        if pallas_active(ngroups):
            valid = col.valid_mask()
            g = jnp.where(valid, gids, -1)
            sums, counts = segment_sum_fused(
                jnp.where(valid, data, 0.0), g, ngroups)
            out = jnp.where(counts > 0,
                            sums.astype(jnp.float64) /
                            jnp.maximum(counts.astype(jnp.float64), 1.0), 0.0)
            return Column("f64", out, counts > 0)
    out, nonempty = _agg_avg_impl(data, col.valid, gids, ngroups)
    return Column("f64", out, nonempty)


@functools.partial(jax.jit, static_argnums=(3,))
def _agg_stddev_impl(data, valid, gids, ngroups):
    v = (jnp.ones(data.shape[0], dtype=bool) if valid is None else valid)
    d = jnp.where(v, data, 0.0)
    s1 = jax.ops.segment_sum(d, gids, num_segments=ngroups)
    s2 = jax.ops.segment_sum(d * d, gids, num_segments=ngroups)
    c = jax.ops.segment_sum(v.astype(jnp.float64), gids, num_segments=ngroups)
    mean = s1 / jnp.maximum(c, 1.0)
    var = (s2 - c * mean * mean) / jnp.maximum(c - 1.0, 1.0)
    return jnp.sqrt(jnp.maximum(var, 0.0)), c > 1


def agg_stddev_samp(col: Column, gids, ngroups) -> Column:
    col = plain_col(col)
    data = col.data.astype(jnp.float64)
    if is_dec(col.kind):
        data = data / (10.0 ** col.scale)
    out, enough = _agg_stddev_impl(data, col.valid, gids, ngroups)
    return Column("f64", out, enough)


# ---------------------------------------------------------------------------
# filter / compact
# ---------------------------------------------------------------------------


def filter_table(table: DeviceTable, predicate: Column) -> DeviceTable:
    """Keep rows where the predicate is true (SQL: null counts as false)."""
    mask = predicate.data.astype(bool)
    if predicate.valid is not None:
        mask = mask & predicate.valid
    return compact_table(table, mask)


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------

_HASH_C1 = np.uint64(0x9E3779B97F4A7C15)
_HASH_C2 = np.uint64(0xBF58476D1CE4E5B9)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 30)) * jnp.uint64(_HASH_C2)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> 31)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _key_hash_impl(views, valids, side_salt: int, null_safe: bool, n_valid,
                   excluded=None):
    """64-bit composite hash of prepared key views (see :func:`_hash_views`).

    Default SQL join semantics: rows with any null key get a per-row unique
    value that cannot match the other side (null joins nothing). With
    ``null_safe`` (set operations, null-safe equality), the null flag is
    folded into the hash instead so null keys compare equal. Pad rows past
    ``n_valid``, and rows flagged in ``excluded`` (a deferred filter mask the
    planner chose not to materialize), always get the unmatchable per-row
    value."""
    n = views[0].shape[0]
    h = jnp.full(n, jnp.uint64(0x243F6A8885A308D3), dtype=jnp.uint64)
    any_null = jnp.zeros(n, dtype=bool)
    for v, valid in zip(views, valids):
        if v.dtype == jnp.float64:
            # equality-preserving int words (the hash is only a candidate
            # prefilter — _verify_pairs compares exactly — and a f64->s64
            # bitcast does not compile under the TPU x64-emulation rewrite):
            # the integer part plus a 52-bit fraction word keep distinct
            # doubles in distinct buckets at full double resolution
            vf = jnp.nan_to_num(v)
            ip = jnp.clip(vf, -9.0e18, 9.0e18).astype(jnp.int64)
            frac = ((vf - jnp.floor(vf)) * float(2 ** 52)).astype(jnp.int64)
            words = (ip.astype(jnp.uint64), frac.astype(jnp.uint64))
        else:
            words = (v.astype(jnp.uint64),)
        # the null-marker mix must be applied identically on both join sides,
        # including columns with no mask at all
        if valid is not None:
            words = tuple(jnp.where(valid, w, jnp.uint64(0)) for w in words)
            marker = jnp.where(valid, jnp.uint64(0),
                               jnp.uint64(0xA5A5A5A5A5A5A5A5))
            any_null = any_null | ~valid
        else:
            marker = jnp.zeros(n, dtype=jnp.uint64)
        h = _mix64(h ^ marker)
        for w in words:
            h = _mix64(h ^ w * jnp.uint64(_HASH_C1))
    unmatchable = jnp.zeros(n, dtype=bool) if null_safe else any_null
    unmatchable = unmatchable | (jnp.arange(n) >= n_valid)
    if excluded is not None:
        unmatchable = unmatchable | excluded
    row_ids = jnp.arange(n, dtype=jnp.uint64)
    # bit layout: bits 0-1 side tag, bit 2 = REAL marker (exactly zero on
    # sentinels — the exchange path classifies on it), row id from bit 3
    sentinel = jnp.uint64(1 if side_salt else 2) + (row_ids << jnp.uint64(3))
    return jnp.where(unmatchable, sentinel, h | jnp.uint64(4))


def _hash_views(left_keys, right_keys):
    """Per-pair hashable views of the join keys. String pairs are mapped
    through one merged dictionary ordering first: the per-column dictionary
    codes of the two sides are NOT comparable (equal strings get different
    codes), so hashing raw codes would silently drop every cross-dictionary
    match."""
    lviews, rviews = [], []
    for lk, rk in zip(left_keys, right_keys):
        if lk.kind == "str" and rk.kind == "str":
            lv, rv = ordered_codes_merged(lk, rk)
        else:
            # encoded int keys decode to the shared logical space (codes
            # from different encodings are not comparable) — a fused
            # elementwise widen inside the jit program, zero syncs
            lv, rv = plain_data(lk), plain_data(rk)
        lviews.append(lv)
        rviews.append(rv)
    return tuple(lviews), tuple(rviews)


def _verify_pairs(l_idx, r_idx, left_keys, right_keys,
                  null_safe: bool = False) -> jnp.ndarray:
    """Exact key equality for candidate pairs (hash-collision safety).
    With ``null_safe``, null == null."""
    ok = jnp.ones(l_idx.shape[0], dtype=bool)
    for lk, rk in zip(left_keys, right_keys):
        if lk.kind == "str" and rk.kind == "str":
            # dictionary codes come from different dicts; compare via ranks in
            # a merged ordering
            lmap, rmap = ordered_codes_merged(lk, rk)
            lv = jnp.take(lmap, l_idx)
            rv = jnp.take(rmap, r_idx)
        else:
            lv = jnp.take(plain_data(lk), l_idx)
            rv = jnp.take(plain_data(rk), r_idx)
        eq = lv == rv
        lvalid = None if lk.valid is None else jnp.take(lk.valid, l_idx)
        rvalid = None if rk.valid is None else jnp.take(rk.valid, r_idx)
        if null_safe:
            lnull = jnp.zeros_like(eq) if lvalid is None else ~lvalid
            rnull = jnp.zeros_like(eq) if rvalid is None else ~rvalid
            eq = jnp.where(lnull | rnull, lnull & rnull, eq)
        else:
            if lvalid is not None:
                eq = eq & lvalid
            if rvalid is not None:
                eq = eq & rvalid
        ok = ok & eq
    return ok


_merged_cache: dict = {}


def ordered_codes_merged(a: Column, b: Column):
    """Map two string columns' codes into one shared value ordering, cached
    per dictionary pair (host arrays — see :func:`_dict_ranks`)."""
    def compute():
        union, inverse = np.unique(
            np.concatenate([a.dict_values.astype(str), b.dict_values.astype(str)]),
            return_inverse=True)
        a_map = inverse[: len(a.dict_values)].astype(np.int64)
        b_map = inverse[len(a.dict_values):].astype(np.int64)
        return a_map, b_map
    a_map, b_map = _identity_cache(
        _merged_cache, 256, (a.dict_values, b.dict_values), compute)
    return jnp.take(jnp.asarray(a_map), a.data), \
        jnp.take(jnp.asarray(b_map), b.data)


def _probe_candidates(left_keys, right_keys, null_safe=False,
                      n_left=None, n_right=None, l_excl=None, r_excl=None):
    """Hash-probe phase shared by the monolithic and chunked joins: returns
    ``(counts, lo, order, total)`` — per-left-row candidate counts, start
    offsets into the hash-sorted right side, the right-side sort order, and
    the total candidate-pair count (host sync)."""
    plen_l = len(left_keys[0])
    plen_r = len(right_keys[0])
    n_left = plen_l if n_left is None else n_left
    n_right = plen_r if n_right is None else n_right
    lviews, rviews = _hash_views(left_keys, right_keys)
    lvalids = tuple(c.valid for c in left_keys)
    rvalids = tuple(c.valid for c in right_keys)
    rh = _key_hash_impl(rviews, rvalids, 1, null_safe, count_arr(n_right),
                        r_excl)
    order = jnp.argsort(rh)
    rh_sorted = jnp.take(rh, order)
    if stream_bounds_on():
        # chunk-invariant program: no data-dependent sizing sync. The
        # caller sizes its pair bucket from static bounds and registers a
        # device-side overflow flag (checked at the pipeline's single
        # materializing sync). The probe side may take the fused Pallas
        # bound-bucket probe (one VMEM pass: bitwise _key_hash_impl +
        # both searchsorted sides against the resident dimension hash
        # table) — candidate counts are identical by construction, so
        # the XLA arm below stays the always-available fallback.
        if not null_safe:
            from nds_tpu.engine.kernels import try_fused_probe
            got = try_fused_probe(left_keys, lviews, lvalids,
                                  count_arr(n_left), l_excl, rh_sorted)
            if got is not None:
                counts, lo = got
                return counts, lo, order, None
        lh = _key_hash_impl(lviews, lvalids, 0, null_safe,
                            count_arr(n_left), l_excl)
        lo = jnp.searchsorted(rh_sorted, lh, side="left")
        hi = jnp.searchsorted(rh_sorted, lh, side="right")
        return hi - lo, lo, order, None
    lh = _key_hash_impl(lviews, lvalids, 0, null_safe, count_arr(n_left),
                        l_excl)
    lo = jnp.searchsorted(rh_sorted, lh, side="left")
    hi = jnp.searchsorted(rh_sorted, lh, side="right")
    counts = hi - lo
    total = host_sync(jnp.sum(counts))                 # host sync 1
    return counts, lo, order, total


def join_indices(left_keys, right_keys, how: str = "inner",
                 null_safe: bool = False,
                 n_left: int | None = None, n_right: int | None = None,
                 l_excl=None, r_excl=None, probe=None):
    """Equi-join. Returns ``(l_idx, r_idx, n_pairs, l_extra, n_lx, r_extra,
    n_rx)``: bucket-padded matched pair indices with their logical count,
    plus (for outer joins) the bucket-padded unmatched row indices of each
    side. Pad slots hold out-of-range indices (gathers clip, scatters drop).
    ``l_excl``/``r_excl`` are deferred filter masks (True = row filtered
    out): such rows join nothing, which lets the planner push a filter into
    the join without a compaction sync. ``probe`` passes a precomputed
    :func:`_probe_candidates` result.
    """
    plen_l = len(left_keys[0])
    plen_r = len(right_keys[0])
    n_left = plen_l if n_left is None else n_left
    n_right = plen_r if n_right is None else n_right
    counts, lo, order, total = probe if probe is not None else \
        _probe_candidates(left_keys, right_keys, null_safe,
                          n_left, n_right, l_excl, r_excl)
    if total is None or total > 0:
        if total is None:
            # stream-bounds join: the candidate total stays on device, so
            # the pair bucket is sized from STATIC bounds (probe-side
            # bucket x a power-of-two fanout allowance). A chunk whose
            # true candidate count exceeds it would silently drop pairs,
            # so the excess registers as a device-side overflow flag the
            # streaming executor checks at its single materializing sync.
            total_dev = jnp.sum(counts)
            cand = min(bucket_len(count_bound(n_left)) * stream_fanout(),
                       bucket_len(pair_budget()))
            stream_overflow(total_dev > cand)
            pair_live = jnp.arange(cand) < total_dev
            n_pairs_bound = cand
        else:
            cand = bucket_len(total)
            pair_live = live_mask(cand, total)
            n_pairs_bound = total
        l_idx = jnp.repeat(jnp.arange(plen_l), counts, total_repeat_length=cand)
        starts = jnp.cumsum(counts) - counts
        pos = jnp.arange(cand) - jnp.repeat(starts, counts, total_repeat_length=cand)
        r_pos = jnp.repeat(lo, counts, total_repeat_length=cand) + pos
        r_idx = jnp.take(order, jnp.clip(r_pos, 0, max(plen_r - 1, 0)))
        ok = _verify_pairs(l_idx, r_idx, left_keys, right_keys, null_safe)
        ok = ok & pair_live
        # NO pair-count sync: verified pairs compact to the prefix of the
        # candidate bucket (the verify only removes hash collisions, so the
        # bucket is near-tight) and the exact count rides as a DeviceCount.
        # An outer join resolves it below — batched with the extra counts
        # into ONE transfer (DESIGN.md item 3) — because the concatenated
        # output layout needs host offsets; an inner join never syncs here.
        n_pairs = DeviceCount(jnp.sum(ok), n_pairs_bound)
        keep = jnp.nonzero(ok, size=cand, fill_value=cand)[0]
        # out-of-range pads: point pad pairs past both inputs
        l_idx = jnp.take(l_idx, keep, mode="fill", fill_value=plen_l)
        r_idx = jnp.take(r_idx, keep, mode="fill", fill_value=plen_r)
    else:
        n_pairs = 0
        cap0 = bucket_len(0)
        l_idx = jnp.full(cap0, plen_l, dtype=jnp.int64)
        r_idx = jnp.full(cap0, plen_r, dtype=jnp.int64)

    l_extra = r_extra = None
    n_lx = n_rx = 0
    miss = miss_r = None
    if how in ("left", "full"):
        matched = jnp.zeros(plen_l, dtype=bool).at[l_idx].set(
            True, mode="drop")
        miss = ~matched & live_mask(plen_l, n_left)
        if l_excl is not None:
            miss = miss & ~l_excl
        n_lx = DeviceCount(jnp.sum(miss), count_bound(n_left))
    if how in ("right", "full"):
        matched_r = jnp.zeros(plen_r, dtype=bool).at[r_idx].set(
            True, mode="drop")
        miss_r = ~matched_r & live_mask(plen_r, n_right)
        if r_excl is not None:
            miss_r = miss_r & ~r_excl
        n_rx = DeviceCount(jnp.sum(miss_r), count_bound(n_right))
    # one batched transfer resolves every count this join created
    if miss is not None:
        n_lx = n_lx.to_int()
        l_extra = compact_indices(miss, n_lx)
    if miss_r is not None:
        n_rx = n_rx.to_int()
        r_extra = compact_indices(miss_r, n_rx)
    return l_idx, r_idx, n_pairs, l_extra, n_lx, r_extra, n_rx


@jax.jit
def _semi_sorted_impl(lv, lvalid, rv, rvalid, n_left, n_right):
    """Sort-based existence probe on directly comparable key views: dead
    right rows take the sentinel (never exposing their value), live rows
    sort live-first, so one leftmost searchsorted + equality + liveness
    check answers "does any LIVE right row hold this value" — exact (no
    hash, no collision verify), duplicate-tolerant, and sync-free."""
    plen_r = rv.shape[0]
    ok_r = jnp.arange(plen_r) < n_right
    if rvalid is not None:
        ok_r = ok_r & rvalid
    dk = jnp.where(ok_r, rv.astype(jnp.int64), _PK_SENTINEL)
    order = jnp.lexsort((~ok_r, dk))
    dks = jnp.take(dk, order)
    lvv = lv.astype(jnp.int64)
    lo = jnp.clip(jnp.searchsorted(dks, lvv), 0, max(plen_r - 1, 0))
    hit = (jnp.take(dks, lo) == lvv) & jnp.take(jnp.take(ok_r, order), lo)
    ok_l = jnp.arange(lv.shape[0]) < n_left
    if lvalid is not None:
        ok_l = ok_l & lvalid
    return hit & ok_l


def semi_join_mask(left_keys, right_keys, negate: bool = False,
                   null_safe: bool = False,
                   n_left: int | None = None,
                   n_right: int | None = None) -> jnp.ndarray:
    """Boolean per-left-row mask: has (semi) / lacks (anti) a match on the
    right. Used for IN / EXISTS / NOT EXISTS and (null-safe) set ops.
    Pad rows always come back False."""
    plen_l = len(left_keys[0])
    n_left = plen_l if n_left is None else n_left
    lk, rk = left_keys[0], right_keys[0]
    if len(left_keys) == 1 and not null_safe and \
            lk.kind != "f64" and rk.kind != "f64" and \
            (lk.kind == rk.kind or
             {lk.kind, rk.kind} <= {"i64", "date"}):
        # single integer-comparable key (i64/date/decimal/str ranks): the
        # sort probe answers existence directly — no candidate-pair sync
        # (_probe_candidates' total), which is one blocking round trip per
        # IN/EXISTS subquery on the generic path (DESIGN.md item 2)
        if lk.kind == "str" and rk.kind == "str":
            lview, rview = ordered_codes_merged(lk, rk)
        elif lk.kind != "str" and rk.kind != "str":
            lview, rview = plain_data(lk), plain_data(rk)
        else:
            lview = rview = None
        if lview is not None:
            plen_r = len(rk)
            n_r = plen_r if n_right is None else n_right
            matched = _semi_sorted_impl(lview, lk.valid, rview, rk.valid,
                                        count_arr(n_left), count_arr(n_r))
            out = ~matched if negate else matched
            return out & live_mask(plen_l, n_left)
    l_idx, _, _, _, _, _, _ = join_indices(
        left_keys, right_keys, "inner", null_safe, n_left, n_right)
    matched = jnp.zeros(plen_l, dtype=bool).at[l_idx].set(True, mode="drop")
    out = ~matched if negate else matched
    return out & live_mask(plen_l, n_left)


_PK_SENTINEL = jnp.iinfo(jnp.int64).max


@jax.jit
def _pk_gather_impl(fkey, fvalid, dkey, dvalid, n_fact, n_dim,
                    f_excl, d_excl):
    """Exact merge-probe of fact keys against a UNIQUE dimension key.

    Dead dimension rows (pads, filtered, null keys) take an unmatchable
    sentinel before the sort, so one searchsorted + equality check finds the
    unique match — no hash, no collision verify, no host sync. Returns
    ``(r_idx, matched)`` at fact physical length.
    """
    plen_d = dkey.shape[0]
    ok_d = jnp.arange(plen_d) < n_dim
    if dvalid is not None:
        ok_d = ok_d & dvalid
    if d_excl is not None:
        ok_d = ok_d & ~d_excl
    dk = jnp.where(ok_d, dkey.astype(jnp.int64), _PK_SENTINEL)
    # live-first tie-break: a dead row's sentinel must sort after a live row
    # holding the same (legitimate) key value, so leftmost searchsorted
    # always lands on the live row when one exists
    order = jnp.lexsort((~ok_d, dk))
    dks = jnp.take(dk, order)
    fk = fkey.astype(jnp.int64)
    lo = jnp.clip(jnp.searchsorted(dks, fk), 0, plen_d - 1)
    hit = jnp.take(dks, lo) == fk
    plen_f = fkey.shape[0]
    ok_f = jnp.arange(plen_f) < n_fact
    if fvalid is not None:
        ok_f = ok_f & fvalid
    if f_excl is not None:
        ok_f = ok_f & ~f_excl
    # gate on the matched dim row's liveness rather than on the fact key
    # value: a legitimate key equal to the sentinel (2^63-1) can only "hit"
    # a live dim row holding that same real key, so it still matches, while
    # hits on dead (sentinel-keyed) dim rows are rejected
    matched = hit & ok_f & jnp.take(jnp.take(ok_d, order), lo)
    return jnp.take(order, lo), matched


_dense_dim_cache: dict = {}


def _dense_dim_info(dim_key: Column, n_dim: int):
    """(base, device position map) when the dimension key is a dense-ish
    unique integer range (every TPC-DS surrogate key is), else None.
    Cached per key-array identity — built once per loaded dimension, it
    replaces the per-join searchsorted (a 17-iteration binary-search loop
    over emulated int64, ~0.6s for a 4M-row probe on v5e) with ONE gather."""
    if dim_key.kind == "str" or dim_key.enc is not None or n_dim == 0 \
            or n_dim > (1 << 24):
        return None                # encoded dim keys take the sort probe

    def compute():
        def fetch():
            live = np.asarray(dim_key.data[:n_dim]).astype(np.int64)
            if dim_key.valid is not None and \
                    not bool(np.all(np.asarray(dim_key.valid[:n_dim]))):
                return None                   # null PKs: sort path handles
            mn = int(live.min())
            span = int(live.max()) - mn + 1
            # sparse keys blow the map; 4x slack covers SCD-style gaps
            if span > max(4 * n_dim, 1 << 16) or span > (1 << 26):
                return None
            pos = np.full(span, n_dim, dtype=np.int64)  # n_dim = miss mark
            pos[live - mn] = np.arange(n_dim)
            return mn, pos

        # the host part (the fetched key array -> position map) routes
        # through the replay log; only the device upload stays outside
        got = timed_read("dense_dim", fetch)
        if got is None:
            return None
        mn, pos = got
        return mn, jnp.asarray(pos)

    # n_dim in the key: the position map's miss marker and coverage are
    # built for one logical row count, so a re-probe of the same array at a
    # different n_dim must not reuse a stale map
    return _identity_cache(_dense_dim_cache, 64, (dim_key.data,), compute,
                           static_key=n_dim)


@jax.jit
def _pk_gather_dense_impl(fkey, fvalid, dkey, dvalid, pos_map, base,
                          n_fact, n_dim, f_excl, d_excl):
    """Dense-range merge probe: position-map gather instead of sort +
    searchsorted. Same contract as :func:`_pk_gather_impl`."""
    plen_d = dkey.shape[0]
    plen_f = fkey.shape[0]
    ok_d = jnp.arange(plen_d) < n_dim
    if dvalid is not None:
        ok_d = ok_d & dvalid
    if d_excl is not None:
        ok_d = ok_d & ~d_excl
    fk = fkey.astype(jnp.int64)
    off = fk - base
    span = pos_map.shape[0]
    inb = (off >= 0) & (off < span)
    r_idx = jnp.take(pos_map, jnp.clip(off, 0, span - 1))
    r_ok = inb & (r_idx < n_dim)
    r_idx = jnp.clip(r_idx, 0, plen_d - 1)
    hit = r_ok & (jnp.take(dkey.astype(jnp.int64), r_idx) == fk)
    hit = hit & jnp.take(ok_d, r_idx)
    ok_f = jnp.arange(plen_f) < n_fact
    if fvalid is not None:
        ok_f = ok_f & fvalid
    if f_excl is not None:
        ok_f = ok_f & ~f_excl
    return r_idx, hit & ok_f


def pk_gather_join(fact_key: Column, dim_key: Column,
                   n_fact: int, n_dim: int, f_excl=None, d_excl=None):
    """Planner-facing wrapper of :func:`_pk_gather_impl`: prepares
    comparable integer views (merged dictionary ranks for string pairs),
    and takes the dense-range position-map probe when the dimension key
    is a dense unique integer range (all TPC-DS surrogate keys)."""
    # the dense position map is HOST-built per dimension, so a lazy dim
    # count resolves here (batched); dimensions are load-time tables with
    # host counts on every hot path, so this stays sync-free in practice
    if isinstance(n_dim, DeviceCount):
        n_dim = n_dim.to_int()
    if fact_key.kind == "str" and dim_key.kind == "str":
        fview, dview = ordered_codes_merged(fact_key, dim_key)
    else:
        fview, dview = plain_data(fact_key), plain_data(dim_key)
        dense = _dense_dim_info(dim_key, n_dim)
        if dense is not None:
            base, pos_map = dense
            return _pk_gather_dense_impl(
                fview, fact_key.valid, dview, dim_key.valid, pos_map,
                jnp.int64(base), count_arr(n_fact), n_dim, f_excl, d_excl)
    return _pk_gather_impl(fview, fact_key.valid, dview, dim_key.valid,
                           count_arr(n_fact), n_dim, f_excl, d_excl)


_dim_span_cache: dict = {}


@jax.jit
def _pack_keys_impl(views, valids, offsets, widths, spans):
    """Pack offset key codes into one int64, with a combined validity
    (per-key nulls AND in-range — a fact key outside the dim's span can
    never match)."""
    plen = views[0].shape[0]
    packed = jnp.zeros(plen, dtype=jnp.int64)
    ok = jnp.ones(plen, dtype=bool)
    for v, valid, off, width, span in zip(views, valids, offsets, widths,
                                          spans):
        k = v.astype(jnp.int64) - off
        ok = ok & (k >= 0) & (k <= span)
        if valid is not None:
            ok = ok & valid
        packed = (packed << width) | jnp.clip(k, 0, span)
    return packed, ok


def pk_gather_join_multi(fact_keys, dim_keys, n_fact: int, n_dim: int,
                         f_excl=None, d_excl=None):
    """Composite-key merge probe against a UNIQUE key set (the fact/returns
    composite primary keys): pack every key into one int64 (widths from the
    dim side's value spans — one fused range sync, identity-cached per key
    set) and run the single-key exact probe. Returns ``(r_idx, matched)``
    or None when the keys cannot pack (non-integer kinds or >62 combined
    bits) — callers fall back to the hash join."""
    if len(fact_keys) == 1:
        return pk_gather_join(fact_keys[0], dim_keys[0], n_fact, n_dim,
                              f_excl, d_excl)
    kinds = {c.kind for c in list(fact_keys) + list(dim_keys)}
    if any(k in ("str", "f64") or k.startswith("dec") for k in kinds):
        return None
    if isinstance(n_dim, DeviceCount):      # host span plan (see above)
        n_dim = n_dim.to_int()
    # encoded keys pack through their decoded logical views (the span
    # plan is identity-cached per dim-key ARRAY, which is unencoded on
    # every dimension; the fact side decodes fused)
    fact_keys = [plain_col(c) for c in fact_keys]
    dim_keys = [plain_col(c) for c in dim_keys]

    def compute():
        def fetch():
            mins, maxs = _int_key_ranges(
                tuple(c.data for c in dim_keys), n_dim)
            add_syncs()
            t0 = time.perf_counter_ns()
            out = (np.asarray(mins), np.asarray(maxs))
            add_sync_wait(time.perf_counter_ns() - t0)
            return out

        mins, maxs = host_read("dim_ranges", fetch)
        offsets, widths, spans, total = [], [], [], 0
        for lo, hi in zip(mins, maxs):
            span = max(int(hi) - int(lo), 0)
            width = max(int(span).bit_length(), 1)
            offsets.append(int(lo))
            widths.append(width)
            spans.append(span)
            total += width
        if total > 62:
            return None
        return tuple(offsets), tuple(widths), tuple(spans)

    plan = _identity_cache(_dim_span_cache, 128,
                           tuple(c.data for c in dim_keys), compute,
                           static_key=n_dim)
    if plan is None:
        return None
    offsets, widths, spans = plan
    fpacked, fok = _pack_keys_impl(
        tuple(c.data for c in fact_keys),
        tuple(c.valid for c in fact_keys), offsets, widths, spans)
    dpacked, dok = _pack_keys_impl(
        tuple(c.data for c in dim_keys),
        tuple(c.valid for c in dim_keys), offsets, widths, spans)
    return _pk_gather_impl(fpacked, fok, dpacked, dok, count_arr(n_fact),
                           n_dim, f_excl, d_excl)


def _null_column_like(col: Column, n: int) -> Column:
    data = jnp.zeros((n,) + col.data.shape[1:], dtype=col.data.dtype)
    return Column(col.kind, data, jnp.zeros(n, dtype=bool), col.dict_values,
                  enc=col.enc)


# candidate-pair budget for one materialized join chunk: beyond this the
# inner join splits the probe side into capacity-bounded chunks (the >HBM
# streaming answer SURVEY §5.7 calls for; the reference's analog is the
# RAPIDS spill store + spark.sql.shuffle.partitions,
# ref: nds/power_run_gpu.template:29-37). Read at USE time: the budget
# sizes the stream-mode pair bucket inside the traced per-chunk program,
# so it is a pipeline-cache key member (engine/stream.py _cache_key).
def pair_budget() -> int:
    return int(os.environ.get("NDS_TPU_PAIR_BUDGET", str(1 << 22)))

# stream-bounds pair-bucket fanout: inside the compiled chunk pipeline a
# hash join cannot sync for its candidate total, so the bucket is the
# probe side's bound times this power-of-two allowance (kept power-of-two
# so bucket shapes stay canonical); overflow falls back to the eager loop.
# Read at USE time (not import): tests and Throughput children that set
# NDS_TPU_STREAM_FANOUT after import must not be silently ignored. The
# static memory model (analysis/mem_audit.py) mirrors this read.
def stream_fanout() -> int:
    return _pow2_ceil(int(os.environ.get("NDS_TPU_STREAM_FANOUT", "4")))


@functools.partial(jax.jit, static_argnames=("cand",))
def _span_pair_indices(counts, lo, order, s, e, cand):
    """Candidate pair indices restricted to probe rows [s, e); padded to the
    static capacity ``cand`` (span boundaries are dynamic, so every span
    with the same capacity reuses one executable)."""
    plen_l = counts.shape[0]
    plen_r = order.shape[0]
    row = jnp.arange(plen_l)
    c_counts = jnp.where((row >= s) & (row < e), counts, 0)
    l_idx = jnp.repeat(row, c_counts, total_repeat_length=cand)
    starts = jnp.cumsum(c_counts) - c_counts
    pos = jnp.arange(cand) - jnp.repeat(starts, c_counts,
                                        total_repeat_length=cand)
    r_pos = jnp.repeat(lo, c_counts, total_repeat_length=cand) + pos
    r_idx = jnp.take(order, jnp.clip(r_pos, 0, max(plen_r - 1, 0)))
    return l_idx, r_idx


def _chunk_spans(counts_np, budget):
    """Greedy contiguous spans of probe rows whose candidate-pair sums stay
    within ``budget`` (a single row exceeding it gets its own span).
    Vectorized: this path triggers exactly when the probe side is large, so
    a per-row Python loop would cost seconds of host time per join."""
    n = len(counts_np)
    cum = np.cumsum(counts_np, dtype=np.int64)
    spans, s = [], 0
    while s < n:
        base = cum[s - 1] if s else 0
        # last row index whose cumulative stays within budget from `base`
        e = int(np.searchsorted(cum, base + budget, side="right"))
        if e <= s:
            e = s + 1                    # oversized single row: own span
        spans.append((s, e))
        s = e
    return spans


def _chunked_inner_join(left, right, left_keys, right_keys, probe,
                        residual_fn) -> DeviceTable:
    """Inner join materialized span-by-span so peak memory is bounded by
    ``pair_budget()`` pairs, with residual predicates applied per span
    before anything is kept — the pair expansion never exists whole."""
    counts, lo, order, total = probe

    def fetch():
        counts_np = np.asarray(counts)
        return (_chunk_spans(counts_np, pair_budget()),
                np.concatenate([[0], np.cumsum(counts_np)]))

    spans, cum = timed_read("chunk_spans", fetch)
    parts, schema_chunk = [], None
    for (s, e) in spans:
        span_total = int(cum[e] - cum[s])
        if span_total == 0:
            continue
        cand = bucket_len(span_total)
        l_idx, r_idx = _span_pair_indices(counts, lo, order, s, e, cand)
        ok = _verify_pairs(l_idx, r_idx, left_keys, right_keys)
        ok = ok & live_mask(cand, span_total)
        raw = DeviceTable(
            {**gather_table_rows(left, l_idx, cand).columns,
             **gather_table_rows(right, r_idx, cand).columns}, cand)
        schema_chunk = raw
        if residual_fn is not None:
            ok = ok & residual_fn(raw)
        n_live = host_sync(jnp.sum(ok))                # host sync per span
        if n_live == 0:
            continue
        keep = compact_indices(ok, n_live)
        parts.append(take_padded(raw, keep, n_live))
    if not parts:
        empty = jnp.zeros(bucket_len(0), dtype=jnp.int64)
        return take_padded(schema_chunk, empty + schema_chunk.plen, 0)
    return concat_tables(parts) if len(parts) > 1 else parts[0]


def _exchange_inner_join(left, right, left_keys, right_keys, mesh,
                         l_excl, r_excl, residual_fn) -> DeviceTable:
    """Repartition join over the mesh: both sides are row-sharded (too big
    for the broadcast threshold), so their (hash, row id) pairs move through
    the ICI all-to-all exchange and the probe runs device-local on
    co-partitioned key ranges (the planner's repartition-join arm; SURVEY.md
    §5.8, the UCX-shuffle role of the reference's accelerated stack)."""
    from nds_tpu.parallel.exchange import exchange_join_pairs
    plen_l = len(left_keys[0])
    plen_r = len(right_keys[0])
    lviews, rviews = _hash_views(left_keys, right_keys)
    lh = _key_hash_impl(lviews, tuple(c.valid for c in left_keys), 0,
                        False, count_arr(left.nrows), l_excl)
    rh = _key_hash_impl(rviews, tuple(c.valid for c in right_keys), 1,
                        False, count_arr(right.nrows), r_excl)
    l_idx_x, r_idx_x, live = exchange_join_pairs(
        lh, jnp.arange(plen_l, dtype=jnp.int64),
        rh, jnp.arange(plen_r, dtype=jnp.int64), mesh)
    ok = live & _verify_pairs(l_idx_x, r_idx_x, left_keys, right_keys)
    n_pairs = host_sync(jnp.sum(ok))                   # host sync
    keep = jnp.nonzero(ok, size=bucket_len(n_pairs),
                       fill_value=int(ok.shape[0]))[0]
    l_idx = jnp.take(l_idx_x, keep, mode="fill", fill_value=plen_l)
    r_idx = jnp.take(r_idx_x, keep, mode="fill", fill_value=plen_r)
    matched = DeviceTable(
        {**gather_table_rows(left, l_idx, n_pairs).columns,
         **gather_table_rows(right, r_idx, n_pairs).columns}, n_pairs)
    if residual_fn is not None:
        mask = residual_fn(matched) & live_mask(matched.plen, n_pairs)
        matched = compact_table(matched, mask)
    return matched


def join_tables(left: DeviceTable, right: DeviceTable, left_on, right_on,
                how: str = "inner", l_excl=None, r_excl=None,
                residual_fn=None) -> DeviceTable:
    """Materialized equi-join of two tables; column name collisions must be
    resolved by the caller (planner aliases). ``l_excl``/``r_excl`` fold
    deferred filter masks into the join (see :func:`join_indices`).
    ``residual_fn`` (inner joins) maps a materialized pair table to a keep
    mask — non-equi residual predicates evaluated inside the join, before
    (in the chunked path) any pair expansion is materialized whole."""
    left_keys = [left[c] for c in left_on]
    right_keys = [right[c] for c in right_on]
    probe = None
    if how == "inner":
        from nds_tpu.parallel.exchange import mesh_of
        lm = mesh_of(*(c.data for c in left_keys))
        rm = mesh_of(*(c.data for c in right_keys))
        if lm is not None and rm is not None:
            # both sides row-sharded => repartition join over the exchange
            # (tables under the broadcast threshold are replicated at load,
            # so fact x dim joins never take this path)
            return _exchange_inner_join(left, right, left_keys, right_keys,
                                        lm, l_excl, r_excl, residual_fn)
        probe = _probe_candidates(left_keys, right_keys,
                                  n_left=left.nrows, n_right=right.nrows,
                                  l_excl=l_excl, r_excl=r_excl)
        # probe[3] is None under stream-bounds: the chunked (span-by-span)
        # join syncs per span, so the streamed path always takes the
        # bound-bucket monolithic arm below
        if probe[3] is not None and probe[3] > pair_budget():
            return _chunked_inner_join(left, right, left_keys, right_keys,
                                       probe, residual_fn)
    l_idx, r_idx, n_pairs, l_extra, n_lx, r_extra, n_rx = join_indices(
        left_keys, right_keys, how,
        n_left=left.nrows, n_right=right.nrows,
        l_excl=l_excl, r_excl=r_excl, probe=probe)
    matched = DeviceTable(
        {**gather_table_rows(left, l_idx, n_pairs).columns,
         **gather_table_rows(right, r_idx, n_pairs).columns}, n_pairs)
    if residual_fn is not None and how == "inner":
        mask = residual_fn(matched) & live_mask(matched.plen, n_pairs)
        matched = compact_table(matched, mask)
    parts = [matched]
    if l_extra is not None and n_lx:
        cols = dict(gather_table_rows(left, l_extra, n_lx).columns)
        cols.update({n: _null_column_like(c, int(l_extra.shape[0]))
                     for n, c in right.columns.items()})
        parts.append(DeviceTable(cols, n_lx))
    if r_extra is not None and n_rx:
        cols = {n: _null_column_like(c, int(r_extra.shape[0]))
                for n, c in left.columns.items()}
        cols.update(gather_table_rows(right, r_extra, n_rx).columns)
        parts.append(DeviceTable(cols, n_rx))
    return concat_tables(parts) if len(parts) > 1 else matched


# ---------------------------------------------------------------------------
# concatenation (UNION ALL) with dictionary merging
# ---------------------------------------------------------------------------


_union_cache: dict = {}


def _align_str_dicts(cols):
    """(per-part code arrays, shared dictionary) for string columns whose
    dictionaries may differ: remap every part's codes into one merged
    value table (identity fast path when all parts share one dictionary).
    The merged dictionary is cached per input-dictionary identity tuple so
    repeated executions hand out the SAME host object — downstream
    identity-keyed caches (expression fusion, rank maps) would otherwise
    miss and retrace every run."""
    dicts = [c.dict_values for c in cols]
    if all(d is dicts[0] for d in dicts):
        return [c.data for c in cols], dicts[0]

    def compute():
        union, inverse = np.unique(
            np.concatenate([d.astype(str) for d in dicts]),
            return_inverse=True)
        # cache HOST arrays only: a device constant created inside a jit
        # trace is a tracer, and caching one leaks it across traces
        maps, off = [], 0
        for d in dicts:
            maps.append(inverse[off:off + len(d)].astype(np.int32))
            off += len(d)
        return maps, union.astype(object)

    maps, union = _identity_cache(_union_cache, 256, tuple(dicts), compute)
    return [jnp.take(jnp.asarray(m), c.data) for m, c in zip(maps, cols)], \
        union


def _align_encodings(cols):
    """Decode parts whose encodings differ (codes from different
    encodings are not concatenable); identical encodings concatenate
    narrow and stay encoded — the partitioned accumulator union path."""
    enc0 = cols[0].enc
    if all(encs_equal(c.enc, enc0) for c in cols) and \
            len({c.data.dtype for c in cols}) == 1:
        return cols, enc0
    return [plain_col(c) for c in cols], None


def concat_columns(cols) -> Column:
    kind = cols[0].kind
    if kind == "str":
        datas, dict_values = _align_str_dicts(cols)
        data = jnp.concatenate(datas)
        valid = _concat_valids(cols)
        return Column("str", data.astype(jnp.int32), valid, dict_values)
    cols, enc = _align_encodings(cols)
    data = jnp.concatenate([c.data for c in cols])
    return Column(kind, data, _concat_valids(cols), enc=enc)


def _concat_valids(cols):
    if all(c.valid is None for c in cols):
        return None
    return jnp.concatenate([c.valid_mask() for c in cols])


@jax.jit
def _concat_cols_impl(parts_datas, parts_valids, part_nrows):
    """Fused concatenation of every column of a UNION ALL (plus the live
    mask) in one device dispatch. ``parts_valids`` entries are per-column
    tuples mixing arrays and None (all-valid parts materialize ones only
    when some sibling carries a mask)."""
    datas = tuple(jnp.concatenate(ds) for ds in parts_datas)
    valids = []
    for ds, vs in zip(parts_datas, parts_valids):
        if vs is None:
            valids.append(None)
        else:
            valids.append(jnp.concatenate([
                v if v is not None else jnp.ones(d.shape[0], dtype=bool)
                for d, v in zip(ds, vs)]))
    plens = [d.shape[0] for d in parts_datas[0]]
    live = jnp.concatenate([jnp.arange(p) < n
                            for p, n in zip(plens, part_nrows)])
    return datas, tuple(valids), live


def concat_tables(tables) -> DeviceTable:
    """UNION ALL. Physical concatenation interleaves each part's pad rows, so
    the result is re-compacted back to prefix-padded form; the logical counts
    are already known on host, so this costs no sync. All columns concatenate
    in one fused dispatch (string columns pre-align their dictionaries on
    host)."""
    names = tables[0].column_names
    # physical concatenation lays parts out with host offsets, so lazy
    # counts must resolve here — all parts in ONE batched transfer
    total = sum(count_int(t.nrows) for t in tables)
    if not names:
        return DeviceTable({}, total, plen=max(bucket_len(total), total))

    parts_datas, parts_valids, metas = [], [], []
    for n in names:
        cols = [t[n] for t in tables]
        kind = cols[0].kind
        enc = None
        if kind == "str":
            datas, dict_values = _align_str_dicts(cols)
        else:
            cols, enc = _align_encodings(cols)
            datas, dict_values = [c.data for c in cols], None
        vs = None if all(c.valid is None for c in cols) else \
            tuple(c.valid for c in cols)
        parts_datas.append(tuple(datas))
        parts_valids.append(vs)
        metas.append((n, kind, dict_values, enc))

    part_nrows = tuple(count_int(t.nrows) for t in tables)
    datas, valids, live = _concat_cols_impl(
        tuple(parts_datas), tuple(parts_valids), part_nrows)
    out = {}
    for (n, kind, dict_values, enc), d, v in zip(metas, datas, valids):
        if kind == "str":
            d = d.astype(jnp.int32)
        out[n] = Column(kind, d, v, dict_values, enc)
    raw = DeviceTable(out, total)
    # fast path only when the summed physical length is itself a canonical
    # bucket: a non-bucket plen (e.g. 16+32=48) would leak into the XLA
    # shape universe and defeat executable reuse downstream
    if total == int(live.shape[0]) and total == bucket_len(total):
        return raw                                    # no pads anywhere
    idx = compact_indices(live, total)
    return take_padded(raw, idx, total)


# ---------------------------------------------------------------------------
# sort / limit
# ---------------------------------------------------------------------------


def sort_table(table: DeviceTable, keys, descending=None, nulls_last=None) -> DeviceTable:
    order = lexsort_indices([table[k] if isinstance(k, str) else k for k in keys],
                            descending, nulls_last, n_valid=table.nrows)
    return gather_table_rows(table, order, table.nrows)


def limit_table(table: DeviceTable, n: int) -> DeviceTable:
    """First ``n`` logical rows (callers sort first; pads always trail).
    LIMIT is output-shaping: a lazy count legitimately resolves here
    (batched), per DESIGN.md item 1's consumer taxonomy."""
    new_n = min(n, count_int(table.nrows))
    cap = bucket_len(new_n)
    if cap >= table.plen:
        return DeviceTable(dict(table.columns), new_n)
    return gather_table_rows(table, jnp.arange(cap), new_n)
