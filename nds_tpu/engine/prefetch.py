# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Bounded prefetch ring: the asynchronous ingest half of the data plane.

Every byte the streamed executor consumes used to enter through ONE host
thread doing the arrow slice, narrow-codec encode and ``jax.device_put``
INLINE in the drive loop — the "double-buffered prefetch" was depth-1
and serial on the driver (dispatch asynchrony hid the device compute,
never the host-side slice+encode). This module moves that host work off
the driver thread:

* a single WORKER thread pulls upcoming chunks from the source iterator
  (``ChunkedTable.padded_chunks`` / the eager loop's ``device_chunks``),
  applies the caller's ``prepare`` step (flatten + nbytes accounting +
  sharded placement — the host slice, encode and upload), and hands the
  ready payloads through a bounded queue;
* the queue depth (``NDS_TPU_PREFETCH_DEPTH``, read at ring-BUILD time,
  default 2) is the BACKPRESSURE bound: the worker blocks once ``depth``
  prepared chunks are waiting, so the ring's extra live set is exactly
  ``depth x chunk bytes`` — the number ``analysis/mem_audit.py`` prices
  into pipeline admission (the lockstep rule);
* delivery is ORDERED by construction (one worker, one FIFO queue):
  chunk k always arrives before chunk k+1, which the accumulator
  scatter and the partition histogram rely on only for determinism of
  the trace labels — the math itself is order-independent;
* ``close()`` is the clean shutdown: it signals the worker, drains the
  queue so a backpressure-blocked ``put`` wakes, and joins the thread —
  called from the drive loops' ``finally`` so an overflow/eager-rerun or
  a trace-divergence exception never leaks a thread or pins payloads;
* a worker exception is PROPAGATED: it rides the queue as an error
  payload and re-raises in the driver at the next fetch, so a corrupt
  chunk store or a codec bug fails the statement exactly like the
  inline path would (strict mode and the eager fallback both see the
  original exception).

``depth <= 0`` disables the ring entirely: :func:`chunk_ring` returns an
inline pump that runs ``prepare`` on the driver thread at each fetch —
bit-for-bit today's path (same thread, same order, same dispatch
interleaving), the escape hatch and the A/B baseline of the slow-source
differential (``tests/test_prefetch.py``).

Contract for ``prepare`` (and the source iterator's per-item work, which
also runs on the worker): NO host reads and NO spans. The worker thread
has its own thread-local sync counters and span ring, so a sync there
would vanish from the driver's accounting and a span would land in the
``unattributed`` diagnostics ring — the ``host-sync-in-prefetch-worker``
jax_lint rule (error severity) rejects both statically, and the conc
audit's ring-liveness probe (``tools/conc_audit_diff.py``) exercises the
shutdown path under real threads. Slice + encode + ``device_put`` are
all sync-free by construction (numpy work plus an async upload), which
is why the whole ingest step can leave the driver thread at all.

The driver-side fetch (:meth:`ChunkRing.next_chunk`) accumulates the
time the driver spent BLOCKED waiting on the ring (``stall_ns``) — the
number ``StreamEvent.prefetch_stall_ms`` surfaces per scan and
``tools/trace_report.py`` prices as its own phase column: overlap is
evidence, not assertion. With the ring disabled the same counter holds
the inline slice+encode+upload time (the cost the ring exists to hide),
so the depth-0 vs depth-N differential reads directly off the events.
"""

from __future__ import annotations

import os
import queue
import threading
import time

from nds_tpu.engine import faults as _F

# sentinel kinds riding the queue (payloads are (kind, value) pairs)
_ITEM = "item"
_DONE = "done"
_ERR = "err"


def _prepare_guarded(prepare, item):
    """One prepare attempt behind the ``prefetch`` fault seam: the
    injection point sits exactly where a real slice/encode/upload fault
    would interrupt (``device-put`` injections fire inside ``prepare``
    itself — engine/stream.py's ``_prepare_chunk``)."""
    _F.fault_point("prefetch")
    return item if prepare is None else prepare(item)

# how long a blocked worker put waits between shutdown checks: short
# enough that close() never stalls the caller, long enough to stay off
# the scheduler's back during normal backpressure
_PUT_POLL_S = 0.05


def prefetch_depth() -> int:
    """``NDS_TPU_PREFETCH_DEPTH``: bounded ring depth (chunks the worker
    may run ahead of the driver). Read at ring-BUILD time, never frozen
    at import (the PR 6/13 env-knob discipline); ``<= 0`` disables the
    ring — the inline, bit-for-bit-today path. Default 2: one chunk
    uploading while one sits ready, matching the double-buffer the
    drive loop's async dispatch already assumed."""
    try:
        return int(os.environ.get("NDS_TPU_PREFETCH_DEPTH", "2"))
    except ValueError:
        return 2


class _InlineRing:
    """Depth-0 escape hatch: same interface, no thread — ``prepare``
    runs on the driver at each fetch, exactly the pre-ring drive loop.
    ``stall_ns`` then measures the inline host fetch (slice + encode +
    upload) so the differential against a live ring is observable."""

    def __init__(self, it, prepare=None):
        self._it = iter(it)
        self._prepare = prepare
        self.stall_ns = 0

    def next_chunk(self):
        t0 = time.perf_counter_ns()
        try:
            item = next(self._it, None)
            if item is None:
                return None
            # same bounded-retry policy as the threaded worker (the
            # ``prefetch`` transient seam), on the driver thread — the
            # depth-0 pump stays bit-for-bit except under a real fault
            return _F.with_retry(
                "prefetch", lambda: _prepare_guarded(self._prepare, item))
        finally:
            self.stall_ns += time.perf_counter_ns() - t0

    def stall_ms(self) -> float:
        return self.stall_ns / 1e6

    def close(self) -> None:
        self._it = iter(())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ChunkRing:
    """Bounded, ordered, single-worker prefetch ring over one chunk
    iterator. See the module docstring for the full contract."""

    def __init__(self, it, prepare=None, depth=2, name="nds-prefetch"):
        self._q: queue.Queue = queue.Queue(maxsize=max(int(depth), 1))
        self._stop = threading.Event()
        self._exhausted = False
        self.stall_ns = 0
        # worker-side recovery evidence: FaultEvents are thread-scoped
        # (like sync counters), so a retry that recovered ON THE WORKER
        # parks its event here and the driver re-records it into its own
        # ring at the next fetch — instance state under one dedicated
        # lock (the conc-audit classification)
        self._faults: list = []
        self._faults_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._work, args=(iter(it), prepare), daemon=True,
            name=name)
        self._thread.start()

    # ------------------------------------------------------------ worker

    def _put(self, payload) -> bool:
        """Backpressure-bounded put that stays responsive to shutdown:
        returns False when the ring closed while waiting."""
        while not self._stop.is_set():
            try:
                self._q.put(payload, timeout=_PUT_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _sink(self, seam, action, attempt=0, detail=""):
        """Worker-side FaultEvent sink (see __init__)."""
        with self._faults_lock:
            self._faults.append((seam, action, attempt, detail))

    def _drain_worker_faults(self) -> None:
        """Re-record worker-side recovery events on the DRIVER thread so
        they land in the query's own evidence ring."""
        with self._faults_lock:
            got, self._faults[:] = list(self._faults), []
        for (seam, action, attempt, detail) in got:
            _F.record_fault_event(seam, action, attempt=attempt,
                                  detail=detail)

    def _work(self, it, prepare) -> None:
        try:
            for item in it:
                if self._stop.is_set():
                    return
                # bounded deterministic retry of the prepare step (the
                # ``prefetch`` transient seam): a transient slice/encode/
                # upload fault recovers in place; exhausted or
                # non-transient errors ride the queue and re-raise at the
                # driver's next fetch exactly like the inline path
                payload = _F.with_retry(
                    "prefetch",
                    lambda i=item: _prepare_guarded(prepare, i),
                    record=self._sink)
                if not self._put((_ITEM, payload)):
                    return
            self._put((_DONE, None))
        except BaseException as exc:  # propagate to the driver, always
            self._put((_ERR, exc))

    # ------------------------------------------------------------ driver

    def next_chunk(self):
        """Next prepared payload, or None at end of stream. Re-raises a
        worker exception at the point the inline path would have raised
        it. The blocked wait is accumulated into ``stall_ns``."""
        if self._exhausted:
            return None
        t0 = time.perf_counter_ns()
        kind, value = self._q.get()
        self.stall_ns += time.perf_counter_ns() - t0
        self._drain_worker_faults()
        if kind is _ITEM:
            return value
        self._exhausted = True
        if kind is _ERR:
            self.close()
            raise value
        return None

    def stall_ms(self) -> float:
        """Driver milliseconds spent blocked on the ring so far — the
        ``StreamEvent.prefetch_stall_ms`` evidence."""
        return self.stall_ns / 1e6

    def close(self) -> None:
        """Clean shutdown (idempotent): signal the worker, drain the
        queue so a backpressure-blocked put wakes, join the thread. Any
        worker-side recovery evidence still parked is re-recorded here
        so a fault on the FINAL chunk is never lost."""
        self._stop.set()
        self._exhausted = True
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=60.0)
        self._drain_worker_faults()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def chunk_ring(it, prepare=None, depth=None, name="nds-prefetch"):
    """The ONE ring constructor the drive loops use: a :class:`ChunkRing`
    when the (build-time) depth is positive, the inline pump otherwise.
    ``prepare`` runs on the worker thread — it must never host-read or
    open a span (``host-sync-in-prefetch-worker`` enforces this
    statically)."""
    d = prefetch_depth() if depth is None else int(depth)
    if d <= 0:
        return _InlineRing(it, prepare)
    return ChunkRing(it, prepare, depth=d, name=name)
