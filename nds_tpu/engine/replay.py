# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Whole-query trace-replay compilation: ONE XLA program per query.

The engine executes eagerly, table-at-a-time; on a remote-attached chip
every one of the ~100-400 small dispatches a query makes pays tunnel
latency, which dominates wall time even after the lazy-count work cut the
BLOCKING reads to 1-3 per query (PERF.md: syncWait is still 80%+ of wall
on tunneled SF0.05). The reference never has this problem: Spark compiles
each stage to one JVM loop and the driver makes one round trip
(ref: nds/nds_power.py:125-135).

The TPU-native answer is the same one jit gives training loops: TRACE the
whole query into one program and REPLAY it. Mechanics:

1. RECORD: run the query eagerly once under ``ops.recording()`` — every
   host read (bucket-sizing syncs, batched count resolutions, host-built
   dimension maps, chunk span plans) logs its value in order.
2. COMPILE: re-run the SAME planner code under ``jax.jit`` with the
   session's catalog columns as arguments and ``ops.replaying(log)``
   serving every host read from the recording — no device contact during
   tracing. The result is one fused XLA program for the entire pipeline:
   scans, joins, aggregation, sort, limit.
3. REPLAY: subsequent executions of the same query text on the same data
   version call the compiled program: one dispatch, one result fetch —
   the reference's one-round-trip execution contract, plus XLA now
   fuses/optimizes ACROSS operator boundaries the eager path could not.

Safety: the replay cache is keyed on (query text, session data version);
any catalog mutation bumps the version. A divergence between trace and
recording raises ``ops.ReplayMismatch`` and the query permanently falls
back to the eager path. Streaming (>HBM ChunkedTable) scans never enter
the cache — their chunk loop is host-driven by design.
"""

from __future__ import annotations

from dataclasses import replace as _replace

import jax

from nds_tpu.engine import ops as E
from nds_tpu.engine.table import DeviceTable


class _NotReplayable(Exception):
    pass


import os as _os

_MAX_EQNS = int(_os.environ.get("NDS_TPU_REPLAY_MAX_EQNS", "4500"))


def _count_eqns(jaxpr) -> int:
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)     # unwrap ClosedJaxpr
    n = 0
    for eq in jaxpr.eqns:
        n += 1
        for v in eq.params.values():
            if hasattr(v, "jaxpr"):
                n += _count_eqns(v.jaxpr)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if hasattr(x, "jaxpr"):
                        n += _count_eqns(x.jaxpr)
    return n


# log entries whose array payloads are DEVICE OPERANDS (consumed via
# jnp.asarray and elementwise math only): these lift into jit arguments
# instead of baking fact-sized constants into the program. Entries whose
# values drive HOST decisions (sync counts, chunk spans, key ranges) must
# stay literal. Maps tag -> indices of liftable tuple elements (None =
# the whole value is one array).
_LIFTABLE = {
    "cast_str": (0,),          # (inv codes, dictionary)
    "concat": (0,),
    "date_part": None,
    "month_arith": None,
    "dense_dim": (1,),         # (base, position map) — may be None
}
_LIFT_MIN_ELEMS = 1024


def _lift_log(log):
    """Split a recorded log into (log-with-ArgRefs, operand arrays)."""
    import numpy as np
    out_log, operands = [], []

    def lift(arr):
        operands.append(arr)
        return E.ArgRef(len(operands) - 1)

    for tag, val in log:
        idxs = _LIFTABLE.get(tag, ())
        if idxs is None and isinstance(val, np.ndarray) and \
                val.size >= _LIFT_MIN_ELEMS:
            val = lift(val)
        elif idxs and isinstance(val, tuple):
            val = tuple(
                lift(x) if (i in idxs and isinstance(x, np.ndarray)
                            and x.size >= _LIFT_MIN_ELEMS) else x
                for i, x in enumerate(val))
        out_log.append((tag, val))
    return out_log, operands


class CompiledQuery:
    """One compiled whole-query program + the metadata to call it."""

    def __init__(self, session, stmt, log, out_template):
        self.session = session
        self.stmt = stmt
        # big array payloads become jit ARGUMENTS (program stays small and
        # the executable is not re-specialized to them)
        self.log, self.operands = _lift_log(list(log))
        # (names, kinds, dict_values, valids-present, plen, nrows_bound)
        self.out_template = out_template
        self.arg_spec = None       # [(table, col, has_valid)]
        self.jitted = None

    # ---------------------------------------------------------------- build

    def _flat_args(self):
        """The session catalog's column buffers, in a deterministic order
        (re-collected at every call so maintenance-refreshed tables feed
        the current buffers — the data version guards semantic change)."""
        args = []
        for tname, cname, has_valid in self.arg_spec:
            col = self.session.catalog[tname][cname]
            args.append(col.data)
            if has_valid:
                args.append(col.valid)
        return args

    def compile(self):
        from nds_tpu.sql.planner import Planner
        catalog = self.session.catalog
        # lazy view counts resolve up front: a DeviceCount closed over the
        # trace would leak a stale device scalar into the program
        for t in catalog.values():
            if isinstance(t, DeviceTable) and \
                    isinstance(t.nrows, E.DeviceCount):
                t.nrows = t.nrows.to_int()
        # argument universe: every device table in the catalog (chunked
        # tables disqualified the query before we get here)
        self.arg_spec = []
        for tname in sorted(catalog):
            t = catalog[tname]
            if not isinstance(t, DeviceTable):
                raise _NotReplayable(f"{tname} is not device-resident")
            for cname, col in t.columns.items():
                self.arg_spec.append((tname, cname, col.valid is not None))
        spec = self.arg_spec
        base_tables = set(self.session.base_tables)
        stmt, log = self.stmt, self.log
        names, kinds, dicts, valided, plen, bound = self.out_template

        def traced(flat, operands):
            # rebuild the catalog around the traced buffers
            cat = {}
            i = 0
            for tname, cname, has_valid in spec:
                data = flat[i]
                i += 1
                valid = None
                if has_valid:
                    valid = flat[i]
                    i += 1
                src = catalog[tname][cname]
                cat.setdefault(tname, {})[cname] = _replace(
                    src, data=data, valid=valid)
            cat2 = {t: DeviceTable(cols, catalog[t].nrows)
                    for t, cols in cat.items()}
            planner = Planner(cat2, base_tables=base_tables)
            with E.replaying(log, operands):
                out = planner.query(stmt)
            outs = []
            for n in names:
                c = out[n]
                outs.append(c.data)
                outs.append(c.valid)
            outs.append(E.count_arr(out.nrows))
            return tuple(outs)

        # validate the replay log end-to-end with the SAME trace the jit
        # cache will reuse, and gate on program size: a handful of
        # rollup+window giants (q67-class) trip superlinear XLA
        # optimization time; they stay on the eager path rather than
        # stall a compile queue
        E.resolve_counts()   # the trace must start with a clean batch
        self.jitted = jax.jit(traced)
        try:
            jaxpr = self.jitted.trace(
                self._flat_args(), self.operands).jaxpr
        except AttributeError:  # pragma: no cover - older jax
            jaxpr = jax.make_jaxpr(traced)(
                self._flat_args(), self.operands).jaxpr
        n_eqns = _count_eqns(jaxpr)
        if n_eqns > _MAX_EQNS:
            self.jitted = None
            raise _NotReplayable(
                f"program too large to fuse profitably: {n_eqns} eqns")
        return self

    # ----------------------------------------------------------------- run

    def run(self, block: bool = False) -> DeviceTable:
        from nds_tpu.engine.column import Column
        names, kinds, dicts, valided, plen, bound = self.out_template
        # the first call traces: stray real counts must not sit in the
        # pending list where the traced resolve would batch them
        E.resolve_counts()
        outs = self.jitted(self._flat_args(), self.operands)
        if block:
            import jax as _jax
            _jax.block_until_ready(outs[-1])
        cols = {}
        for j, n in enumerate(names):
            data, valid = outs[2 * j], outs[2 * j + 1]
            cols[n] = Column(kinds[j], data, valid, dicts[j])
        nrows = E.DeviceCount(outs[-1], bound)
        return DeviceTable(cols, nrows, plen=plen)


def out_template_of(table: DeviceTable):
    names = list(table.column_names)
    kinds = [table[n].kind for n in names]
    dicts = [table[n].dict_values for n in names]
    valided = [table[n].valid is not None for n in names]
    return (names, kinds, dicts, valided, table.plen,
            E.count_bound(table.nrows))


def record_eligible(session) -> bool:
    """Only fully device-resident catalogs replay (a ChunkedTable's chunk
    loop is host-driven)."""
    return all(isinstance(t, DeviceTable) for t in session.catalog.values())
