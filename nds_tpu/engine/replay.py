# Copyright (c) 2026, nds-tpu authors. Licensed under the Apache License, Version 2.0.
"""Whole-query trace-replay compilation: ONE XLA program per query.

The engine executes eagerly, table-at-a-time; on a remote-attached chip
every one of the ~100-400 small dispatches a query makes pays tunnel
latency, which dominates wall time even after the lazy-count work cut the
BLOCKING reads to 1-3 per query (PERF.md: syncWait is still 80%+ of wall
on tunneled SF0.05). The reference never has this problem: Spark compiles
each stage to one JVM loop and the driver makes one round trip
(ref: nds/nds_power.py:125-135).

The TPU-native answer is the same one jit gives training loops: TRACE the
whole query into one program and REPLAY it. Mechanics:

1. RECORD: run the query eagerly once under ``ops.recording()`` — every
   host read (bucket-sizing syncs, batched count resolutions, host-built
   dimension maps, chunk span plans) logs its value in order.
2. COMPILE: re-run the SAME planner code under ``jax.jit`` with the
   session's catalog columns as arguments and ``ops.replaying(log)``
   serving every host read from the recording — no device contact during
   tracing. The result is one fused XLA program for the entire pipeline:
   scans, joins, aggregation, sort, limit.
3. REPLAY: subsequent executions of the same query text on the same data
   version call the compiled program: one dispatch, one result fetch —
   the reference's one-round-trip execution contract, plus XLA now
   fuses/optimizes ACROSS operator boundaries the eager path could not.

Safety: the replay cache is keyed on (query text, session data version);
any catalog mutation bumps the version. A divergence between trace and
recording raises ``ops.ReplayMismatch`` and the query permanently falls
back to the eager path. A query that binds a streaming (>HBM
ChunkedTable) scan is blacklisted to the eager chunk loop at compile
time; other queries in the same session replay normally.
"""

from __future__ import annotations

from dataclasses import replace as _replace

import jax

from nds_tpu.engine import ops as E
from nds_tpu.engine.table import DeviceTable
from nds_tpu.obs import trace as _obs


class _NotReplayable(Exception):
    pass


try:
    from jax.core import DropVar as _DropVar
except ImportError:  # pragma: no cover - future jax relocations
    from jax.extend.core import DropVar as _DropVar  # type: ignore

import os as _os

# segmentation budget, read at USE time (not import): a post-import
# change to the knob must shape the next replay build, not be silently
# frozen (the conc-audit env-freeze rule).
def _max_eqns() -> int:
    return int(_os.environ.get("NDS_TPU_REPLAY_MAX_EQNS", "4500"))


def _count_eqns(jaxpr) -> int:
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)     # unwrap ClosedJaxpr
    n = 0
    for eq in jaxpr.eqns:
        n += _eqn_weight(eq)
    return n


def _eqn_weight(eq) -> int:
    """1 + every equation nested in the eqn's sub-jaxprs (pjit bodies,
    scan/cond branches) — the unit XLA optimization time scales with."""
    n = 1
    for v in eq.params.values():
        if hasattr(v, "jaxpr"):
            n += _count_eqns(v.jaxpr)
        elif isinstance(v, (list, tuple)):
            for x in v:
                if hasattr(x, "jaxpr"):
                    n += _count_eqns(x.jaxpr)
    return n


# read at USE time like _max_eqns() above
def _max_segments() -> int:
    return int(_os.environ.get("NDS_TPU_REPLAY_MAX_SEGMENTS", "6"))


def _split_jaxpr(closed, max_eqns):
    """Partition a whole-query ClosedJaxpr into sequential segments of
    bounded optimization weight, each compiled as its OWN XLA program.

    XLA's optimization passes go superlinear on the handful of
    megaprograms the biggest queries trace to (q14/q67-class); chaining
    K bounded programs keeps compile time ~linear while still replacing
    the few-hundred-dispatch eager stream with K dispatches. Returns
    ``(segments, out_src)`` where each segment is ``(jaxpr, const_vals,
    invars, outvars)`` and ``out_src`` maps every program output var to
    its position, or None when the program does not split cleanly
    (effects, or a single oversized equation)."""
    from jax.extend import core as jex_core
    jaxpr = closed.jaxpr
    if jaxpr.effects:
        return None
    weights = [_eqn_weight(eq) for eq in jaxpr.eqns]
    if not jaxpr.eqns or max(weights) > max_eqns:
        return None                       # one indivisible giant equation
    groups, cur, cur_w = [], [], 0
    for eq, w in zip(jaxpr.eqns, weights):
        if cur and cur_w + w > max_eqns:
            groups.append(cur)
            cur, cur_w = [], 0
        cur.append(eq)
        cur_w += w
    if cur:
        groups.append(cur)
    if len(groups) > _max_segments():
        return None
    const_of = dict(zip(jaxpr.constvars, closed.consts))
    # var -> defining group index (inputs/consts = -1)
    def_in = {v: -1 for v in list(jaxpr.invars) + list(jaxpr.constvars)}
    for gi, eqns in enumerate(groups):
        for eq in eqns:
            for ov in eq.outvars:
                def_in[ov] = gi
    is_var = lambda a: not isinstance(a, jex_core.Literal)  # noqa: E731
    # vars each group consumes from OUTSIDE itself
    needs = [[] for _ in groups]
    for gi, eqns in enumerate(groups):
        seen = set()
        for eq in eqns:
            for iv in eq.invars:
                if is_var(iv) and def_in[iv] != gi and iv not in seen:
                    seen.add(iv)
                    needs[gi].append(iv)
    # vars that must cross a segment boundary (consumed later or output)
    final_out = [v for v in jaxpr.outvars if is_var(v)]
    crossers = set(final_out)
    for gi in range(len(groups)):
        crossers.update(v for v in needs[gi] if def_in[v] >= 0)
    segments = []
    for gi, eqns in enumerate(groups):
        invars = needs[gi]
        outvars = []
        for eq in eqns:
            for ov in eq.outvars:
                if ov in crossers and not isinstance(ov, _DropVar):
                    outvars.append(ov)
        seg_consts = [const_of[v] for v in invars if v in const_of]
        cvars = [v for v in invars if v in const_of]
        rvars = [v for v in invars if v not in const_of]
        # NO debug_info on segments: a segment's invars/outvars are a
        # re-partition of the whole program's, so inheriting its
        # arg_names/result_paths trips the constructor's length
        # assertion on jax >= 0.4.30 and the whole split silently
        # blacklisted the query (the seed's one red tier-1 test). The
        # segments are synthetic — there are no user-meaningful names
        # to preserve.
        seg = jex_core.Jaxpr(constvars=cvars, invars=rvars,
                             outvars=outvars, eqns=eqns)
        segments.append((seg, seg_consts, rvars, outvars))
    return segments, list(jaxpr.outvars), const_of


# log entries whose array payloads are DEVICE OPERANDS (consumed via
# jnp.asarray and elementwise math only): these lift into jit arguments
# instead of baking fact-sized constants into the program. Entries whose
# values drive HOST decisions (sync counts, chunk spans, key ranges) must
# stay literal. Maps tag -> indices of liftable tuple elements (None =
# the whole value is one array).
_LIFTABLE = {
    "cast_str": (0,),          # (inv codes, dictionary)
    "concat": (0,),
    "date_part": None,
    "month_arith": None,
    "dense_dim": (1,),         # (base, position map) — may be None
}
_LIFT_MIN_ELEMS = 1024


def _lift_log(log):
    """Split a recorded log into (log-with-ArgRefs, operand arrays)."""
    import numpy as np
    out_log, operands = [], []

    def lift(arr):
        operands.append(arr)
        return E.ArgRef(len(operands) - 1)

    for tag, val in log:
        idxs = _LIFTABLE.get(tag, ())
        if idxs is None and isinstance(val, np.ndarray) and \
                val.size >= _LIFT_MIN_ELEMS:
            val = lift(val)
        elif idxs and isinstance(val, tuple):
            val = tuple(
                lift(x) if (i in idxs and isinstance(x, np.ndarray)
                            and x.size >= _LIFT_MIN_ELEMS) else x
                for i, x in enumerate(val))
        out_log.append((tag, val))
    return out_log, operands


class CompiledQuery:
    """One compiled whole-query program + the metadata to call it."""

    def __init__(self, session, stmt, log, out_template):
        self.session = session
        self.stmt = stmt
        # big array payloads become jit ARGUMENTS (program stays small and
        # the executable is not re-specialized to them)
        self.log, self.operands = _lift_log(list(log))
        # (names, kinds, dict_values, valids-present, plen, nrows_bound)
        self.out_template = out_template
        self.arg_spec = None       # [(table, col, has_valid)]
        self.jitted = None
        self.segments = None       # chained programs when too big for one
        self.seg_invars = None
        self.seg_outsrc = None
        self.seg_constenv = None

    # ---------------------------------------------------------------- build

    def _flat_args(self):
        """The session catalog's column buffers, in a deterministic order
        (re-collected at every call so maintenance-refreshed tables feed
        the current buffers — the data version guards semantic change)."""
        args = []
        for tname, cname, has_valid in self.arg_spec:
            col = self.session.catalog[tname][cname]
            args.append(col.data)
            if has_valid:
                args.append(col.valid)
        return args

    def compile(self):
        from nds_tpu.sql.planner import Planner
        catalog = self.session.catalog
        # lazy view counts resolve up front: a DeviceCount closed over the
        # trace would leak a stale device scalar into the program
        for t in catalog.values():
            if isinstance(t, DeviceTable) and \
                    isinstance(t.nrows, E.DeviceCount):
                t.nrows = t.nrows.to_int()
        # argument universe: every DEVICE table in the catalog. Host-
        # resident ChunkedTables are left out: a query that binds one is
        # filtered upstream by record_eligible() and routed to the
        # compiled streaming executor (engine/stream.py) instead, while
        # every other query in the same >HBM session stays
        # replay-eligible.
        self.arg_spec = []
        for tname in sorted(catalog):
            t = catalog[tname]
            if not isinstance(t, DeviceTable):
                continue
            for cname, col in t.columns.items():
                self.arg_spec.append((tname, cname, col.valid is not None))
        spec = self.arg_spec
        base_tables = set(self.session.base_tables)
        stmt, log = self.stmt, self.log
        names, kinds, dicts, valided, plen, bound = self.out_template

        def traced(flat, operands):
            # rebuild the catalog around the traced buffers
            cat = {}
            i = 0
            for tname, cname, has_valid in spec:
                data = flat[i]
                i += 1
                valid = None
                if has_valid:
                    valid = flat[i]
                    i += 1
                src = catalog[tname][cname]
                cat.setdefault(tname, {})[cname] = _replace(
                    src, data=data, valid=valid)
            cat2 = {t: DeviceTable(cols, catalog[t].nrows)
                    for t, cols in cat.items()}
            planner = Planner(cat2, base_tables=base_tables)
            with E.replaying(log, operands):
                out = planner.query(stmt)
            outs = []
            for n in names:
                c = out[n]
                outs.append(c.data)
                outs.append(c.valid)
            outs.append(E.count_arr(out.nrows))
            return tuple(outs)

        # validate the replay log end-to-end with the SAME trace the jit
        # cache will reuse, and gate on program size: a handful of
        # rollup+window giants (q14/q67-class) trip superlinear XLA
        # optimization time as ONE program — those split into a chain of
        # bounded segment programs instead (compile ~linear, K dispatches)
        E.resolve_counts()   # the trace must start with a clean batch
        self.jitted = jax.jit(traced)
        # span covers the whole-query re-trace (the host-side cost of
        # turning the recording into one program); XLA backend compile
        # lands on the first run() and is metered there via compile_ns
        with _obs.span("replay.compile", statement="whole-query"):
            try:
                closed = self.jitted.trace(
                    self._flat_args(), self.operands).jaxpr
            except AttributeError:  # pragma: no cover - older jax
                closed = jax.make_jaxpr(traced)(
                    self._flat_args(), self.operands)
        n_eqns = _count_eqns(closed.jaxpr)
        if n_eqns > _max_eqns():
            self.jitted = None
            split = _split_jaxpr(closed, _max_eqns())
            if split is None:
                raise _NotReplayable(
                    f"program too large to fuse profitably ({n_eqns} eqns) "
                    "and not cleanly splittable")
            segs, out_src, const_env = split
            import functools
            from jax import core as jcore
            self.segments = [
                (jax.jit(functools.partial(jcore.eval_jaxpr, seg)),
                 consts, invars, outvars)
                for seg, consts, invars, outvars in segs]
            self.seg_invars = closed.jaxpr.invars
            self.seg_outsrc = out_src
            # a program output may BE a jaxpr constvar (a recorded value
            # reaching the output untransformed): those never cross a
            # segment boundary, so the run env must be seeded with them
            self.seg_constenv = const_env
        return self

    # ----------------------------------------------------------------- run

    def _run_segments(self):
        """Execute the chained segment programs, feeding each segment from
        an environment of prior outputs (K dispatches instead of 1)."""
        from jax.extend import core as jex_core
        import jax.tree_util as jtu
        leaves = jtu.tree_leaves((self._flat_args(), self.operands))
        env = dict(self.seg_constenv)
        env.update(zip(self.seg_invars, leaves))
        for seg_fn, consts, invars, outvars in self.segments:
            outs = seg_fn(consts, *[env[v] for v in invars])
            env.update(zip(outvars, outs))
        import jax.numpy as jnp
        # literal outputs carry raw trace-time scalars (TypedInt); jit
        # would have returned arrays, so the chained path must too
        return tuple(
            jnp.asarray(v.val, dtype=v.aval.dtype)
            if isinstance(v, jex_core.Literal)
            else env[v] for v in self.seg_outsrc)

    def run(self, block: bool = False) -> DeviceTable:
        with _obs.span("replay.drive",
                       segments=len(self.segments or ()) or 1):
            return self._run(block)

    def _run(self, block: bool) -> DeviceTable:
        from nds_tpu.engine.column import Column
        names, kinds, dicts, valided, plen, bound = self.out_template
        # the first call traces: stray real counts must not sit in the
        # pending list where the traced resolve would batch them
        E.resolve_counts()
        if self.segments is not None:
            # the jaxpr's outvars are the FLAT leaves (None valids are
            # dropped by tracing); re-expand to the (data, valid)*N +
            # count layout run() consumes using the template's flags
            flat = list(self._run_segments())
            outs = []
            for has_valid in valided:
                outs.append(flat.pop(0))
                outs.append(flat.pop(0) if has_valid else None)
            outs.append(flat.pop(0))
        else:
            outs = self.jitted(self._flat_args(), self.operands)
        if block:
            import jax as _jax
            _jax.block_until_ready(outs[-1])
        cols = {}
        for j, n in enumerate(names):
            data, valid = outs[2 * j], outs[2 * j + 1]
            cols[n] = Column(kinds[j], data, valid, dicts[j])
        nrows = E.DeviceCount(outs[-1], bound)
        return DeviceTable(cols, nrows, plen=plen)


def out_template_of(table: DeviceTable):
    names = list(table.column_names)
    kinds = [table[n].kind for n in names]
    dicts = [table[n].dict_values for n in names]
    valided = [table[n].valid is not None for n in names]
    return (names, kinds, dicts, valided, table.plen,
            E.count_bound(table.nrows))


def _binds_chunked(session, stmt) -> bool:
    """True when any table reference in the statement resolves to a
    host-resident ChunkedTable in the session catalog. Conservative on
    shadowing: a CTE reusing a chunked table's name still counts (the
    statement simply stays on the planner path, which handles it)."""
    from nds_tpu.engine.table import ChunkedTable
    from nds_tpu.sql import ast as A
    chunked = {name for name, t in session.catalog.items()
               if isinstance(t, ChunkedTable)}
    if not chunked:
        return False
    found = False

    def walk(x):
        nonlocal found
        if found:
            return
        if isinstance(x, A.TableRef) and x.name.lower() in chunked:
            found = True
            return
        if hasattr(x, "__dataclass_fields__"):
            for f in vars(x).values():
                walk_any(f)

    def walk_any(f):
        if isinstance(f, (list, tuple)):
            for y in f:
                walk_any(y)
        elif hasattr(f, "__dataclass_fields__"):
            walk(f)
    walk(stmt)
    return found


def record_eligible(session, stmt=None) -> bool:
    """Recording is attempted per QUERY, not per catalog: a session with
    >HBM ChunkedTables still replays every query that binds only device
    tables. A query that DOES bind a chunked scan is routed away from
    whole-query record/replay up front — recording it would log one host
    decision per chunk and the compile trace cannot rebuild a
    host-resident table from jit arguments. Its streaming is compiled
    one layer down instead: the planner's ``_stream_join_parts`` hands the
    join graph to the chunk pipeline executor (engine/stream.py), which
    applies the same record/replay machinery to ONE chunk-invariant
    per-chunk program."""
    if stmt is not None and _binds_chunked(session, stmt):
        return False
    return True
